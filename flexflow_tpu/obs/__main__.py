"""Telemetry artifact CLI.

Usage:
    python -m flexflow_tpu.obs trace    <events.jsonl> [-o trace.json]
    python -m flexflow_tpu.obs summary  <events.jsonl>
    python -m flexflow_tpu.obs prom     <metrics.jsonl> [-o metrics.prom]
    python -m flexflow_tpu.obs requests <events.jsonl> [--slowest K]
    python -m flexflow_tpu.obs explain  [--top N] [--in-situ] [shape flags]
    python -m flexflow_tpu.obs bench    [--src DIR] [--tolerance F]
    python -m flexflow_tpu.obs calibrate inspect <store.json>
    python -m flexflow_tpu.obs calibrate prune   <store.json> --max-age-h H
    python -m flexflow_tpu.obs calibrate diff    <a.json> <b.json>

``trace`` converts a structured event log to Chrome-trace JSON (open at
https://ui.perfetto.dev). ``summary`` schema-validates the log and
prints per-category/event counts plus step/search aggregates — and,
when the log carries a step-observatory capture, the overlap-
realization/HBM numbers, per-collective hidden/exposed attribution and
the measured-vs-simulated per-op drift from the overlay file.
``bench`` prints the BENCH_r*.json round trajectory with the newest
round's regression attributed per phase (fwd/bwd/opt/sync).
``prom`` re-renders the last metrics.jsonl snapshot as Prometheus text.
``requests`` reconstructs per-request lifecycles from the serving
flight recorder's events (cat "requests"): stage breakdown, top-K
slowest, shed and requeue causes. ``explain`` compiles the benchmark
Transformer (CPU-sized by default; pass --seq/--hidden/... for the real
bench shape on a TPU host), joins the cost model against on-device
profile_ops measurements and prints the miscalibrated-op kernel
worklist — each perf round starts from this list (docs/performance.md).
``calibrate`` inspects/maintains a persistent cost-model calibration
store (obs/calibration.py).

This module is a CLI entry point: bare print() is its job (fflint FFL201
allowlists __main__ modules).
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter

from .tracer import lanes_from_events, read_events_jsonl, to_chrome_trace


def _cmd_trace(args) -> int:
    events, problems = read_events_jsonl(args.events)
    for p in problems:
        print(f"warning: {p}", file=sys.stderr)
    out = args.output or "trace.json"
    with open(out, "w") as f:
        json.dump(to_chrome_trace(events,
                                  lane_names=lanes_from_events(events)), f)
    print(f"wrote {out}: {len(events)} event(s) "
          f"({len(problems)} malformed line(s) skipped)")
    return 0


def _cmd_summary(args) -> int:
    events, problems = read_events_jsonl(args.events)
    if problems:
        for p in problems:
            print(f"schema: {p}", file=sys.stderr)
    by_name = Counter((e["cat"], e["name"]) for e in events)
    print(f"{args.events}: {len(events)} event(s), "
          f"{len(problems)} malformed line(s)")
    for (cat, name), n in sorted(by_name.items()):
        print(f"  {cat:<12} {name:<24} {n}")
    steps = [e for e in events
             if e["name"] == "step" and e["ph"] == "X"]
    if steps:
        total = sum(e["dur"] for e in steps)
        print(f"steps: {len(steps)}, total {total:.3f}s, "
              f"mean {total / len(steps) * 1e3:.2f}ms")
    mcmc = [e for e in events if e["name"] == "mcmc_iter"]
    if mcmc:
        acc = sum(1 for e in mcmc if e.get("args", {}).get("accept"))
        print(f"mcmc: {len(mcmc)} proposal(s), {acc} accepted "
              f"({100.0 * acc / len(mcmc):.1f}%)")
    cands = [e for e in events if e["name"] == "xfer_candidate"]
    if cands:
        best = sum(1 for e in cands if e.get("args", {}).get("best"))
        print(f"substitutions: {len(cands)} candidate(s), "
              f"{best} improved the best strategy")
    _summarize_step_profile(args.events, events)
    return 1 if problems else 0


def _summarize_step_profile(events_path: str, events) -> None:
    """Step-observatory section of ``summary``: the capture's headline
    numbers (overlap realization, HBM accuracy), per-collective
    hidden/exposed attribution, and — when the overlay file sits next to
    the event log — the measured-vs-simulated per-op drift."""
    import os

    from .step_profile import MEASURED_CAT, OVERLAY_FILE

    sp = next((e for e in events
               if e["name"] == "step_profile" and e["cat"] == MEASURED_CAT),
              None)
    if sp is None:
        return
    a = sp.get("args", {})
    print("step observatory (obs.capture_step_profile):")
    rr = a.get("realized_ratio")
    print(f"  mode {a.get('mode')}/{a.get('backend')}, "
          f"fused step {float(a.get('step_wall_s', 0)) * 1e3:.3f} ms "
          f"(serial {float(a.get('serial_step_wall_s', 0)) * 1e3:.3f} ms)")
    if rr is not None:
        print(f"  overlap realization: {float(rr):.2f} measured vs "
              f"{float(a.get('assumed_efficiency', 1.0)):.2f} assumed "
              f"(hidden {float(a.get('hidden_sync_s', 0)) * 1e3:.3f} of "
              f"{float(a.get('total_sync_s', 0)) * 1e3:.3f} ms sync)")
    acc = a.get("hbm_static_accuracy")
    if acc is not None:
        print(f"  HBM: measured peak {int(a.get('hbm_peak_bytes', 0))} B "
              f"({a.get('hbm_source')}), static accuracy {float(acc):.2f}")
    syncs = [e for e in events
             if e["cat"] == MEASURED_CAT and e["ph"] == "X"
             and e["name"].endswith(".grad_sync")]
    for e in syncs:
        sa = e.get("args", {})
        print(f"  {e['name']:<34} {sa.get('collective', '?'):<28} "
              f"hidden {float(sa.get('hidden_s', 0)) * 1e3:>8.3f} ms  "
              f"exposed {float(sa.get('exposed_s', 0)) * 1e3:>8.3f} ms")
    overlay = os.path.join(os.path.dirname(os.path.abspath(events_path)),
                           OVERLAY_FILE)
    if not os.path.exists(overlay):
        return
    with open(overlay) as f:
        tr = json.load(f).get("traceEvents", [])
    pid_names = {e["pid"]: e["args"]["name"] for e in tr
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    sim: dict = {}
    meas: dict = {}
    for e in tr:
        if e.get("ph") != "X":
            continue
        group = pid_names.get(e.get("pid"))
        name = e["name"].removesuffix(".bwd")
        if name.endswith(".grad_sync"):
            continue
        bucket = sim if group == "simulated" else (
            meas if group == "measured" else None)
        if bucket is not None:
            # dur is µs in the overlay; one span per device — keep max
            bucket[name] = max(bucket.get(name, 0.0), e.get("dur", 0.0))
    both = sorted(set(sim) & set(meas),
                  key=lambda n: abs(meas[n] - sim[n]), reverse=True)
    if both:
        print(f"  measured-vs-simulated drift ({OVERLAY_FILE}, worst 5):")
        print(f"    {'op':<28} {'sim ms':>9} {'meas ms':>9} {'drift':>7}")
        for n in both[:5]:
            s, m = sim[n] / 1e3, meas[n] / 1e3
            drift = (m / s) if s > 0 else float("inf")
            print(f"    {n[:28]:<28} {s:>9.4f} {m:>9.4f} {drift:>6.2f}x")


def _cmd_prom(args) -> int:
    from .metrics import MetricsRegistry

    reg = MetricsRegistry()
    with open(args.metrics) as f:
        records = [json.loads(line) for line in f if line.strip()]
    # keep only the newest snapshot per (name, labels)
    latest = {}
    for r in records:
        latest[(r["name"], tuple(sorted(r["labels"].items())))] = r
    for r in latest.values():
        labels = dict(r["labels"])
        if r["kind"] == "counter":
            reg.counter(r["name"], **labels).inc(r["value"])
        elif r["kind"] == "gauge":
            reg.gauge(r["name"], **labels).set(r["value"])
        else:  # histogram snapshots only carry aggregates; re-emit sum
            h = reg.histogram(r["name"], **labels)
            h.sum, h.count = r.get("sum", 0.0), r.get("count", 0)
    text = reg.to_prometheus()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {args.output}")
    else:
        print(text, end="")
    return 0


def _cmd_requests(args) -> int:
    from .request_trace import REQUEST_CAT

    events, problems = read_events_jsonl(args.events)
    for p in problems:
        print(f"warning: {p}", file=sys.stderr)
    lanes = {tid: name for (cat, name), tid
             in lanes_from_events(events).items() if cat == REQUEST_CAT}
    reqs: dict = {}
    for e in events:
        if e.get("cat") != REQUEST_CAT:
            continue
        rid = e.get("args", {}).get("request")
        if rid is None:
            continue  # lane metadata etc.
        reqs.setdefault(rid, []).append(e)
    if not reqs:
        print(f"{args.events}: no request events (cat={REQUEST_CAT!r}); "
              "was the session started with request_sample_rate > 0?")
        return 1
    rows = []
    shed_causes: Counter = Counter()
    requeues = 0
    for rid, evs in reqs.items():
        stages = {"queue": 0.0, "prefill": 0.0, "decode": 0.0}
        replicas = set()
        sheds = []
        gens = []
        tokens = None
        done = False
        for e in evs:
            name, a = e["name"], e.get("args", {})
            if e["ph"] == "X" and name in stages:
                stages[name] += float(e.get("dur", 0.0))
            if name == "shed":
                sheds.append((a.get("reason"), a.get("stage")))
                shed_causes[a.get("reason")] += 1
            elif name == "requeue":
                gens.append(a.get("generation"))
            elif name == "complete":
                done = True
                tokens = a.get("tokens")
            tid = int(e.get("tid", 0))
            if tid in lanes and lanes[tid] != "admission":
                replicas.add(lanes[tid])
        requeues += len(gens)
        ts = [float(e["ts"]) for e in evs]
        spans = [float(e["ts"]) + float(e.get("dur", 0.0)) for e in evs]
        rows.append({
            "request": rid, "total_s": max(spans) - min(ts),
            "stages": stages, "replicas": sorted(replicas),
            "sheds": sheds, "requeue_generations": gens,
            "completed": done, "tokens": tokens,
        })
    rows.sort(key=lambda r: r["total_s"], reverse=True)
    n_done = sum(1 for r in rows if r["completed"])
    print(f"{args.events}: {len(rows)} traced request(s), "
          f"{n_done} completed, {requeues} requeue(s), "
          f"{sum(shed_causes.values())} shed(s)")
    if shed_causes:
        print("  shed causes: " + ", ".join(
            f"{k}={v}" for k, v in shed_causes.most_common()))
    k = max(1, args.slowest)
    print(f"slowest {min(k, len(rows))} (stage seconds):")
    print(f"  {'request':<14} {'total':>8} {'queue':>8} {'prefill':>8} "
          f"{'decode':>8}  outcome")
    for r in rows[:k]:
        st = r["stages"]
        if r["completed"]:
            outcome = f"completed tokens={r['tokens']}"
        elif r["sheds"]:
            reason, stage = r["sheds"][-1]
            outcome = f"shed {reason}@{stage}"
        else:
            outcome = "in flight"
        if r["requeue_generations"]:
            outcome += (f" (requeued x{len(r['requeue_generations'])}"
                        f" gen={r['requeue_generations']})")
        if r["replicas"]:
            outcome += " on " + ",".join(r["replicas"])
        print(f"  {r['request'][:14]:<14} {r['total_s']:>8.4f} "
              f"{st['queue']:>8.4f} {st['prefill']:>8.4f} "
              f"{st['decode']:>8.4f}  {outcome}")
    return 0


def _cmd_calibrate(args) -> int:
    from .calibration import DEFAULT_MAX_AGE_S, CalibrationStore

    if args.action == "inspect":
        store = CalibrationStore(args.store)
        s = store.summary()
        print(json.dumps(s, indent=2, sort_keys=True, default=str))
        bad = store.problems(max_age_s=args.max_age_h * 3600.0
                             if args.max_age_h else DEFAULT_MAX_AGE_S)
        if bad:
            print("unusable for THIS process:", file=sys.stderr)
            for b in bad:
                print(f"  - {b}", file=sys.stderr)
            return 1
        print("usable: fingerprint/backend match, entries fresh")
        return 0
    if args.action == "prune":
        store = CalibrationStore(args.store)
        if args.max_age_h is None:
            print("prune: --max-age-h is required", file=sys.stderr)
            return 2
        n = store.prune(args.max_age_h * 3600.0)
        if n:
            store.save()
        print(f"pruned {n} entr{'y' if n == 1 else 'ies'}; "
              f"{len(store.ops)} remain")
        return 0
    # diff
    a, b = CalibrationStore(args.store), CalibrationStore(args.other)
    delta = a.diff(b)
    if not delta:
        print("stores agree on every shared key")
        return 0
    for d in delta:
        if d["status"] == "changed":
            print(f"  ~ {d['op_type']:<22} x{d['ratio']:.3f} "
                  f"({d['total_s_a'] * 1e3:.4f} -> "
                  f"{d['total_s_b'] * 1e3:.4f} ms)  {d['key'][:60]}")
        else:
            side = "a only" if d["status"] == "only_in_a" else "b only"
            print(f"  {side:>8}: {d['op_type']:<22} {d['key'][:60]}")
    print(f"{len(delta)} difference(s)")
    return 0


def _cmd_explain(args) -> int:
    from .. import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from ..models.transformer import build_transformer
    from .explain import explain_strategy

    cfg = FFConfig()
    cfg.batch_size = args.batch
    cfg.allow_mixed_precision = args.bf16
    model = FFModel(cfg)
    build_transformer(
        model, batch_size=args.batch, seq_length=args.seq,
        hidden_size=args.hidden, num_heads=args.heads,
        num_layers=args.layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    prof = None
    if args.in_situ:
        import numpy as np

        from .step_profile import capture_step_profile

        rng = np.random.RandomState(0)
        in_pt = model.executor.input_pts[0]
        x = rng.rand(*in_pt.material_shape()).astype(np.float32)
        y = rng.rand(*in_pt.material_shape()).astype(np.float32)
        prof = capture_step_profile(model, x, y, batch_size=args.batch)
        print(f"in-situ capture: mode={prof.mode}, "
              f"fused step {prof.step_wall_s * 1e3:.3f} ms, "
              f"realized overlap {prof.realized_ratio}")
    exp = explain_strategy(model, repeats=args.repeats, step_profile=prof)
    print(exp.summary(args.top))
    print(f"kernel worklist (top {args.top} by |simulated - measured|):")
    for w in exp.worklist(args.top):
        verdict = ("cost model optimistic — fuse/speed up this kernel"
                   if w["ratio"] > 1.0 else
                   "cost model pessimistic — recalibrate this class")
        print(f"  #{w['rank']} {w['name']} [{w['op_type']}] "
              f"meas {w['meas_total_s'] * 1e3:.4f} ms vs "
              f"sim {w['sim_total_s'] * 1e3:.4f} ms "
              f"(x{w['ratio']:.2f}) — {verdict}")
    return 0


def _cmd_bench(args) -> int:
    from .step_profile import bench_regression_attribution, load_bench_history

    history = load_bench_history(args.src)
    if not history:
        print(f"bench: no BENCH_r*.json artifacts under {args.src}")
        return 1
    print(f"{len(history)} bench round(s) under {args.src}:")
    print(f"  {'round':>5} {'value':>10} {'unit':<15} {'chips':>5} "
          f"{'backend':<8} {'fwd ms':>8} {'bwd ms':>8} {'opt ms':>8} "
          f"{'sync ms':>8}")
    for r in history:
        ph = r.get("phases") or {}

        def ms(k, _ph=ph):
            v = _ph.get(k)
            return f"{v * 1e3:>8.3f}" if isinstance(v, (int, float)) \
                else f"{'-':>8}"

        print(f"  {r['round'] if r['round'] is not None else '?':>5} "
              f"{r['value'] if r['value'] is not None else '-':>10} "
              f"{(r['unit'] or '-')[:15]:<15} "
              f"{r['n_chips'] if r['n_chips'] is not None else '-':>5} "
              f"{(r['backend'] or '-')[:8]:<8} "
              f"{ms('fwd')} {ms('bwd')} {ms('opt')} {ms('sync')}")
    att = bench_regression_attribution(history, tolerance=args.tolerance)
    if att.get("status") != "ok":
        print(f"attribution: {att.get('status')} "
              f"({att.get('rounds', 0)} usable round(s))")
        return 0
    print(f"newest r{att['cur_round']:02d} vs r{att['prev_round']:02d}: "
          f"{att['cur_value']:.3f} vs {att['prev_value']:.3f} "
          f"(ratio {att['throughput_ratio']:.3f}"
          + (", REGRESSED" if att["regressed"] else "") + ")")
    if att.get("phases"):
        for ph, d in att["phases"].items():
            share = d.get("share_of_regression", 0.0)
            print(f"  {ph:<5} {d['prev_s'] * 1e3:>8.3f} -> "
                  f"{d['cur_s'] * 1e3:>8.3f} ms "
                  f"({d['delta_s'] * 1e3:+.3f}; "
                  f"{share:.0%} of the regression)")
        if att.get("dominant_phase"):
            print(f"  dominant phase: {att['dominant_phase']} "
                  f"(step {att['step_delta_s'] * 1e3:+.3f} ms)")
    return 1 if att["regressed"] and args.strict else 0


def _fleet_domains(args):
    if not getattr(args, "domains", None):
        return None
    import json as _json

    from ..runtime.fault_domains import FaultDomainMap

    with open(args.domains) as f:
        return FaultDomainMap.from_json(_json.load(f))


def _cmd_fleet(args) -> int:
    import time as _time

    from .fleet import FleetAggregator

    agg = FleetAggregator(args.spool_dir, staleness_s=args.staleness,
                          death_s=args.death,
                          fault_domains=_fleet_domains(args))
    while True:
        view = agg.aggregate()
        if args.prom:
            with open(args.prom, "w") as f:
                f.write(view.to_prometheus())
        if args.watch:
            print("\033[2J\033[H", end="")
        print(view.table())
        corrupt = [r for r in view.records if r.error]
        for r in corrupt:
            print(f"CORRUPT {r.process}: {r.error}")
        if not args.watch:
            return 1 if corrupt else 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _cmd_forensics(args) -> int:
    import json as _json
    import os as _os

    from . import flight_recorder as fr

    entries, problems = fr.read_index(args.dir)
    if args.validate:
        entries, problems = fr.validate_dir(args.dir)
        for msg in problems:
            print(f"PROBLEM: {msg}")
        print(f"{len(entries)} bundle(s) indexed, "
              f"{len(problems)} problem(s)")
        return 1 if problems else 0
    if args.show:
        if not entries:
            print("no forensics bundles indexed")
            return 1
        if args.show == "latest":
            rec = entries[-1]
        else:
            hits = [e for e in entries if e.get("file") == args.show
                    or args.show in (e.get("file") or "")]
            if not hits:
                print(f"no bundle matches {args.show!r}")
                return 1
            rec = hits[-1]
        payload = fr.read_bundle(_os.path.join(rec["_dir"], rec["file"]))
        if args.json:
            print(_json.dumps(payload, indent=2, sort_keys=True))
            return 0
        err = payload.get("error") or {}
        print(f"bundle:  {rec['file']}")
        print(f"process: {payload.get('process')} "
              f"(pid {payload.get('pid')})")
        print(f"reason:  {payload.get('reason')}"
              + (f" — {err.get('type')}: {err.get('message')}" if err
                 else ""))
        events = payload.get("events") or []
        print(f"events:  {len(events)} in ring"
              + (f"; tail: " + ", ".join(
                  str(e.get("name")) for e in events[-8:]) if events
                 else ""))
        metrics = payload.get("metrics") or {}
        for series in sorted(metrics):
            pts = metrics[series]
            vals = [v for _, v in pts[-5:]]
            print(f"metric:  {series} ({len(pts)} samples; recent "
                  + ", ".join(f"{v:.4g}" for v in vals) + ")")
        for name in sorted(payload.get("state") or {}):
            print(f"state:   {name}")
        if payload.get("extra"):
            blob = _json.dumps(payload["extra"], sort_keys=True)
            print(f"extra:   {blob[:300]}")
        return 0
    for rec in entries:
        print(f"{rec.get('unixtime', 0):.3f} {rec.get('process', '?'):<16} "
              f"{rec.get('reason', '?'):<24} {rec.get('file')}")
    for msg in problems:
        print(f"PROBLEM: {msg}")
    if not entries and not problems:
        print("no forensics bundles indexed")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.obs",
        description=__doc__.split("\n\n")[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("trace", help="events.jsonl -> Chrome/Perfetto trace")
    t.add_argument("events")
    t.add_argument("-o", "--output")
    s = sub.add_parser("summary", help="validate + summarize an event log")
    s.add_argument("events")
    m = sub.add_parser("prom", help="metrics.jsonl -> Prometheus text")
    m.add_argument("metrics")
    m.add_argument("-o", "--output")
    r = sub.add_parser(
        "requests",
        help="per-request stage breakdown + slowest/shed/requeue report "
             "from the serving flight recorder's events",
    )
    r.add_argument("events")
    r.add_argument("--slowest", type=int, default=10,
                   help="how many slowest requests to detail")
    c = sub.add_parser(
        "calibrate",
        help="inspect/prune/diff a persistent cost-model calibration "
             "store (obs/calibration.py)",
    )
    c.add_argument("action", choices=("inspect", "prune", "diff"))
    c.add_argument("store", help="calibration store JSON path")
    c.add_argument("other", nargs="?",
                   help="second store (diff only)")
    c.add_argument("--max-age-h", type=float, default=None,
                   help="staleness horizon in hours (inspect verdict / "
                        "prune cutoff)")
    e = sub.add_parser(
        "explain",
        help="print the miscalibrated-op kernel worklist for the "
             "benchmark Transformer on this host's device",
    )
    e.add_argument("--top", type=int, default=3)
    e.add_argument("--batch", type=int, default=2)
    e.add_argument("--seq", type=int, default=64)
    e.add_argument("--hidden", type=int, default=128)
    e.add_argument("--heads", type=int, default=4)
    e.add_argument("--layers", type=int, default=2)
    e.add_argument("--repeats", type=int, default=1)
    e.add_argument("--bf16", action="store_true")
    e.add_argument("--in-situ", action="store_true",
                   help="also capture a step profile of the fused jitted "
                        "step and join its per-op seconds into the rows")
    b = sub.add_parser(
        "bench",
        help="BENCH_r*.json round trajectory + newest-round regression "
             "attribution per phase (fwd/bwd/opt/sync)",
    )
    b.add_argument("--src", default=".",
                   help="directory holding BENCH_r*.json (default: .)")
    b.add_argument("--tolerance", type=float, default=0.05,
                   help="fractional throughput drop that counts as a "
                        "regression (default 0.05)")
    b.add_argument("--strict", action="store_true",
                   help="exit 1 when the newest round regressed")
    fl = sub.add_parser(
        "fleet",
        help="aggregate a fleet spool directory (obs/fleet.py): live "
             "table, merged ff_fleet_* Prometheus page, staleness "
             "classification",
    )
    fl.add_argument("spool_dir")
    fl.add_argument("--prom", help="write the merged Prometheus page here")
    fl.add_argument("--watch", action="store_true",
                    help="refresh the table until interrupted")
    fl.add_argument("--interval", type=float, default=2.0)
    fl.add_argument("--staleness", type=float, default=10.0,
                    help="spool age (s) after which a process is stale")
    fl.add_argument("--death", type=float, default=30.0,
                    help="spool age (s) after which a process is dead")
    fl.add_argument("--domains",
                    help="FaultDomainMap JSON (to_json) mapping spool "
                         "process names to slices")
    fo = sub.add_parser(
        "forensics",
        help="inspect flight-recorder forensics bundles "
             "(obs/flight_recorder.py): list the index, --show one "
             "bundle, --validate everything",
    )
    fo.add_argument("dir",
                    help="forensics dir (or the telemetry dir holding "
                         "one)")
    fo.add_argument("--show",
                    help="bundle file name (or 'latest') to detail")
    fo.add_argument("--json", action="store_true",
                    help="with --show: dump the raw payload JSON")
    fo.add_argument("--validate", action="store_true",
                    help="integrity-check every indexed bundle; exit 1 "
                         "on any problem")
    args = p.parse_args(argv)
    if args.cmd == "calibrate" and args.action == "diff" \
            and not args.other:
        p.error("calibrate diff needs two store paths")
    return {"trace": _cmd_trace, "summary": _cmd_summary,
            "prom": _cmd_prom, "requests": _cmd_requests,
            "calibrate": _cmd_calibrate, "explain": _cmd_explain,
            "bench": _cmd_bench, "fleet": _cmd_fleet,
            "forensics": _cmd_forensics}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
