"""PyTorch-FX frontend: import a torch.nn.Module into FFModel.

TPU-native equivalent of reference python/flexflow/torch/model.py (2607 LoC):
`PyTorchModel(torch_module).torch_to_ff(ffmodel, input_tensors)` traces the
module with torch.fx.symbolic_trace (model.py:2427 _trace_model) and maps
each fx node onto FFModel ops (per-node `to_ff`, model.py:2496). Weights are
transferred from the torch module so imported models start from the same
parameters (the reference does this via set_tensor after compile; we stage
them and FFModel applies at compile).

File format (reference: torch_to_flexflow export + PyTorchModel.file_to_ff
import, model.py:2540): `torch_to_flexflow(module, path)` serializes the
traced graph as JSON-lines — one record per fx node, with module configs
extracted so replay needs no torch — and `PyTorchModel.file_to_ff(path,
ffmodel, input_tensors)` rebuilds the FFModel ops from the file. Both paths
share one builder table (`_MODULE_BUILDERS`), so live trace and file replay
cannot drift apart.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ...ff_types import ActiMode, AggrMode, DataType, OperatorType, PoolType

try:
    import torch
    import torch.fx

    HAS_TORCH = True
except Exception:  # pragma: no cover
    HAS_TORCH = False


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


# ---------------------------------------------------------------------------
# Module specs: one entry per supported nn.Module type.
#   export(mod)             -> JSON-serializable config dict
#   build(ff, cfg, args, name) -> output Tensor(s)
#   weights(mod)            -> [np arrays] in our layout, or None
# ---------------------------------------------------------------------------

def _linear_export(mod):
    return {"out_features": mod.out_features, "bias": mod.bias is not None}


def _linear_build(ff, cfg, args, name):
    return ff.dense(args[0], cfg["out_features"], use_bias=cfg["bias"], name=name)


def _linear_weights(mod):
    w = [mod.weight.detach().numpy().T]  # torch (out,in) -> ours (in,out)
    if mod.bias is not None:
        w.append(mod.bias.detach().numpy())
    return w


def _conv2d_export(mod):
    return {
        "out_channels": mod.out_channels,
        "kernel": list(_pair(mod.kernel_size)),
        "stride": list(_pair(mod.stride)),
        "padding": list(_pair(mod.padding)),
        "groups": mod.groups,
        "bias": mod.bias is not None,
    }


def _conv2d_build(ff, cfg, args, name):
    k, s, p = cfg["kernel"], cfg["stride"], cfg["padding"]
    return ff.conv2d(
        args[0], cfg["out_channels"], k[0], k[1], s[0], s[1], p[0], p[1],
        groups=cfg["groups"], use_bias=cfg["bias"], name=name,
    )


def _conv2d_weights(mod):
    w = [mod.weight.detach().numpy()]
    if mod.bias is not None:
        w.append(mod.bias.detach().numpy())
    return w


def _pool_export(mod):
    k = _pair(mod.kernel_size)
    s = _pair(mod.stride) if mod.stride is not None else k
    return {"kernel": list(k), "stride": list(s),
            "padding": list(_pair(mod.padding))}


def _maxpool_build(ff, cfg, args, name):
    k, s, p = cfg["kernel"], cfg["stride"], cfg["padding"]
    return ff.pool2d(args[0], k[0], k[1], s[0], s[1], p[0], p[1],
                     PoolType.POOL_MAX, name=name)


def _avgpool_build(ff, cfg, args, name):
    k, s, p = cfg["kernel"], cfg["stride"], cfg["padding"]
    return ff.pool2d(args[0], k[0], k[1], s[0], s[1], p[0], p[1],
                     PoolType.POOL_AVG, name=name)


def _adaptive_export(mod):
    return {"output_size": list(_pair(mod.output_size))}


def _adaptive_build(ff, cfg, args, name):
    x = args[0]
    h, w = x.dims[2], x.dims[3]
    osz = tuple(cfg["output_size"])
    if osz == (1, 1):
        return ff.pool2d(x, h, w, 1, 1, 0, 0, PoolType.POOL_AVG, name=name)
    assert (h, w) == osz, "unsupported AdaptiveAvgPool2d size"
    return x


def _bn_export(mod):
    return {}


def _bn_build(ff, cfg, args, name):
    return ff.batch_norm(args[0], relu=False, name=name)


def _bn_weights(mod):
    if mod.weight is None:  # BatchNorm2d(affine=False)
        return None
    return [mod.weight.detach().numpy(), mod.bias.detach().numpy()]


def _ln_export(mod):
    return {"normalized_shape": list(mod.normalized_shape), "eps": mod.eps,
            "affine": mod.elementwise_affine}


def _ln_build(ff, cfg, args, name):
    return ff.layer_norm(
        args[0], axes=tuple(range(-len(cfg["normalized_shape"]), 0)),
        eps=cfg["eps"], name=name,
    )


def _ln_weights(mod):
    if not mod.elementwise_affine:
        return None
    return [mod.weight.detach().numpy(), mod.bias.detach().numpy()]


def _emb_export(mod):
    return {"num": mod.num_embeddings, "dim": mod.embedding_dim}


def _emb_build(ff, cfg, args, name):
    return ff.embedding(args[0], cfg["num"], cfg["dim"],
                        AggrMode.AGGR_MODE_NONE, name=name)


def _emb_weights(mod):
    return [mod.weight.detach().numpy()]


def _act_build(method):
    def build(ff, cfg, args, name):
        return getattr(ff, method)(args[0], name=name)

    return build


def _softmax_export(mod):
    return {"dim": mod.dim if mod.dim is not None else -1}


def _softmax_build(ff, cfg, args, name):
    return ff.softmax(args[0], axis=cfg["dim"], name=name)


def _dropout_export(mod):
    return {"p": mod.p}


def _dropout_build(ff, cfg, args, name):
    return ff.dropout(args[0], cfg["p"], name=name)


def _mha_export(mod):
    return {"embed_dim": mod.embed_dim, "num_heads": mod.num_heads,
            "dropout": mod.dropout, "bias": mod.in_proj_bias is not None}


def _mha_build(ff, cfg, args, name):
    return ff.multihead_attention(
        args[0], args[1], args[2], cfg["embed_dim"], cfg["num_heads"],
        dropout=cfg["dropout"], bias=cfg["bias"], name=name,
    )


def _none_export(mod):
    return {}


# type name -> (export, build, weights|None)
_MODULE_BUILDERS = {
    "Linear": (_linear_export, _linear_build, _linear_weights),
    "Conv2d": (_conv2d_export, _conv2d_build, _conv2d_weights),
    "MaxPool2d": (_pool_export, _maxpool_build, None),
    "AvgPool2d": (_pool_export, _avgpool_build, None),
    "AdaptiveAvgPool2d": (_adaptive_export, _adaptive_build, None),
    "BatchNorm2d": (_bn_export, _bn_build, _bn_weights),
    "LayerNorm": (_ln_export, _ln_build, _ln_weights),
    "Embedding": (_emb_export, _emb_build, _emb_weights),
    "ReLU": (_none_export, _act_build("relu"), None),
    "GELU": (_none_export, _act_build("gelu"), None),
    "Sigmoid": (_none_export, _act_build("sigmoid"), None),
    "Tanh": (_none_export, _act_build("tanh"), None),
    "ELU": (_none_export, _act_build("elu"), None),
    "Identity": (_none_export, _act_build("identity"), None),
    "Flatten": (_none_export, lambda ff, c, a, n: ff.flat(a[0], name=n), None),
    "Softmax": (_softmax_export, _softmax_build, None),
    "Dropout": (_dropout_export, _dropout_build, None),
    "MultiheadAttention": (_mha_export, _mha_build, None),
}


class PyTorchModel:
    """reference: torch/model.py:2408 PyTorchModel"""

    def __init__(self, module, is_hf_model: bool = False, input_names=None,
                 batch_size: int = 1, seq_length=None):
        # A path string means a `torch_to_flexflow` export to replay
        # (bootcamp_demo/ff_alexnet_cifar10.py: PyTorchModel("alexnet.ff"));
        # replay needs no live torch module, so torch is optional there.
        self._file = module if isinstance(module, str) else None
        assert self._file is not None or HAS_TORCH, "torch is not available"
        self.module = module
        self.is_hf_model = is_hf_model
        self.input_names = input_names
        self.batch_size = batch_size
        self.seq_length = seq_length
        self._weight_loads = []  # (ff_layer, [np arrays]) applied post-compile

    def _trace(self):
        """reference: model.py:2427 _trace_model (HF variant uses
        transformers.utils.fx with input_names/batch/seq; plain variant
        torch.fx)."""
        if self.is_hf_model:
            from transformers.utils import fx as hf_fx

            kw = {"input_names": self.input_names}
            if self.seq_length is not None:
                kw["sequence_length"] = self.seq_length
            try:
                return hf_fx.symbolic_trace(self.module, **kw)
            except TypeError:  # older/newer hf signatures
                return hf_fx.symbolic_trace(self.module,
                                            input_names=self.input_names)
        return torch.fx.symbolic_trace(self.module)

    def apply(self, ffmodel, input_tensors: List) -> List:
        """Uniform entry point matching ONNXModel.apply (onnx/model.py:287):
        replays a .ff file when constructed from a path, traces live
        otherwise."""
        if self._file is not None:
            return PyTorchModel.file_to_ff(self._file, ffmodel, input_tensors)
        return self.torch_to_ff(ffmodel, input_tensors)

    # ------------------------------------------------------------------
    def torch_to_ff(self, ffmodel, input_tensors: List) -> List:
        """Map the traced graph onto ffmodel; returns output tensors."""
        assert self._file is None, (
            "constructed from a file — use apply()/file_to_ff()"
        )
        traced = self._trace()
        modules = dict(traced.named_modules())
        env: Dict[str, object] = {}
        inputs = list(input_tensors)
        outputs: List = []

        for node in traced.graph.nodes:
            if node.op != "placeholder" and node.op != "output" and not node.users:
                # dead value (e.g. the discarded attention-weights half of
                # `out, _ = mha(...)`): nothing consumes it, skip
                continue
            if node.op == "placeholder":
                env[node.name] = inputs.pop(0)
            elif node.op == "call_module":
                mod = modules[node.target]
                args = [env[a.name] if isinstance(a, torch.fx.Node) else a
                        for a in node.args]
                env[node.name] = self._module_to_ff(ffmodel, mod, args, node)
            elif node.op == "call_function":
                env[node.name] = self._function_to_ff(ffmodel, node, env)
            elif node.op == "call_method":
                env[node.name] = self._method_to_ff(ffmodel, node, env)
            elif node.op == "get_attr":
                env[node.name] = self._fetch_attr(node.target)
            elif node.op == "output":
                def collect(a):
                    if isinstance(a, torch.fx.Node):
                        v = env[a.name]
                        if _is_ff_tensor(v):
                            outputs.append(v)
                        elif _concrete_np(v) is not None:
                            # concrete output (e.g. a mask that never met
                            # the graph): lift so arity/order match torch
                            outputs.append(_lift(ffmodel, v))
                        # None (unused HF ModelOutput fields) is dropped
                    elif isinstance(a, (tuple, list)):
                        for x in a:
                            collect(x)
                    elif isinstance(a, dict):  # HF ModelOutput dataclasses
                        for x in a.values():
                            collect(x)
                collect(node.args[0])
        self._ffmodel = ffmodel
        return outputs

    def _fetch_attr(self, target: str):
        obj = self.module
        for part in target.split("."):
            obj = getattr(obj, part)
        return obj

    # -- modules ---------------------------------------------------------
    def _module_to_ff(self, ff, mod, args, node):
        tname = type(mod).__name__
        spec = _MODULE_BUILDERS.get(tname)
        if spec is None:
            raise NotImplementedError(f"torch module {tname}")
        if node.kwargs:
            # builders bind positionally; silently dropping kwargs (e.g.
            # MultiheadAttention's key_padding_mask) would lose semantics —
            # same loud failure as the file-export path
            raise NotImplementedError(
                f"module {tname} called with kwargs {sorted(node.kwargs)}"
            )
        # concrete tensor args (e.g. Embedding over eagerly-computed
        # relative-position buckets) enter the graph as baked constants
        args = [
            _lift(ff, a) if _concrete_np(a) is not None else a for a in args
        ]
        export, build, weights = spec
        out = build(ff, export(mod), args, node.name)
        if weights is not None:
            w = weights(mod)
            if w is not None:
                self._weight_loads.append((ff.layers[-1], w))
        return out

    # -- functions -------------------------------------------------------
    @staticmethod
    def _resolve(node, env):
        """Map fx Nodes to runtime values through nested args (tuples,
        lists, dicts, AND slice bounds — fx puts Nodes inside slices)."""
        args = torch.fx.node.map_arg(node.args, lambda n: env[n.name])
        kwargs = torch.fx.node.map_arg(node.kwargs, lambda n: env[n.name])
        return list(args), dict(kwargs)

    def _function_to_ff(self, ff, node, env):
        args, kwargs = self._resolve(node, env)
        if not _any_ff(args) and not _any_ff(kwargs):
            # fully concrete (mask/position arithmetic): evaluate eagerly
            # with the real torch function — exact semantics for free
            return node.target(*args, **kwargs)
        return _replay_fn(ff, _fn_name(node.target), args, kwargs)

    def _method_to_ff(self, ff, node, env):
        args, kwargs = self._resolve(node, env)
        if not _any_ff(args) and not _any_ff(kwargs):
            return getattr(args[0], node.target)(*args[1:], **kwargs)
        return _replay_fn(ff, node.target, args, kwargs)

    # ------------------------------------------------------------------
    def load_weights(self, ffmodel=None):
        """Copy the torch module's parameters into the compiled model
        (reference: torch weight transfer via set_tensor)."""
        for layer, arrays in self._weight_loads:
            for wt, arr in zip(layer.weights, arrays):
                wt.set_tensor(self._ffmodel, arr)

    # -- file-format import (reference: model.py:2540 file_to_ff) -------
    @staticmethod
    def file_to_ff(filename: str, ffmodel, input_tensors: List) -> List:
        """Rebuild FFModel ops from a `torch_to_flexflow` export. Works
        without torch installed (the file carries extracted configs)."""
        env: Dict[str, object] = {}
        inputs = list(input_tensors)
        outputs: List = []

        def val(a):
            if isinstance(a, dict) and "ref" in a:
                return env[a["ref"]]
            if isinstance(a, list):
                return [val(x) for x in a]
            return a

        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind, name = rec["op"], rec["name"]
                if kind == "placeholder":
                    env[name] = inputs.pop(0)
                elif kind == "call_module":
                    spec = _MODULE_BUILDERS.get(rec["module_type"])
                    if spec is None:
                        raise NotImplementedError(
                            f"module {rec['module_type']} in {filename}"
                        )
                    _, build, _ = spec
                    args = [val(a) for a in rec["args"]]
                    env[name] = build(ffmodel, rec["config"], args, name)
                elif kind in ("call_function", "call_method"):
                    env[name] = _replay_fn(
                        ffmodel, rec["target"], [val(a) for a in rec["args"]],
                        rec.get("kwargs", {}),
                    )
                elif kind == "output":
                    for a in rec["args"]:
                        outputs.append(val(a))
        return outputs


def _fn_name(fn) -> str:
    """Normalize a live call_function target to its serialized name — the
    same `fn.__name__` torch_to_flexflow writes, so live trace and file
    replay go through the one `_replay_fn` dispatch."""
    return fn if isinstance(fn, str) else fn.__name__


# ---------------------------------------------------------------------------
# hybrid replay: FF graph tensors vs concrete values
# ---------------------------------------------------------------------------
# HF traces (T5/mt5, BERT) interleave real tensor compute with attention-mask
# and relative-position arithmetic on constants. Under static shapes the
# latter is fully concrete at import time, so the replay keeps two value
# kinds: FF Tensors build graph ops; everything else (torch tensors, numpy,
# ints) evaluates eagerly with torch, and is lifted to a baked constant
# tensor only at the point it meets the graph (reference: torch/model.py
# special-cases these nodes per-class; eager evaluation covers them all).


def _is_ff_tensor(v) -> bool:
    return hasattr(v, "guid") and hasattr(v, "dims") and hasattr(v, "data_type")


def _any_ff(v) -> bool:
    if _is_ff_tensor(v):
        return True
    if isinstance(v, (list, tuple)):
        return any(_any_ff(x) for x in v)
    if isinstance(v, dict):
        return any(_any_ff(x) for x in v.values())
    return False


def _concrete_np(v):
    """numpy view of a concrete (non-FF) tensor-like value, else None."""
    if isinstance(v, np.ndarray):
        return v
    if HAS_TORCH and isinstance(v, torch.Tensor):
        return v.detach().cpu().numpy()
    return None


def _lift(ff, v):
    """Bake a concrete array (or scalar) into the graph as a constant."""
    if _is_ff_tensor(v):
        return v
    arr = _concrete_np(v)
    if arr is None and isinstance(v, (bool, int, float, np.number)):
        # python float defaults to f64 — keep constants in the f32/i32
        # world jax runs in (x64 is off)
        dt = np.float32 if isinstance(v, float) else None
        arr = np.asarray(v, dt)
    assert arr is not None, f"cannot lift {type(v)} into the graph"
    if arr.ndim == 0:
        arr = arr.reshape((1,))  # rank-1 broadcasts everywhere; no 0-d PCG
    return ff.create_constant_tensor(arr)


_TORCH_TO_DT = {}
if HAS_TORCH:
    _TORCH_TO_DT = {
        torch.float32: DataType.DT_FLOAT,
        torch.float64: DataType.DT_DOUBLE,
        torch.float16: DataType.DT_HALF,
        torch.bfloat16: DataType.DT_BF16,
        torch.int32: DataType.DT_INT32,
        torch.int64: DataType.DT_INT64,
        torch.bool: DataType.DT_BOOLEAN,
    }


def _as_dt(dtype) -> DataType:
    if isinstance(dtype, DataType):
        return dtype
    if HAS_TORCH and dtype in _TORCH_TO_DT:
        return _TORCH_TO_DT[dtype]
    from ...ff_types import to_data_type

    return to_data_type(dtype)


def _slice_is_identity(x, idx) -> bool:
    """True when x[idx] would return x unchanged (static shapes), e.g. the
    T5 `position_bias[:, :, -seq_len:, :]` no-cache slice."""
    items = idx if isinstance(idx, tuple) else (idx,)
    if sum(1 for it in items if it is Ellipsis) > 1:
        return False
    if any(it is Ellipsis for it in items):
        # expand ... to full slices so positions after it hit TRAILING dims
        pos = items.index(Ellipsis)
        n_missing = len(x.dims) - (len(items) - 1)
        if n_missing < 0:
            return False
        items = (items[:pos] + (slice(None),) * n_missing + items[pos + 1:])
    if len(items) > len(x.dims) or any(not isinstance(it, slice) for it in items):
        return False
    for dim, sl in zip(x.dims, items):
        try:
            bounds = slice(
                None if sl.start is None else int(sl.start),
                None if sl.stop is None else int(sl.stop),
                None if sl.step is None else int(sl.step),
            ).indices(dim)
        except (TypeError, ValueError):
            return False
        if bounds != (0, dim, 1):
            return False
    return True


def _replay_fn(ff, target: str, args, kwargs):
    """The single call_function/call_method dispatch, shared by the live fx
    walk (torch_to_ff) and file replay (file_to_ff). Targets are normalized
    names (`operator.add`/`torch.add` → "add", methods keep their string)."""
    x = args[0] if args else None
    if target in ("add", "sub", "subtract", "mul", "multiply", "truediv",
                  "div", "divide"):
        key = {"subtract": "sub", "multiply": "mul", "divide": "div"}.get(
            target, target
        )
        scalar_ops = {"add": ff.scalar_add, "sub": ff.scalar_sub,
                      "mul": ff.scalar_multiply,
                      "truediv": ff.scalar_true_divide,
                      "div": ff.scalar_true_divide}
        pair_ops = {"add": ff.add, "sub": ff.subtract, "mul": ff.multiply,
                    "truediv": ff.divide, "div": ff.divide}
        a, b = args[0], args[1]
        if _is_scalar(b) and _is_ff_tensor(a):
            return scalar_ops[key](a, float(b))
        if _is_scalar(a) and _is_ff_tensor(b):
            # reversed scalar op: c - t = -t + c; c / t via pow(-1)
            if key == "add":
                return ff.scalar_add(b, float(a))
            if key == "mul":
                return ff.scalar_multiply(b, float(a))
            if key == "sub":
                return ff.scalar_add(ff.scalar_multiply(b, -1.0), float(a))
            return ff.scalar_multiply(ff.pow(b, -1.0), float(a))
        return pair_ops[key](_lift(ff, a), _lift(ff, b))
    if target in ("relu", "gelu", "sigmoid", "tanh", "elu", "exp", "sin",
                  "cos", "rsqrt", "sqrt", "log"):
        return getattr(ff, target)(x)
    if target == "softmax":
        dim = kwargs.get("dim", args[1] if len(args) > 1 else -1)
        return ff.softmax(x, axis=dim if dim is not None else -1)
    if target in ("cat", "concat"):
        dim = kwargs.get("dim", args[1] if len(args) > 1 else 0)
        return ff.concat(list(args[0]), dim)
    if target in ("flatten", "flat"):
        return ff.flat(x)
    if target in ("matmul", "bmm"):
        return ff.batch_matmul(_lift(ff, x), _lift(ff, args[1]))
    if target in ("min", "max") and len(args) > 1:
        op = ff.min if target == "min" else ff.max
        return op(_lift(ff, x), _lift(ff, args[1]))
    if target == "where":
        return ff.where(_lift(ff, args[0]), _lift(ff, args[1]),
                        _lift(ff, args[2]))
    if target == "masked_fill":
        # x[mask] = value ⇒ where(mask, full(value), x); mask is concrete
        # in HF traces (causal / padding masks)
        mask = _concrete_np(args[1])
        assert mask is not None, "masked_fill with a traced mask tensor"
        # keep the mask at its traced (usually broadcastable) shape and the
        # fill at rank-1 — OP_WHERE broadcast-infers, so baking full-size
        # copies per attention layer would only waste HBM
        fill = np.full((1,), float(args[2]), x.data_type.np_dtype)
        return ff.where(ff.create_constant_tensor(mask.astype(np.bool_)),
                        ff.create_constant_tensor(fill), x)
    if target == "neg":
        return ff.scalar_multiply(x, -1.0)
    if target == "abs":
        return ff.max(x, ff.scalar_multiply(x, -1.0, inplace=False))
    if target == "dropout":
        p = kwargs.get("p", args[1] if len(args) > 1 else 0.5)
        training = kwargs.get("training", args[2] if len(args) > 2 else True)
        if not training:  # F.dropout(..., training=False) is a no-op
            return x
        return ff.dropout(x, rate=float(p))
    if target in ("zeros_like", "full_like", "ones_like") and _is_ff_tensor(x):
        fill = {"zeros_like": 0.0, "ones_like": 1.0}.get(
            target, float(args[1]) if len(args) > 1 else 0.0
        )
        # stays concrete: downstream use lifts it back if needed
        return np.full(tuple(x.dims), fill, x.data_type.np_dtype)
    if target in ("to", "type_as", "float", "half", "double", "type"):
        if target == "float":
            return ff.cast(_lift(ff, x), DataType.DT_FLOAT)
        if target == "half":
            return ff.cast(_lift(ff, x), DataType.DT_HALF)
        if target == "double":
            return ff.cast(_lift(ff, x), DataType.DT_DOUBLE)
        other = kwargs.get("dtype", args[1] if len(args) > 1 else None)
        if other is None:
            return x
        if _is_ff_tensor(other):
            return ff.cast(_lift(ff, x), other.data_type)
        c = _concrete_np(other)
        if c is not None:  # type_as(concrete tensor)
            return ff.cast(_lift(ff, x), _as_dt(c.dtype))
        if isinstance(other, str) or (
            HAS_TORCH and isinstance(other, torch.device)
        ):
            return x  # .to(device): placement is XLA's job
        return ff.cast(_lift(ff, x), _as_dt(other))  # loud on unknown dtypes
    if target == "dim":
        return len(x.dims)
    if target == "unsqueeze":
        return ff.unsqueeze(x, [args[1]])
    if target == "squeeze":
        dim = kwargs.get("dim", args[1] if len(args) > 1 else None)
        return ff.squeeze(x, () if dim is None else [dim])
    if target in ("expand", "expand_as", "broadcast_to"):
        # rely on downstream broadcasting (XLA handles it); sizes already
        # compatible by torch semantics
        return x
    if target == "getattr" and _is_ff_tensor(x):
        attr = args[1]
        if attr == "shape":
            return tuple(x.dims)
        if attr == "dtype":
            # as a torch.dtype so both eager torch consumers
            # (mask.to(hidden.dtype)) and the graph-side cast handler
            # (_as_dt) accept it
            for tdt, fdt in _TORCH_TO_DT.items():
                if fdt == x.data_type:
                    return tdt
            return x.data_type
        if attr == "ndim":
            return len(x.dims)
        if attr == "device":
            return "cpu"  # import-time eager ops run on host
        raise NotImplementedError(f"getattr({attr}) on graph tensor")
    if target == "pow":
        return ff.pow(x, float(args[1]))
    if target == "mean":
        dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
        keep = kwargs.get("keepdim", False)
        if dims is None:  # torch.mean(x): global mean over every dim
            dims = list(range(len(x.dims)))
        dims = [dims] if isinstance(dims, int) else list(dims)
        return ff.mean(x, dims, keep)
    if target == "transpose":
        d0, d1 = args[1], args[2]
        perm = list(range(len(x.dims)))
        perm[d0], perm[d1] = perm[d1], perm[d0]
        return ff.transpose(x, perm)
    if target == "permute":
        perm = args[1] if isinstance(args[1], (list, tuple)) else args[1:]
        return ff.transpose(x, list(perm))
    if target in ("view", "reshape"):
        shape = args[1:] if not isinstance(args[1], (list, tuple)) else args[1]
        shape = [-1 if isinstance(s, str) else int(s) for s in shape]
        return ff.reshape(x, shape)
    if target in ("contiguous", "detach", "clone", "identity"):
        return x
    if target == "size":
        return x.dims if len(args) == 1 else x.dims[args[1]]
    if target == "getitem":
        if isinstance(x, (list, tuple)):
            return x[args[1]]
        idx = args[1]
        if _slice_is_identity(x, idx):
            # e.g. T5's position_bias[:, :, -seq_len:, :] with no KV cache
            return x
        if isinstance(idx, tuple) and any(it is None for it in idx):
            # newaxis-only indexing → unsqueeze at the None positions
            if all(it is None or (isinstance(it, slice) and it == slice(None))
                   for it in idx):
                axes = [i for i, it in enumerate(idx) if it is None]
                return ff.unsqueeze(x, axes)
        owner_op = getattr(getattr(x, "owner_layer", None), "op_type", None)
        if idx == 0 and owner_op in (
            OperatorType.OP_MULTIHEAD_ATTENTION, OperatorType.OP_LSTM,
        ):
            # tuple-returning torch ops (MultiheadAttention's
            # (output, weights), LSTM's (output, state)) map to a single
            # output Tensor here; true tensor indexing stays a loud error
            return x
        raise NotImplementedError(f"getitem[{idx}] on single-output op")
    raise NotImplementedError(f"torch call {target}")


def _is_scalar(v) -> bool:
    return isinstance(v, (int, float))


def torch_to_flexflow(module, path: str, batch_size: int = 1) -> str:
    """Serialize a torch module's fx graph to the flexflow file format
    (reference: torch/model.py torch_to_flexflow). JSON-lines, one record
    per fx node; module configs are extracted so `file_to_ff` replays
    without torch."""
    assert HAS_TORCH, "torch is not available"
    traced = torch.fx.symbolic_trace(module)
    modules = dict(traced.named_modules())

    def ser(a):
        if isinstance(a, torch.fx.Node):
            return {"ref": a.name}
        if isinstance(a, (tuple, list)):
            return [ser(x) for x in a]
        if isinstance(a, (int, float, str, bool)) or a is None:
            return a
        raise NotImplementedError(f"cannot serialize arg {a!r}")

    with open(path, "w") as f:
        for node in traced.graph.nodes:
            if node.op != "placeholder" and node.op != "output" and not node.users:
                continue  # dead value, same skip as the live walk
            rec = {"op": node.op, "name": node.name}
            if node.op == "placeholder":
                pass
            elif node.op == "call_module":
                mod = modules[node.target]
                tname = type(mod).__name__
                spec = _MODULE_BUILDERS.get(tname)
                if spec is None:
                    raise NotImplementedError(f"torch module {tname}")
                if node.kwargs:
                    # refuse to write a file that silently loses semantics
                    # (e.g. MultiheadAttention key_padding_mask=...)
                    raise NotImplementedError(
                        f"kwargs on module call {tname}: {sorted(node.kwargs)}"
                    )
                rec["module_type"] = tname
                rec["config"] = spec[0](mod)
                rec["args"] = [ser(a) for a in node.args]
            elif node.op in ("call_function", "call_method"):
                t = node.target
                rec["target"] = t if isinstance(t, str) else t.__name__
                rec["args"] = [ser(a) for a in node.args]
                rec["kwargs"] = {k: ser(v) for k, v in node.kwargs.items()}
            elif node.op == "output":
                flat = []

                def collect(a):
                    if isinstance(a, torch.fx.Node):
                        flat.append({"ref": a.name})
                    elif isinstance(a, (tuple, list)):
                        for x in a:
                            collect(x)

                collect(node.args[0])
                rec["args"] = flat
            elif node.op == "get_attr":  # pragma: no cover
                raise NotImplementedError("get_attr not serializable")
            f.write(json.dumps(rec) + "\n")
    return path


# reference model.py:2607 exposes file_to_ff module-level (usable sans torch)
file_to_ff = PyTorchModel.file_to_ff
