"""RegNet-style grouped-conv network via torch import (reference:
examples/python/pytorch/regnet.py uses torchvision regnet_x; torchvision is
not in this image so the X-block stack is declared inline with the same
structure: stem + stages of grouped-bottleneck blocks)."""
import torch.nn as nn

from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import cifar10
from flexflow.torch.model import PyTorchModel

from _example_args import example_args


class XBlock(nn.Module):
    def __init__(self, cin, cout, group_width=8, stride=1):
        super().__init__()
        groups = max(1, cout // group_width)
        self.conv1 = nn.Conv2d(cin, cout, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, stride=stride, padding=1,
                               groups=groups, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.conv3 = nn.Conv2d(cout, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.down = (
            nn.Conv2d(cin, cout, 1, stride=stride, bias=False)
            if (stride != 1 or cin != cout) else None
        )

    def forward(self, x):
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        skip = self.down(x) if self.down is not None else x
        return self.relu(y + skip)


def regnet(widths=(24, 56, 152), depths=(1, 2, 4), num_classes=10):
    mods = [nn.Conv2d(3, 16, 3, padding=1, bias=False),
            nn.BatchNorm2d(16), nn.ReLU()]
    cin = 16
    for w, d in zip(widths, depths):
        for i in range(d):
            mods.append(XBlock(cin, w, stride=2 if i == 0 else 1))
            cin = w
    mods += [nn.AdaptiveAvgPool2d(1), nn.Flatten(),
             nn.Linear(cin, num_classes), nn.Softmax(dim=-1)]
    return nn.Sequential(*mods)


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    input_tensor = ffmodel.create_tensor(
        [args.batch_size, 3, 32, 32], DataType.DT_FLOAT)

    output_tensors = PyTorchModel(regnet()).torch_to_ff(ffmodel, [input_tensor])

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY])

    (x_train, y_train), _ = cifar10.load_data(n_train=args.num_samples)
    x_train = x_train.transpose(0, 3, 1, 2).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    ffmodel.fit(x=x_train, y=y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("regnet (pytorch import)")
    top_level_task(example_args())
