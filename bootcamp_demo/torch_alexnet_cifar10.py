"""Bootcamp demo, step 1: define AlexNet in plain PyTorch and export it to
a .ff file for FlexFlow-TPU to replay (reference:
bootcamp_demo/torch_alexnet_cifar10.py, which exports via
flexflow.torch.fx.torch_to_flexflow).

Run: python bootcamp_demo/torch_alexnet_cifar10.py  →  writes alexnet.ff
"""
import torch.nn as nn

import flexflow.torch.fx as fx


class AlexNet(nn.Module):
    """torchvision-style AlexNet (same stack the reference script builds)."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 64, kernel_size=11, stride=4, padding=2),
            nn.ReLU(inplace=True),
            nn.MaxPool2d(kernel_size=3, stride=2),
            nn.Conv2d(64, 192, kernel_size=5, padding=2),
            nn.ReLU(inplace=True),
            nn.MaxPool2d(kernel_size=3, stride=2),
            nn.Conv2d(192, 384, kernel_size=3, padding=1),
            nn.ReLU(inplace=True),
            nn.Conv2d(384, 256, kernel_size=3, padding=1),
            nn.ReLU(inplace=True),
            nn.Conv2d(256, 256, kernel_size=3, padding=1),
            nn.ReLU(inplace=True),
            nn.MaxPool2d(kernel_size=3, stride=2),
        )
        self.classifier = nn.Sequential(
            nn.Linear(256 * 6 * 6, 4096),
            nn.ReLU(inplace=True),
            nn.Linear(4096, 4096),
            nn.ReLU(inplace=True),
            nn.Linear(4096, num_classes),
            nn.Softmax(dim=-1),
        )

    def forward(self, x):
        x = self.features(x)
        x = x.flatten(1)
        return self.classifier(x)


if __name__ == "__main__":
    model = AlexNet(num_classes=10)
    fx.torch_to_flexflow(model, "alexnet.ff")
    print("exported alexnet.ff")
