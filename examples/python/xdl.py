"""XDL ads-ranking model — embeddings + MLP
(reference: examples/cpp/XDL/xdl.cc; scripts/osdi22ae/xdl.sh).

Usage: python examples/python/xdl.py -b 64
"""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.misc import build_xdl


def main():
    ffconfig = FFConfig()
    model = FFModel(ffconfig)
    n_sparse = 4
    vocab = 100000
    build_xdl(model, ffconfig.batch_size, embedding_sizes=(vocab,) * n_sparse)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    n = ffconfig.batch_size * 4
    rng = np.random.RandomState(0)
    sparse = [rng.randint(0, vocab, (n, 1)).astype(np.int32) for _ in range(n_sparse)]
    dense = rng.randn(n, 16).astype(np.float32)
    y = rng.randint(0, 2, (n, 1)).astype(np.int32)
    model.fit(sparse + [dense], y, epochs=ffconfig.epochs)


if __name__ == "__main__":
    main()
