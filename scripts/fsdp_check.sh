#!/usr/bin/env bash
# FSDP/ZeRO weight-sharding sweep (ISSUE 7): the WeightShard parallel op
# over 8- and 4-device CPU meshes (docs/fsdp.md). Three legs per device
# count, all inside tests/test_weight_sharding.py:
#
#   * search-under-budget — a model whose replicated strategy statically
#     fails FFA301 compiles after graph_optimize_with_memory chooses
#     weight sharding, with zero FFA errors;
#   * verify — FSDP training matches the replicated/serial reference
#     (op lowering exactness + verify_strategy);
#   * elastic reshard — an 8-way FSDP checkpoint restores as 4-way with
#     the sharded optimizer state preserved bit-exactly (8-device leg
#     only; the 4-device leg covers manual sharding + analysis).
#
# Use before touching parallel/weight_sharding.py, the fsdp_* rewrites,
# the cost model's memory accounting, or the mesh lowering:
#
#   scripts/fsdp_check.sh                 # full sweep (8, 4-device meshes)
#   FF_FSDP_DEVICES=8 scripts/fsdp_check.sh -k memory_lambda
set -euo pipefail
cd "$(dirname "$0")/.."

devices="${FF_FSDP_DEVICES:-8 4}"
for n in $devices; do
    echo "=== fsdp sweep: ${n}-device CPU mesh ==="
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_weight_sharding.py -v \
        -p no:cacheprovider "$@"
done
