"""K.sum reductions (reference: examples/python/keras/reduce_sum.py
test_reduce_sum1/2/3)."""
import numpy as np

import flexflow.keras.models
import flexflow.keras.optimizers
from flexflow.keras.layers import Input, Dense, Reshape
from flexflow.keras import backend as K

from _example_args import example_args


def reduce_one_axis(args):
    in0 = Input(shape=(32,), dtype="float32")
    x0 = Dense(20, activation="relu")(in0)
    nx0 = Reshape((10, 2))(x0)
    out = K.sum(nx0, axis=1)  # B,2
    model = flexflow.keras.models.Model(in0, out)
    model.compile(optimizer=flexflow.keras.optimizers.Adam(learning_rate=0.001),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    model.fit(np.random.randn(n, 32).astype(np.float32),
              np.random.randn(n, 2).astype(np.float32), epochs=args.epochs)


def reduce_two_axes(args):
    in0 = Input(shape=(32,), dtype="float32")
    x0 = Dense(20, activation="relu")(in0)
    nx0 = Reshape((10, 2))(x0)
    out = K.sum(nx0, axis=[1, 2])  # B
    model = flexflow.keras.models.Model(in0, out)
    model.compile(optimizer=flexflow.keras.optimizers.Adam(learning_rate=0.001),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    model.fit(np.random.randn(n, 32).astype(np.float32),
              np.random.randn(n).astype(np.float32), epochs=args.epochs)


def top_level_task(args):
    reduce_one_axis(args)
    reduce_two_axes(args)


if __name__ == "__main__":
    print("K.sum reduce")
    top_level_task(example_args(epochs=2, num_samples=512))
