"""Mixture-of-Experts operator family: Group_by, Aggregate, AggregateSpec,
Cache.

TPU-native equivalents of reference src/ops/group_by.cc (534 LoC + CUDA),
aggregate.cc (569), aggregate_spec.cc (519), cache.cc (291). The reference
routes tokens to per-expert tensors with scatter CUDA kernels; the TPU-native
formulation is the dense dispatch/combine einsum (Mesh-TensorFlow / GShard
style): a one-hot dispatch mask [tokens, experts, capacity] turns routing into
two MXU matmuls, which is both jit-static and shardable over an expert mesh
axis (expert parallelism).

Load balancing: the reference injects a lambda_bal term directly into the
gate gradients in aggregate's hand-written backward (aggregate.cc backward
task). Functionally we expose the same knob as an auxiliary load-balance loss
produced by group_by (ctx-free, differentiable), which jax.grad folds into
the gate weights — same gradient signal, no custom backward.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ..ff_types import DataType, OperatorType
from .registry import register_op


def _capacity(batch_tokens: int, k: int, n: int, alpha: float) -> int:
    """reference: group_by.cc max_size = (int)ceil(alpha * k / n * batch)"""
    return max(1, int(math.ceil(alpha * k / n * batch_tokens)))


def _dispatch_mask(assign: jnp.ndarray, n: int, capacity: int):
    """Build the [b*k, n, capacity] one-hot dispatch mask from assignments.

    Tokens beyond an expert's capacity are dropped, matching the reference's
    fixed-size per-expert buffers (group_by.cc).
    """
    flat = assign.reshape(-1).astype(jnp.int32)  # [b*k]
    onehot = jax.nn.one_hot(flat, n, dtype=jnp.float32)  # [b*k, n]
    pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based rank within expert
    kept = (pos <= capacity).astype(jnp.float32) * onehot
    slot = jax.nn.one_hot((pos - 1.0).astype(jnp.int32), capacity, dtype=jnp.float32)
    return kept[..., None] * slot  # [b*k, n, capacity]


@dataclasses.dataclass(frozen=True)
class GroupByParams:
    """reference: include/flexflow/ops/groupby_params.h"""

    n: int  # number of experts
    alpha: float = 1.0  # capacity factor


def _gb_infer(params: GroupByParams, in_shapes, in_dtypes):
    inp, assign = in_shapes  # [b, d], [b, k]
    b, d = inp[0], inp[-1]
    k = assign[-1]
    cap = _capacity(b, k, params.n, params.alpha)
    return [(cap, d)] * params.n, [in_dtypes[0]] * params.n


def _gb_forward(params: GroupByParams, w, x, ctx):
    inp, assign = x  # [b, d], [b, k]
    b, d = inp.shape[0], inp.shape[-1]
    k = assign.shape[-1]
    cap = _capacity(b, k, params.n, params.alpha)
    mask = _dispatch_mask(assign, params.n, cap)  # [b*k, n, cap]
    rep = jnp.repeat(inp, k, axis=0)  # [b*k, d] token copies per slot
    packed = jnp.einsum("td,tnc->ncd", rep, mask.astype(inp.dtype))
    return [packed[e] for e in range(params.n)]


register_op(
    OperatorType.OP_GROUP_BY, "GroupBy", infer=_gb_infer, forward=_gb_forward,
    num_inputs=2,
)


@dataclasses.dataclass(frozen=True)
class AggregateParams:
    """reference: include/flexflow/ops/aggregate_params.h"""

    n: int
    lambda_bal: float = 0.0


def _agg_infer(params: AggregateParams, in_shapes, in_dtypes):
    # inputs: gate_preds [b,k], gate_assign [b,k], true_gate_assign [b,k],
    # full_gate_grads [b,n], exp_preds x n [cap, d]
    # (reference: aggregate.cc ctor — 4 + n inputs)
    d = in_shapes[4][-1]
    b = in_shapes[0][0]
    return [(b, d)], [in_dtypes[4]]


def _agg_forward(params: AggregateParams, w, x, ctx):
    gate_preds, gate_assign = x[0], x[1]
    exp_preds = x[4:]  # n tensors [cap, d]
    b, k = gate_preds.shape
    n = params.n
    cap = exp_preds[0].shape[0]
    stacked = jnp.stack(exp_preds, axis=0)  # [n, cap, d]
    mask = _dispatch_mask(gate_assign, n, cap)  # [b*k, n, cap]
    combine = mask * gate_preds.reshape(-1)[:, None, None].astype(jnp.float32)
    out_per_slot = jnp.einsum(
        "ncd,tnc->td", stacked.astype(jnp.float32), combine
    )  # [b*k, d]
    out = out_per_slot.reshape(b, k, -1).sum(axis=1)
    # Load-balance loss (reference: aggregate.cc backward folds lambda_bal
    # into gate grads). Switch-Transformer formulation: n * Σ_e f_e · P_e,
    # where f_e = dispatch fraction (stop-grad) and P_e = mean full-gate
    # probability (differentiable through x[3] = full gate activations).
    if params.lambda_bal > 0.0:
        full_gate = x[3].astype(jnp.float32)  # [b, n]
        probs = jax.nn.softmax(full_gate, axis=-1)
        p_mean = probs.mean(axis=0)  # [n]
        f = jax.lax.stop_gradient(mask.sum(axis=(0, 2)) / max(1, b * k))  # [n]
        ctx.add_aux_loss(params.lambda_bal * n * jnp.sum(f * p_mean))
    return [out.astype(exp_preds[0].dtype)]


register_op(
    OperatorType.OP_AGGREGATE, "Aggregate", infer=_agg_infer, forward=_agg_forward,
    num_inputs=-1,
)


@dataclasses.dataclass(frozen=True)
class AggregateSpecParams:
    """reference: include/flexflow/ops/aggregate_spec_params.h — speculative
    aggregation: same combine as Aggregate but each expert prediction is
    scored against replicated labels (model.cc:2875 replicates labels)."""

    n: int
    lambda_bal: float = 0.0


def _aggspec_infer(params: AggregateSpecParams, in_shapes, in_dtypes):
    # inputs: gate_preds [b,k], gate_assign [b,k], exp_preds x n [cap, d]
    d = in_shapes[2][-1]
    b = in_shapes[0][0]
    k = in_shapes[0][1]
    return [(b * k, d)], [in_dtypes[2]]


def _aggspec_forward(params: AggregateSpecParams, w, x, ctx):
    gate_preds, gate_assign = x[0], x[1]
    exp_preds = x[2:]
    b, k = gate_preds.shape
    n = params.n
    cap = exp_preds[0].shape[0]
    stacked = jnp.stack(exp_preds, axis=0)
    mask = _dispatch_mask(gate_assign, n, cap)
    out = jnp.einsum("ncd,tnc->td", stacked.astype(jnp.float32), mask)
    return [out.astype(exp_preds[0].dtype)]


register_op(
    OperatorType.OP_AGG_SPEC, "AggregateSpec", infer=_aggspec_infer,
    forward=_aggspec_forward, num_inputs=-1,
)


@dataclasses.dataclass(frozen=True)
class CacheParams:
    """reference: include/flexflow/ops/cache_params.h — caches an input
    tensor across batches (MoE gating cache: cache.cc keeps num_batches
    snapshots, CACHE_UPDATE_TASK writes the current batch, and a score
    function decides whether the cache is fresh enough to serve).

    Here the cache is a net_state buffer threaded through the train step:
    training passes the live input through AND writes it to the buffer
    (exponential blend over ~num_batches like the reference's rolling
    window); inference serves the CACHED value — the gating-cache
    behavior that lets MoE routing reuse recent statistics."""

    num_batches: int = 1


def _cache_state(params: CacheParams, in_shapes, in_dtypes):
    from .registry import WeightSpec

    # State buffers are DT_FLOAT regardless of the input dtype: the training
    # blend (1-alpha)*cached + alpha*x is float math, and a buffer typed to
    # an integer input would change dtype across the update, breaking the
    # lax.scan carry structure in build_train_scan. Values are cast on
    # write and cast back to the input dtype on serve.
    return [WeightSpec("cached", tuple(in_shapes[0]), DataType.DT_FLOAT,
                       "zero"),
            WeightSpec("filled", (1,), DataType.DT_FLOAT, "zero")]


def _cache_forward_stateful(params: CacheParams, weights, state, inputs, ctx):
    (x,) = inputs
    if not state:
        return [x], {}
    if ctx.training:
        # rolling blend over ~num_batches (reference keeps a window of
        # num_batches snapshots; the exponential average has the same
        # effective horizon without num_batches x memory)
        alpha = 1.0 / max(1, params.num_batches)
        filled = jnp.minimum(state["filled"] + 1.0, 1.0)
        xf = x.astype(state["cached"].dtype)
        cached = jnp.where(
            state["filled"] > 0,
            (1.0 - alpha) * state["cached"] + alpha * xf,
            xf,
        )
        cached = cached.astype(state["cached"].dtype)
        filled = filled.astype(state["filled"].dtype)
        return [x], {"cached": cached, "filled": filled}
    # inference: serve the cache when it has ever been written
    out = jnp.where(state["filled"] > 0, state["cached"].astype(x.dtype), x)
    return [out], state


register_op(
    OperatorType.OP_CACHE,
    "Cache",
    infer=lambda p, s, dt: ([s[0]], [dt[0]]),
    forward=lambda p, w, x, ctx: [x[0]],
    state_spec=_cache_state,
    forward_stateful=_cache_forward_stateful,
)
