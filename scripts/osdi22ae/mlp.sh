#!/usr/bin/env bash
# reference: scripts/osdi22ae/mlp.sh
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

echo "Running MLP with a parallelization strategy discovered by Unity"
run_example mlp_unify.py --budget 20

echo "Running MLP with data parallelism"
run_example mlp_unify.py --budget 20 --only-data-parallel
