"""ResNet-50 training throughput on the real chip (BASELINE.json config:
'ResNet-50 / ImageNet-synthetic ... data+parameter parallel' — here the
single-chip number; multi-chip comes from the mesh)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _harness import run_throughput


def build(model, batch):
    from flexflow_tpu.models.resnet import build_resnet

    build_resnet(model, batch_size=batch, num_classes=1000,
                 height=224, width=224)


if __name__ == "__main__":
    run_throughput(build, metric="resnet50_imagenet_train_throughput",
                   batch=64, label_classes=1000, spd=10)
