"""On-chip ablations of the bench train step: where is the recoverable
time? Each variant patches ONE component out of the compiled step and
reports ms/step, so the delta against `base` bounds what optimizing that
component can buy (methodology mirrors step_breakdown.py; reference
analog: the per-component budget in BASELINE.md).

Variants run in their own process (jit caches + env flags are
per-process).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))


def run(tag: str, *, no_update=False, no_metrics=False, grads_no_update=False,
        bf16_grads=False, spd=25, chunks=3):
    import jax

    from flexflow_tpu import (
        FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )
    from flexflow_tpu.models.transformer import build_transformer

    batch = 8
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.allow_mixed_precision = True
    model = FFModel(cfg)
    build_transformer(model, batch_size=batch)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    ex = model.executor
    if no_update:
        # NOTE: with the grads entirely unconsumed XLA dead-code-eliminates
        # the whole backward pass — this variant measures FORWARD-only
        # (fwd + loss + metrics), not "step minus update".
        class _NoOpt:
            def update(self, params, grads, state):
                return params, state
        ex.optimizer = _NoOpt()
    if grads_no_update:
        # Backward stays alive (full-reduce probe of every grad leaf into
        # the opt_state carry — reductions fuse into the producing kernels)
        # but the param sweep (read p+g, write p) is gone: base minus this
        # bounds what overlapping/fusing the SGD update could buy.
        import jax.numpy as jnp

        class _ProbeOpt:
            def update(self, params, grads, state):
                probe = sum(
                    jnp.sum(leaf.astype(jnp.float32))
                    for leaf in jax.tree_util.tree_leaves(grads)
                )
                return params, {"probe": probe}
        ex.optimizer = _ProbeOpt()
        model.state = model.state.__class__(
            params=model.state.params, opt_state={"probe": jnp.float32(0)},
            step=model.state.step, net_state=model.state.net_state)
    if bf16_grads:
        # SGD reading bf16 grads: the f32->bf16 convert fuses into the
        # grad-producing matmul epilogues (grads hit HBM at half width) and
        # the update reads half the bytes. Bounds the bf16-grad-store win.
        import jax.numpy as jnp

        class _Bf16SGD:
            def update(self, params, grads, state):
                def upd(w, g):
                    return w - 0.01 * g.astype(jnp.bfloat16).astype(w.dtype)
                return jax.tree_util.tree_map(upd, params, grads), state
        ex.optimizer = _Bf16SGD()
    if no_metrics:
        class _NoMetrics:
            def compute(self, logits, labels):
                return {}
        ex.metrics = _NoMetrics()
    in_pt = ex.input_pts[0]
    rng = np.random.RandomState(0)
    x = ex.shard_batch(in_pt, rng.randn(*in_pt.material_shape()).astype(np.float32))
    y = jax.numpy.asarray(rng.randn(*in_pt.material_shape()).astype(np.float32))
    state = model.state
    probe = jax.jit(
        lambda params: sum(
            leaf.reshape(-1)[0].astype(jax.numpy.float32)
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )

    def sync(st):
        return float(np.asarray(probe(st.params)))

    scan = ex.build_train_scan()
    xs = [jax.numpy.broadcast_to(x, (spd,) + x.shape)]
    ys = jax.numpy.broadcast_to(y, (spd,) + y.shape)
    keys = jax.random.split(jax.random.PRNGKey(0), spd)
    for _ in range(2):
        state, _ = scan(state, xs, ys, keys)
    sync(state)
    t0 = time.perf_counter()
    for _ in range(chunks):
        state, _ = scan(state, xs, ys, keys)
    sync(state)
    dt = time.perf_counter() - t0
    iters = spd * chunks
    print(json.dumps({
        "tag": tag,
        "ms_per_step": round(1e3 * dt / iters, 3),
        "samples_per_s_chip": round(batch * iters / dt, 2),
    }), flush=True)


if __name__ == "__main__":
    import multiprocessing as mp

    variants = [
        ("base", {}),
        ("fwd_only", {"no_update": True}),
        ("no_metrics", {"no_metrics": True}),
        ("grads_no_update", {"grads_no_update": True}),
        ("sgd_bf16_grads", {"bf16_grads": True}),
    ]
    only = sys.argv[1:] or None
    for tag, kw in variants:
        if only and tag not in only:
            continue
        p = mp.Process(target=run, args=(tag,), kwargs=kw)
        p.start()
        p.join()
