"""MCMC (simulated annealing) strategy search + task-graph simulation.

TPU-native re-implementation of the reference's legacy MLSys'19 search
(FFModel::mcmc_optimize, src/runtime/model.cc:3285: random per-op
ParallelConfig rewrites accepted with probability exp(-alpha·Δ)) and of the
event-driven runtime simulation it scores with
(Simulator::simulate_runtime, src/runtime/simulator.cc:815-1000: per-op-shard
fwd+bwd SimTasks + comm tasks, list-scheduled onto per-device timelines).
Kept for parity and as a fallback when the DP search's graph-split
preconditions don't hold.
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Tuple

from ..pcg.graph import Graph
from ..pcg.machine_view import MachineResource, MachineView, enumerate_machine_views
from ..pcg.op import PCGOp
from .cost_model import CostModel


def simulate_runtime(
    graph: Graph,
    views: Dict[int, MachineView],
    cost_model: CostModel,
    *,
    overlap_backward_update: Optional[bool] = None,
) -> float:
    """List-schedule fwd+bwd (+weight sync) task graph onto per-device
    timelines (reference: simulator.cc:822 simulate_runtime).

    Simplification vs the reference: one task per op per pass covering its
    whole view (per-shard tasks run concurrently on their devices anyway
    under SPMD), comm folded into task start via xfer estimates.

    overlap_backward_update (None = follow the cost model's flag) models
    the overlapped executor schedule (parallel/executor.py
    set_overlap_grad_sync): each STATICALLY overlappable weight-grad sync
    (analysis/collectives.overlappable_grad_syncs) runs on a comm channel
    concurrent with the compute timeline — it starts when the producing
    op's backward finishes (and the channel is free; collectives
    serialize on the wire) and only extends the makespan past the compute
    end. Non-overlappable syncs stay serial, exactly as executed.
    """
    if overlap_backward_update is None:
        overlap_backward_update = getattr(
            cost_model, "overlap_backward_update", False
        )
    machine = cost_model.machine
    dev_free: Dict[int, float] = {}
    ready_fwd: Dict[int, float] = {}  # tensor guid -> time available

    topo = graph.topo_order()
    prod = graph.producers()

    def run_task(view: MachineView, start_lb: float, duration: float) -> float:
        ids = view.device_ids()
        start = max([start_lb] + [dev_free.get(d, 0.0) for d in ids])
        end = start + duration
        for d in ids:
            dev_free[d] = end
        return end

    # forward
    fwd_end: Dict[int, float] = {}
    for op in topo:
        view = views[op.guid]
        cm = cost_model.measure_operator_cost(op, view)
        lb = 0.0
        flows = []
        for t in op.inputs:
            p = prod.get(t.guid)
            if p is None:
                continue
            src_view = views[p[0].guid]
            lb = max(
                lb,
                ready_fwd.get(t.guid, 0.0)
                + cost_model.estimate_xfer_cost(t, src_view, view),
            )
            flows.append((t, src_view, view))
        if len(flows) > 1:
            # an op's input transfers overlap in time — price link sharing
            # (reference: simulator task overlap over EnhancedMachineModel
            # comm devices; zero on flat machines)
            lb += cost_model.concurrent_xfer_penalty(flows)
        dur = cm.forward_time
        if op.is_parallel_op:
            dur += cost_model.parallel_op_cost(op)
        end = run_task(view, lb, dur)
        fwd_end[op.guid] = end
        for t in op.outputs:
            ready_fwd[t.guid] = end

    # backward (reverse topo); grad of op ready when all consumers' bwd done
    bwd_end: Dict[int, float] = {}
    makespan = max(fwd_end.values()) if fwd_end else 0.0
    consumers: Dict[int, List[PCGOp]] = {}
    for op in topo:
        for t in op.inputs:
            p = prod.get(t.guid)
            if p is not None:
                consumers.setdefault(p[0].guid, []).append(op)
    overlappable: set = set()
    if overlap_backward_update:
        from ..analysis.collectives import overlappable_grad_syncs

        overlappable = overlappable_grad_syncs(graph)
    comm_free = 0.0  # the comm channel: overlapped syncs serialize here
    for op in reversed(topo):
        view = views[op.guid]
        cm = cost_model.measure_operator_cost(op, view)
        lb = makespan if not consumers.get(op.guid) else 0.0
        grad_flows = []
        flow_keys = set()  # (consumer, tensor) dedupe: consumers holds one
        # entry PER consumed input, and a consumer reading two outputs of
        # this op is still one gradient transfer per tensor
        for c in consumers.get(op.guid, []):
            lb = max(lb, bwd_end.get(c.guid, makespan))
            for t in op.outputs:
                if any(x.guid == t.guid for x in c.inputs) and \
                        (c.guid, t.guid) not in flow_keys:
                    flow_keys.add((c.guid, t.guid))
                    grad_flows.append((t, views[c.guid], view))
        if len(grad_flows) > 1:
            # gradients from several consumers arrive simultaneously
            lb += cost_model.concurrent_xfer_penalty(grad_flows)
        dur = cm.backward_time
        if op.is_parallel_op:
            dur += cost_model.parallel_op_cost(op)
        end = run_task(view, lb, dur)
        # weight sync (allreduce) after wgrad: overlappable syncs ride
        # the comm channel concurrent with later backward tasks; the
        # rest (and every sync when overlap is off) stay serial
        if cm.sync_time > 0:
            if op.guid in overlappable:
                comm_free = max(comm_free, end) + cm.sync_time
            else:
                end = run_task(view, end, cm.sync_time)
        bwd_end[op.guid] = end

    total = max(dev_free.values()) if dev_free else 0.0
    return max(total, comm_free)


class MCMCSearch:
    """reference: model.cc:3285 mcmc_optimize / :3260 rewrite."""

    def __init__(
        self,
        cost_model: CostModel,
        *,
        alpha: float = 0.05,
        seed: int = 0,
        trajectory=None,
    ):
        self.cost_model = cost_model
        self.alpha = alpha
        self.rng = random.Random(seed)
        # obs.SearchTrajectory: records one entry per proposal (proposed
        # op + view, simulated cost, accept/reject) so the search is
        # explainable after the fact (obs/trajectory.py)
        self.trajectory = trajectory

    def _valid_views(self, op: PCGOp, machine) -> List[MachineView]:
        degree = op.outputs[0].get_total_degree() if op.outputs else 1
        views = [
            v
            for v in enumerate_machine_views(machine.num_nodes, machine.workers_per_node)
            if v.num_parts() == degree
        ]
        return views or [MachineView(start_device_id=0, dim=(1,), stride=(1,))]

    def data_parallel_start(self, graph: Graph) -> Dict[int, MachineView]:
        """reference: start from data-parallel config
        (get_basic_data_parallel_config, model.h:250)."""
        machine = self.cost_model.machine
        out = {}
        for op in graph.ops:
            vs = self._valid_views(op, machine)
            out[op.guid] = vs[0]
        return out

    def optimize(
        self,
        graph: Graph,
        budget: int = 100,
        start: Optional[Dict[int, MachineView]] = None,
        use_native: bool = True,
    ) -> Tuple[Dict[int, MachineView], float]:
        machine = self.cost_model.machine
        # slice-loss survivability bias (search/survivability.py): on
        # hierarchical machines with the penalty armed, every proposal's
        # simulated runtime is scaled by the cross-slice-sharded weight
        # fraction — which also forces the Python annealer (the native
        # one costs proposals in C++ and cannot see the bias)
        pen = getattr(self.cost_model, "survivability_penalty", 0.0)
        biased = bool(pen) and machine.num_nodes > 1
        if biased:
            from .survivability import survivability_cost_factor

            def cost_of(vs):
                return simulate_runtime(
                    graph, vs, self.cost_model
                ) * survivability_cost_factor(graph, vs, self.cost_model)
        else:
            def cost_of(vs):
                return simulate_runtime(graph, vs, self.cost_model)
        if use_native and not biased:
            result = self._optimize_native(graph, budget, start)
            if result is not None:
                if self.trajectory is not None:
                    # the native annealer iterates in C++: no per-proposal
                    # visibility, record the summary instead
                    self.trajectory.event("mcmc_native", cost=result[1],
                                          budget=budget)
                return result
        views = dict(start) if start else self.data_parallel_start(graph)
        cur = cost_of(views)
        best_views, best = dict(views), cur
        traj = self.trajectory
        if traj is not None:
            traj.event("search_begin", engine="mcmc", cost=cur,
                       budget=budget, ops=len(graph.ops))
        ops = list(graph.ops)
        for i in range(budget):
            # rewrite: random op -> random valid view (model.cc:3260)
            op = self.rng.choice(ops)
            cands = self._valid_views(op, machine)
            nxt = dict(views)
            proposed = self.rng.choice(cands)
            nxt[op.guid] = proposed
            c = cost_of(nxt)
            delta = c - cur
            accept = (delta < 0
                      or self.rng.random() < math.exp(-self.alpha * delta * 1e6))
            if traj is not None:
                traj.event("mcmc_iter", iter=i, op=op.name,
                           view=repr(proposed), cost=c, current=cur,
                           best=best, delta=delta, accept=accept)
            if accept:
                views, cur = nxt, c
                if cur < best:
                    best_views, best = dict(views), cur
        if traj is not None:
            traj.event("search_end", engine="mcmc", cost=best)
        return best_views, best

    def _optimize_native(self, graph, budget, start):
        """C++ fast path (native/src/simulator.cc): flatten once, anneal in
        native code. Returns None when the native lib is unavailable."""
        try:
            from .. import native

            if not native.available():
                return None
            from ..native.simulator import NativeSimulator
        except Exception:
            return None
        machine = self.cost_model.machine
        ops = graph.topo_order()
        views_per_op = {op.guid: self._valid_views(op, machine) for op in ops}
        sim = NativeSimulator(graph, self.cost_model, views_per_op)
        slots = []
        for op in ops:
            if start and op.guid in start:
                cands = views_per_op[op.guid]
                h = start[op.guid].hash()
                slot = next((i for i, v in enumerate(cands) if v.hash() == h), 0)
            else:
                slot = 0
            slots.append(slot)
        views, cost = sim.mcmc(
            slots, budget, alpha=self.alpha, seed=self.rng.randrange(1 << 30)
        )
        return views, cost
