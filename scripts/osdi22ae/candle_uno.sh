#!/usr/bin/env bash
# reference: scripts/osdi22ae/candle_uno.sh
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

echo "Running CANDLE Uno with a parallelization strategy discovered by Unity"
run_example candle_uno.py --budget 20

echo "Running CANDLE Uno with data parallelism"
run_example candle_uno.py --budget 20 --only-data-parallel
