"""PyTorch-FX frontend (reference: python/flexflow/torch/)."""
from .model import PyTorchModel, torch_to_flexflow  # noqa: F401
