#!/bin/bash
# reference: scripts/test_run.sh — build + run the op unit-test batch. The
# reference version rebuilds protobuf/GASNet/Legion and runs each C++ op
# test binary; here the whole stack is Python/XLA, so the equivalent is the
# pytest suite on a virtual 8-device CPU mesh (tests/conftest.py forces the
# cpu platform, so no TPU is needed).
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."
python -m pytest tests/ -q "$@"
