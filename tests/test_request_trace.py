"""Request-level distributed tracing + the persistent calibration store
(flexflow_tpu/obs/request_trace.py, obs/calibration.py).

The contract: a trace context minted at submit follows ONE request
through queue -> admission -> prefill -> per-iteration decode ->
completion, across replica failover, under the SAME trace id — with
head-based sampling whose off path is the shared allocation-free null
object. Independently, measured per-op costs persist across processes
through a fingerprint-checked on-disk store that compile(calibration=)
attaches without re-profiling.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import flexflow_tpu.obs as obs
from flexflow_tpu import TelemetryConfig
from flexflow_tpu.obs.calibration import (
    CalibrationStore,
    CalibrationStoreError,
    op_key_str,
    resolve_calibration,
)
from flexflow_tpu.obs.request_trace import (
    NULL_REQUEST_TRACE,
    SLOMonitor,
    _sampled,
    mint_request_trace,
    record_request_stages,
)
from flexflow_tpu.obs.tracer import lanes_from_events, read_events_jsonl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_session():
    obs.finish()
    yield
    obs.finish()


def _request_events(events, rid):
    return [e for e in events
            if e.get("cat") == "requests"
            and e.get("args", {}).get("request") == rid]


# ----------------------------------------------------------------------
# sampling + the null fast path
# ----------------------------------------------------------------------
def test_no_session_mints_shared_null_trace():
    t1 = mint_request_trace("a")
    t2 = mint_request_trace("b")
    assert t1 is NULL_REQUEST_TRACE and t2 is NULL_REQUEST_TRACE
    assert not t1.sampled
    # every lifecycle method is a no-op, including the span protocol
    t1.queue_begin()
    t1.admitted("r0")
    sp = t1.span("prefill", replica="r0")
    sp.set(x=1)
    sp.done()
    t1.iteration("r0", t0=0.0, dur_s=0.0)
    t1.requeued("r0", generation=1)
    t1.shed("deadline", stage="decode")
    t1.completed("r0")


def test_sampling_is_deterministic_and_rate_shaped(tmp_path):
    ids = [f"req-{i}" for i in range(400)]
    # same id -> same verdict, across calls (failover re-mint safety)
    for rid in ids[:20]:
        assert _sampled(rid, 0.5) == _sampled(rid, 0.5)
    assert all(_sampled(rid, 1.0) for rid in ids)
    assert not any(_sampled(rid, 0.0) for rid in ids)
    hit = sum(1 for rid in ids if _sampled(rid, 0.25))
    assert 0.10 * len(ids) < hit < 0.40 * len(ids)
    with obs.session(TelemetryConfig(dir=str(tmp_path),
                                     request_sample_rate=0.0)):
        assert mint_request_trace("anything") is NULL_REQUEST_TRACE
    with obs.session(TelemetryConfig(dir=str(tmp_path / "on"),
                                     request_sample_rate=1.0)):
        tr = mint_request_trace("req-9")
        assert tr.sampled and tr.trace_id == "req-9"


# ----------------------------------------------------------------------
# stage decomposition + SLO monitor
# ----------------------------------------------------------------------
class _FakeReq:
    def __init__(self, *, submitted, admitted, first, finished,
                 max_new_tokens=8):
        self.submitted_t = submitted
        self.admitted_t = admitted
        self.first_token_t = first
        self.finished_t = finished
        self.max_new_tokens = max_new_tokens


def test_record_request_stages_decomposition(tmp_path):
    t0 = time.monotonic() - 10.0
    req = _FakeReq(submitted=t0, admitted=t0 + 1.0, first=t0 + 1.5,
                   finished=t0 + 5.5)
    with obs.session(TelemetryConfig(dir=str(tmp_path))) as tel:
        stages = record_request_stages(req, generated=5)
        assert stages["queue"] == pytest.approx(1.0)
        assert stages["prefill"] == pytest.approx(0.5)
        assert stages["decode"] == pytest.approx(4.0)
        assert stages["total"] == pytest.approx(5.5)
        assert stages["stall"] == pytest.approx(0.0)
        assert stages["tpot"] == pytest.approx(1.0)  # 4s / (5-1) tokens
        for stage in ("queue", "prefill", "decode", "total", "tpot"):
            h = tel.metrics.find("ff_request_stage_seconds", stage=stage)
            assert h is not None and h.count == 1
    # a failover-delayed request: the lost first attempt shows as stall
    req2 = _FakeReq(submitted=t0, admitted=t0 + 4.0, first=t0 + 4.5,
                    finished=t0 + 6.5)
    stages2 = record_request_stages(req2, generated=3)
    assert stages2["stall"] == pytest.approx(0.0)
    assert stages2["queue"] == pytest.approx(4.0)


def test_slo_monitor_targets_and_scale_signal(tmp_path):
    inert = SLOMonitor()
    assert not inert.enabled
    inert.observe(ttft_s=99.0, latency_s=99.0)
    assert not inert.should_scale_up()
    assert inert.violation_rate() != inert.violation_rate()  # NaN

    with obs.session(TelemetryConfig(dir=str(tmp_path))) as tel:
        m = SLOMonitor(ttft_target_s=0.1, latency_p99_target_s=1.0)
        for _ in range(10):
            m.observe(ttft_s=0.05, latency_s=0.5)  # all within target
        assert not m.should_scale_up()
        assert m.violation_rate("ttft") == 0.0
        for _ in range(5):
            m.observe(ttft_s=0.3, latency_s=0.5)  # ttft violations
        assert m.should_scale_up()
        assert m.violation_rate("ttft") == pytest.approx(5 / 15)
        assert m.violation_rate("p99_latency") == 0.0
        assert m.violation_rate() == pytest.approx(5 / 15)  # worst window
        c = tel.metrics.find("ff_slo_violations_total", slo="ttft")
        assert c is not None and c.value == 5.0
        assert m.latency_quantile(0.5) == 0.5
        assert m.sample_count == 15
        snap = m.snapshot()
        assert snap["violations"]["ttft"] == 5


# ----------------------------------------------------------------------
# end-to-end: spans across replica tracks + failover propagation
# ----------------------------------------------------------------------
def _build_lm():
    from tests.test_serving import build_lm

    return build_lm()


def test_request_spans_render_across_replica_tracks(tmp_path):
    """Acceptance: a sampled request's life — queue -> admit -> prefill
    -> decode iterations -> complete — lands as schema-valid events on a
    named per-replica lane, and the exported trace.json carries the
    Perfetto thread_name metadata for that lane."""
    from flexflow_tpu.runtime.serving import ReplicaSet
    from tests.test_serving import VOCAB, build_lm
    from tests.test_serving import _serve_cfg

    tel_dir = tmp_path / "tel"
    rng = np.random.RandomState(11)
    with obs.session(TelemetryConfig(dir=str(tel_dir),
                                     request_sample_rate=1.0)):
        rs = ReplicaSet(build_lm, _serve_cfg(), replicas=1,
                        health_timeout_s=60.0).start()
        try:
            reqs = [rs.submit(rng.randint(0, VOCAB, 3).astype(np.int32),
                              max_new_tokens=4, deadline_s=120.0)
                    for _ in range(3)]
            for r in reqs:
                r.result(timeout=120.0)
                assert r.trace.sampled and r.trace.trace_id == r.id
        finally:
            rs.stop()
    events, problems = read_events_jsonl(str(tel_dir / "events.jsonl"))
    assert not problems  # request events obey the tracer schema
    rid = reqs[0].id
    mine = _request_events(events, rid)
    names = [e["name"] for e in mine]
    for expected in ("queue", "admit", "prefill", "decode", "complete"):
        assert expected in names, f"missing {expected} for {rid}: {names}"
    assert names.count("complete") == 1
    # decode iterations are spans with occupancy/pos payloads
    decode = [e for e in mine if e["name"] == "decode"]
    assert all(e["ph"] == "X" for e in decode)
    assert all(e["args"]["occupancy"] >= 1 for e in decode)
    # the kv accounting shows up on the sampled trace
    assert any(e["name"] == "kv_reserve" for e in mine)
    # the replica lane is named, and events actually sit on it
    lanes = lanes_from_events(events)
    rep_lanes = {name: tid for (cat, name), tid in lanes.items()
                 if cat == "requests" and name != "admission"}
    assert rep_lanes, f"no replica lane recorded: {lanes}"
    admit = next(e for e in mine if e["name"] == "admit")
    assert admit["tid"] in rep_lanes.values()
    # exported trace is Perfetto-loadable with named tracks
    trace = json.load(open(tel_dir / "trace.json"))
    assert "traceEvents" in trace
    tnames = [m["args"]["name"] for m in trace["traceEvents"]
              if m.get("ph") == "M" and m.get("name") == "thread_name"]
    assert set(rep_lanes) <= set(tnames)
    # per-stage histograms populated for every completed request
    metrics = open(tel_dir / "metrics.prom").read()
    assert "ff_request_stage_seconds" in metrics


def test_trace_context_survives_replica_failover(tmp_path):
    """Kill a replica mid-decode (replica_death fault site): every
    requeued request must finish under its ORIGINAL trace id, with a
    requeue event carrying the new generation tag and exactly one
    complete event."""
    from flexflow_tpu.runtime.resilience import FaultInjector
    from flexflow_tpu.runtime.serving import ReplicaDeathError, ReplicaSet
    from tests.test_serving import VOCAB, _serve_cfg, build_lm

    fi = FaultInjector()
    fi.inject("replica_death", at_step=2, replica="replica0",
              exc=ReplicaDeathError("injected"))
    tel_dir = tmp_path / "tel"
    rng = np.random.RandomState(12)
    with obs.session(TelemetryConfig(dir=str(tel_dir),
                                     request_sample_rate=1.0)):
        rs = ReplicaSet(build_lm, _serve_cfg(), replicas=2,
                        ckpt_dir=str(tmp_path / "ckpt"),
                        fault_injector=fi, health_timeout_s=60.0,
                        restart_backoff_s=0.05).start()
        try:
            reqs = [rs.submit(rng.randint(0, VOCAB, 3).astype(np.int32),
                              max_new_tokens=5, deadline_s=120.0)
                    for _ in range(6)]
            for r in reqs:
                r.result(timeout=180.0)
        finally:
            rs.stop()
    assert fi.fired["replica_death"] == 1
    events, problems = read_events_jsonl(str(tel_dir / "events.jsonl"))
    assert not problems
    requeued = {e["args"]["request"]: e for e in events
                if e.get("cat") == "requests" and e["name"] == "requeue"}
    assert requeued, "the death stranded no request — fault not exercised"
    for rid, ev in requeued.items():
        assert ev["args"]["generation"] >= 1
        mine = _request_events(events, rid)
        names = [e["name"] for e in mine]
        # exactly-once completion under the original trace id
        assert names.count("complete") == 1
        # the requeued request waited in queue again, then re-admitted
        assert names.count("queue") >= 2
        assert names.count("admit") >= 2
        done = next(e for e in mine if e["name"] == "complete")
        assert done["args"]["generation"] >= 1  # finished by the 2nd owner


# ----------------------------------------------------------------------
# calibration store
# ----------------------------------------------------------------------
KEY = ("OP_LINEAR", (("out_dim", 16),), (("DT_FLOAT", (8, 4)),),
       (("DT_FLOAT", (4, 16)),))


def test_calibration_store_roundtrip_and_table(tmp_path):
    p = str(tmp_path / "calib.json")
    st = CalibrationStore(p)
    assert st.record_op(KEY, 1e-3, 2e-3)
    assert not st.record_op(KEY, float("nan"), 1.0)  # NaN skipped
    st.record_globals(overlap_efficiency=0.66,
                      collectives={"all_reduce": 1e10})
    assert st.dirty
    st.save()
    assert not st.dirty
    st2 = CalibrationStore(p)
    assert st2.globals["overlap_efficiency"] == 0.66
    tbl = st2.table()
    assert len(tbl) == 1
    assert tbl.get(KEY) == (1e-3, 2e-3)
    assert tbl.get(("OP_RELU", (), (), ())) is None
    assert tbl.source == p
    assert op_key_str(KEY) in st2.ops
    # same-process fingerprint/backend: usable
    assert st2.problems() == []


def test_calibration_store_rejects_mismatch_and_staleness(tmp_path):
    p = str(tmp_path / "calib.json")
    st = CalibrationStore(p)
    st.record_op(KEY, 1e-3, 2e-3)
    st.save()
    doc = json.load(open(p))
    # a different topology: rejected with the differing keys named
    doc["fingerprint"] = {"num_devices": 4096, "platform": "tpu"}
    json.dump(doc, open(p, "w"))
    st2 = CalibrationStore(p)
    probs = st2.problems()
    assert any("fingerprint mismatch" in s for s in probs)
    tbl, glb = resolve_calibration(p)
    assert tbl is None and glb == {}
    # stale entries: rejected, then prunable
    doc["fingerprint"] = {}
    doc["ops"][op_key_str(KEY)]["recorded_at"] = time.time() - 90 * 86400
    json.dump(doc, open(p, "w"))
    st3 = CalibrationStore(p)
    assert any("stale" in s for s in st3.problems())
    assert st3.prune(max_age_s=30 * 86400) == 1
    assert len(st3.ops) == 0
    assert any("empty" in s for s in st3.problems())
    # schema mismatch is a typed error
    json.dump({"schema_version": 999}, open(p, "w"))
    with pytest.raises(CalibrationStoreError):
        CalibrationStore(p)
    tbl, glb = resolve_calibration(p)  # rejected, not raised
    assert tbl is None


def test_calibration_store_diff(tmp_path):
    a = CalibrationStore(str(tmp_path / "a.json"))
    b = CalibrationStore(str(tmp_path / "b.json"))
    a.record_op(KEY, 1e-3, 2e-3)
    b.record_op(KEY, 2e-3, 4e-3)
    b.record_op(("OP_RELU", (), (), ()), 1e-4, 1e-4)
    delta = a.diff(b)
    changed = [d for d in delta if d["status"] == "changed"]
    assert len(changed) == 1 and changed[0]["ratio"] == pytest.approx(2.0)
    assert any(d["status"] == "only_in_b" for d in delta)


def test_explain_apply_persists_and_compile_loads(tmp_path):
    """The acceptance loop in-process: explain -> apply persists measured
    costs; a later compile(calibration=path) attaches them so the cost
    model prices serial views from measurement WITHOUT re-profiling."""
    from flexflow_tpu.pcg.machine_view import MachineView
    from tests.test_obs import small_model

    p = str(tmp_path / "calib.json")
    m = small_model()
    ex = obs.explain_strategy(m, repeats=1, warmup=1)
    store = CalibrationStore(p)
    n = ex.apply(m, store=store)
    assert n == len(ex.rows) and os.path.exists(p)
    assert store.globals.get("overlap_efficiency") is not None

    # "fresh model" standing in for a fresh process: calibration by path
    m2 = small_model()
    tbl, glb = resolve_calibration(p)
    assert tbl is not None and len(tbl) == len(ex.rows)
    m2._profiled_op_costs = tbl
    cm = m2._build_cost_model()
    assert cm.calibration_source == p
    v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    op = next(o for o in m2.graph.ops if not o.is_parallel_op)
    row = next(r for r in ex.rows if r["name"] == op.name)
    got = cm.measure_operator_cost(op, v1)
    assert got.forward_time == pytest.approx(row["meas_fwd_s"])
    assert cm.measured_hits >= 1
    prov = cm.provenance()
    assert prov["source"] == p and prov["measured_hits"] >= 1


def test_compile_calibration_kwarg_and_perf_provenance(tmp_path):
    """compile(calibration=path) is the public seam: the searched model's
    cost model resolves measured costs and perf_diagnostics reports the
    oracle's provenance as the FFA500 INFO line."""
    from flexflow_tpu import (
        ActiMode,
        DataType,
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from flexflow_tpu.analysis.perf import perf_diagnostics
    from tests.test_obs import small_model

    p = str(tmp_path / "calib.json")
    m = small_model()
    ex = obs.explain_strategy(m, repeats=1, warmup=1)
    ex.apply(m, store=CalibrationStore(p))

    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = -1
    m2 = FFModel(cfg)
    x = m2.create_tensor((8, 4), DataType.DT_FLOAT)
    t = m2.dense(x, 16, ActiMode.AC_MODE_RELU)
    t = m2.dense(t, 3)
    t = m2.softmax(t)
    m2.compile(SGDOptimizer(lr=0.1),
               LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               [MetricsType.METRICS_ACCURACY], calibration=p)
    cm = m2._build_cost_model()
    assert cm.calibration_source == p
    rep = perf_diagnostics(m2.graph,
                           views=getattr(m2, "searched_views", None),
                           cost_model=cm)
    info = [d for d in rep.diagnostics if d.code == "FFA500"]
    assert len(info) == 1 and p in info[0].message


# ----------------------------------------------------------------------
# CLI: requests + calibrate subcommands
# ----------------------------------------------------------------------
def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", *argv],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_cli_requests_report(tmp_path):
    from flexflow_tpu.runtime.serving import ReplicaSet
    from tests.test_serving import VOCAB, _serve_cfg, build_lm

    tel_dir = tmp_path / "tel"
    rng = np.random.RandomState(13)
    with obs.session(TelemetryConfig(dir=str(tel_dir),
                                     request_sample_rate=1.0)):
        rs = ReplicaSet(build_lm, _serve_cfg(), replicas=1,
                        health_timeout_s=60.0).start()
        try:
            reqs = [rs.submit(rng.randint(0, VOCAB, 3).astype(np.int32),
                              max_new_tokens=3, deadline_s=120.0)
                    for _ in range(2)]
            for r in reqs:
                r.result(timeout=120.0)
        finally:
            rs.stop()
    r = _run_cli("requests", str(tel_dir / "events.jsonl"), "--slowest", "5")
    assert r.returncode == 0, r.stderr
    assert "traced request(s)" in r.stdout
    assert "2 completed" in r.stdout
    assert reqs[0].id[:14] in r.stdout
    # empty log is a loud non-zero exit
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    r2 = _run_cli("requests", str(empty))
    assert r2.returncode == 1


def test_cli_calibrate_inspect_prune_diff(tmp_path):
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    a, b = CalibrationStore(pa), CalibrationStore(pb)
    a.record_op(KEY, 1e-3, 2e-3)
    b.record_op(KEY, 3e-3, 6e-3)
    a.save(), b.save()
    r = _run_cli("calibrate", "inspect", pa)
    assert r.returncode == 0, r.stderr
    assert '"ops": 1' in r.stdout and "usable" in r.stdout
    r = _run_cli("calibrate", "diff", pa, pb)
    assert r.returncode == 0 and "x3.000" in r.stdout
    r = _run_cli("calibrate", "prune", pa, "--max-age-h", "0")
    assert r.returncode == 0 and "pruned 1" in r.stdout
    r = _run_cli("calibrate", "inspect", pa)
    assert r.returncode == 1  # now empty -> unusable, exit 1
    r = _run_cli("calibrate", "diff", pa)
    assert r.returncode == 2  # missing second path -> argparse error
