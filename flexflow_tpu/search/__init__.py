"""Strategy search: cost model, DP machine-view assignment, substitution
engine, MCMC fallback (TPU-native equivalents of reference
src/runtime/{simulator,graph,substitution,model-mcmc}.cc)."""
from .cost_model import CostMetrics, CostModel  # noqa: F401
from .dp_search import GraphCostResult, SearchHelper, research_views  # noqa: F401
from .machine_model import (  # noqa: F401
    MachineModel,
    TPUChipSpec,
    for_device_count,
    parse_machine_config,
)
from .mcmc import MCMCSearch, simulate_runtime  # noqa: F401
from .substitution import (  # noqa: F401
    GraphSearchHelper,
    Substitution,
    generate_all_pcg_xfers,
)
