"""Shim: reference python/flexflow/keras/backend/internal.py."""
from flexflow_tpu.frontends.keras.backend.internal import *  # noqa: F401,F403
from flexflow_tpu.frontends.keras.backend.internal import gather, rsqrt  # noqa: F401
