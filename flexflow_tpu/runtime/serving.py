"""Inference serving: a batching scheduler over a compiled model.

TPU-native counterpart to the reference's Triton prototype (triton/src/,
~8k LoC "incomplete prototype" serving ONNX models on Legion — SURVEY §2.6).
Instead of a Triton backend we provide the piece that matters on TPU: a
request queue + dynamic batcher that pads/packs incoming requests to the
compiled batch size, runs the jitted forward, and fans results back out.
Models arrive through any frontend (ONNX importer included, matching the
prototype's ONNX surface).
"""
from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .verify import NotCompiledError, ServingConfigError


def greedy_generate(
    model,
    encoder_ids: np.ndarray,
    *,
    max_new_tokens: Optional[int] = None,
    start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
) -> np.ndarray:
    """Greedy autoregressive seq2seq decode over a compiled encoder-decoder
    FFModel (e.g. an imported MT5ForConditionalGeneration) whose two graph
    inputs are (encoder_ids, decoder_ids) and whose output is per-position
    vocab logits.

    The compiled graph is static-shape, so each step re-runs the SAME
    jitted forward with the decoder prefix grown by one token — the causal
    mask guarantees position t sees only tokens <= t, so the padded tail
    cannot leak. No KV cache: one full forward per token (O(L) calls of
    one cached executable). The reference has no generation API at all —
    its serving story is the Triton prototype's single forward — so this
    is a capability upgrade on the serving side.
    """
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    fwd = model.executor.build_forward()
    enc_t, dec_t = model._fit_input_tensors[:2]
    bs, dec_len = dec_t.dims[0], dec_t.dims[1]
    if tuple(encoder_ids.shape) != tuple(enc_t.dims):
        raise ServingConfigError(
            f"encoder_ids shape {tuple(encoder_ids.shape)} != compiled input "
            f"shape {tuple(enc_t.dims)}"
        )
    want = dec_len - 1 if max_new_tokens is None else max_new_tokens
    steps = min(want, dec_len - 1)
    enc = np.asarray(encoder_ids, enc_t.data_type.np_dtype)

    def next_logits(t, dec):
        return np.asarray(fwd(model.state.params, [enc, dec],
                              model.state.net_state))[:, t]

    return _greedy_decode_loop(
        bs, dec_len, steps, next_logits, dec_t.data_type.np_dtype,
        start_token_id=start_token_id, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id,
    )


def _greedy_decode_loop(bs, dec_len, steps, next_logits, dec_dt, *,
                        start_token_id, eos_token_id, pad_token_id):
    """The shared greedy seq2seq loop: greedy_generate (full forward per
    token) and incremental_seq2seq_generate (KV-cache step per token)
    differ ONLY in how position t's logits are produced — sharing the
    scaffold keeps their documented token-exact equivalence structural.
    next_logits(t, dec) -> (bs, vocab) values for position t given the
    decoder buffer so far."""
    dec = np.full((bs, dec_len), pad_token_id, dec_dt)
    dec[:, 0] = start_token_id
    if steps <= 0:
        return dec[:, :1]
    finished = np.zeros(bs, bool)
    for t in range(steps):
        nxt = next_logits(t, dec).argmax(-1)
        if eos_token_id is not None:
            nxt = np.where(finished, pad_token_id, nxt)
            finished |= nxt == eos_token_id
        dec[:, t + 1] = nxt
        if eos_token_id is not None and finished.all():
            break
    return dec[:, : t + 2]


def incremental_seq2seq_generate(
    model,
    encoder_ids: np.ndarray,
    *,
    max_new_tokens: Optional[int] = None,
    start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    assume_causal: bool = False,
) -> np.ndarray:
    """KV-cache greedy decode for a compiled encoder-decoder FFModel —
    same signature and token-exact output as greedy_generate, but
    O(1)/token: the encoder runs ONCE (executor.build_decode computes the
    static subgraph and the cross-attention K/V at init), each step feeds
    one decoder position through the liveness-analyzed decoder subgraph
    (parallel/decode.py). Works on imported HF graphs (mt5) where
    attention is primitive batch_matmul/softmax ops."""
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    if len(model._fit_input_tensors) < 2:
        raise ServingConfigError(
            "incremental_seq2seq_generate needs an encoder-decoder model "
            "(two graph inputs); use incremental_generate for decoder-only"
        )
    ex = model.executor
    enc_t, dec_t = model._fit_input_tensors[:2]
    bs, dec_len = dec_t.dims[0], dec_t.dims[1]
    if tuple(encoder_ids.shape) != tuple(enc_t.dims):
        raise ServingConfigError(
            f"encoder_ids shape {tuple(encoder_ids.shape)} != compiled input "
            f"shape {tuple(enc_t.dims)}"
        )
    want = dec_len - 1 if max_new_tokens is None else max_new_tokens
    steps = min(want, dec_len - 1)
    if steps <= 0:
        out = np.full((bs, 1), start_token_id, dec_t.data_type.np_dtype)
        return out
    init_caches, step = ex.build_decode(bs, dec_len,
                                        assume_causal=assume_causal)
    caches = init_caches(
        model.state.params,
        [np.asarray(encoder_ids, enc_t.data_type.np_dtype)],
    )

    def next_logits(t, dec):
        nonlocal caches
        logits, caches = step(
            model.state.params, caches, jnp.int32(t),
            [jnp.asarray(dec[:, t : t + 1])],
        )
        return np.asarray(logits)[:, -1]

    return _greedy_decode_loop(
        bs, dec_len, steps, next_logits, dec_t.data_type.np_dtype,
        start_token_id=start_token_id, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id,
    )


def incremental_generate(
    model,
    prompt_ids: np.ndarray,
    *,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    static_inputs=(),
    decode_input: Optional[int] = None,
    assume_causal: bool = False,
) -> np.ndarray:
    """KV-cache autoregressive decoding for a causal decoder-only FFModel
    (token ids in, per-position vocab logits out): each step feeds ONE
    position through executor.build_decode, appending that position's K/V
    to per-layer caches — one O(max_len)-wide attention row per token
    instead of greedy_generate's full O(L²) forward per token. Capability the reference
    lacks entirely (its Triton prototype serves single forwards).

    prompt_ids: (batch, prompt_len) int array. Returns (batch, total_len)
    including the prompt.

    static_inputs: arrays for any non-decode graph inputs (e.g. an
    explicit attention-mask input), passed through to init_caches;
    decode_input selects which graph input the prompt drives (default:
    build_decode's convention, the last); assume_causal vouches for
    primitive-op attention whose causality can't be proven from baked
    constants (parallel/decode.py)."""
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    prompt_ids = np.asarray(prompt_ids)
    bs, plen = prompt_ids.shape
    if max_new_tokens <= 0:
        return prompt_ids.copy()
    total = plen + max_new_tokens
    cap = max_len or total
    if cap < total:
        raise ServingConfigError(f"max_len {cap} < prompt+new {total}")
    init_caches, step = model.executor.build_decode(
        bs, cap, decode_input=decode_input, assume_causal=assume_causal
    )
    caches = init_caches(model.state.params, list(static_inputs))
    dec_idx = (decode_input if decode_input is not None
               else len(model._fit_input_tensors) - 1)
    in_t = model._fit_input_tensors[dec_idx]
    id_dt = in_t.data_type.np_dtype

    out = np.full((bs, total), pad_token_id, id_dt)
    out[:, :plen] = prompt_ids
    finished = np.zeros(bs, bool)
    # one-shot prefill: the whole prompt goes through a single step (the
    # decode kernels handle any block width with intra-block causal
    # masking), populating every prompt position's K/V at once
    logits, caches = step(
        model.state.params, caches, jnp.int32(0),
        [jnp.asarray(prompt_ids.astype(id_dt))],
    )
    nxt = np.asarray(logits)[:, -1].argmax(-1)
    if eos_token_id is not None:
        finished |= nxt == eos_token_id
    out[:, plen] = nxt
    for t in range(plen, total - 1):
        if eos_token_id is not None and finished.all():
            break  # out is already pad-filled to the documented full width
        tok = out[:, t : t + 1].astype(id_dt)
        logits, caches = step(
            model.state.params, caches, jnp.int32(t), [jnp.asarray(tok)]
        )
        nxt = np.asarray(logits)[:, 0].argmax(-1)
        if eos_token_id is not None:
            nxt = np.where(finished, pad_token_id, nxt)
            finished |= nxt == eos_token_id
        out[:, t + 1] = nxt
    return out


def incremental_beam_generate(
    model,
    prompt_ids: np.ndarray,
    *,
    num_beams: int = 4,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    encoder_ids: Optional[np.ndarray] = None,
    static_inputs=(),
    assume_causal: bool = False,
) -> np.ndarray:
    """Beam search over the KV-cache decoder: the decode step is built at
    batch=num_beams (build_decode jits for any batch, so no
    compiled-batch packing), each step feeds ONE position per beam, and on
    a beam reorder the per-layer caches are gathered along the batch axis
    on-device. Scores are sums of log-probs (probability and logit output
    heads both handled — _as_log_probs), no length penalty; samples decode
    sequentially.

    prompt_ids: (n, prompt_len). Returns (n, prompt_len + max_new_tokens)
    top beams. For encoder-decoder models pass encoder_ids (n, enc_len)
    and a prompt of start tokens — each sample's encoder statics and
    cross-attention K/V are computed once at its init."""
    import jax

    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    prompt_ids = np.asarray(prompt_ids)
    plen = prompt_ids.shape[1]
    if max_new_tokens <= 0:
        return prompt_ids.copy()
    in_t = model._fit_input_tensors[-1]
    total = plen + max_new_tokens
    cap = max_len or total
    if cap < total:
        raise ServingConfigError(f"max_len {cap} < prompt+new {total}")
    init_caches, step = model.executor.build_decode(
        num_beams, cap, assume_causal=assume_causal
    )
    id_dt = in_t.data_type.np_dtype
    prob_hint = model.output_probability_like()
    if encoder_ids is not None:
        enc_t = model._fit_input_tensors[0]
        enc_rows = np.asarray(encoder_ids, enc_t.data_type.np_dtype)
        if enc_rows.shape[0] != prompt_ids.shape[0]:
            raise ServingConfigError(
                f"encoder_ids rows {enc_rows.shape[0]} != prompt rows "
                f"{prompt_ids.shape[0]}"
            )

    outs = []
    for i, row in enumerate(prompt_ids.astype(id_dt)):
        if encoder_ids is None:
            # static_inputs (if any) must be shaped for batch=num_beams
            caches = init_caches(model.state.params, list(static_inputs))
        else:
            enc_block = np.broadcast_to(
                enc_rows[i], (num_beams,) + enc_rows[i].shape
            ).copy()
            # static_inputs are the non-decode inputs AFTER the encoder
            # ids (input order), shaped for batch=num_beams
            caches = init_caches(model.state.params,
                                 [enc_block] + list(static_inputs))
        beams = np.full((num_beams, total), pad_token_id, id_dt)
        beams[:, :plen] = row
        scores = np.full(num_beams, -np.inf)
        scores[0] = 0.0  # beams identical until the first branch
        done = np.zeros(num_beams, bool)
        # prefill: same prompt in every beam slot, one block step
        block = np.broadcast_to(row, (num_beams, plen)).copy()
        logits, caches = step(model.state.params, caches, jnp.int32(0),
                              [jnp.asarray(block)])
        logp = _as_log_probs(np.asarray(logits)[:, -1], prob_hint)
        for t in range(plen, total):
            src_beams, toks, scores = _beam_topk(
                scores, logp, done, pad_token_id, num_beams
            )
            beams = beams[src_beams]
            beams[:, t] = np.where(done[src_beams], pad_token_id, toks)
            if eos_token_id is not None:
                done = done[src_beams] | (beams[:, t] == eos_token_id)
            # per-beam caches follow their beams (identity gathers are
            # common early on; jnp.take keeps the shuffle on-device).
            # "static" and "mha_static" (cross-attention encoder K/V) stay
            # untouched: they are beam-invariant, and constant-derived
            # static entries have leading axis 1 — a batch gather would
            # fill out-of-bounds rows with NaN.
            idx = jnp.asarray(src_beams.astype(np.int32))
            gathered = jax.tree_util.tree_map(
                lambda c: jnp.take(c, idx, axis=0),
                {"prefix": caches["prefix"], "mha": caches["mha"]},
            )
            caches = {"static": caches["static"],
                      "mha_static": caches["mha_static"], **gathered}
            if (eos_token_id is not None and done.all()) or t == total - 1:
                break
            logits, caches = step(
                model.state.params, caches, jnp.int32(t),
                [jnp.asarray(beams[:, t : t + 1])],
            )
            logp = _as_log_probs(np.asarray(logits)[:, 0], prob_hint)
        outs.append(beams[0])
    return np.stack(outs)


def _log_softmax(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    e = np.exp(x - m)
    return (x - m) - np.log(e.sum(axis=-1, keepdims=True))


def _as_log_probs(x: np.ndarray,
                  probability: Optional[bool] = None) -> np.ndarray:
    """Model outputs may be PROBABILITIES (the framework convention: CE
    models end in softmax/sigmoid) or raw logits (imported heads).
    log-softmax of probabilities is NOT log(p) — it flattens every gap to
    <1 nat and corrupts beam accumulation. The caller passes the answer
    from the graph's tail op (model.output_probability_like()); the
    numeric sniff (non-negative rows summing to ~1) is only the fallback
    for the undetermined case — bf16 softmax heads over large vocabs can
    drift past its tolerance, so the structural answer wins."""
    if probability is None:
        probability = bool(
            (x >= 0).all() and np.allclose(x.sum(axis=-1), 1.0, atol=1e-3)
        )
    if probability:
        return np.log(np.clip(x, 1e-30, None))
    return _log_softmax(x)


def _beam_topk(scores, logp, done, pad_token_id, num_beams):
    """One beam-search selection step, shared by beam_generate and
    incremental_beam_generate: finished beams propagate unchanged via a
    single pad candidate; top-k via argpartition (O(n), no full sort)."""
    vocab = logp.shape[-1]
    cand = scores[:, None] + np.where(done[:, None], -np.inf, logp)
    for b in np.nonzero(done)[0]:
        cand[b, pad_token_id] = scores[b]
    flat = np.argpartition(cand.ravel(), -num_beams)[-num_beams:]
    flat = flat[np.argsort(cand.ravel()[flat])[::-1]]
    return flat // vocab, flat % vocab, cand.ravel()[flat]


def beam_generate(
    model,
    encoder_ids: np.ndarray,
    *,
    num_beams: int = 4,
    max_new_tokens: Optional[int] = None,
    start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
) -> np.ndarray:
    """Beam-search decode over the same compiled forward as greedy_generate
    (scores are sum of per-token log-probs; no length penalty). Each step
    runs the beams of ONE sample as a batch-shaped forward, so the
    compiled batch size must be >= num_beams; samples decode sequentially.
    num_beams=1 degenerates to greedy."""
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    fwd = model.executor.build_forward()
    enc_t, dec_t = model._fit_input_tensors[:2]
    bs, dec_len = dec_t.dims[0], dec_t.dims[1]
    if num_beams > bs:
        raise ServingConfigError(
            f"num_beams {num_beams} > compiled batch {bs}; recompile with a "
            "larger batch"
        )
    if tuple(encoder_ids.shape[1:]) != tuple(enc_t.dims[1:]):
        raise ServingConfigError(
            f"encoder_ids row shape {tuple(encoder_ids.shape[1:])} != "
            f"compiled {tuple(enc_t.dims[1:])}"
        )
    want = dec_len - 1 if max_new_tokens is None else max_new_tokens
    steps = min(want, dec_len - 1)
    n_rows = encoder_ids.shape[0]
    if steps <= 0:
        return np.full((n_rows, 1), start_token_id, dec_t.data_type.np_dtype)
    prob_hint = model.output_probability_like()

    outs = []
    for row in np.asarray(encoder_ids, enc_t.data_type.np_dtype):
        # beams packed into the compiled batch; unused slots repeat beam 0
        enc = np.broadcast_to(row, (bs,) + row.shape).copy()
        beams = np.full((num_beams, dec_len), pad_token_id,
                        dec_t.data_type.np_dtype)
        beams[:, 0] = start_token_id
        scores = np.full(num_beams, -np.inf)
        scores[0] = 0.0  # all beams identical at t=0: keep one alive
        done = np.zeros(num_beams, bool)
        for t in range(steps):
            dec = np.full((bs, dec_len), pad_token_id, beams.dtype)
            dec[:num_beams] = beams
            logp = _as_log_probs(
                np.asarray(fwd(model.state.params, [enc, dec],
                               model.state.net_state))[:num_beams, t],
                prob_hint,
            )
            src, tok, scores = _beam_topk(scores, logp, done, pad_token_id,
                                          num_beams)
            beams = beams[src]
            beams[:, t + 1] = tok
            done = done[src]
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
                if done.all():
                    break
        # fixed width for every sample (early-stopped rows carry pad after
        # EOS) so the batch stacks even when samples finish at different t
        outs.append(beams[int(np.argmax(scores)), : steps + 1])
    return np.stack(outs, axis=0)


class InferenceRequest:
    def __init__(self, inputs: List[np.ndarray]):
        self.id = uuid.uuid4().hex
        self.inputs = inputs
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class BatchScheduler:
    """Dynamic batcher (reference: triton/src/instance.cc lifecycle +
    per-request execution, re-thought as a batch queue).

    `max_delay_s`: how long to wait to fill a batch before running partial.

    Fault tolerance (runtime/resilience.py): `infer` raises a typed
    InferenceTimeout (retried under `retry_policy`) instead of asserting,
    and when the worker thread has died — crashed on a batch, or never
    started — falls back to DEGRADED mode, running the request unbatched
    on the caller's thread so the service keeps answering (slower, but
    up). A crashed worker is auto-restarted up to `max_worker_restarts`
    times with exponential backoff (`restart_backoff_s` base); once the
    budget is spent the scheduler stays degraded until the operator
    intervenes. Restart counts surface in `stats["worker_restarts"]`.
    `fault_injector` site ``serving_worker`` kills the worker
    deterministically in tests."""

    def __init__(self, model, *, max_delay_s: float = 0.005,
                 retry_policy=None, fault_injector=None,
                 max_worker_restarts: int = 3,
                 restart_backoff_s: float = 0.25):
        if model.executor is None:
            raise NotCompiledError("compile() the model first")
        from .resilience import RetryPolicy

        self.model = model
        self.batch_size = model.executor.input_pts[0].material_shape()[0]
        self.max_delay_s = max_delay_s
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, base_delay_s=0.01, max_delay_s=0.5
        )
        self.fault_injector = fault_injector
        self.max_worker_restarts = max(0, max_worker_restarts)
        self.restart_backoff_s = restart_backoff_s
        self._q: "queue.Queue[InferenceRequest]" = queue.Queue()
        self._fwd = model.executor.build_forward()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._started = False
        self._worker_error: Optional[BaseException] = None
        self._restart_lock = threading.Lock()
        self._next_restart_t = 0.0
        self.stats = {"requests": 0, "batches": 0, "padded_slots": 0,
                      "degraded": 0, "timeouts": 0, "worker_restarts": 0}

    # -- client API ------------------------------------------------------
    def start(self):
        if not self._started:
            self._worker.start()
            self._started = True
        return self

    def stop(self):
        self._stop.set()
        if self._started:
            self._worker.join(timeout=5)

    def worker_alive(self) -> bool:
        return (self._started and self._worker.is_alive()
                and self._worker_error is None)

    def _maybe_restart_worker(self) -> bool:
        """Bounded auto-restart after a worker crash: spawn a fresh worker
        thread once the backoff window has elapsed, at most
        `max_worker_restarts` times. Returns True when a live worker is
        available (already alive, or just restarted); False keeps the
        caller on the degraded path."""
        if self.worker_alive():
            return True
        if not self._started or self._stop.is_set():
            return False
        with self._restart_lock:
            if self.worker_alive():  # another caller beat us to it
                return True
            if self.stats["worker_restarts"] >= self.max_worker_restarts:
                return False  # budget spent: stay degraded
            if time.monotonic() < self._next_restart_t:
                return False  # still backing off: degraded for now
            self.stats["worker_restarts"] += 1
            from .. import obs

            obs.count("ff_serving_worker_restarts_total",
                      help="serving worker threads restarted after crash")
            obs.event("serving_worker_restart", cat="serving",
                      restarts=self.stats["worker_restarts"])
            self._worker_error = None
            self._worker = threading.Thread(target=self._loop, daemon=True)
            self._worker.start()
            return True

    def submit(self, inputs: List[np.ndarray]) -> InferenceRequest:
        """Each request carries ONE sample per model input (no batch dim)."""
        req = InferenceRequest([np.asarray(a) for a in inputs])
        self._q.put(req)
        return req

    def infer(self, inputs: List[np.ndarray], timeout: float = 30.0) -> np.ndarray:
        """Blocking single-sample inference. Timeouts raise
        InferenceTimeout and are retried per `self.retry_policy`; a dead
        worker degrades to direct unbatched execution instead of hanging
        every caller until restart."""
        from .. import obs
        from .resilience import InferenceTimeout, retry

        t_start = time.perf_counter()

        def attempt():
            if not self._maybe_restart_worker():
                return self._infer_direct(inputs)
            req = self.submit(inputs)
            if not req.event.wait(timeout):
                self.stats["timeouts"] += 1
                if not self.worker_alive():
                    # died while we waited — the request will never be
                    # answered from the queue
                    return self._infer_direct(inputs)
                raise InferenceTimeout(
                    f"request {req.id} unanswered after {timeout}s "
                    f"(queue depth {self._q.qsize()})"
                )
            if req.error is not None:
                # the worker failed ON this batch; answer from the
                # degraded path rather than bubbling its crash to callers
                return self._infer_direct(inputs)
            return req.result

        try:
            out = retry(attempt, self.retry_policy)
        except BaseException:
            obs.count("ff_serving_errors_total",
                      help="serving requests that failed after retries")
            raise
        # latency percentiles ride the histogram's reservoir
        # (metrics.prom buckets + p50/p95/p99 in metrics.jsonl)
        obs.observe("ff_serving_latency_seconds",
                    time.perf_counter() - t_start,
                    help="end-to-end serving request latency")
        obs.count("ff_serving_requests_total",
                  help="serving requests answered")
        return out

    def _infer_direct(self, inputs: List[np.ndarray]) -> np.ndarray:
        """DEGRADED mode: run one request on the caller's thread, padded
        to the compiled batch (same jitted executable, no queue)."""
        self.stats["degraded"] += 1
        arrays = [
            jnp.asarray(np.broadcast_to(
                np.asarray(a)[None], (self.batch_size,) + np.asarray(a).shape
            ))
            for a in inputs
        ]
        out = np.asarray(self._fwd(self.model.state.params, arrays,
                                   self.model.state.net_state))
        return out[0]

    # -- batching loop ---------------------------------------------------
    def _loop(self):
        import jax.numpy as jnp

        n_inputs = len(self.model.executor.input_pts)
        while not self._stop.is_set():
            batch: List[InferenceRequest] = []
            try:
                batch.append(self._q.get(timeout=0.05))
            except queue.Empty:
                continue
            deadline = time.monotonic() + self.max_delay_s
            while len(batch) < self.batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                if self.fault_injector is not None:
                    self.fault_injector.fire("serving_worker",
                                             self.stats["batches"])
                pad = self.batch_size - len(batch)
                arrays = []
                for i in range(n_inputs):
                    rows = [r.inputs[i] for r in batch]
                    stacked = np.stack(rows + [rows[-1]] * pad, axis=0)
                    arrays.append(jnp.asarray(stacked))
                out = np.asarray(self._fwd(self.model.state.params, arrays,
                                           self.model.state.net_state))
            except BaseException as e:
                # worker is no longer trustworthy: fail the in-flight
                # requests (their callers re-run degraded) and exit so
                # worker_alive() routes future traffic around the queue
                # until _maybe_restart_worker's backoff window opens
                self._worker_error = e
                self._next_restart_t = time.monotonic() + (
                    self.restart_backoff_s
                    * (2.0 ** self.stats["worker_restarts"])
                )
                for r in batch:
                    r.error = e
                    r.event.set()
                return
            for j, r in enumerate(batch):
                r.result = out[j]
                r.event.set()
            self.stats["requests"] += len(batch)
            self.stats["batches"] += 1
            self.stats["padded_slots"] += pad
