"""Shim: reference python/flexflow/torch/nn/modules/__init__.py"""
from .module import Module  # noqa: F401
