"""Transformer encoder benchmark — the reference OSDI'22 headline config
(reference: examples/cpp/Transformer/transformer.cc; scripts/osdi22ae/bert.sh:
batch 8, hidden 1024, 16 heads, 12 layers, seq 512).

Usage:
  python examples/python/transformer.py -b 8                 # data parallel
  python examples/python/transformer.py -b 8 --budget 20     # Unity search
"""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.transformer import build_transformer


def main():
    ffconfig = FFConfig()
    model = FFModel(ffconfig)
    build_transformer(
        model,
        batch_size=ffconfig.batch_size,
        seq_length=512,
        hidden_size=1024,
        num_heads=16,
        num_layers=12,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    n = ffconfig.batch_size * max(1, ffconfig.iterations)
    rng = np.random.RandomState(0)
    x = rng.randn(n, 512, 1024).astype(np.float32)
    y = rng.randn(n, 512, 1024).astype(np.float32)
    model.fit(x, y, epochs=ffconfig.epochs)


if __name__ == "__main__":
    main()
