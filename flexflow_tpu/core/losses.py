"""Loss functions.

TPU-native equivalents of reference src/loss_functions/ (214 cc + 141 cu):
categorical CE, sparse categorical CE, MSE (avg/sum reduce), identity. The
reference hand-writes logit-gradient kernels (LOSS_BWD_TASK); here each loss
is a scalar-valued jnp function and jax.grad produces the same gradients
(including the reference's scale factor handling for replicas, which is
subsumed by mean-reduction over the global batch).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ff_types import LossType


def categorical_crossentropy(logits_or_probs, labels):
    """Labels are one-hot/probabilities (reference: loss expects label tensor
    matching logit shape). The final Softmax op produces probs, so we take
    log of probs like the reference's CE-from-softmax backward."""
    p = jnp.clip(logits_or_probs.astype(jnp.float32), 1e-12, 1.0)
    return -jnp.mean(jnp.sum(labels.astype(jnp.float32) * jnp.log(p), axis=-1))


def sparse_categorical_crossentropy(probs, labels):
    """Labels are int class ids with shape (..., 1) or (...)."""
    if labels.ndim == probs.ndim:
        labels = labels[..., 0]
    p = jnp.clip(probs.astype(jnp.float32), 1e-12, 1.0)
    logp = jnp.log(p)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)
    return jnp.mean(nll)


def mean_squared_error_avg(preds, labels):
    d = preds.astype(jnp.float32) - labels.astype(jnp.float32)
    return jnp.mean(jnp.sum(d * d, axis=-1))


def mean_squared_error_sum(preds, labels):
    d = preds.astype(jnp.float32) - labels.astype(jnp.float32)
    return jnp.sum(d * d)


def identity_loss(preds, labels):
    """reference: LOSS_IDENTITY — the model output *is* the loss."""
    return jnp.mean(preds.astype(jnp.float32))


_LOSS_FNS = {
    LossType.LOSS_CATEGORICAL_CROSSENTROPY: categorical_crossentropy,
    LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY: sparse_categorical_crossentropy,
    LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE: mean_squared_error_avg,
    LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE: mean_squared_error_sum,
    LossType.LOSS_IDENTITY: identity_loss,
}

_BY_NAME = {
    "categorical_crossentropy": LossType.LOSS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mean_squared_error_sum": LossType.LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE,
    "identity": LossType.LOSS_IDENTITY,
}


def to_loss_type(spec) -> LossType:
    if isinstance(spec, LossType):
        return spec
    return _BY_NAME[spec]


def get_loss_fn(loss_type) -> callable:
    return _LOSS_FNS[to_loss_type(loss_type)]
