"""CIFAR-10: a Sequential feature extractor concatenated with a functional
branch (reference: examples/python/keras/func_cifar10_cnn_concat_seq_model.py)."""
from flexflow.keras.models import Model, Sequential
from flexflow.keras.layers import (
    Input, Conv2D, MaxPooling2D, Flatten, Dense, Activation, Concatenate)
import flexflow.keras.optimizers

from accuracy import ModelAccuracy
from _cifar import load_cifar
from _example_args import example_args, verify_callbacks


def top_level_task(args):
    num_classes = 10
    x_train, y_train = load_cifar(args.num_samples)

    seq = Sequential([
        Conv2D(filters=32, input_shape=(3, 32, 32), kernel_size=(3, 3),
               strides=(1, 1), padding=(1, 1), activation="relu"),
        MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid"),
        Flatten(),
    ])

    in2 = Input(shape=(3, 32, 32))
    f2 = Flatten()(Conv2D(filters=32, kernel_size=(3, 3), strides=(1, 1),
                          padding=(1, 1), activation="relu")(in2))

    merged = Concatenate(axis=1)([seq.outputs[0], f2])
    x = Dense(512, activation="relu")(merged)
    out = Activation("softmax")(Dense(num_classes)(x))

    model = Model([seq.inputs[0], in2], out)
    opt = flexflow.keras.optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"],
                  batch_size=args.batch_size)
    model.fit([x_train, x_train], y_train, epochs=args.epochs,
              callbacks=verify_callbacks(args, ModelAccuracy.CIFAR10_CNN))


if __name__ == "__main__":
    print("Functional API, cifar10 cnn concat seq model")
    top_level_task(example_args())
