"""Keras frontend tests (reference: examples/python/keras mnist_mlp/cnn
patterns + keras callbacks)."""
import numpy as np
import pytest

from flexflow_tpu.frontends import keras


def synth(n, shape, classes, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, *shape).astype(np.float32)
    w = rng.randn(int(np.prod(shape)), classes).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, 1).astype(np.int32)[:, None]
    return x, y


def test_functional_mlp():
    inp = keras.Input(shape=(16,))
    t = keras.Dense(64, activation="relu")(inp)
    t = keras.Dense(4, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=t)
    model.compile(optimizer=keras.SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=32)
    x, y = synth(256, (16,), 4)
    model.fit(x, y, batch_size=32, epochs=8, verbose=False)
    pm = model.evaluate(x, y, batch_size=32)
    assert pm.get_accuracy() > 40.0


def test_sequential_cnn():
    model = keras.Sequential()
    model.add(keras.Input(shape=(1, 8, 8)))
    model.add(keras.Conv2D(4, 3, padding="same", activation="relu"))
    model.add(keras.MaxPooling2D(2))
    model.add(keras.Flatten())
    model.add(keras.Dense(3, activation="softmax"))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=16)
    x, y = synth(64, (1, 8, 8), 3)
    pm = model.fit(x, y, batch_size=16, epochs=2, verbose=False)
    assert pm.train_all == 64


def test_merge_and_callbacks():
    calls = []
    inp = keras.Input(shape=(8,))
    a = keras.Dense(8)(inp)
    b = keras.Dense(8)(inp)
    t = keras.Add()([a, b])
    t = keras.Dense(2, activation="softmax")(t)
    model = keras.Model(inputs=inp, outputs=t)
    model.compile(optimizer=keras.Adam(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=16)
    cb = keras.callbacks.LambdaCallback(
        on_epoch_end=lambda e, logs: calls.append(e))
    x, y = synth(64, (8,), 2)
    model.fit(x, y, batch_size=16, epochs=3, verbose=False, callbacks=[cb])
    assert calls == [0, 1, 2]


def test_get_set_weights():
    inp = keras.Input(shape=(4,))
    layer = keras.Dense(3)
    t = layer(inp)
    model = keras.Model(inputs=inp, outputs=t)
    model.compile(optimizer="sgd", loss="mse",
                  metrics=[], batch_size=8)
    w = layer.get_weights()
    assert w[0].shape == (4, 3)
    layer.set_weights([np.ones((4, 3), np.float32), np.zeros(3, np.float32)])
    np.testing.assert_allclose(layer.get_weights()[0], 1.0)
