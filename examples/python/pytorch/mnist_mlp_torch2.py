"""Train the MNIST MLP via a LIVE torch.fx trace (reference:
examples/python/pytorch/mnist_mlp_torch2.py — PyTorchModel(mod).torch_to_ff,
weights carried over from the torch module)."""
from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import mnist
from flexflow.torch.model import PyTorchModel

from _example_args import example_args
from mnist_mlp_torch import MLP


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    input_tensor = ffmodel.create_tensor([args.batch_size, 784], DataType.DT_FLOAT)

    torch_model = PyTorchModel(MLP())
    output_tensors = torch_model.torch_to_ff(ffmodel, [input_tensor])

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY])

    (x_train, y_train), _ = mnist.load_data(n_train=args.num_samples)
    x_train = x_train.reshape(-1, 784).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    ffmodel.fit(x=x_train, y=y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("mnist mlp torch2 (live trace)")
    top_level_task(example_args())
