"""Persistent strategy/artifact store: fleet cold-start as a cache lookup.

ROADMAP item 4: every replica boot, warm-spare build and elastic 8->4
failover re-runs the Unity search from scratch, so fleet recovery time is
bounded by the search budget rather than checkpoint restore. This module
keys searched strategies by the three fingerprints that already exist —

  * **graph**        — the pre-search lowering's identity
                       (``graph_fingerprint``: op names/types/shapes in
                       topological order),
  * **topology**     — ``elastic.topology_fingerprint`` of the machine the
                       strategy was searched for,
  * **calibration**  — the resolved CalibrationStore content
                       (``calibration_fingerprint``; a re-measured machine
                       legitimately changes what the search would find),

— and stores, per key: the winning strategy (strategy_io records + the
mesh axes it lowers onto), provenance, and the StrategyTuner's quarantine
fingerprints (previously in-memory only, lost on restart). Serialized XLA
executables ride through JAX's own persistent compilation cache where the
backend supports it (``enable_jax_compilation_cache``); on backends where
deserialized executables are unsafe (CPU: donated-buffer aliasing breaks
on jax 0.4.x) the store stays strategy-only — skipping the *search* is
the long pole either way.

Robustness is the design center:

  * every entry is written tmp-then-``os.replace`` (crash-atomic) with a
    schema version and a crc32 over the canonical payload bytes;
  * a truncated/bit-flipped/unparseable entry raises the typed
    :class:`ArtifactCorruptionError` AFTER being moved into
    ``<root>/quarantine/`` and counted — consumers fall back to a fresh
    search, so a poisoned cache is never worse than no cache;
  * concurrent replicas racing to populate the same key serialize writes
    through an advisory ``fcntl`` file lock (best-effort no-op where the
    platform lacks fcntl);
  * retention is bounded: ``max_entries`` with LRU eviction (access time
    is refreshed on every hit);
  * FaultInjector sites ``artifact_corruption`` / ``artifact_stale``
    (runtime/resilience.py) force each degradation leg in chaos tests.

Observability: ``ff_artifact_cache_total{event=hit|miss|corrupt|stale|
put|evict}`` plus ``artifact_cache`` events (docs/artifact_cache.md).
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import threading
import zlib
from typing import Any, Dict, Iterable, List, Optional, Set

logger = logging.getLogger("flexflow_tpu.runtime.artifact_store")

# Bump when the on-disk entry envelope changes. Entries declaring a NEWER
# schema are treated as corrupt (we cannot guess fields we've never seen);
# older ones we keep reading.
SCHEMA_VERSION = 1

CACHE_METRIC = "ff_artifact_cache_total"
CACHE_METRIC_HELP = (
    "artifact-store lookups/updates by event "
    "(hit|miss|corrupt|stale|put|evict)"
)


class ArtifactCorruptionError(RuntimeError):
    """An artifact-store entry failed integrity validation (truncated,
    bit-flipped, unparseable, or written by a newer schema). The entry
    has already been quarantined and counted when this is raised —
    consumers fall back to a fresh search."""

    def __init__(self, msg: str, *, path: Optional[str] = None):
        super().__init__(msg)
        self.path = path


# ----------------------------------------------------------------------
# fingerprints
# ----------------------------------------------------------------------
def graph_fingerprint(graph) -> str:
    """Stable identity of a lowered (pre-search) PCG: op names, types
    and output shapes/dtypes in topological order. Machine views and
    parallel degrees are deliberately EXCLUDED — the fingerprint
    identifies the problem the search solved, not its answer, so a
    fresh lowering of the same model hits entries written by any prior
    winner for it. Layer guids are excluded too: they come off a
    process-global counter (a rebuilt model_fn's second instance would
    never hit), while op names are per-model stable and are what replay
    matches by."""
    lines = []
    for op in graph.topo_order():
        outs = ",".join(
            f"{tuple(t.material_shape())}:{t.data_type.name}"
            for t in op.outputs
        )
        lines.append(f"{op.name}|{op.op_type.name}|{outs}")
    return hashlib.sha1("\n".join(lines).encode()).hexdigest()[:16]


def topology_digest(fp: Optional[dict]) -> str:
    """Collapse an ``elastic.topology_fingerprint`` dict to a short
    stable digest (the full dict rides in the entry for mismatch
    rejection)."""
    blob = json.dumps(fp or {}, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def calibration_fingerprint(table: Optional[dict],
                            globals_: Optional[dict]) -> str:
    """Digest of the resolved calibration a compile searched under
    (per-op cost table + cost-model globals). 'none' when the analytic
    roofline stood — re-measuring the machine legitimately changes what
    the search would find, so it must change the cache key."""
    if not table and not globals_:
        return "none"
    blob = repr((sorted((table or {}).items(), key=lambda kv: repr(kv[0])),
                 sorted((globals_ or {}).items(), key=lambda kv: repr(kv[0]))))
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def make_key(*, graph: str, topology: str, calibration: str,
             objective: str = "train", num_devices: int = 0) -> Dict[str, Any]:
    """The composite cache key. ``num_devices`` rides separately from
    the topology digest so a shrunk jax.devices() view (elastic tests)
    and a genuinely different machine both miss cleanly."""
    return {
        "graph": graph,
        "topology": topology,
        "calibration": calibration,
        "objective": objective,
        "num_devices": int(num_devices),
    }


def key_id(key: Dict[str, Any]) -> str:
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:20]


def _canonical_payload_bytes(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()


# ----------------------------------------------------------------------
# ambient store (consumers that build models through opaque model_fns —
# ReplicaSet warm spares, autoscaler scale-up — wrap the build in
# `with store.ambient():` and compile() picks it up without plumbing)
# ----------------------------------------------------------------------
_ambient = threading.local()


def get_ambient() -> Optional["ArtifactStore"]:
    return getattr(_ambient, "store", None)


class ArtifactStore:
    """On-disk, versioned strategy/artifact store. See module docstring.

    Layout::

        <root>/.lock                    advisory writer lock
        <root>/entries/<key_id>.json    one integrity-enveloped entry
        <root>/quarantine/              corrupt/stale entries moved aside
        <root>/quarantine/<scope>.q.json  persisted tuner quarantines
        <root>/xla_cache/               JAX compilation cache (optional)
    """

    def __init__(self, root: str, *, max_entries: int = 64,
                 fault_injector=None, executable_cache: Optional[bool] = None):
        self.root = os.path.abspath(root)
        self.max_entries = max(1, int(max_entries))
        self.fault_injector = fault_injector
        self.counts: Dict[str, int] = {}
        self.entries_dir = os.path.join(self.root, "entries")
        self.quarantine_dir = os.path.join(self.root, "quarantine")
        os.makedirs(self.entries_dir, exist_ok=True)
        os.makedirs(self.quarantine_dir, exist_ok=True)
        self._clean_stale_tmp()
        # serialized-executable leg: JAX's persistent compilation cache,
        # gated per-backend (CPU deserialized executables mishandle
        # donated buffers on jax 0.4.x — see docs/artifact_cache.md), so
        # the default is auto-enable on TPU/GPU only
        self.executable_cache_enabled = False
        if executable_cache is None:
            executable_cache = self._backend_supports_executables()
        if executable_cache:
            self.executable_cache_enabled = \
                self.enable_jax_compilation_cache()

    # -- integrity envelope ---------------------------------------------
    def _entry_path(self, key: Dict[str, Any]) -> str:
        return os.path.join(self.entries_dir, key_id(key) + ".json")

    def _clean_stale_tmp(self) -> None:
        for d in (self.entries_dir, self.quarantine_dir):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if ".tmp-" in name:
                    try:
                        os.remove(os.path.join(d, name))
                    except OSError:
                        pass

    @contextlib.contextmanager
    def _locked(self):
        """Advisory writer lock so replicas racing to populate the same
        key never interleave a write with an eviction. Platforms without
        fcntl (or read-only stores) degrade to best-effort: writes stay
        individually atomic via os.replace either way."""
        lock_path = os.path.join(self.root, ".lock")
        fd = None
        try:
            try:
                import fcntl

                fd = os.open(lock_path, os.O_RDWR | os.O_CREAT, 0o644)
                fcntl.flock(fd, fcntl.LOCK_EX)
            except (ImportError, OSError):
                fd = None
            yield
        finally:
            if fd is not None:
                try:
                    import fcntl

                    fcntl.flock(fd, fcntl.LOCK_UN)
                except (ImportError, OSError):
                    pass
                os.close(fd)

    def _count(self, event: str, **extra) -> None:
        from .. import obs

        # local mirror of the counter: harnesses (scripts/load_check.py)
        # read hit/corrupt counts without needing a telemetry session
        self.counts[event] = self.counts.get(event, 0) + 1
        obs.count(CACHE_METRIC, help=CACHE_METRIC_HELP, event=event)
        obs.event("artifact_cache", cat="runtime", event=event, **extra)

    def _quarantine_file(self, path: str, reason: str) -> None:
        """Move a bad entry aside so it can never poison another lookup;
        keep the bytes for postmortem rather than deleting evidence."""
        if not os.path.exists(path):
            return
        dest = os.path.join(
            self.quarantine_dir,
            f"{os.path.basename(path)}.{reason}-{os.getpid()}",
        )
        try:
            os.replace(path, dest)
        except OSError:
            try:
                os.remove(path)
            except OSError:
                pass

    def _read_entry(self, path: str, key: Dict[str, Any]) -> dict:
        """Parse + integrity-check one entry file. Raises
        ArtifactCorruptionError (envelope broken) or returns the payload
        dict; a key mismatch raises _StaleEntry for the caller to count."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
            envelope = json.loads(raw.decode("utf-8"))
        except (OSError, ValueError, UnicodeDecodeError) as e:
            raise ArtifactCorruptionError(
                f"artifact entry {path} is unreadable: {e}", path=path
            ) from e
        if not isinstance(envelope, dict):
            raise ArtifactCorruptionError(
                f"artifact entry {path} is not an object", path=path
            )
        schema = envelope.get("schema")
        if not isinstance(schema, int) or schema > SCHEMA_VERSION:
            raise ArtifactCorruptionError(
                f"artifact entry {path} declares schema {schema!r} "
                f"(supported <= {SCHEMA_VERSION})", path=path
            )
        payload = envelope.get("payload")
        if not isinstance(payload, dict):
            raise ArtifactCorruptionError(
                f"artifact entry {path} has no payload object", path=path
            )
        crc = zlib.crc32(_canonical_payload_bytes(payload)) & 0xFFFFFFFF
        if crc != envelope.get("crc32"):
            raise ArtifactCorruptionError(
                f"artifact entry {path} failed crc32 "
                f"({envelope.get('crc32')!r} recorded, {crc} computed) — "
                "truncated or bit-flipped on disk", path=path
            )
        if envelope.get("key") != key:
            raise _StaleEntry(
                f"artifact entry {path} was written for a different key "
                f"({envelope.get('key')!r} != {key!r})"
            )
        return payload

    # -- lookup / store --------------------------------------------------
    def get(self, key: Dict[str, Any]) -> Optional[dict]:
        """The payload stored under `key`, or None on a (counted) miss.
        Corrupt entries are quarantined, counted and raised as
        ArtifactCorruptionError; fingerprint-mismatched ones are
        quarantined, counted as stale and returned as a miss. A hit
        refreshes the entry's LRU access time."""
        path = self._entry_path(key)
        fi = self.fault_injector
        if fi is not None and os.path.exists(path):
            if fi.fire("artifact_stale", None) is not None:
                self._quarantine_file(path, "stale")
                self._count("stale", key=key_id(key), injected=True)
                return None
            if fi.fire("artifact_corruption", None) is not None:
                self._quarantine_file(path, "corrupt")
                self._count("corrupt", key=key_id(key), injected=True)
                raise ArtifactCorruptionError(
                    f"artifact entry {path}: injected corruption "
                    "(FaultInjector site artifact_corruption)", path=path,
                )
        if not os.path.exists(path):
            self._count("miss", key=key_id(key))
            return None
        try:
            payload = self._read_entry(path, key)
        except _StaleEntry as e:
            logger.warning("%s", e)
            self._quarantine_file(path, "stale")
            self._count("stale", key=key_id(key), detail=str(e)[:300])
            return None
        except ArtifactCorruptionError as e:
            logger.warning("artifact store: quarantining corrupt entry: %s",
                           e)
            self._quarantine_file(path, "corrupt")
            self._count("corrupt", key=key_id(key), detail=str(e)[:300])
            raise
        try:
            os.utime(path)  # LRU access time
        except OSError:
            pass
        self._count("hit", key=key_id(key))
        return payload

    def put(self, key: Dict[str, Any], payload: dict) -> str:
        """Atomically write `payload` under `key` (last writer wins — both
        racers computed a valid strategy for the same key) and evict past
        ``max_entries``, LRU-first."""
        path = self._entry_path(key)
        envelope = {
            "schema": SCHEMA_VERSION,
            "key": key,
            "crc32": zlib.crc32(_canonical_payload_bytes(payload))
            & 0xFFFFFFFF,
            "payload": payload,
        }
        with self._locked():
            tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
            with open(tmp, "w") as f:
                json.dump(envelope, f, indent=1)
            os.replace(tmp, path)
            self._evict_locked()
        self._count("put", key=key_id(key))
        return path

    def note_stale(self, key: Dict[str, Any], reason: str) -> None:
        """A consumer found the entry inapplicable on replay (records
        matched no op, validators failed, mesh axes don't fit): count it
        and quarantine the entry so the next boot goes straight to a
        fresh search instead of re-tripping the same fallback."""
        path = self._entry_path(key)
        self._quarantine_file(path, "stale")
        self._count("stale", key=key_id(key), detail=reason[:300])

    def entries(self) -> List[str]:
        try:
            return sorted(
                n for n in os.listdir(self.entries_dir)
                if n.endswith(".json") and ".tmp-" not in n
            )
        except OSError:
            return []

    def _evict_locked(self) -> None:
        names = self.entries()
        if len(names) <= self.max_entries:
            return
        by_age = []
        for n in names:
            p = os.path.join(self.entries_dir, n)
            try:
                by_age.append((os.path.getmtime(p), p))
            except OSError:
                continue
        by_age.sort()
        for _, p in by_age[: max(0, len(by_age) - self.max_entries)]:
            try:
                os.remove(p)
            except OSError:
                continue
            self._count("evict", entry=os.path.basename(p))

    # -- tuner quarantine persistence ------------------------------------
    def _quarantine_set_path(self, scope: str) -> str:
        return os.path.join(self.quarantine_dir, f"{scope}.q.json")

    def load_quarantine(self, scope: str) -> Set[str]:
        """The persisted strategy-fingerprint quarantine set for `scope`
        (graph+topology digest). A corrupt quarantine file degrades to
        the empty set (counted) — losing quarantines re-proposes a bad
        candidate, which the tuner's own gates then re-reject; crashing
        here would lose the whole run."""
        path = self._quarantine_set_path(scope)
        if not os.path.exists(path):
            return set()
        try:
            payload = self._read_entry(path, {"quarantine_scope": scope})
            fps = payload.get("fingerprints", [])
            return {fp for fp in fps if isinstance(fp, str)}
        except (_StaleEntry, ArtifactCorruptionError) as e:
            logger.warning(
                "artifact store: quarantine set %s unreadable (%s); "
                "starting empty", path, e,
            )
            self._quarantine_file(path, "corrupt")
            self._count("corrupt", scope=scope, kind="quarantine_set")
            return set()

    def add_quarantine(self, scope: str, fingerprints: Iterable[str]) -> None:
        """Merge `fingerprints` into the persisted set for `scope`
        (read-merge-write under the writer lock, so two replicas
        quarantining concurrently lose nothing)."""
        with self._locked():
            merged = self.load_quarantine(scope) | set(fingerprints)
            payload = {"fingerprints": sorted(merged)}
            envelope = {
                "schema": SCHEMA_VERSION,
                "key": {"quarantine_scope": scope},
                "crc32": zlib.crc32(_canonical_payload_bytes(payload))
                & 0xFFFFFFFF,
                "payload": payload,
            }
            path = self._quarantine_set_path(scope)
            tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
            with open(tmp, "w") as f:
                json.dump(envelope, f, indent=1)
            os.replace(tmp, path)

    # -- consumer plumbing ----------------------------------------------
    @contextlib.contextmanager
    def ambient(self):
        """Make this store the process-ambient one for the duration:
        compile() calls with no explicit ``artifact_store=`` pick it up.
        How ReplicaSet routes opaque model_fns through the store."""
        prev = getattr(_ambient, "store", None)
        _ambient.store = self
        try:
            yield self
        finally:
            _ambient.store = prev

    # -- serialized executables (per-backend) ----------------------------
    @staticmethod
    def _backend_supports_executables() -> bool:
        """Deserialized XLA executables are only trusted off-CPU: on CPU
        (jax 0.4.x) a compilation-cache-restored executable mishandles
        donated-buffer aliasing (runtime/checkpoint.py records the same
        hazard for zero-copy views), so CPU stays strategy-only."""
        try:
            import jax

            return jax.default_backend() not in ("cpu",)
        except Exception:
            return False

    def enable_jax_compilation_cache(self) -> bool:
        """Point JAX's persistent compilation cache into this store so
        recompiles of a cached strategy also skip XLA compilation where
        the backend supports it. Returns whether it took effect."""
        cache_dir = os.path.join(self.root, "xla_cache")
        try:
            os.makedirs(cache_dir, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            return True
        except Exception as e:  # older jax / unsupported backend
            logger.info(
                "artifact store: JAX compilation cache unavailable (%r); "
                "staying strategy-only", e,
            )
            return False


class _StaleEntry(ValueError):
    """Internal: entry envelope is intact but keyed for something else."""


# ----------------------------------------------------------------------
# strategy payloads (the compile()/tuner write-through format)
# ----------------------------------------------------------------------
# Bump when the payload's graph serialization changes. A replay only
# accepts its own version: the payload is a FULL post-search PCG (nodes,
# edges, per-dim sharding state), so a field we didn't write can't be
# guessed and a field we no longer read can't be trusted. Version
# mismatch degrades to stale -> fresh search, never to a wrong replay.
# v4: output records carry compute_dtype/accum_dtype (precision-flow
# annotations, analysis/precision.py) so a cache hit replays with the
# byte accounting and verify tolerances it was searched under.
STRATEGY_PAYLOAD_SCHEMA = 4


def _dim_to_json(d) -> list:
    return [int(d.size), int(d.degree), int(d.parallel_idx),
            1 if d.is_replica_dim else 0, getattr(d, "axis_tag", None)]


def _dim_from_json(rec):
    from ..pcg.parallel_tensor import ParallelDim

    size, degree, pidx, replica, tag = rec
    return ParallelDim(size=int(size), degree=int(degree),
                       parallel_idx=int(pidx),
                       is_replica_dim=bool(replica), axis_tag=tag)


def _param_classes() -> dict:
    from ..parallel.parallel_ops import (
        AllToAllParams,
        CombineParams,
        FusedParallelOpParams,
        ReductionParams,
        RepartitionParams,
        ReplicateParams,
    )
    from ..parallel.weight_sharding import WeightShardParams

    return {
        cls.__name__: cls
        for cls in (RepartitionParams, CombineParams, ReplicateParams,
                    ReductionParams, AllToAllParams, FusedParallelOpParams,
                    WeightShardParams)
    }


def _params_to_json(params) -> Optional[dict]:
    """Serialize a parallel op's frozen params dataclass. Returns None
    when the class isn't in the known parallel-params vocabulary — the
    caller then refuses to serialize the graph (a constructible replay
    needs every inserted op's params)."""
    import dataclasses

    classes = _param_classes()
    cls = type(params).__name__
    if cls not in classes:
        return None
    fields = {}
    for f in dataclasses.fields(params):
        v = getattr(params, f.name)
        if f.name == "stages":  # FusedParallelOpParams: nested records
            v = [_params_to_json(s) for s in v]
            if any(s is None for s in v):
                return None
        fields[f.name] = v
    return {"cls": cls, "fields": fields}


def _params_from_json(rec: dict):
    from .strategy_io import StrategyImportError

    classes = _param_classes()
    cls = classes.get(rec.get("cls"))
    if cls is None:
        raise StrategyImportError(
            f"stored parallel op has unknown params class {rec.get('cls')!r}"
        )
    fields = dict(rec.get("fields") or {})
    if "stages" in fields:
        fields["stages"] = tuple(
            _params_from_json(s) for s in fields["stages"]
        )
    return cls(**fields)


def strategy_payload(graph, views: Optional[dict], *, cost=None,
                     mesh_axes: Dict[str, int],
                     provenance: Optional[dict] = None) -> dict:
    """Serialize a searched winner as a store payload: the FULL
    post-search PCG — every node (including search-inserted
    Repartition/Combine/Reduction/WeightShard ops and their params),
    its edges, and per-dim sharding state (degree, mesh-axis index,
    replica flag, axis tag) — plus the mesh axes the winner lowered
    onto, so a hit rebuilds the exact searched graph and mesh without
    re-deriving anything.

    Op records alone are NOT enough: the search inserts resharding ops
    and retensors outputs (partial-sum replica dims), and the lowering
    maps dims to mesh axes through parallel_idx — replaying just
    name-matched degrees onto a fresh lowering loses all three and
    either fails validation or silently lowers replicated.

    Raises ValueError when the graph isn't serializable (an inserted op
    with params outside the known vocabulary) — callers treat the write
    as best-effort."""
    views = views or {}
    topo = graph.topo_order()
    inputs = graph.input_tensors()
    input_pos = {t.guid: i for i, t in enumerate(inputs)}
    out_ref = {}  # tensor guid -> ("node", producer name, output index)
    for op in topo:
        for i, t in enumerate(op.outputs):
            out_ref[t.guid] = ["node", op.name, i]
    nodes = []
    for op in topo:
        refs = []
        for t in op.inputs:
            if t.guid in out_ref:
                refs.append(out_ref[t.guid])
            elif t.guid in input_pos:
                refs.append(["input", input_pos[t.guid], 0])
            else:
                raise ValueError(
                    f"op {op.name!r} consumes a tensor that is neither a "
                    "graph input nor another op's output"
                )
        params = None
        if op.is_parallel_op:
            params = _params_to_json(op.params)
            if params is None:
                raise ValueError(
                    f"parallel op {op.name!r} carries unserializable "
                    f"params {type(op.params).__name__}"
                )
        view = views.get(op.guid) or getattr(op, "machine_view", None)
        nodes.append({
            "name": op.name,
            "op_type": op.op_type.name,
            "params": params,
            "inputs": refs,
            "outputs": [
                {"dtype": t.data_type.name,
                 "compute_dtype": (t.compute_dtype.name
                                   if t.compute_dtype is not None else None),
                 "accum_dtype": (t.accum_dtype.name
                                 if t.accum_dtype is not None else None),
                 "dims": [_dim_to_json(d) for d in t.dims]}
                for t in op.outputs
            ],
            "weights": [[_dim_to_json(d) for d in w.dims]
                        for w in op.weights],
            "machine_view": (
                {"start_device_id": view.start_device_id,
                 "dim": list(view.dim), "stride": list(view.stride)}
                if view is not None else None
            ),
        })
    return {
        "kind": "strategy",
        "strategy_schema": STRATEGY_PAYLOAD_SCHEMA,
        "cost": cost,
        "mesh_axes": {str(k): int(v) for k, v in (mesh_axes or {}).items()},
        "inputs": [[_dim_to_json(d) for d in t.dims] for t in inputs],
        "nodes": nodes,
        "provenance": provenance or {},
    }


def _check_degrees_feasible(name: str, dim_lists, num_devices: int) -> None:
    from .strategy_io import StrategyImportError

    for dims in dim_lists:
        prod = 1
        for d in dims:
            prod *= int(d[1])
        if prod > 1 and (prod > num_devices or num_devices % prod != 0):
            raise StrategyImportError(
                f"op {name!r}: degree product {prod} does not divide the "
                f"{num_devices} available devices"
            )


def replay_strategy(graph, payload: dict, *, num_devices: int):
    """Rebuild a stored winner around a freshly lowered PCG.

    Compute ops are reused from `graph` by name (they carry the weights,
    initializers and params a payload can't serialize); search-inserted
    parallel ops are reconstructed from their stored params; every
    tensor's sharding state (degrees, mesh-axis indices, replica dims,
    axis tags) and every machine view comes from the payload. Returns
    (rebuilt_graph, views_by_guid, mesh_axes, cost).

    Raises StrategyImportError when the entry cannot be applied soundly —
    wrong payload version, node set that doesn't cover the fresh
    lowering (the winner rewrote compute ops this model doesn't have),
    shapes that don't line up, degrees/views infeasible for the live
    machine, or a rebuilt graph that fails the structural validators.
    Callers treat all of those as a STALE entry and fall back to a fresh
    search; the fresh lowering may have been mutated by a partial replay
    and must be re-lowered. A structurally invalid strategy never
    reaches an executor."""
    from ..ff_types import DataType, OperatorType
    from ..pcg.graph import Graph
    from ..pcg.op import PCGOp
    from ..pcg.parallel_tensor import ParallelTensor
    from ..pcg.machine_view import MachineView
    from .strategy_io import StrategyImportError

    def _prec_of(name, srec):
        """Decode the stored precision annotations (None = unannotated)."""
        out = []
        for key in ("compute_dtype", "accum_dtype"):
            v = srec.get(key)
            if v is None:
                out.append(None)
                continue
            try:
                out.append(DataType[v])
            except KeyError:
                raise StrategyImportError(
                    f"op {name!r}: unknown {key} {v!r}"
                )
        return out

    if payload.get("kind") != "strategy":
        raise StrategyImportError(
            f"artifact payload kind {payload.get('kind')!r} is not a "
            "strategy"
        )
    schema = payload.get("strategy_schema")
    if schema != STRATEGY_PAYLOAD_SCHEMA:
        raise StrategyImportError(
            f"artifact strategy schema {schema!r} != supported "
            f"{STRATEGY_PAYLOAD_SCHEMA} — written by a different build"
        )
    mesh_axes = payload.get("mesh_axes") or {}
    prod = 1
    for v in mesh_axes.values():
        prod *= int(v)
    if prod < 1 or prod > num_devices:
        raise StrategyImportError(
            f"artifact mesh axes {mesh_axes} need {prod} devices, have "
            f"{num_devices}"
        )
    nodes = payload.get("nodes") or []
    if not nodes:
        raise StrategyImportError("artifact strategy carries no nodes")

    fresh_ops = {}
    for op in graph.ops:
        if op.name in fresh_ops:
            raise StrategyImportError(
                f"fresh lowering has duplicate op name {op.name!r}"
            )
        fresh_ops[op.name] = op
    stored_names = {n.get("name") for n in nodes}
    missing = sorted(set(fresh_ops) - stored_names)
    if missing:
        raise StrategyImportError(
            f"{len(missing)} fresh op(s) have no stored node (e.g. "
            f"{missing[:3]}) — the entry was written for a different model"
        )

    # graph inputs: match stored input slots to fresh input tensors by
    # ordinal, falling back to shape+dtype signature (parallel-op
    # insertion can reorder first-consumer positions)
    fresh_inputs = graph.input_tensors()
    stored_inputs = payload.get("inputs") or []
    if len(stored_inputs) != len(fresh_inputs):
        raise StrategyImportError(
            f"stored graph has {len(stored_inputs)} input(s), fresh "
            f"lowering has {len(fresh_inputs)}"
        )
    taken = [False] * len(fresh_inputs)
    input_map = {}
    for i, dims in enumerate(stored_inputs):
        sizes = [int(d[0]) for d in dims if not d[3]]
        cand = None
        if i < len(fresh_inputs) and not taken[i] and \
                [d.size for d in fresh_inputs[i].dims
                 if not d.is_replica_dim] == sizes:
            cand = i
        else:
            for j, t in enumerate(fresh_inputs):
                if not taken[j] and [d.size for d in t.dims
                                     if not d.is_replica_dim] == sizes:
                    cand = j
                    break
        if cand is None:
            raise StrategyImportError(
                f"stored graph input {i} (sizes {sizes}) matches no fresh "
                "input tensor"
            )
        taken[cand] = True
        t = fresh_inputs[cand]
        t.dims = [_dim_from_json(d) for d in dims]
        input_map[i] = t

    g2 = Graph()
    tensors = {}  # ("node", name, idx) -> ParallelTensor
    views = {}
    for node in nodes:
        name = node.get("name")
        try:
            resolved = []
            for kind, a, b in node.get("inputs", []):
                resolved.append(input_map[a] if kind == "input"
                                else tensors[(a, int(b))])
        except KeyError as e:
            raise StrategyImportError(
                f"op {name!r} references undefined tensor {e} — stored "
                "graph is not topologically consistent"
            )
        outs = node.get("outputs") or []
        _check_degrees_feasible(
            name,
            [o["dims"] for o in outs] + list(node.get("weights") or []),
            num_devices,
        )
        op = fresh_ops.get(name)
        if op is not None:
            # reuse the fresh compute op: weights/initializers/params ride
            # along; only wiring + sharding state come from the store
            if op.op_type.name != node.get("op_type"):
                raise StrategyImportError(
                    f"op {name!r} is {op.op_type.name} in the fresh "
                    f"lowering but {node.get('op_type')!r} in the entry"
                )
            if len(resolved) != len(op.inputs):
                raise StrategyImportError(
                    f"op {name!r}: stored input count {len(resolved)} != "
                    f"fresh {len(op.inputs)}"
                )
            op.inputs = resolved
            if len(outs) != len(op.outputs):
                raise StrategyImportError(
                    f"op {name!r}: stored output count {len(outs)} != "
                    f"fresh {len(op.outputs)}"
                )
            for t, srec in zip(op.outputs, outs):
                new_dims = [_dim_from_json(d) for d in srec["dims"]]
                old_n = 1
                for d in t.dims:
                    if not d.is_replica_dim:
                        old_n *= d.size
                new_n = 1
                for d in new_dims:
                    if not d.is_replica_dim:
                        new_n *= d.size
                if old_n != new_n:
                    raise StrategyImportError(
                        f"op {name!r}: stored output volume {new_n} != "
                        f"fresh {old_n}"
                    )
                t.dims = new_dims
                t.compute_dtype, t.accum_dtype = _prec_of(name, srec)
            wrecs = node.get("weights") or []
            if len(wrecs) != len(op.weights):
                raise StrategyImportError(
                    f"op {name!r}: stored weight count {len(wrecs)} != "
                    f"fresh {len(op.weights)}"
                )
            for w, dims in zip(op.weights, wrecs):
                if [d.size for d in w.dims] != [int(d[0]) for d in dims]:
                    raise StrategyImportError(
                        f"op {name!r}: stored weight shape "
                        f"{[int(d[0]) for d in dims]} != fresh "
                        f"{[d.size for d in w.dims]}"
                    )
                w.dims = [_dim_from_json(d) for d in dims]
        else:
            # search-inserted parallel op: reconstruct from stored params
            try:
                op_type = OperatorType[node.get("op_type")]
            except KeyError:
                raise StrategyImportError(
                    f"op {name!r} has unknown op_type "
                    f"{node.get('op_type')!r}"
                )
            if node.get("params") is None:
                raise StrategyImportError(
                    f"op {name!r} matches no fresh op and carries no "
                    "constructible params — the entry was written for a "
                    "different model"
                )
            op = PCGOp(op_type, _params_from_json(node["params"]),
                       resolved, name=name)
            for srec in outs:
                try:
                    dtype = DataType[srec["dtype"]]
                except KeyError:
                    raise StrategyImportError(
                        f"op {name!r}: unknown output dtype "
                        f"{srec.get('dtype')!r}"
                    )
                t = ParallelTensor(
                    dims=[_dim_from_json(d) for d in srec["dims"]],
                    data_type=dtype,
                )
                t.compute_dtype, t.accum_dtype = _prec_of(name, srec)
                t.owner_op = op
                op.outputs.append(t)
        mv = node.get("machine_view")
        if mv is not None:
            last = mv["start_device_id"] + sum(
                (d - 1) * s for d, s in zip(mv["dim"], mv["stride"])
            )
            if last >= num_devices:
                raise StrategyImportError(
                    f"op {name!r}: machine_view addresses device {last} "
                    f"but only {num_devices} devices are available"
                )
            op.machine_view = MachineView(
                start_device_id=mv["start_device_id"],
                dim=tuple(mv["dim"]), stride=tuple(mv["stride"]),
            )
            views[op.guid] = op.machine_view
        for i, t in enumerate(op.outputs):
            tensors[(name, i)] = t
        g2.add_op(op)

    from ..search import run_strategy_validators

    problems = run_strategy_validators(g2, views, num_devices)
    if problems:
        raise StrategyImportError(
            "stored strategy failed structural validation for the live "
            "machine: " + "; ".join(problems[:5])
        )
    return g2, views, {str(k): int(v) for k, v in mesh_axes.items()}, \
        payload.get("cost")
