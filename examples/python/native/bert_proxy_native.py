"""BERT-proxy encoder stack through the native-python core API (reference:
examples/python/native/bert_proxy_native.py; network from models/misc)."""
import argparse

from flexflow.core import *  # noqa: F401,F403
import numpy as np

from flexflow_tpu.models.misc import build_bert_proxy


def top_level_task(args):
    ffconfig = FFConfig()
    ffmodel = FFModel(ffconfig)

    input_tensor, _ = build_bert_proxy(
        ffmodel, batch_size=ffconfig.batch_size, seq_length=args.seq_length,
        hidden_size=args.hidden_size, num_heads=args.num_heads,
        num_layers=args.num_layers)

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    label_tensor = ffmodel.label_tensor

    n = args.num_samples
    shape = (n, args.seq_length, args.hidden_size)
    rng = np.random.RandomState(0)
    dl_x = ffmodel.create_data_loader(
        input_tensor, rng.rand(*shape).astype("float32"))
    dl_y = ffmodel.create_data_loader(
        label_tensor, rng.rand(*shape).astype("float32"))

    ffmodel.init_layers()
    ts_start = ffconfig.get_current_time()
    ffmodel.fit(x=dl_x, y=dl_y, epochs=ffconfig.epochs)
    ts_end = ffconfig.get_current_time()
    run_time = 1e-6 * (ts_end - ts_start)
    print("epochs %d, ELAPSED TIME = %.4fs, THROUGHPUT = %.2f samples/s\n" % (
        ffconfig.epochs, run_time, n * ffconfig.epochs / run_time))


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--seq-length", type=int, default=64)
    p.add_argument("--hidden-size", type=int, default=128)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-samples", type=int, default=64)
    args, _ = p.parse_known_args()
    print("bert proxy")
    top_level_task(args)
