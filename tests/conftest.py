"""Test config: run everything on a virtual 8-device CPU mesh so sharding
paths are exercised without TPU hardware (SURVEY §4: substitutes for the
reference's no-cluster gap; the reference needs real GPUs for most tests).

The environment may auto-register a remote-TPU ("axon") jax backend at
interpreter boot whose client init blocks on a tunnel; tests must never touch
it. Deregistering the factory + forcing the cpu platform post-import is the
reliable way since sitecustomize already imported jax.
"""
import os

os.environ.setdefault("JAX_ENABLE_X64", "0")

# Subprocess-launching tests (example smoke tests) must not inherit a
# remote-TPU backend either — a wedged tunnel would hang the child at jax
# init. Export the CPU-mesh env so children match the in-process config.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if os.environ.get("JAX_PLATFORMS", "axon") == "axon":
    # ambient axon (remote-TPU) config can't work once the pool IPs are
    # dropped; anything else (an operator's explicit cpu/tpu) is honored
    os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ.get("JAX_NUM_CPU_DEVICES", "8")
    ).strip()

# Persistent XLA compilation cache: the suite's wall clock is dominated by
# recompiling the same tiny models on this 1-core host; cache hits make
# repeat runs (and the example-script subprocesses, which inherit the env
# var) skip XLA entirely. Safe to delete the dir at any time.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)
# exported (not just config.update) so example-script subprocesses cache
# their sub-second compiles too
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # fflint: disable=FFL002 — jax-internal API may not exist
    pass
jax.config.update("jax_platforms", "cpu")
# JAX_NUM_CPU_DEVICES overrides the 8-device default so sweeps can vary
# the PROCESS-level topology (scripts/elastic_check.sh runs the elastic
# suite on 8/4/2-device meshes; device-count-specific tests skip)
_NDEV = int(os.environ.get("JAX_NUM_CPU_DEVICES", "8"))
try:
    jax.config.update("jax_num_cpu_devices", _NDEV)
except AttributeError:
    # older jax: the --xla_force_host_platform_device_count XLA_FLAGS
    # exported above provides the 8-device CPU mesh instead
    pass
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
)
