"""Callback demo: LearningRateScheduler on a CIFAR-10 CNN (reference:
examples/python/keras/callback.py)."""
from flexflow.keras.models import Model
from flexflow.keras.layers import (
    Input, Conv2D, MaxPooling2D, Flatten, Dense, Activation)
import flexflow.keras.optimizers
from flexflow.keras.callbacks import LearningRateScheduler
from flexflow.keras import backend as K

from accuracy import ModelAccuracy
from _cifar import load_cifar
from _example_args import example_args, verify_callbacks


def lr_scheduler(epoch):
    return 0.01 if epoch == 0 else 0.02


def top_level_task(args):
    print(K.backend())
    num_classes = 10
    x_train, y_train = load_cifar(args.num_samples)

    inp = Input(shape=(3, 32, 32))
    x = Conv2D(filters=32, kernel_size=(3, 3), strides=(1, 1), padding=(1, 1),
               activation="relu")(inp)
    x = MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid")(x)
    x = Flatten()(x)
    x = Dense(256, activation="relu")(x)
    out = Activation("softmax")(Dense(num_classes)(x))

    model = Model(inp, out)
    opt = flexflow.keras.optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"],
                  batch_size=args.batch_size)
    cbs = [LearningRateScheduler(lr_scheduler)]
    cbs += verify_callbacks(args, ModelAccuracy.CIFAR10_CNN)
    model.fit(x_train, y_train, epochs=max(args.epochs, 2), callbacks=cbs)


if __name__ == "__main__":
    print("Callbacks, cifar10 cnn")
    top_level_task(example_args())
