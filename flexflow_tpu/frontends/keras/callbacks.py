"""Keras-style callbacks (reference: python/flexflow/keras/callbacks.py —
Callback, LambdaCallback, VerifyMetrics, EpochVerifyMetrics)."""
from __future__ import annotations

from typing import Callable, Optional


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass


class LambdaCallback(Callback):
    """reference: keras/callbacks.py LambdaCallback"""

    def __init__(
        self,
        on_epoch_begin: Optional[Callable] = None,
        on_epoch_end: Optional[Callable] = None,
        on_train_begin: Optional[Callable] = None,
        on_train_end: Optional[Callable] = None,
        on_batch_begin: Optional[Callable] = None,
        on_batch_end: Optional[Callable] = None,
    ):
        if on_epoch_begin:
            self.on_epoch_begin = on_epoch_begin
        if on_epoch_end:
            self.on_epoch_end = on_epoch_end
        if on_train_begin:
            self.on_train_begin = lambda logs=None: on_train_begin()
        if on_train_end:
            self.on_train_end = lambda logs=None: on_train_end()
        if on_batch_begin:
            self.on_batch_begin = on_batch_begin
        if on_batch_end:
            self.on_batch_end = on_batch_end


class VerifyMetrics(Callback):
    """Asserts final accuracy reaches a threshold (reference:
    keras/callbacks.py VerifyMetrics + examples accuracy.py ModelAccuracy)."""

    def __init__(self, accuracy_threshold):
        # the reference passes ModelAccuracy enum members; unwrap to the
        # numeric threshold (examples accuracy.py ModelAccuracy.value)
        self.threshold = getattr(accuracy_threshold, "value", accuracy_threshold)

    def on_train_end(self, logs=None):
        pm = self.model.ffmodel.get_perf_metrics()
        acc = pm.get_accuracy()
        assert acc >= self.threshold, (
            f"accuracy {acc:.2f}% below threshold {self.threshold}%"
        )


class EpochVerifyMetrics(Callback):
    """Asserts accuracy threshold reached by some epoch (reference:
    keras/callbacks.py EpochVerifyMetrics)."""

    def __init__(self, accuracy_threshold):
        self.threshold = getattr(accuracy_threshold, "value", accuracy_threshold)
        self.best = 0.0

    def on_epoch_end(self, epoch, logs=None):
        if logs and "accuracy" in logs:
            self.best = max(self.best, logs["accuracy"])

    def on_train_end(self, logs=None):
        assert self.best >= self.threshold, (
            f"best epoch accuracy {self.best:.2f}% below {self.threshold}%"
        )


class LearningRateScheduler(Callback):
    """Per-epoch LR schedule (reference: keras/callbacks.py
    LearningRateScheduler — examples/python/keras/callback.py). Mutates the
    compiled optimizer's lr and invalidates the cached train step so the
    next epoch re-traces with the new rate (Legion-trace ≈ jit-cache
    analogy: a changed constant means a new trace)."""

    def __init__(self, schedule):
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        ffmodel = getattr(self.model, "ffmodel", self.model)
        ffmodel.set_learning_rate(self.schedule(epoch))
