"""Pipeline parallelism tests (8 virtual CPU devices via conftest).

The reference never implements pipeline parallelism (OP_PIPELINE is
enum-only, ffconst.h:158) — these tests cover the TPU build's GPipe
implementation (parallel/pipeline.py + ops/pipeline.py): the pipelined
schedule must produce bit-comparable results to the sequential layer scan,
and the full train step must compile and run under pp x dp meshes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.transformer import build_transformer


def _build(pp, batch=8, seq=16, hidden=32, heads=4, layers=4, n_micro=0):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.pipeline_parallel_degree = pp
    cfg.num_microbatches = n_micro
    model = FFModel(cfg)
    build_transformer(
        model,
        batch_size=batch,
        seq_length=seq,
        hidden_size=hidden,
        num_heads=heads,
        num_layers=layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    return model


def test_gpipe_matches_sequential_scan():
    """The GPipe schedule is just a reordering — outputs must match the
    plain sequential scan over layers on identical weights."""
    from flexflow_tpu.ops.pipeline import BlockStackParams, _encoder_block
    from flexflow_tpu.parallel.mesh import build_mesh
    from flexflow_tpu.parallel.pipeline import gpipe_spmd, scan_blocks
    import functools

    L, e, h = 4, 32, 4
    d = e // h
    rng = np.random.RandomState(0)
    weights = {
        "wq": jnp.asarray(rng.randn(L, e, h, d).astype(np.float32) * 0.1),
        "wk": jnp.asarray(rng.randn(L, e, h, d).astype(np.float32) * 0.1),
        "wv": jnp.asarray(rng.randn(L, e, h, d).astype(np.float32) * 0.1),
        "wo": jnp.asarray(rng.randn(L, h, d, e).astype(np.float32) * 0.1),
        "bias_o": jnp.asarray(rng.randn(L, e).astype(np.float32) * 0.1),
        "w1": jnp.asarray(rng.randn(L, e, e).astype(np.float32) * 0.1),
        "w2": jnp.asarray(rng.randn(L, e, e).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(8, 16, e).astype(np.float32))
    block = functools.partial(_encoder_block, head_dim=d, compute_dtype=None)
    ref = scan_blocks(block, weights, x)
    mesh = build_mesh({"data": 2, "pipe": 4})
    got = gpipe_spmd(block, weights, x, n_stages=4, n_micro=2, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_gpipe_grads_match_sequential():
    """jax.grad through the pipeline (scan + ppermute + psum) must equal
    grads of the sequential scan."""
    from flexflow_tpu.ops.pipeline import _encoder_block
    from flexflow_tpu.parallel.mesh import build_mesh
    from flexflow_tpu.parallel.pipeline import gpipe_spmd, scan_blocks
    import functools

    L, e, h = 2, 16, 2
    d = e // h
    rng = np.random.RandomState(1)
    weights = {
        k: jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
        for k, shape in {
            "wq": (L, e, h, d), "wk": (L, e, h, d), "wv": (L, e, h, d),
            "wo": (L, h, d, e), "bias_o": (L, e),
            "w1": (L, e, e), "w2": (L, e, e),
        }.items()
    }
    x = jnp.asarray(rng.randn(4, 8, e).astype(np.float32))
    block = functools.partial(_encoder_block, head_dim=d, compute_dtype=None)
    mesh = build_mesh({"data": 1, "pipe": 2})

    def loss_seq(w):
        return jnp.sum(scan_blocks(block, w, x) ** 2)

    def loss_pipe(w):
        return jnp.sum(
            gpipe_spmd(block, w, x, n_stages=2, n_micro=2, mesh=mesh) ** 2
        )

    g_ref = jax.grad(loss_seq)(weights)
    g_pipe = jax.jit(jax.grad(loss_pipe))(weights)
    for k in weights:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_ref[k]), rtol=1e-4, atol=1e-4
        )


def test_pipelined_model_matches_per_layer_graph():
    """Full FFModel path: forward under pp=4 x dp=2 must equal the
    PER-LAYER graph (MHA + 2 Dense ops per block, the reference's
    transformer.cc block) with the stacked weights sliced into it — this
    pins ops/pipeline.py's _encoder_block to ops/attention.py + linear.py
    math, as models/transformer.py promises."""
    m_pp = _build(pp=4)
    m_ref = _build(pp=1)  # builds the per-layer MHA+Dense graph

    # Slice the pipelined model's stacked weights (leading dim = layer)
    # into the per-layer model's attention/dense ops, in topo order.
    (stack_name,) = list(m_pp.state.params)
    stacked = m_pp.state.params[stack_name]
    ref_params = {op: dict(wd) for op, wd in m_ref.state.params.items()}
    layer_idx = 0
    dense_slot = 0  # 0 -> w1 (relu dense), 1 -> w2
    for op in m_ref.executor.topo:
        if not op.weights:
            continue
        if op.op_type.name == "OP_MULTIHEAD_ATTENTION":
            ref_params[op.name] = {
                k: stacked[k][layer_idx] for k in ("wq", "wk", "wv", "wo", "bias_o")
            }
            dense_slot = 0
        elif op.op_type.name == "OP_LINEAR":
            key = "w1" if dense_slot == 0 else "w2"
            ref_params[op.name] = {"kernel": stacked[key][layer_idx]}
            if dense_slot == 1:
                layer_idx += 1
            dense_slot += 1
    assert layer_idx == 4, f"weight mapping covered {layer_idx}/4 layers"
    m_ref.state.params.update(ref_params)

    rng = np.random.RandomState(2)
    x = rng.randn(8, 16, 32).astype(np.float32)
    fwd_pp = m_pp.executor.build_forward()
    fwd_ref = m_ref.executor.build_forward()
    y_pp = fwd_pp(m_pp.state.params, [m_pp.executor.shard_batch(
        m_pp.executor.input_pts[0], x)])
    y_ref = fwd_ref(m_ref.state.params, [jnp.asarray(x)])
    np.testing.assert_allclose(
        np.asarray(y_pp), np.asarray(y_ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("pp,micro", [(2, 4), (4, 0)])
def test_pipelined_train_step_runs_and_learns(pp, micro):
    model = _build(pp=pp, n_micro=micro)
    ex = model.executor
    step = ex.build_train_step()
    rng = np.random.RandomState(3)
    x = rng.randn(8, 16, 32).astype(np.float32)
    y = jnp.asarray((x * 0.5).astype(np.float32))
    bx = [ex.shard_batch(ex.input_pts[0], x)]
    key = jax.random.PRNGKey(0)
    state = model.state
    losses = []
    for i in range(6):
        key, sub = jax.random.split(key)
        state, partials = step(state, bx, y, sub)
        losses.append(float(partials["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_hybrid_mesh_fallback_single_slice():
    """build_hybrid_mesh on homogeneous (CPU) devices falls back to a flat
    mesh with dcn axes leading, so dp crosses the slower links."""
    from flexflow_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh({"model": 2, "pipe": 2}, {"data": 2})
    assert mesh.axis_names == ("data", "model", "pipe")
    assert dict(mesh.shape) == {"data": 2, "model": 2, "pipe": 2}


def test_remat_grads_exact():
    """cfg.remat recomputes attention internals in the backward via
    jax.checkpoint — same math as the stored path, so loss and updated
    params must agree to float tolerance (bitwise equality is NOT
    guaranteed: checkpoint's prevent_cse barriers can change XLA fusion
    and hence rounding)."""
    def build(remat):
        cfg = FFConfig()
        cfg.batch_size = 4
        cfg.remat = remat
        m = FFModel(cfg)
        build_transformer(m, batch_size=4, seq_length=8, hidden_size=16,
                          num_heads=2, num_layers=2)
        m.compile(
            optimizer=SGDOptimizer(lr=0.05),
            loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
            metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
        )
        return m

    m0, m1 = build(False), build(True)
    # identical seeds -> identical init params
    rng = np.random.RandomState(5)
    x = rng.randn(4, 8, 16).astype(np.float32)
    y = jnp.asarray(rng.randn(4, 8, 16).astype(np.float32))
    key = jax.random.PRNGKey(7)
    outs = []
    for m in (m0, m1):
        ex = m.executor
        step = ex.build_train_step()
        bx = [ex.shard_batch(ex.input_pts[0], x)]
        st, partials = step(m.state, bx, y, key)
        outs.append((float(partials["loss"]),
                     np.asarray(jax.tree_util.tree_leaves(st.params)[0])))
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-6)
    np.testing.assert_allclose(outs[0][1], outs[1][1], rtol=1e-6, atol=1e-6)


def test_search_path_keeps_pipe_axis():
    """Unity-search compile must carry the pipe mesh axis for block-stack
    ops (their num_stages is fixed at graph build), or GPipe silently
    degrades to the sequential scan."""
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.pipeline_parallel_degree = 2
    cfg.search_budget = 3
    model = FFModel(cfg)
    build_transformer(model, batch_size=8, seq_length=16, hidden_size=32,
                      num_heads=4, num_layers=4)
    model.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    mesh = model.executor.mesh
    assert mesh.shape.get("pipe") == 2, dict(mesh.shape)
    ex = model.executor
    step = ex.build_train_step()
    rng = np.random.RandomState(6)
    x = rng.randn(8, 16, 32).astype(np.float32)
    y = jnp.asarray((x * 0.5).astype(np.float32))
    st, partials = step(model.state, [ex.shard_batch(ex.input_pts[0], x)], y,
                        jax.random.PRNGKey(0))
    assert np.isfinite(float(partials["loss"]))


# -- generalized pipeline over arbitrary PCGs (round 2; VERDICT r1 weak #7:
#    OP_BLOCK_STACK required the uniform benchmark block) -------------------

def _build_nonuniform(pp, batch=8):
    """A deliberately NON-uniform model: conv tower into an MLP with a
    residual add — nothing the block-stack path can express."""
    from flexflow_tpu import ActiMode, DataType

    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.pipeline_parallel_degree = pp
    m = FFModel(cfg)
    x = m.create_tensor((batch, 3, 16, 16), DataType.DT_FLOAT)
    t = m.conv2d(x, 8, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = m.flat(t)
    t = m.dense(t, 64, ActiMode.AC_MODE_RELU)
    skip = t
    t = m.dense(t, 64)
    t = m.add(t, skip)  # residual crossing a potential stage cut
    t = m.dense(t, 10)
    m.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    return m


def test_nonuniform_pipeline_matches_sequential():
    """gpipe_pcg (stage-partitioned arbitrary graph) must reproduce the
    unpipelined forward, including a residual that crosses a cut."""
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 3, 16, 16).astype(np.float32)

    m_seq = _build_nonuniform(pp=1)
    m_pp = _build_nonuniform(pp=2)
    assert m_pp.executor.pipeline_plan is not None, (
        "non-uniform graph did not produce a generalized pipeline plan"
    )
    assert m_pp.executor.pipeline_plan.n_stages == 2
    for opn, ws in m_seq.state.params.items():
        for wn, w in ws.items():
            m_pp.state.params[opn][wn] = jnp.asarray(np.asarray(w))
    want = np.asarray(m_seq.executor.build_forward()(
        m_seq.state.params, [jnp.asarray(xv)]))
    got = np.asarray(m_pp.executor.build_forward()(
        m_pp.state.params, [jnp.asarray(xv)]))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_nonuniform_pipeline_trains_and_matches_loss():
    """One train step through the pipelined non-uniform model produces the
    same loss as the sequential graph (grads flow through switch +
    ppermute + scan)."""
    rng = np.random.RandomState(1)
    xv = rng.randn(8, 3, 16, 16).astype(np.float32)
    yv = rng.randn(8, 10).astype(np.float32)

    losses = []
    for pp in (1, 2):
        m = _build_nonuniform(pp=pp)
        if pp == 2:
            src = _build_nonuniform(pp=1)
            for opn, ws in src.state.params.items():
                for wn, w in ws.items():
                    m.state.params[opn][wn] = jnp.asarray(np.asarray(w))
        ex = m.executor
        step = ex.build_train_step()
        x = ex.shard_batch(ex.input_pts[0], xv)
        y = jnp.asarray(yv)
        state, partials = step(m.state, [x], y, jax.random.PRNGKey(0))
        jax.block_until_ready(state.params)
        losses.append(float(partials["loss"]))
    assert losses[0] == pytest.approx(losses[1], rel=2e-4)


def test_nonuniform_pipeline_stage_cut_balances_cost():
    """The cut is cost-model-proposed: both stages carry nonempty op
    groups and every compute op lands in exactly one stage."""
    m = _build_nonuniform(pp=2)
    plan = m.executor.pipeline_plan
    names = [o.name for s in plan.stages for o in s]
    assert len(names) == len(set(names))
    assert all(len(s) >= 1 for s in plan.stages)
    assert len(plan.cuts) == 1 and len(plan.cuts[0]) >= 1


def _build_budgeted(layers, width, batch, device_mem):
    from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel,
                              LossType, MetricsType, SGDOptimizer)

    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = 2
    cfg.device_mem = device_mem
    m = FFModel(cfg)
    x = m.create_tensor((batch, width), DataType.DT_FLOAT)
    t = x
    for _ in range(layers):
        t = m.dense(t, width, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    m.softmax(t)
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def test_search_chooses_pipeline_when_memory_overflows():
    """VERDICT r2 #6 / r3 #2: pipeline as a SEARCHED dimension under
    TRAINING memory accounting (weights + grads + optimizer slots). A
    deep narrow stack (16 x dense-1024, batch 512) at a 24 MB budget has
    no fitting unpipelined strategy — tensor parallelism shards the
    weights but its replicated per-layer activations still overflow —
    so the search adopts GPipe and the model trains through the
    generalized pipeline executor. With ample memory, pipeline is NOT
    chosen."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    # ample memory: pipeline NOT chosen
    m1 = _build_budgeted(4, 1024, 64, device_mem=1 << 40)
    assert m1.executor.mesh.shape.get("pipe", 1) == 1
    assert getattr(m1, "searched_pipeline_degree", 1) == 1

    # 17 x ~4 MB of dense weights (x2 with gradients) + 512-batch
    # activations against 24 MB: only a stage split fits
    m2 = _build_budgeted(16, 1024, 512, device_mem=24 << 20)
    pipe = m2.executor.mesh.shape.get("pipe", 1)
    assert pipe > 1, m2.executor.mesh.shape
    assert m2.searched_pipeline_degree == pipe
    assert m2.executor.pipeline_plan is not None
    ex = m2.executor
    step = ex.build_train_step()
    x = ex.shard_batch(ex.input_pts[0],
                       np.zeros((512, 1024), np.float32))
    y = jnp.zeros((512, 1), jnp.int32)
    st, partials = step(m2.state, [x], y, jax.random.PRNGKey(0))
    jax.block_until_ready(st.params)
    assert np.isfinite(float(partials["loss"]))


def test_fitting_tensor_parallel_beats_pipeline():
    """The negative pin VERDICT r3 #2 asks for: when a FITTING
    unpipelined strategy exists and beats the GPipe estimate on cost,
    the search must adopt it instead of pipelining. 5 x dense-2048 at
    batch 16 overflows unsharded (~17 MB weights x2 with grads per
    layer vs 36 MB); a 4-stage pipeline fits (~34 MB/stage) but so does
    a degree-8 parameter-parallel strategy that divides the weight+grad
    bytes — and at this tiny batch, where GPipe's bubble dominates, TP
    wins on simulated runtime."""
    from flexflow_tpu.search.memory_optimization import measure_memory

    budget = 36 << 20
    m = _build_budgeted(4, 2048, 16, device_mem=budget)
    assert m.executor.mesh.shape.get("pipe", 1) == 1
    assert getattr(m, "searched_pipeline_degree", 1) == 1
    # the adopted alternative is genuinely sharded AND genuinely fits
    # under training accounting (grads counted; SGD, no momentum slots)
    assert m.executor.mesh.shape.get("model", 1) > 1, m.executor.mesh.shape
    mem = measure_memory(
        m.graph, m.searched_views, m._build_cost_model(),
        train=True, optimizer=m.optimizer,
        grad_bytes_ratio=m._grad_bytes_ratio(),
    )
    assert mem.max_bytes <= budget
