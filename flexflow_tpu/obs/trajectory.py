"""Search-trajectory recorder.

compile() always records WHAT the strategy search did — every MCMC
proposal with its simulated cost and accept/reject, every substitution
candidate the best-first loop evaluated, the DP's split decisions, and
the compile phase timings — into a bounded in-memory trajectory on the
model (`model.search_trajectory`). Recording is unconditional because it
is cheap relative to the search itself and two consumers need it after
the fact:

  * `fit(telemetry=...)` replays it into the event log, so the Perfetto
    trace covers the search even though telemetry was configured later;
  * `obs.explain_strategy` joins it with on-device measurements to rank
    cost-model miscalibration.

Entries are plain dicts `{"kind": ..., "t": perf_counter(), ...}`;
`limit` bounds memory (overflow counted in `dropped`).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional


class SearchTrajectory:
    """Bounded append-only record of search/compile decisions."""

    def __init__(self, limit: int = 20_000):
        self.limit = limit
        self.events: List[dict] = []
        self.dropped: Dict[str, int] = {}

    def event(self, kind: str, **fields) -> None:
        if len(self.events) >= self.limit:
            self.dropped[kind] = self.dropped.get(kind, 0) + 1
            return
        rec = {"kind": kind, "t": time.perf_counter()}
        rec.update(fields)
        self.events.append(rec)

    def phase(self, name: str, t0: float, **fields) -> None:
        """Record a completed compile phase (t0 from perf_counter())."""
        self.event("phase", name=name, t0=t0,
                   dur=time.perf_counter() - t0, **fields)

    # -- views -----------------------------------------------------------
    def of_kind(self, kind: str) -> List[dict]:
        return [e for e in self.events if e["kind"] == kind]

    def mcmc_iterations(self) -> List[dict]:
        return self.of_kind("mcmc_iter")

    def summary(self) -> dict:
        """Aggregate view for reports and the CLI."""
        mcmc = self.mcmc_iterations()
        cands = self.of_kind("xfer_candidate")
        phases = {
            e["name"]: e["dur"] for e in self.of_kind("phase")
        }
        out = {
            "events": len(self.events),
            "dropped": dict(self.dropped),
            "phases_s": phases,
            "mcmc": {
                "iterations": len(mcmc),
                "accepted": sum(1 for e in mcmc if e.get("accept")),
            },
            "substitution": {
                "candidates": len(cands),
                "improved": sum(1 for e in cands if e.get("best")),
            },
            "dp": {
                "splits": len(self.of_kind("dp_split")),
            },
        }
        ends = self.of_kind("search_end")
        if ends:
            out["final_cost"] = ends[-1].get("cost")
        return out
