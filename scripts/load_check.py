#!/usr/bin/env python
"""Sustained-load serving harness (ROADMAP Open item 3 / docs/serving.md).

Drives a ReplicaSet of continuous-batching replicas through a 10x
offered-load ramp on the virtual CPU mesh, kills one replica mid-ramp,
and asserts the overload-robustness contract:

  1. **bounded tail latency for admitted work** — p99 latency of
     admitted requests during/after the ramp stays within
     ``--p99-factor`` (default 3x) of the pre-ramp p99;
  2. **zero silent drops** — every request the generator offered either
     returns tokens or raises a TYPED shed/deadline error; nothing
     hangs, nothing vanishes;
  3. **failover completes** — the killed replica's in-flight work is
     requeued onto its sibling and a replacement comes back through the
     elastic-restore path (checkpoint resharded onto the live
     topology), so the run ends at full replica strength;
  4. **(with --telemetry-dir) the flight recorder is coherent** — the
     session's events.jsonl is schema-valid, at least one sampled
     request carries the full queue -> admit -> prefill -> decode ->
     complete lifecycle, and when the kill fired, some requeued request
     finished under its ORIGINAL trace id with exactly one complete
     event (obs/request_trace.py).

With ``--shared-prefix`` the ramp is replaced by the KV-dedup A/B
check (docs/serving.md "Prefix sharing"): the same burst of sessions —
one long block-aligned common prompt prefix, unique tails — is served
twice from an identically starved page pool, sharing off then on, and
the run asserts >= ``--share-factor`` (default 5x) the concurrent
sessions in the same HBM budget, ``ff_kv_pages_shared > 0`` at peak,
token-exact output vs ``incremental_generate`` in BOTH phases, and a
zero-violation ``PagePool.audit()`` per phase.
scripts/kvshare_check.sh runs this leg in CI.

Exit 0 with a JSON summary on stdout when all criteria hold; exit 1
(with the failed criterion) otherwise. scripts/serving_check.sh runs
this on 8- and 4-device CPU meshes in CI; scripts/obs_check.sh runs the
telemetry-enabled leg.
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# honor JAX_NUM_CPU_DEVICES like tests/conftest.py: virtual CPU mesh size
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("JAX_NUM_CPU_DEVICES", "8")
).strip()
# runnable as `python scripts/load_check.py` from a source checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import jax  # noqa: E402

try:
    jax.config.update("jax_num_cpu_devices",
                      int(os.environ.get("JAX_NUM_CPU_DEVICES", "8")))
except AttributeError:
    pass  # older jax: the XLA_FLAGS export above does it


def build_model_fn(args):
    from flexflow_tpu import (ActiMode, AggrMode, DataType, FFConfig,
                              FFModel, LossType, MetricsType, SGDOptimizer)

    def model_fn():
        cfg = FFConfig()
        cfg.batch_size = 2
        cfg.search_budget = args.search_budget
        m = FFModel(cfg)
        ids = m.create_tensor((2, args.max_len), DataType.DT_INT32)
        t = m.embedding(ids, args.vocab, args.hidden, AggrMode.AGGR_MODE_NONE)
        for _ in range(args.layers):
            t = m.multihead_attention(t, t, t, args.hidden, args.heads,
                                      causal=True)
            t = m.dense(t, args.hidden, ActiMode.AC_MODE_RELU)
        t = m.softmax(m.dense(t, args.vocab))
        m.compile(SGDOptimizer(lr=0.01),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
        if args.decode_strategy:
            # disaggregated prefill/decode (docs/serving.md): the
            # batched decode step lowers from the decode-objective
            # strategy; the harness asserts the same typed-accounting
            # invariants either way
            m.compile_decode()
        return m

    return model_fn


class Record:
    __slots__ = ("req", "phase", "submit_error")

    def __init__(self, req, phase, submit_error=None):
        self.req = req
        self.phase = phase
        self.submit_error = submit_error


def offered_load(rs, args, records, stop_evt, killed_evt, fi, kill_info):
    """Open-loop generator: warm at the base rate, ramp to ramp x base,
    cool back down. The replica kill fires mid-ramp."""
    from flexflow_tpu.runtime.serving import RequestShedError

    rng = np.random.RandomState(args.seed)
    phases = [("warm", args.warm_s, args.base_rate),
              ("ramp", args.ramp_s, args.base_rate * args.ramp),
              ("post", args.post_s, args.base_rate)]
    for phase, dur, rate in phases:
        t_end = time.monotonic() + dur
        period = 1.0 / rate
        while time.monotonic() < t_end and not stop_evt.is_set():
            if (phase == "ramp" and not killed_evt.is_set()
                    and time.monotonic() > t_end - dur * (1 - args.kill_at)):
                # kill the BUSIEST replica, and only once it provably has
                # in-flight work: criterion 4's "requeued request finishes
                # under its original trace id" needs the victim to strand
                # something, and an idle victim mid-tick would make the
                # whole check flaky. If every replica is momentarily idle
                # this retries next loop iteration.
                with rs._lock:
                    busy = sorted(
                        ((r.batcher.active_slots, name)
                         for name, r in rs._replicas.items()
                         if r.batcher.thread_alive()), reverse=True)
                if busy and busy[0][0] > 0:
                    victim = busy[0][1]
                    fi.inject("replica_death", replica=victim)
                    kill_info["victim"] = victim
                    killed_evt.set()
                    print(f"[load_check] injected replica_death on "
                          f"{victim}", file=sys.stderr)
            plen = int(rng.randint(2, args.max_prompt + 1))
            prompt = rng.randint(0, args.vocab, plen).astype(np.int32)
            new = int(rng.randint(2, args.max_new + 1))
            try:
                req = rs.submit(prompt, max_new_tokens=new,
                                deadline_s=args.deadline_s)
                records.append(Record(req, phase))
            except RequestShedError as e:
                records.append(Record(None, phase, submit_error=e))
            time.sleep(period)


def verify_request_trace(tel_dir, *, expect_requeue):
    """Criterion 4: reconstruct per-request lifecycles from the finished
    session's events.jsonl and judge the flight-recorder contract.
    Returns (verdict-dict-for-summary, failure-strings)."""
    from flexflow_tpu.obs.tracer import read_events_jsonl

    failures = []
    events_path = os.path.join(tel_dir, "events.jsonl")
    trace_path = os.path.join(tel_dir, "trace.json")
    events, problems = read_events_jsonl(events_path)
    if problems:
        failures.append(
            f"events.jsonl has {len(problems)} schema-invalid line(s): "
            + "; ".join(problems[:3])
        )
    by_req = {}
    for e in events:
        if e.get("cat") != "requests":
            continue
        rid = e.get("args", {}).get("request")
        if rid is not None:
            by_req.setdefault(rid, []).append(e["name"])
    lifecycle = ("queue", "admit", "prefill", "decode", "complete")
    full = [rid for rid, names in by_req.items()
            if all(s in names for s in lifecycle)]
    requeued_ok = [
        rid for rid, names in by_req.items()
        if "requeue" in names and names.count("complete") == 1
    ]
    double_complete = [rid for rid, names in by_req.items()
                       if names.count("complete") > 1]
    verdict = {
        "traced_requests": len(by_req),
        "full_lifecycle": len(full),
        "requeued_completed": len(requeued_ok),
        "schema_problems": len(problems),
        "perfetto_trace": trace_path,
    }
    if not by_req:
        failures.append("telemetry enabled but no request events recorded")
    elif not full:
        failures.append(
            "no traced request carries the full queue->admit->prefill->"
            "decode->complete lifecycle"
        )
    if double_complete:
        failures.append(
            f"{len(double_complete)} request(s) completed more than once "
            f"in the trace: {double_complete[:3]}"
        )
    if expect_requeue and not requeued_ok:
        failures.append(
            "replica kill fired but no requeued request finished under "
            "its original trace id"
        )
    if not os.path.exists(trace_path):
        failures.append(f"missing Perfetto export {trace_path}")
    else:
        with open(trace_path) as f:
            tr = json.load(f)
        if "traceEvents" not in tr:
            failures.append("trace.json is not Chrome-trace shaped")
    return verdict, failures


def verify_fleet(args, *, expected_requests, victim, killed):
    """The --fleet-spool criteria (obs/fleet.py, docs/observability.md
    "Fleet observatory"): judged from the spool directory and the
    finished telemetry session AFTER the ReplicaSet has stopped.

      a. the cross-process rollup **conserves request counts** — the
         fleet-summed ``ff_serving_requests_total`` equals the client's
         completed count (warmup + offered load), i.e. the killed
         replica's final tally survived in its terminal spool;
      b. the killed replica's spool reads as **stale or dead**, never
         live (its death spool declares the terminal status);
      c. when the autoscaler added capacity, the ``replica_scale_up``
         event names the **anomaly** the sentinel blamed it on;
      d. a ``replica_death`` **forensics bundle** names the victim and
         passes ``validate_bundle``.
    Returns (verdict-dict-for-summary, failure-strings)."""
    from flexflow_tpu.obs import flight_recorder as fr
    from flexflow_tpu.obs.fleet import FleetAggregator

    failures = []
    agg = FleetAggregator(args.fleet_spool, staleness_s=5.0, death_s=15.0)
    view = agg.aggregate()
    states = view.states()
    total = view.counter_total("ff_serving_requests_total")
    corrupt = [r.process for r in view.records if r.error is not None]
    verdict = {
        "spooled_processes": len(view.records),
        "states": states,
        "requests_total": total,
        "expected_requests": expected_requests,
        "corrupt_spools": corrupt,
    }
    if corrupt:
        failures.append(f"corrupt spool file(s): {corrupt}")
    if not view.records:
        failures.append("fleet spool dir has no spools at all")
    # (a) counter conservation across the kill
    if total != expected_requests:
        failures.append(
            f"fleet rollup lost requests: ff_serving_requests_total sums "
            f"to {total:.0f} across spools but the client saw "
            f"{expected_requests} completions"
        )
    # (b) the victim's terminal spool classifies stale/dead, not live
    if killed and victim is not None:
        vstate = states.get(victim)
        if vstate is None:
            failures.append(
                f"killed replica {victim} left no spool behind")
        elif vstate not in ("stale", "dead"):
            failures.append(
                f"killed replica {victim} classified {vstate!r}, "
                "expected stale/dead")
        verdict["victim"] = victim
        verdict["victim_state"] = vstate
    # (c) anomaly-attributed scale-up, from the finished events.jsonl
    if args.telemetry_dir:
        from flexflow_tpu.obs.tracer import read_events_jsonl

        events, _ = read_events_jsonl(
            os.path.join(args.telemetry_dir, "events.jsonl"))
        ups = [e for e in events if e.get("name") == "replica_scale_up"]
        tagged = [e for e in ups if e.get("args", {}).get("anomaly")]
        verdict["scale_ups"] = len(ups)
        verdict["scale_up_anomalies"] = sorted(
            {e["args"]["anomaly"] for e in tagged})
        if ups and not tagged:
            failures.append(
                f"{len(ups)} replica_scale_up event(s) but none carries "
                "the anomaly tag that motivated it")
        if args.expect_scale_up and not ups:
            failures.append(
                "fleet leg expected the overload ramp to trigger a "
                "replica_scale_up but none fired")
    # (d) a valid replica_death forensics bundle naming the victim
    if killed and args.telemetry_dir:
        entries, index_problems = fr.read_index(args.telemetry_dir)
        failures.extend(index_problems)
        deaths = [e for e in entries
                  if e.get("reason") == "replica_death"]
        verdict["forensics_bundles"] = len(entries)
        verdict["replica_death_bundles"] = len(deaths)
        named = []
        for e in deaths:
            path = os.path.join(e["_dir"], e["file"])
            problems = fr.validate_bundle(path)
            if problems:
                failures.append(
                    f"replica_death bundle {e['file']} invalid: "
                    + "; ".join(problems[:3]))
                continue
            payload = fr.read_bundle(path)
            if payload.get("extra", {}).get("replica") == victim:
                named.append(e["file"])
        if not deaths:
            failures.append(
                "replica kill fired but no replica_death forensics "
                "bundle was dumped")
        elif victim is not None and not named:
            failures.append(
                f"no replica_death bundle names the victim {victim}")
    return verdict, failures


def run_shared_prefix(args):
    """The --shared-prefix A/B criterion: identical starved pool, the
    same same-prefix session burst, sharing off vs on. The geometry is
    chosen so one session needs `blocks+1` pages unshared but only ONE
    page once the prefix is published: prefix = `blocks` full pages,
    and the unique tail plus every decoded token fit inside a single
    extra page."""
    from flexflow_tpu.runtime.serving import (AdmissionQueue,
                                              ContinuousBatcher,
                                              GenerationRequest,
                                              ServingConfig,
                                              incremental_generate)

    ps = args.page_size
    if ps < 4:
        print("[load_check] --shared-prefix needs --page-size >= 4",
              file=sys.stderr)
        return 1
    blocks = 8                      # shared prefix: 8 full pages
    plen = blocks * ps + 2          # + 2-token unique tail
    max_new = ps - 2                # decode stays inside the tail page
    args.max_len = (blocks + 1) * ps
    pages_per = blocks + 1          # unshared worst case per session
    num_pages = args.num_pages or 2 * pages_per + 2  # fits TWO unshared
    slots = max(args.slots, 12)
    sessions = slots + 4            # more offered than can ever run

    import jax

    ndev = len(jax.devices())
    print(f"[load_check] shared-prefix A/B: {ndev} device(s), "
          f"{num_pages}-page pool, {pages_per} pages/session unshared, "
          f"{sessions} sessions offered", file=sys.stderr)
    model = build_model_fn(args)()
    rng = np.random.RandomState(args.seed)
    prefix = rng.randint(0, args.vocab, blocks * ps).astype(np.int32)
    prompts = [np.concatenate([prefix, np.array(
        [(i // args.vocab) % args.vocab, i % args.vocab], np.int32)])
        for i in range(sessions)]
    refs = [incremental_generate(model, p[None], max_new_tokens=max_new)[0]
            for p in prompts]

    phases = {}
    failures = []
    for label, share in (("unshared", False), ("shared", True)):
        cfg = ServingConfig(
            max_len=args.max_len, slots=slots, page_size=ps,
            num_pages=num_pages, share_prefixes=share, precompile=False,
            max_queue_depth=sessions + 4,
            default_deadline_s=args.deadline_s,
        )
        q = AdmissionQueue(max_depth=sessions + 4)
        b = ContinuousBatcher(model, cfg, q).start()
        peak = {"sessions": 0, "pages_shared": 0}
        poll_stop = threading.Event()

        def poll(b=b, peak=peak, poll_stop=poll_stop):
            while not poll_stop.is_set():
                peak["sessions"] = max(peak["sessions"], b.active_slots)
                peak["pages_shared"] = max(peak["pages_shared"],
                                           b.pool.pages_shared)
                time.sleep(0.001)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        try:
            reqs = [GenerationRequest(p.copy(), max_new,
                                      deadline_s=args.deadline_s)
                    for p in prompts]
            for r in reqs:
                q.offer(r)
            outs = [r.result(timeout=300.0) for r in reqs]
        finally:
            poll_stop.set()
            poller.join(timeout=2.0)
            report = b.pool.audit()
            pool_stats = dict(b.pool.stats)
            b.stop()
        exact = sum(1 for o, ref in zip(outs, refs)
                    if np.array_equal(o, ref))
        phases[label] = {
            "peak_concurrent_sessions": peak["sessions"],
            "peak_pages_shared": peak["pages_shared"],
            "exact_outputs": exact,
            "prefix_hits": pool_stats["prefix_hits"],
            "cow": pool_stats["cow"],
            "accounting_errors": pool_stats["accounting_errors"],
            "audit_violations": len(report.violations),
            "pages_resident_at_end": report.pages_resident,
        }
        if exact != sessions:
            failures.append(
                f"{label}: only {exact}/{sessions} outputs exact vs "
                f"incremental_generate")
        if not report.ok:
            failures.append(
                f"{label}: pool audit found {len(report.violations)} "
                f"violation(s); first: {report.violations[0].kind}")
        if report.pages_resident:
            failures.append(
                f"{label}: {report.pages_resident} page(s) leaked after "
                f"the burst drained")

    ratio = (phases["shared"]["peak_concurrent_sessions"]
             / max(1, phases["unshared"]["peak_concurrent_sessions"]))
    summary = {
        "devices": ndev,
        "geometry": {"page_size": ps, "prefix_blocks": blocks,
                     "prompt_len": plen, "max_new": max_new,
                     "num_pages": num_pages, "slots": slots,
                     "sessions_offered": sessions,
                     "pages_per_session_unshared": pages_per},
        "phases": phases,
        "concurrency_ratio": round(ratio, 2),
        "required_ratio": args.share_factor,
    }
    if ratio < args.share_factor:
        failures.append(
            f"sharing sustained only {ratio:.2f}x the unshared concurrent "
            f"sessions (need >= {args.share_factor}x in the same "
            f"{num_pages}-page budget)")
    if phases["shared"]["peak_pages_shared"] <= 0:
        failures.append("ff_kv_pages_shared never rose above 0 with "
                        "sharing on")
    if phases["shared"]["prefix_hits"] < 1:
        failures.append("no admission attached a shared prefix")
    if phases["unshared"]["prefix_hits"] or phases["unshared"][
            "peak_pages_shared"]:
        failures.append("sharing leaked into the share_prefixes=False "
                        "control phase")

    print(json.dumps(summary, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2, default=str)
    if failures:
        for f_ in failures:
            print(f"[load_check] FAIL: {f_}", file=sys.stderr)
        return 1
    print("[load_check] OK", file=sys.stderr)
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--max-replicas", type=int, default=None,
                    help="autoscaler ceiling (default: --replicas, i.e. "
                         "no scale-up headroom); the fleet leg sets this "
                         "above --replicas so the overload ramp provokes "
                         "an anomaly-attributed replica_scale_up")
    ap.add_argument("--fleet-spool", type=str, default=None,
                    help="fleet spool directory (obs/fleet.py): every "
                         "replica's counters are spooled per autoscale "
                         "tick and once more with a terminal status at "
                         "death/drain; adds the fleet criteria — counter "
                         "conservation through the kill, stale/dead "
                         "classification of the victim, anomaly-tagged "
                         "scale-ups, and a valid replica_death forensics "
                         "bundle (needs --telemetry-dir for the last two)")
    ap.add_argument("--expect-scale-up", action="store_true",
                    help="with --fleet-spool: fail unless the ramp "
                         "actually triggered a replica_scale_up")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=32)
    ap.add_argument("--max-prompt", type=int, default=6)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=8)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="KV page-pool size per replica (default: covers "
                         "slots x max_len); small values exercise "
                         "admission backpressure")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--search-budget", type=int, default=2)
    ap.add_argument("--decode-strategy", action="store_true",
                    help="compile_decode() each replica model: serve the "
                         "batched decode step from the decode-objective "
                         "strategy (docs/serving.md)")
    ap.add_argument("--base-rate", type=float, default=6.0,
                    help="pre-ramp offered load, requests/s")
    ap.add_argument("--ramp", type=float, default=10.0,
                    help="offered-load multiplier during the ramp")
    ap.add_argument("--warm-s", type=float, default=4.0)
    ap.add_argument("--ramp-s", type=float, default=6.0)
    ap.add_argument("--post-s", type=float, default=3.0)
    ap.add_argument("--kill-at", type=float, default=0.4,
                    help="fraction into the ramp to kill a replica")
    ap.add_argument("--deadline-s", type=float, default=8.0)
    ap.add_argument("--queue-depth", type=int, default=24)
    ap.add_argument("--p99-factor", type=float, default=3.0)
    ap.add_argument("--p99-floor-s", type=float, default=0.25,
                    help="pre-ramp p99 floor so CPU timing noise cannot "
                         "make the 3x bound vacuously tight")
    # generous on the CPU harness: every replica shares ONE process, so a
    # sibling's restart (strategy search + XLA compile, GIL-heavy) can
    # legitimately stall live iterations for seconds — a tight watchdog
    # here false-positives into cascading failovers. Production replicas
    # run in separate processes and use tight timeouts.
    ap.add_argument("--health-timeout-s", type=float, default=60.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", type=str, default=None,
                    help="also write the summary JSON to this path")
    ap.add_argument("--no-kill", action="store_true",
                    help="skip the replica kill (latency-only run)")
    ap.add_argument("--telemetry-dir", type=str, default=None,
                    help="run under a telemetry session writing to this "
                         "dir and verify the request flight recorder "
                         "(criterion 4)")
    ap.add_argument("--artifact-store", type=str, default=None,
                    help="persistent strategy store dir "
                         "(runtime/artifact_store.py): replica/spare "
                         "builds boot from cached strategies; adds the "
                         "cold-start criterion — at least one cache hit, "
                         "no corrupt entries (docs/artifact_cache.md)")
    ap.add_argument("--request-sample-rate", type=float, default=1.0,
                    help="head-based request trace sampling rate for the "
                         "telemetry session")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="run the KV prefix-sharing A/B criterion instead "
                         "of the load ramp: >= --share-factor x concurrent "
                         "sessions in the same page budget with sharing "
                         "on, exact outputs, zero audit violations")
    ap.add_argument("--share-factor", type=float, default=5.0,
                    help="required concurrent-session multiplier for "
                         "--shared-prefix")
    args = ap.parse_args()

    if args.shared_prefix:
        return run_shared_prefix(args)

    from flexflow_tpu.runtime.resilience import FaultInjector, InferenceTimeout
    from flexflow_tpu.runtime.serving import ReplicaSet, RequestShedError, \
        ServingConfig

    import jax

    ndev = len(jax.devices())
    print(f"[load_check] {ndev} device(s), {args.replicas} replica(s), "
          f"{args.slots} slot(s) each", file=sys.stderr)

    telemetry = None
    if args.telemetry_dir:
        import flexflow_tpu.obs as obs
        from flexflow_tpu import TelemetryConfig

        telemetry = obs.start(TelemetryConfig(
            dir=args.telemetry_dir,
            request_sample_rate=args.request_sample_rate,
        ))
        print(f"[load_check] telemetry session -> {args.telemetry_dir} "
              f"(request_sample_rate={args.request_sample_rate})",
              file=sys.stderr)

    store = None
    if args.artifact_store:
        from flexflow_tpu.runtime.artifact_store import ArtifactStore

        store = ArtifactStore(args.artifact_store)
        print(f"[load_check] artifact store -> {args.artifact_store} "
              f"({len(store.entries())} entries)", file=sys.stderr)

    fi = FaultInjector()
    cfg = ServingConfig(
        max_len=args.max_len, slots=args.slots, page_size=args.page_size,
        num_pages=args.num_pages, max_queue_depth=args.queue_depth,
        default_deadline_s=args.deadline_s,
    )
    ckpt_dir = tempfile.mkdtemp(prefix="ff_load_check_ckpt_")
    rs = ReplicaSet(
        build_model_fn(args), cfg, replicas=args.replicas,
        max_replicas=args.max_replicas,
        ckpt_dir=ckpt_dir, fault_injector=fi,
        health_timeout_s=args.health_timeout_s,
        restart_backoff_s=0.1,
        # a warm spare makes failover a checkpoint-restore instead of an
        # in-process rebuild — on the shared-core CPU harness a rebuild's
        # strategy search would starve the surviving replicas mid-ramp
        warm_spares=1,
        artifact_store=store,
        fleet_spool_dir=args.fleet_spool,
    ).start()

    # jit warmup: run a few requests through every replica so the decode
    # executables (and prefill buckets) are compiled BEFORE the measured
    # warm phase — compile time is a cold-start cost, not serving latency,
    # and leaving it in would inflate the pre-ramp p99 the bound hangs off
    wrng = np.random.RandomState(args.seed + 1)

    def warm_req():
        plen = int(wrng.randint(2, args.max_prompt + 1))
        return rs.submit(wrng.randint(0, args.vocab, plen).astype(np.int32),
                         max_new_tokens=args.max_new, deadline_s=120.0)

    n_warm = 2 * args.replicas * args.slots
    if args.max_replicas and args.max_replicas > args.replicas:
        # with scale-up headroom, the jit-warmup flood must stay below
        # the autoscale queue threshold — a warmup-triggered scale-up
        # would fire before the anomaly sentinel has any baseline, and
        # the fleet criterion wants the RAMP's scale-up, blamed on a
        # real anomaly
        wave = max(1, rs.scale_up_queue_depth - 1)
        warmups = []
        for i in range(0, n_warm, wave):
            batch = [warm_req() for _ in range(min(wave, n_warm - i))]
            for w in batch:
                w.wait(timeout=120.0)
            warmups.extend(batch)
    else:
        warmups = [warm_req() for _ in range(n_warm)]
    warm_completed = 0
    for w in warmups:
        w.wait(timeout=120.0)
        try:
            w.result(timeout=0.5)
            warm_completed += 1
        except BaseException:
            pass  # shed warmups don't count toward conservation
    print("[load_check] warmup done, starting offered load",
          file=sys.stderr)

    records = []
    stop_evt = threading.Event()
    killed_evt = threading.Event()
    kill_info = {}
    if args.no_kill:
        killed_evt.set()
    gen = threading.Thread(
        target=offered_load,
        args=(rs, args, records, stop_evt, killed_evt, fi, kill_info),
        daemon=True,
    )
    t_run0 = time.monotonic()
    gen.start()
    gen.join(timeout=args.warm_s + args.ramp_s + args.post_s + 60.0)
    stop_evt.set()

    # -- account for EVERY offered request (criterion 2) -----------------
    lat = {"warm": [], "ramp": [], "post": []}
    counts = {"offered": 0, "completed": 0, "shed_submit": 0,
              "shed_typed": 0, "hung_or_silent": 0, "untyped_error": 0}
    shed_reasons = {}
    wait_budget = time.monotonic() + 90.0
    for rec in records:
        counts["offered"] += 1
        if rec.req is None:  # shed synchronously at submit — typed
            counts["shed_submit"] += 1
            reason = getattr(rec.submit_error, "reason", "unknown")
            shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
            continue
        try:
            rec.req.result(timeout=max(0.5, wait_budget - time.monotonic()))
            counts["completed"] += 1
            lat[rec.phase].append(rec.req.finished_t - rec.req.submitted_t)
        except RequestShedError as e:
            counts["shed_typed"] += 1
            reason = getattr(e, "reason", "unknown")
            shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
        except InferenceTimeout:
            counts["hung_or_silent"] += 1
        except BaseException as e:
            counts["untyped_error"] += 1
            print(f"[load_check] UNTYPED failure: {type(e).__name__}: {e}",
                  file=sys.stderr)
    t_run = time.monotonic() - t_run0

    # criterion 3 needs the replacement replica live before we judge
    if not args.no_kill:
        t_wait = time.monotonic() + 30.0
        while (rs.replica_count() < args.replicas
               and time.monotonic() < t_wait):
            time.sleep(0.1)

    def p99(xs):
        return float(np.percentile(xs, 99)) if xs else float("nan")

    pre_p99 = p99(lat["warm"])
    load_p99 = p99(lat["ramp"] + lat["post"])
    bound = args.p99_factor * max(pre_p99, args.p99_floor_s)
    summary = {
        "devices": ndev,
        "counts": counts,
        "shed_reasons": shed_reasons,
        "latency_s": {
            "pre_ramp_p99": round(pre_p99, 4),
            "under_load_p99": round(load_p99, 4),
            "bound": round(bound, 4),
            "admitted_warm": len(lat["warm"]),
            "admitted_ramp": len(lat["ramp"]),
            "admitted_post": len(lat["post"]),
        },
        "failover": {
            "killed": killed_evt.is_set() and not args.no_kill,
            "restarts": rs.stats["restarts"],
            "requeued": rs.stats["requeued"],
            "spares_used": rs.stats["spares_used"],
            "replicas_at_end": rs.replica_count(),
            "elastic_ckpt": True,
        },
        "run_seconds": round(t_run, 2),
        "replica_stats": rs.aggregate_stats(),
    }
    cold = rs.stats["cold_start_s"]
    summary["cold_start"] = {
        "builds": len(cold),
        "p95_s": round(float(np.percentile(cold, 95)), 4) if cold
        else None,
        "max_s": round(max(cold), 4) if cold else None,
        "artifact_store": bool(store),
        "cache_counts": dict(store.counts) if store else None,
    }

    failures = []
    # criterion 1: bounded tail latency for admitted requests
    if not lat["warm"]:
        failures.append("no pre-ramp completions to baseline p99 against")
    elif lat["ramp"] + lat["post"] and not load_p99 <= bound:
        failures.append(
            f"admitted p99 under load {load_p99:.3f}s exceeds bound "
            f"{bound:.3f}s (pre-ramp p99 {pre_p99:.3f}s x "
            f"{args.p99_factor})"
        )
    # criterion 2: zero silent drops or hangs
    if counts["hung_or_silent"] or counts["untyped_error"]:
        failures.append(
            f"silent/hung/untyped requests: {counts['hung_or_silent']} hung, "
            f"{counts['untyped_error']} untyped"
        )
    if counts["completed"] == 0:
        failures.append("no requests completed at all")
    # criterion 3: the killed replica came back (elastic restore path)
    if not args.no_kill:
        if not killed_evt.is_set():
            failures.append("replica kill never fired")
        if rs.stats["restarts"] < 1:
            failures.append("killed replica was not restarted")
        if rs.replica_count() < args.replicas:
            failures.append(
                f"replica strength {rs.replica_count()} < "
                f"{args.replicas} at end"
            )
    # cold-start criterion (with --artifact-store): replica builds hit
    # the strategy cache instead of re-searching, and nothing corrupted
    if store is not None:
        if store.counts.get("hit", 0) < 1:
            failures.append(
                "artifact store attached but no replica build hit the "
                f"strategy cache (counts: {store.counts})"
            )
        if store.counts.get("corrupt", 0):
            failures.append(
                f"artifact store reported {store.counts['corrupt']} "
                "corrupt entr(ies) during the run"
            )

    rs.stop()

    # criterion 4: the request flight recorder is coherent
    if telemetry is not None:
        import flexflow_tpu.obs as obs

        obs.finish()  # flush events.jsonl + trace.json
        verdict, trace_failures = verify_request_trace(
            args.telemetry_dir,
            expect_requeue=killed_evt.is_set() and not args.no_kill,
        )
        summary["trace"] = verdict
        failures.extend(trace_failures)

    # fleet criteria (with --fleet-spool): counter conservation through
    # the kill, victim classification, anomaly-attributed scale-ups, and
    # a valid replica_death forensics bundle. Judged after obs.finish()
    # so events.jsonl is flushed.
    if args.fleet_spool:
        fleet_verdict, fleet_failures = verify_fleet(
            args,
            expected_requests=warm_completed + counts["completed"],
            victim=kill_info.get("victim"),
            killed=killed_evt.is_set() and not args.no_kill,
        )
        summary["fleet"] = fleet_verdict
        failures.extend(fleet_failures)

    print(json.dumps(summary, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2, default=str)
    if failures:
        for f_ in failures:
            print(f"[load_check] FAIL: {f_}", file=sys.stderr)
        return 1
    print("[load_check] OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
