"""Static model of the overlapped executor step schedule.

PR 8's overlapped gradient sync (parallel/executor.py
``set_overlap_grad_sync``) decomposes the data-parallel step into
per-weight task chains — backward → reduce-scatter(grad) → sharded
optimizer update → all-gather(updated params) — with the collectives
issued asynchronously so they hide behind later backward compute, and
with the old param/optimizer storage DONATED to the new values. That
schedule is correct only because of dataflow edges XLA inserts; a
rewrite that drops one (or a buffer two tasks secretly share, e.g. a
tied weight) turns into a silent read-of-garbage the runtime canary
(runtime/verify.py) only catches probabilistically.

This module makes the schedule a first-class static object:

  * ``ScheduleTask`` — one step task: what it reads, writes, donates,
    what must complete before it, and whether it is an async collective
    (completion unordered unless a dependency edge says otherwise).
  * ``build_overlap_schedule(graph, eligible)`` — reconstructs the
    executor's overlapped step for a PCG: the same per-weight chains
    ``_make_step`` traces, with buffers named by VALUE (weight buffers
    by tensor guid, so tied weights alias).
  * ``PCGExecutor.overlap_schedule()`` — the introspection hook: the
    live executor describes its own schedule through this builder.
  * ``schedule_race_diagnostics(schedule)`` — the FFA502 checker: walks
    the happens-before relation and flags (a) a donated buffer a task
    can still read, (b) an async collective's output read without a
    completion edge, (c) unordered writer/reader pairs on one buffer.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .diagnostics import AnalysisReport, Severity


@dataclasses.dataclass(frozen=True)
class ScheduleTask:
    """One task of the (modelled) executor step.

    Buffers are VALUES, not storage: a task that `donates` a buffer
    consumes its storage while producing a successor value under a new
    name (the all-gather donates ``param:<guid>`` and writes
    ``param_next:<guid>``). ``after`` lists task names that must have
    COMPLETED before this task may start; for an ``async_collective``
    the dependency edge is also the only completion guarantee readers
    of its outputs can rely on.
    """

    name: str
    kind: str  # backward | reduce_scatter | update | all_gather | all_reduce | barrier
    reads: Tuple[str, ...] = ()
    writes: Tuple[str, ...] = ()
    donates: Tuple[str, ...] = ()
    after: Tuple[str, ...] = ()
    async_collective: bool = False
    op_guid: Optional[int] = None
    op_name: str = ""


class OverlapSchedule:
    """An ordered collection of ScheduleTasks (one modelled step)."""

    def __init__(self, tasks: Sequence[ScheduleTask]):
        self.tasks: List[ScheduleTask] = list(tasks)
        self._by_name: Dict[str, ScheduleTask] = {}
        for t in self.tasks:
            if t.name in self._by_name:
                raise ValueError(f"duplicate schedule task {t.name!r}")
            self._by_name[t.name] = t

    def task(self, name: str) -> ScheduleTask:
        return self._by_name[name]

    def replace(self, name: str, **changes) -> "OverlapSchedule":
        """A copy with one task altered — the seeded-defect seam tests
        use to drop a dependency edge or mis-donate a buffer."""
        return OverlapSchedule([
            dataclasses.replace(t, **changes) if t.name == name else t
            for t in self.tasks
        ])

    def without(self, name: str) -> "OverlapSchedule":
        return OverlapSchedule([t for t in self.tasks if t.name != name])

    def __iter__(self):
        return iter(self.tasks)

    def __len__(self):
        return len(self.tasks)

    def __repr__(self):
        return f"OverlapSchedule({len(self.tasks)} task(s))"


@dataclasses.dataclass(frozen=True)
class _OpRef:
    """Minimal op stand-in so AnalysisReport.add can anchor a schedule
    diagnostic to the originating PCG op."""

    guid: Optional[int]
    name: str


def build_overlap_schedule(graph, eligible: Set[Tuple[str, str]],
                           ) -> OverlapSchedule:
    """Reconstruct the overlapped step schedule for `graph`.

    eligible: the (op name, weight name) pairs on the overlapped
    reduce-scatter → sharded-update → all-gather path (the executor's
    ``_overlap_specs()`` keys). Every other weight rides the plain
    all-reduce + full update path. Weight buffers are named by tensor
    guid so weights shared between ops alias to ONE buffer — exactly
    the aliasing the donation-race check exists for.
    """
    topo = graph.topo_order()
    prod = graph.producers()
    consumers: Dict[int, List] = {}
    for op in topo:
        for t in op.inputs:
            p = prod.get(t.guid)
            if p is not None:
                consumers.setdefault(p[0].guid, []).append(op)

    tasks: List[ScheduleTask] = []
    # -- backward pass: op's bwd starts once every consumer's bwd is done
    for op in topo:
        after = tuple(sorted({f"bwd:{c.name}"
                              for c in consumers.get(op.guid, [])}))
        reads = tuple(f"param:{w.guid}" for w in op.weights)
        writes = tuple(f"grad:{op.name}.{wn}" for wn in op.weight_names)
        tasks.append(ScheduleTask(
            name=f"bwd:{op.name}", kind="backward", reads=reads,
            writes=writes, after=after, op_guid=op.guid, op_name=op.name,
        ))
    # -- per-weight gradient sync + update chains
    final: List[str] = []
    for op in topo:
        for wn, w in zip(op.weight_names, op.weights):
            key = f"{op.name}.{wn}"
            if (op.name, wn) in eligible:
                tasks.append(ScheduleTask(
                    name=f"rs:{key}", kind="reduce_scatter",
                    reads=(f"grad:{key}",), writes=(f"gshard:{key}",),
                    after=(f"bwd:{op.name}",), async_collective=True,
                    op_guid=op.guid, op_name=op.name,
                ))
                tasks.append(ScheduleTask(
                    name=f"update:{key}", kind="update",
                    reads=(f"gshard:{key}", f"param:{w.guid}",
                           f"opt:{key}"),
                    writes=(f"pshard:{key}", f"opt_next:{key}"),
                    donates=(f"opt:{key}",),
                    after=(f"rs:{key}",),
                    op_guid=op.guid, op_name=op.name,
                ))
                tasks.append(ScheduleTask(
                    name=f"ag:{key}", kind="all_gather",
                    reads=(f"pshard:{key}",),
                    writes=(f"param_next:{w.guid}",),
                    donates=(f"param:{w.guid}",),
                    after=(f"update:{key}",), async_collective=True,
                    op_guid=op.guid, op_name=op.name,
                ))
                final.append(f"ag:{key}")
            else:
                tasks.append(ScheduleTask(
                    name=f"allreduce:{key}", kind="all_reduce",
                    reads=(f"grad:{key}",), writes=(f"gsync:{key}",),
                    after=(f"bwd:{op.name}",),
                    op_guid=op.guid, op_name=op.name,
                ))
                tasks.append(ScheduleTask(
                    name=f"update:{key}", kind="update",
                    reads=(f"gsync:{key}", f"param:{w.guid}",
                           f"opt:{key}"),
                    writes=(f"param_next:{w.guid}", f"opt_next:{key}"),
                    donates=(f"param:{w.guid}", f"opt:{key}"),
                    after=(f"allreduce:{key}",),
                    op_guid=op.guid, op_name=op.name,
                ))
                final.append(f"update:{key}")
    # -- step barrier: the jitted step's outputs (updated params + opt
    # state) are data-dependent on every chain's last task — the edge
    # that guarantees no collective is still in flight when the next
    # step's forward reads the params
    reads = tuple(sorted(
        b for t in tasks for b in t.writes
        if b.startswith(("param_next:", "opt_next:"))
    ))
    tasks.append(ScheduleTask(
        name="step_end", kind="barrier", reads=reads,
        after=tuple(sorted(final)),
    ))
    return OverlapSchedule(tasks)


def _closure(schedule: OverlapSchedule) -> Tuple[Dict[str, int], List[int]]:
    """name -> index plus reach[i] = bitmask of tasks that must COMPLETE
    before task i starts (transitive closure over `after` edges)."""
    idx = {t.name: i for i, t in enumerate(schedule.tasks)}
    n = len(schedule.tasks)
    reach = [0] * n
    # iterate to a fixed point (schedules are tiny; edges may be listed
    # in any order, so one pass is not enough in general)
    changed = True
    while changed:
        changed = False
        for i, t in enumerate(schedule.tasks):
            m = reach[i]
            for a in t.after:
                j = idx.get(a)
                if j is None:
                    continue
                m |= reach[j] | (1 << j)
            if m != reach[i]:
                reach[i] = m
                changed = True
    return idx, reach


def schedule_race_diagnostics(schedule: OverlapSchedule) -> AnalysisReport:
    """FFA502: static overlap race / aliasing detection over a modelled
    step schedule. Every finding is a schedule that can read freed or
    half-written memory on a real asynchronous runtime — the bug class
    the dynamic SDC canary only catches when the race actually loses.
    """
    rep = AnalysisReport()
    idx, reach = _closure(schedule)

    def before(a: ScheduleTask, b: ScheduleTask) -> bool:
        """a is guaranteed complete before b starts."""
        return bool(reach[idx[b.name]] & (1 << idx[a.name]))

    # dangling dependency edges make every downstream guarantee void
    for t in schedule:
        for a in t.after:
            if a not in idx:
                rep.add(
                    Severity.ERROR, "FFA502",
                    f"task {t.name} depends on unknown task {a!r} — the "
                    "ordering it promises does not exist",
                    op=_OpRef(t.op_guid, t.op_name),
                )

    readers: Dict[str, List[ScheduleTask]] = {}
    writers: Dict[str, List[ScheduleTask]] = {}
    for t in schedule:
        for b in t.reads:
            readers.setdefault(b, []).append(t)
        for b in t.writes:
            writers.setdefault(b, []).append(t)

    for t in schedule:
        # (a) donation race: once t donates buffer B its storage belongs
        # to t's output — every other reader of B must be provably done
        for b in t.donates:
            for r in readers.get(b, []):
                if r.name == t.name:
                    continue  # in-place consume of its own input
                if not before(r, t):
                    rep.add(
                        Severity.ERROR, "FFA502",
                        f"{r.name} ({r.kind}) can read buffer {b!r} "
                        f"while/after {t.name} ({t.kind}) donates its "
                        "storage — the read observes reused memory "
                        "(donation race)",
                        op=_OpRef(t.op_guid, t.op_name or r.op_name),
                        fix_hint=f"order {r.name} before {t.name} (add "
                                 "the dependency edge) or stop donating "
                                 f"{b!r}",
                    )
        # (b) pending-collective read: an async collective's output is
        # complete only past a dependency edge on the collective
        if t.async_collective:
            for b in t.writes:
                for r in readers.get(b, []):
                    if r.name == t.name:
                        continue
                    if not before(t, r):
                        rep.add(
                            Severity.ERROR, "FFA502",
                            f"{r.name} ({r.kind}) reads {b!r} with no "
                            f"completion edge on the pending {t.kind} "
                            f"{t.name} — the collective may still be in "
                            "flight (overlap race)",
                            op=_OpRef(t.op_guid, t.op_name or r.op_name),
                            fix_hint=f"make {r.name} depend on {t.name}",
                        )
    # (c) unordered writer/reader or writer/writer pairs (in-place
    # update vs a concurrent reader of the old value)
    for b, ws in writers.items():
        for w in ws:
            if w.async_collective:
                continue  # rule (b) already covers async writers
            for r in readers.get(b, []):
                if r.name == w.name:
                    continue
                if not before(w, r) and not before(r, w):
                    rep.add(
                        Severity.ERROR, "FFA502",
                        f"{w.name} ({w.kind}) writes {b!r} concurrently "
                        f"with {r.name} ({r.kind}) reading it — the read "
                        "is nondeterministic (in-place update race)",
                        op=_OpRef(w.op_guid, w.op_name or r.op_name),
                        fix_hint=f"order {r.name} and {w.name}",
                    )
        for i in range(len(ws)):
            for j in range(i + 1, len(ws)):
                a, c = ws[i], ws[j]
                if not before(a, c) and not before(c, a):
                    rep.add(
                        Severity.ERROR, "FFA502",
                        f"{a.name} and {c.name} both write {b!r} with no "
                        "ordering between them", op=_OpRef(a.op_guid,
                                                           a.op_name),
                    )
    return rep
