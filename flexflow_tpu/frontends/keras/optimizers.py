"""Keras-style optimizer wrappers (reference: python/flexflow/keras/optimizers.py)."""
from __future__ import annotations

import dataclasses

from ...core.optimizers import AdamOptimizer, SGDOptimizer


class Optimizer:
    def to_core(self):
        raise NotImplementedError


@dataclasses.dataclass
class SGD(Optimizer):
    learning_rate: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0

    def to_core(self):
        return SGDOptimizer(
            lr=self.learning_rate,
            momentum=self.momentum,
            nesterov=self.nesterov,
            weight_decay=self.weight_decay,
        )


@dataclasses.dataclass
class Adam(Optimizer):
    learning_rate: float = 0.001
    beta_1: float = 0.9
    beta_2: float = 0.999
    epsilon: float = 1e-8

    def to_core(self):
        return AdamOptimizer(
            alpha=self.learning_rate,
            beta1=self.beta_1,
            beta2=self.beta_2,
            epsilon=self.epsilon,
        )
