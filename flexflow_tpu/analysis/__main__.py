"""CLI for the static analyzer.

    python -m flexflow_tpu.analysis                  # lint the shipped
                                                     # substitution collection
    python -m flexflow_tpu.analysis rules a.json b.json
    python -m flexflow_tpu.analysis model            # compile the (CPU-
                                                     # sized) bench
                                                     # Transformer and run
                                                     # the FULL pass stack
    python -m flexflow_tpu.analysis model --machine-model-file \\
        machine_config_multislice --fail-on error --json

``model`` builds the benchmark Transformer (CPU-sized by default; pass
--seq/--hidden/... for the real bench shape), searches a strategy on the
configured machine, and runs every analysis pass over the result —
including the FFA5xx perf lints (overlap-discount soundness, padding
roofline, slice-boundary collective cost) and the FFA502 overlap-race
audit of the executor's schedule. This is the CI gate: a searched
strategy whose static story does not hold exits non-zero before any
device time is spent.

``--json`` emits one machine-readable report object on stdout.
``--fail-on error`` (default) exits 1 on ERROR diagnostics;
``--fail-on warning`` also fails on warnings. Exit codes: 0 clean,
1 threshold exceeded, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import analyze_rules_path
from .diagnostics import Severity


def _exceeds(n_err: int, n_warn: int, fail_on: str) -> bool:
    return n_err > 0 or (fail_on == "warning" and n_warn > 0)


def _print_report(path_or_name: str, rep, args) -> None:
    print(f"== {path_or_name}: {len(rep.errors)} error(s), "
          f"{len(rep.warnings)} warning(s)")
    for d in rep:
        if args.quiet and d.severity is not Severity.ERROR:
            continue
        print("  " + d.format())


def _cmd_rules(args) -> int:
    paths = args.paths
    if not paths:
        from ..search.substitution_loader import default_rules_path

        paths = [default_rules_path()]

    files = []
    n_err = n_warn = 0
    for path in paths:
        rep = analyze_rules_path(path)
        n_err += len(rep.errors)
        n_warn += len(rep.warnings)
        if args.json:
            files.append({
                "path": path,
                "errors": len(rep.errors),
                "warnings": len(rep.warnings),
                "diagnostics": [d.to_dict() for d in rep],
            })
        else:
            _print_report(path, rep, args)
    if args.json:
        print(json.dumps({
            "command": "rules", "errors": n_err, "warnings": n_warn,
            "fail_on": args.fail_on, "files": files,
        }, indent=2))
    return 1 if _exceeds(n_err, n_warn, args.fail_on) else 0


def _cmd_model(args) -> int:
    import jax

    from .. import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from ..ff_types import DataType
    from ..models.transformer import build_transformer
    from . import analyze_graph

    cfg = FFConfig()
    cfg.batch_size = args.batch
    if args.machine_model_file:
        cfg.machine_model_file = args.machine_model_file
    if args.budget is not None:
        cfg.search_budget = args.budget
    if args.overlap_discount:
        cfg.search_overlap_backward_update = True
    if args.mixed_precision:
        cfg.allow_mixed_precision = True
    if args.drift_budget is not None:
        cfg.precision_drift_budget = args.drift_budget
    model = FFModel(cfg)
    build_transformer(
        model, batch_size=args.batch, seq_length=args.seq,
        hidden_size=args.hidden, num_heads=args.heads,
        num_layers=args.layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    cost_model = model._build_cost_model()
    # the strategy was searched FOR the configured machine — analyze it
    # against that machine's device count, not this host's (a 2-slice
    # config on a CPU dev box still carries 32-part views by design;
    # lowering demotes what the live mesh can't shard)
    if args.machine_model_file:
        ndev = cost_model.machine.num_workers
    else:
        ndev = min(model.config.numWorkers, len(jax.devices()))
    rep = analyze_graph(
        model.graph,
        views=getattr(model, "searched_views", None),
        num_devices=ndev,
        hbm_bytes=cost_model.machine.chip.hbm_capacity,
        optimizer=model.optimizer,
        train=model._is_training_compile(),
        grad_bytes_ratio=model._grad_bytes_ratio(),
        cost_model=cost_model,
        executor=model.executor,
        drift_budget=getattr(cfg, "precision_drift_budget", None),
        grad_dtype=(DataType.DT_BF16 if model._grad_bytes_ratio() < 1.0
                    else None),
        step_guard=getattr(model.executor, "step_guard", None),
    )
    name = (f"bench transformer (b{args.batch} s{args.seq} "
            f"h{args.hidden} x{args.layers}, {ndev} device(s))")
    if args.json:
        print(json.dumps({
            "command": "model",
            "model": "transformer",
            "batch": args.batch, "seq": args.seq,
            "hidden": args.hidden, "heads": args.heads,
            "layers": args.layers,
            "machine_model_file": args.machine_model_file or None,
            "num_devices": ndev,
            "searched_cost_s": getattr(model, "searched_cost", None),
            "errors": len(rep.errors), "warnings": len(rep.warnings),
            "fail_on": args.fail_on,
            "diagnostics": [d.to_dict() for d in rep],
        }, indent=2))
    else:
        _print_report(name, rep, args)
    return 1 if _exceeds(len(rep.errors), len(rep.warnings),
                         args.fail_on) else 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.analysis",
        description="Static PCG / substitution-rule / strategy-perf "
                    "analyzer",
    )
    p.add_argument("command", nargs="?", default="rules",
                   choices=["rules", "model"],
                   help="what to analyze (default: rules)")
    p.add_argument("paths", nargs="*",
                   help="substitution-rule JSON files (default: the "
                        "shipped collection)")
    p.add_argument("--quiet", action="store_true",
                   help="only print errors")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON report on stdout")
    p.add_argument("--fail-on", choices=["error", "warning"],
                   default="error",
                   help="severity threshold for a non-zero exit "
                        "(default: error)")
    # model-command shape/search knobs (CPU-sized defaults, like
    # `python -m flexflow_tpu.obs explain`)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--budget", type=int, default=None,
                   help="search budget override")
    p.add_argument("--machine-model-file", default="",
                   help="machine description to search/analyze against "
                        "(e.g. machine_config_multislice)")
    p.add_argument("--overlap-discount", action="store_true",
                   help="search with the overlappable-collective "
                        "discount on, so FFA501 audits a live discount")
    p.add_argument("--mixed-precision", action="store_true",
                   help="compile the bench model under bf16 AMP so the "
                        "FFA7xx precision pass audits an annotated "
                        "mixed-precision flow")
    p.add_argument("--drift-budget", type=float, default=None,
                   help="FFA705 accumulated-drift budget override "
                        "(default: FFConfig.precision_drift_budget)")
    args = p.parse_args(argv)

    if args.command == "model":
        return _cmd_model(args)
    return _cmd_rules(args)


if __name__ == "__main__":
    sys.exit(main())
