"""ResNet / ResNeXt model builders.

Same networks as reference examples/cpp/ResNet/resnet.cc (BottleneckBlock)
and examples/cpp/resnext50/resnext.cc (grouped-conv ResNeXt-50), expressed
through the FFModel API.
"""
from __future__ import annotations

from ..core.model import FFModel
from ..ff_types import ActiMode, DataType, PoolType


def bottleneck_block(model: FFModel, t, out_channels: int, stride: int,
                     projection: bool):
    """reference: resnet.cc BottleneckBlock — 1x1 / 3x3 / 1x1 conv with
    batch-norm and residual add."""
    shortcut = t
    t = model.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0)
    t = model.batch_norm(t, relu=True)
    t = model.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1)
    t = model.batch_norm(t, relu=True)
    t = model.conv2d(t, out_channels * 4, 1, 1, 1, 1, 0, 0)
    t = model.batch_norm(t, relu=False)
    if projection:
        shortcut = model.conv2d(shortcut, out_channels * 4, 1, 1, stride, stride, 0, 0)
        shortcut = model.batch_norm(shortcut, relu=False)
    t = model.add(t, shortcut)
    return model.relu(t)


def build_resnet(model: FFModel, batch_size: int, num_classes: int = 10,
                 height: int = 229, width: int = 229, blocks_per_stage=(3, 4, 6, 3)):
    """reference: resnet.cc top_level_task (ResNet-50 shape)."""
    input_t = model.create_tensor((batch_size, 3, height, width), DataType.DT_FLOAT)
    t = model.conv2d(input_t, 64, 7, 7, 2, 2, 3, 3)
    t = model.batch_norm(t, relu=True)
    t = model.pool2d(t, 3, 3, 2, 2, 1, 1)
    channels = 64
    for stage, n_blocks in enumerate(blocks_per_stage):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            t = bottleneck_block(model, t, channels, stride, projection=(b == 0))
        channels *= 2
    t = model.pool2d(t, t.dims[2], t.dims[3], 1, 1, 0, 0, PoolType.POOL_AVG)
    t = model.flat(t)
    t = model.dense(t, num_classes)
    t = model.softmax(t)
    return input_t, t


def resnext_block(model: FFModel, t, stride: int, out_channels: int,
                  groups: int = 32, projection: bool = False):
    """reference: resnext.cc resnext_block (grouped 3x3 conv)."""
    shortcut = t
    t = model.conv2d(t, out_channels, 1, 1, 1, 1, 0, 0)
    t = model.batch_norm(t, relu=True)
    t = model.conv2d(t, out_channels, 3, 3, stride, stride, 1, 1, groups=groups)
    t = model.batch_norm(t, relu=True)
    t = model.conv2d(t, 2 * out_channels, 1, 1, 1, 1, 0, 0)
    t = model.batch_norm(t, relu=False)
    if projection or stride > 1:
        shortcut = model.conv2d(shortcut, 2 * out_channels, 1, 1, stride, stride, 0, 0)
        shortcut = model.batch_norm(shortcut, relu=False)
    t = model.add(t, shortcut)
    return model.relu(t)


def build_resnext50(model: FFModel, batch_size: int, num_classes: int = 10,
                    height: int = 224, width: int = 224):
    """reference: resnext.cc top_level_task."""
    input_t = model.create_tensor((batch_size, 3, height, width), DataType.DT_FLOAT)
    t = model.conv2d(input_t, 64, 7, 7, 2, 2, 3, 3)
    t = model.batch_norm(t, relu=True)
    t = model.pool2d(t, 3, 3, 2, 2, 1, 1)
    for stage, (n_blocks, ch) in enumerate(
        zip((3, 4, 6, 3), (128, 256, 512, 1024))
    ):
        for b in range(n_blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            t = resnext_block(model, t, stride, ch, projection=(b == 0))
    t = model.pool2d(t, t.dims[2], t.dims[3], 1, 1, 0, 0, PoolType.POOL_AVG)
    t = model.flat(t)
    t = model.dense(t, num_classes)
    t = model.softmax(t)
    return input_t, t
