#!/usr/bin/env bash
# Content-addressed prefix-sharing KV pool check (docs/serving.md
# "Prefix sharing"): the dedup win, the chaos legs, and the auditor
# exit-code contract. Three legs:
#   1. 8-device mesh, starved pool, shared-prefix load — sharing must
#      sustain >= 5x the concurrent sessions of the unshared pool in
#      the SAME page budget, token-exact vs incremental_generate, with
#      zero PagePool.audit() violations and zero leaked pages;
#   2. 4-device mesh, chaos sweep over the three new fault sites
#      (shared_page_corruption / release_race / cow_fault) against a
#      live batcher AND the randomized pool selftest — every leg must
#      end typed-only and audit-clean;
#   3. auditor CLI exit codes — `python -m flexflow_tpu.runtime.kvcache
#      audit` returns 0 on a clean state dump and 1 on a corrupted one.
# CI wires this into the lint workflow alongside the other *_check.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
OUT="$(mktemp -d)"
trap 'rm -rf "$OUT"' EXIT

echo "=== kvshare_check leg 1: 8-device mesh, starved pool, shared-prefix load ==="
JAX_NUM_CPU_DEVICES=8 python scripts/load_check.py --shared-prefix \
    --hidden 16 --layers 1 --heads 2 --search-budget 1 \
    --json "$OUT/leg1.json"

echo "=== kvshare_check leg 2: 4-device mesh, chaos sweep over the new fault sites ==="
JAX_NUM_CPU_DEVICES=4 python - "$OUT" <<'EOF'
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("JAX_NUM_CPU_DEVICES", "4")
).strip()

import numpy as np

from flexflow_tpu.runtime.kvcache import (KVCacheAccountingError,
                                          KVCacheConfig, PagePool,
                                          SharedPageCorruptionError)
from flexflow_tpu.runtime.resilience import FaultInjector
from flexflow_tpu.runtime.serving import (AdmissionQueue, ContinuousBatcher,
                                          GenerationRequest, ServingConfig,
                                          incremental_generate)
from tests.test_serving import VOCAB, build_lm

lm = build_lm()
rng = np.random.RandomState(0)
prompt = rng.randint(0, VOCAB, 8).astype(np.int32)
ref = incremental_generate(lm, prompt[None], max_new_tokens=4)[0]

# -- serving legs: corruption degrades, an armed cow_fault never fires
# (decode cannot write a shared page), release_race dies TYPED ---------
for site in ("shared_page_corruption", "cow_fault", "release_race"):
    fi = FaultInjector()
    fi.inject(site, times=1)
    cfg = ServingConfig(max_len=16, slots=2, page_size=4,
                        precompile=False, default_deadline_s=120.0)
    q = AdmissionQueue(max_depth=8)
    b = ContinuousBatcher(lm, cfg, q, fault_injector=fi).start()
    n = 1 if site == "release_race" else 3
    reqs = [GenerationRequest(prompt.copy(), 4, deadline_s=120.0)
            for _ in range(n)]
    for r in reqs:
        q.offer(r)
    for r in reqs:
        np.testing.assert_array_equal(r.result(timeout=120.0), ref)
    b.stop()
    report = b.pool.audit()
    assert report.ok, (site, report.to_dict())
    assert report.pages_resident == 0, (site, "leaked pages")
    if site == "shared_page_corruption":
        assert b.pool.stats["corruptions"] >= 1, site
    elif site == "cow_fault":
        assert fi.fired.get("cow_fault", 0) == 0, \
            "decode wrote a shared page: immutability broken"
    else:
        assert b.dead and isinstance(b.death_cause, KVCacheAccountingError)
        assert b.death_cause.kind == "double_release"
    print(f"kvshare_check: serving chaos leg {site} audit-clean")

# -- pool-level typed legs (cow_fault can only fire here) --------------
fi = FaultInjector()
pool = PagePool(KVCacheConfig(num_pages=32, page_size=4),
                fault_injector=fi)
toks = list(range(100, 132))
pool.reserve("a", 36, tokens=toks)
pool.touch("a", 32)
pool.publish("a", toks)
pool.reserve("b", 36, tokens=toks, writable=True)
fi.inject("cow_fault")
try:
    pool.note_write("b", 0)
    raise SystemExit("cow_fault did not surface typed")
except KVCacheAccountingError as e:
    assert e.kind == "cow_fault"
assert pool.audit().ok  # the fault fired BEFORE any mutation
fi.inject("shared_page_corruption")
try:
    pool.match_prefix(toks)
    raise SystemExit("shared_page_corruption did not surface typed")
except SharedPageCorruptionError:
    pass
pool.release("a")
pool.release("b")
report = pool.audit()
assert report.ok and report.pages_resident == 0
print("kvshare_check: pool-level typed legs audit-clean")
EOF

echo "=== kvshare_check leg 2b: randomized pool selftest under chaos ==="
JAX_NUM_CPU_DEVICES=4 python -m flexflow_tpu.runtime.kvcache \
    selftest --ops 600 --seed 1 > "$OUT/selftest.json"
python - "$OUT" <<'EOF'
import json
import sys

s = json.load(open(f"{sys.argv[1]}/selftest.json"))
assert s["ok"] and s["drained"] and s["violations"] == 0, s
print(f"kvshare_check: selftest {s['ops']} ops, "
      f"{s['typed_errors']} typed error(s), 0 violations — OK")
EOF

echo "=== kvshare_check leg 3: auditor CLI exit codes ==="
python - "$OUT" <<'EOF'
import json
import sys

from flexflow_tpu.runtime.kvcache import KVCacheConfig, PagePool

pool = PagePool(KVCacheConfig(num_pages=16, page_size=4))
pool.reserve("a", 16, tokens=list(range(16)))
pool.touch("a", 16)
pool.publish("a", list(range(16)))
pool.dump_state(f"{sys.argv[1]}/clean.json")
state = pool.to_state()
state["free"].append(state["tables"]["a"][0])  # seq holds a freed page
with open(f"{sys.argv[1]}/corrupt.json", "w") as f:
    json.dump(state, f)
EOF
python -m flexflow_tpu.runtime.kvcache audit "$OUT/clean.json" \
    || { echo "kvshare_check: FAIL — clean state flagged"; exit 1; }
if python -m flexflow_tpu.runtime.kvcache audit "$OUT/corrupt.json" \
    > "$OUT/corrupt_audit.json"; then
  echo "kvshare_check: FAIL — corrupted state passed the auditor"
  exit 1
fi
grep -q '"freed_page_bound"' "$OUT/corrupt_audit.json" \
    || { echo "kvshare_check: FAIL — wrong violation kind"; exit 1; }
echo "kvshare_check: auditor exit codes OK (0 clean / 1 corrupt) — OK"
