"""Train the CIFAR-10 CNN imported from ONNX (reference:
examples/python/onnx/cifar10_cnn.py)."""
import os
import numpy as np

from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import cifar10
from flexflow.onnx.model import ONNXModel

from _example_args import example_args
from cifar10_cnn_pt import export


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    input1 = ffmodel.create_tensor([args.batch_size, 3, 32, 32], DataType.DT_FLOAT)

    path = "cifar10_cnn_pt.onnx"
    if not os.path.exists(path):
        export(path)
    onnx_model = ONNXModel(path)
    t = onnx_model.apply(ffmodel, {"input.1": input1})

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY])
    onnx_model.load_weights(ffmodel)

    (x_train, y_train), _ = cifar10.load_data(n_train=args.num_samples)
    x_train = x_train.transpose(0, 3, 1, 2).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    ffmodel.fit(x=x_train, y=y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("cifar10 cnn onnx")
    top_level_task(example_args())
