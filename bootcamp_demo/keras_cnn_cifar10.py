"""Bootcamp demo, step 3: the same CIFAR-10 CNN through the Keras-compatible
frontend (reference: bootcamp_demo/keras_cnn_cifar10.py).

Run: python bootcamp_demo/keras_cnn_cifar10.py
"""
from flexflow.keras.models import Sequential
from flexflow.keras.layers import (
    Activation,
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    MaxPooling2D,
)
from flexflow.keras.optimizers import SGD
from flexflow.keras.datasets import cifar10


def top_level_task():
    import os

    num_classes = 10
    num_samples = int(os.environ.get("BOOTCAMP_NUM_SAMPLES", 10000))

    (x_train, y_train), _ = cifar10.load_data(num_samples)
    x_train = x_train[:num_samples]
    y_train = y_train[:num_samples]
    if x_train.shape[-1] == 3:  # to the reference's (N, 3, 32, 32) layout
        x_train = x_train.transpose(0, 3, 1, 2)
    x_train = x_train.astype("float32") / 255
    y_train = y_train.astype("int32")
    print("shape: ", x_train.shape[1:])

    model = Sequential()
    model.add(
        Conv2D(filters=32, input_shape=(3, 32, 32), kernel_size=(3, 3),
               strides=(1, 1), padding="valid", activation="relu")
    )
    model.add(Conv2D(filters=32, kernel_size=(3, 3), strides=(1, 1),
                     padding="valid", activation="relu"))
    model.add(MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid"))
    model.add(Conv2D(filters=64, kernel_size=(3, 3), strides=(1, 1),
                     padding="valid", activation="relu"))
    model.add(Conv2D(filters=64, kernel_size=(3, 3), strides=(1, 1),
                     padding="valid"))
    model.add(Activation("relu"))
    model.add(MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid"))
    model.add(Flatten())
    model.add(Dense(512))
    model.add(Activation("relu"))
    model.add(Dropout(0.5))
    model.add(Dense(num_classes))
    model.add(Activation("softmax"))

    opt = SGD(learning_rate=0.01)
    model.compile(
        optimizer=opt,
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy", "sparse_categorical_crossentropy"],
    )
    print(model.summary())

    model.fit(x_train, y_train, batch_size=64, epochs=4)


if __name__ == "__main__":
    print("Sequential API, cifar10 cnn")
    top_level_task()
