"""Shared argv handling (see examples/python/keras/_example_args.py)."""
import argparse


def example_args(epochs=3, num_samples=2048, batch_size=64):
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=epochs)
    p.add_argument("--num-samples", type=int, default=num_samples)
    p.add_argument("-b", "--batch-size", type=int, default=batch_size)
    p.add_argument("--verify", action="store_true")
    args, _ = p.parse_known_args()
    return args
