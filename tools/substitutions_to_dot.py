#!/usr/bin/env python
"""Render substitution rules as graphviz dot — one digraph per rule, source
pattern and target pattern as clustered subgraphs with external inputs as
ellipses and mapped outputs as dashed edges.

TPU-native equivalent of reference tools/substitutions_to_dot (C++ over the
same JSON). Works on both the reference's TASO-style JSON
(substitutions/graph_subst_3_v2.json) and the output of
tools/rules_to_json.py.

Usage:
  python tools/substitutions_to_dot.py rules.json out_dir/ [--limit N]
"""
from __future__ import annotations

import json
import os
import sys


def _pattern_cluster(lines, ops, prefix, label, color):
    lines.append(f'  subgraph cluster_{prefix} {{')
    lines.append(f'    label="{label}"; color={color};')
    ext_inputs = set()
    for i, op in enumerate(ops):
        paras = {
            p["key"]: p["value"] for p in op.get("para", [])
        }
        para_str = "".join(
            f'\\n{k.replace("PM_", "").lower()}={v}' for k, v in paras.items()
        )
        typ = op.get("type", "?").replace("OP_", "")
        lines.append(
            f'    {prefix}{i} [shape=box, label="{i}: {typ}{para_str}"];'
        )
        for t in op.get("input", []):
            op_id, ts_id = t.get("opId", 0), t.get("tsId", 0)
            if op_id < 0:  # external input k encoded as -1-k
                ext = -op_id - 1
                ext_inputs.add(ext)
                lines.append(
                    f'    {prefix}in{ext} -> {prefix}{i} [label="t{ts_id}"];'
                )
            else:
                lines.append(
                    f'    {prefix}{op_id} -> {prefix}{i} [label="t{ts_id}"];'
                )
    for ext in sorted(ext_inputs):
        lines.append(
            f'    {prefix}in{ext} [shape=ellipse, label="input {ext}"];'
        )
    lines.append("  }")


def rule_to_dot(rule: dict, name: str) -> str:
    lines = [f'digraph "{name}" {{', "  rankdir=TB;"]
    _pattern_cluster(lines, rule.get("srcOp", []), "src", "source", "red")
    _pattern_cluster(lines, rule.get("dstOp", []), "dst", "target", "blue")
    for m in rule.get("mappedOutput", []):
        lines.append(
            f'  src{m["srcOpId"]} -> dst{m["dstOpId"]} '
            f'[style=dashed, color=gray, '
            f'label="out t{m["srcTsId"]}->t{m["dstTsId"]}"];'
        )
    lines.append("}")
    return "\n".join(lines)


def main(argv):
    if len(argv) < 3:
        print(__doc__, file=sys.stderr)
        return 1
    limit = None
    if "--limit" in argv:
        i = argv.index("--limit")
        limit = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    with open(argv[1]) as f:
        data = json.load(f)
    rules = data["rule"] if isinstance(data, dict) else data
    os.makedirs(argv[2], exist_ok=True)
    for i, rule in enumerate(rules[:limit]):
        name = rule.get("name", f"rule_{i}")
        with open(os.path.join(argv[2], f"{name}.dot"), "w") as f:
            f.write(rule_to_dot(rule, name))
    print(f"wrote {len(rules[:limit])} dot files to {argv[2]}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
