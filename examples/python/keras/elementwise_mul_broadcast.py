"""Broadcasted Multiply merges (reference:
examples/python/keras/elementwise_mul_broadcast.py broadcast1/2)."""
import numpy as np

from flexflow.keras.models import Model
from flexflow.keras.layers import Input, Dense, Reshape, Multiply
import flexflow.keras.optimizers

from _example_args import example_args


def broadcast(args, first_bigger: bool):
    in0 = Input(shape=(32,), dtype="float32")
    in1 = Input(shape=(10,), dtype="float32")
    x0 = Dense(20, activation="relu")(in0)
    x1 = Dense(10, activation="relu")(in1)
    nx0 = Reshape((10, 2))(x0)
    nx1 = Reshape((10, 1))(x1)
    pair = [nx0, nx1] if first_bigger else [nx1, nx0]
    m0 = Multiply()(pair)  # broadcast (10,1)x(10,2) -> (10,2)
    f0 = Reshape((20,))(m0)
    out = Dense(1)(f0)
    model = Model([in0, in1], out)
    model.compile(optimizer=flexflow.keras.optimizers.Adam(learning_rate=0.001),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    model.fit([np.random.randn(n, 32).astype(np.float32),
               np.random.randn(n, 10).astype(np.float32)],
              np.random.randn(n, 1).astype(np.float32), epochs=args.epochs)


def top_level_task(args):
    broadcast(args, True)
    broadcast(args, False)


if __name__ == "__main__":
    print("Elementwise multiply with broadcast")
    top_level_task(example_args(epochs=2, num_samples=512))
