"""Forward checks for the ONNX-surface ops added for importer coverage:
Squeeze/Unsqueeze (incl. negative axes), Where, PReLU (NCHW per-channel
slope), Resize. Reference handles these inside its ONNX importer
(python/flexflow/onnx/model.py) — here they are first-class registry ops."""
import numpy as np
import pytest

from flexflow_tpu import DataType, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer


def _run(build, x_arrays):
    cfg = FFConfig()
    cfg.batch_size = x_arrays[0].shape[0]
    model = FFModel(cfg)
    ins = build(model)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    ex = model.executor
    fwd = ex.build_forward()
    bx = [ex.shard_batch(pt, a) for pt, a in zip(ex.input_pts, x_arrays)]
    return np.asarray(fwd(model.state.params, bx)), model


def test_squeeze_negative_axis_and_unsqueeze():
    x = np.random.RandomState(0).randn(4, 3, 1).astype(np.float32)

    def build(m):
        t = m.create_tensor((4, 3, 1))
        t = m.squeeze(t, [-1])        # (4, 3)
        t = m.unsqueeze(t, [2])       # (4, 3, 1)
        t = m.squeeze(t)              # no axes: drop all 1-dims -> (4, 3)
        return t

    out, _ = _run(build, [x])
    assert out.shape == (4, 3)
    np.testing.assert_allclose(out, x[:, :, 0])


def test_where():
    rng = np.random.RandomState(1)
    c = (rng.rand(4, 5) > 0.5).astype(np.float32)
    a = rng.randn(4, 5).astype(np.float32)
    b = rng.randn(4, 5).astype(np.float32)

    def build(m):
        tc = m.create_tensor((4, 5))
        ta = m.create_tensor((4, 5))
        tb = m.create_tensor((4, 5))
        return m.where(tc, ta, tb)

    out, _ = _run(build, [c, a, b])
    np.testing.assert_allclose(out, np.where(c > 0, a, b))


def test_prelu_nchw_per_channel():
    x = np.random.RandomState(2).randn(2, 3, 4, 4).astype(np.float32)

    def build(m):
        t = m.create_tensor((2, 3, 4, 4))
        return m.prelu(t)

    out, model = _run(build, [x])
    # default slope 0.25, per NCHW channel (dim 1)
    (wd,) = model.state.params.values()
    assert wd["alpha"].shape == (3,)
    np.testing.assert_allclose(out, np.where(x >= 0, x, 0.25 * x), rtol=1e-6)


def test_resize_nearest():
    x = np.arange(2 * 1 * 2 * 2, dtype=np.float32).reshape(2, 1, 2, 2)

    def build(m):
        t = m.create_tensor((2, 1, 2, 2))
        return m.resize(t, (2, 1, 4, 4))

    out, _ = _run(build, [x])
    assert out.shape == (2, 1, 4, 4)
    np.testing.assert_allclose(out[:, :, ::2, ::2], x)


def test_create_constant_and_introspection():
    """cffi-parity methods: create_constant feeds the graph without being a
    fit() input; get_layer_by_name/print_layers/reset_metrics behave."""
    cfg = FFConfig()
    cfg.batch_size = 4
    m = FFModel(cfg)
    x = m.create_tensor((4, 8))
    c = m.create_constant((4, 8), 2.0)
    t = m.add(x, c, name="plus2")
    t = m.dense(t, 4, name="head")
    m.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    assert len(m.executor.input_pts) == 1  # constant excluded
    ex = m.executor
    fwd = ex.build_forward()
    xin = np.zeros((4, 8), np.float32)
    out = np.asarray(fwd(m.state.params, [xin]))
    # zeros + 2.0 through a linear head: must equal head(2*ones)
    k = np.asarray(m.state.params["head"]["kernel"])
    b = np.asarray(m.state.params["head"]["bias"])
    np.testing.assert_allclose(out, (np.full((4, 8), 2.0) @ k) + b, rtol=1e-5)
    assert m.get_layer_by_name("plus2").name == "plus2"
    m.reset_metrics()
    m.print_layers(0)


def test_batchnorm_running_stats_used_at_eval():
    """BN parity upgrade (reference: cuDNN BN running stats,
    batch_norm.cu): training updates running mean/var; predict() uses
    THEM, so an example's eval output doesn't depend on its batch."""
    import jax.numpy as jnp

    from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer)

    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 4, 6, 6), DataType.DT_FLOAT)
    t = m.batch_norm(x, relu=False)
    t = m.flat(t)
    m.dense(t, 3)
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    rng = np.random.RandomState(0)
    xs = (3.0 + 2.0 * rng.randn(64, 4, 6, 6)).astype(np.float32)
    ys = rng.randint(0, 3, (64, 1)).astype(np.int32)
    bn_name = next(op for op in m.executor.topo
                   if op.op_type.name == "OP_BATCHNORM").name
    before = np.asarray(m.state.net_state[bn_name]["running_mean"]).copy()
    m.fit(xs, ys, batch_size=8, epochs=2, verbose=False)
    after = np.asarray(m.state.net_state[bn_name]["running_mean"])
    assert not np.allclose(before, after)  # stats moved toward data mean ~3

    # the same example must eval identically in two different batches
    probe = xs[:1]
    batch_a = np.concatenate([probe, xs[1:8]])
    batch_b = np.concatenate([probe, 50.0 + xs[8:15]])
    out_a = m.predict(batch_a, batch_size=8)[0]
    out_b = m.predict(batch_b, batch_size=8)[0]
    np.testing.assert_allclose(out_a, out_b, rtol=1e-5, atol=1e-6)


def test_cache_op_serves_cached_value_at_inference():
    """Cache parity (reference: cache.cc — CACHE_UPDATE_TASK writes each
    batch, inference serves the cache): after training, predict() returns
    the cached activations, not the live input's."""
    from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer)

    cfg = FFConfig()
    cfg.batch_size = 4
    m = FFModel(cfg)
    x = m.create_tensor((4, 6), DataType.DT_FLOAT)
    t = m.cache(x, num_batches=1)
    m.dense(t, 2, use_bias=False)
    m.compile(SGDOptimizer(lr=0.0),
              LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
              [MetricsType.METRICS_MEAN_SQUARED_ERROR])
    rng = np.random.RandomState(0)
    xs = rng.randn(4, 6).astype(np.float32)
    ys = rng.randn(4, 2).astype(np.float32)
    m.fit(xs, ys, batch_size=4, epochs=1, verbose=False)
    cache_name = next(op for op in m.executor.topo
                      if op.op_type.name == "OP_CACHE").name
    np.testing.assert_allclose(
        np.asarray(m.state.net_state[cache_name]["cached"]), xs, atol=1e-6)
    # inference on DIFFERENT inputs returns the cached batch's outputs
    out_other = m.predict(rng.randn(4, 6).astype(np.float32), batch_size=4)
    out_cached = m.predict(xs, batch_size=4)
    np.testing.assert_allclose(out_other, out_cached, atol=1e-6)


def test_cache_op_integer_input_keeps_state_dtype():
    """Cache state buffers are float regardless of the input dtype: an
    int32 input's training blend is float math, and a buffer typed to the
    input would change dtype across the update and break the scan carry
    structure (ADVICE r1). Training and inference must both run."""
    import jax.numpy as jnp

    from flexflow_tpu import (AggrMode, DataType, FFConfig, FFModel,
                              LossType, MetricsType, SGDOptimizer)

    cfg = FFConfig()
    cfg.batch_size = 4
    m = FFModel(cfg)
    ids = m.create_tensor((4, 6), DataType.DT_INT32)
    t = m.cache(ids, num_batches=2)
    t = m.embedding(t, 16, 8, AggrMode.AGGR_MODE_SUM)
    m.dense(t, 2)
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
              [MetricsType.METRICS_MEAN_SQUARED_ERROR])
    rng = np.random.RandomState(0)
    xs = rng.randint(0, 16, (4, 6)).astype(np.int32)
    ys = rng.randn(4, 2).astype(np.float32)
    m.fit(xs, ys, batch_size=4, epochs=2, verbose=False)
    cache_name = next(op for op in m.executor.topo
                      if op.op_type.name == "OP_CACHE").name
    st = m.state.net_state[cache_name]
    assert st["cached"].dtype == jnp.float32
    assert st["filled"].dtype == jnp.float32
    out = m.predict(xs, batch_size=4)
    assert np.isfinite(out).all()


def test_batchnorm_running_stats_update_in_stepwise_loop_and_checkpoint(tmp_path):
    """The stepwise forward/backward/update loop must update running stats
    like fit() does, and checkpoints must carry net_state."""
    from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer, restore_checkpoint,
                              save_checkpoint)

    def build():
        cfg = FFConfig()
        cfg.batch_size = 8
        m = FFModel(cfg)
        x = m.create_tensor((8, 4, 6, 6), DataType.DT_FLOAT)
        t = m.batch_norm(x, relu=False)
        t = m.flat(t)
        m.dense(t, 3)
        m.compile(SGDOptimizer(lr=0.01),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
        return m

    m = build()
    rng = np.random.RandomState(0)
    xs = (3.0 + rng.randn(8, 4, 6, 6)).astype(np.float32)
    ys = rng.randint(0, 3, (8, 1)).astype(np.int32)
    bn = next(op for op in m.executor.topo
              if op.op_type.name == "OP_BATCHNORM").name
    m.input_tensors[0].set_tensor(m, xs)
    m.label_tensor.set_tensor(m, ys)
    m.forward()
    m.zero_gradients()
    m.backward()
    m.update()
    after = np.asarray(m.state.net_state[bn]["running_mean"])
    assert not np.allclose(after, 0.0)  # stepwise loop updated the stats

    path = str(tmp_path / "ckpt")
    save_checkpoint(m, path)
    m2 = build()
    assert np.allclose(np.asarray(m2.state.net_state[bn]["running_mean"]), 0)
    restore_checkpoint(m2, path)
    np.testing.assert_allclose(
        np.asarray(m2.state.net_state[bn]["running_mean"]), after, atol=1e-6)
