"""Linear / Dense operator.

TPU-native equivalent of reference src/ops/linear.cc (1184 LoC) +
src/ops/kernels/linear_kernels.cu (cuBLAS GemmEx): here the kernel is a single
jnp.dot that XLA tiles onto the MXU, with the fused activation folded in
(reference fuses activation via cudnnActivationForward).

Weight layout is (in_channels, out_channels) so the MXU contraction is the
natural last-dim dot; the reference stores (out, in) for cuBLAS column-major.
Channel-parallel (tensor parallel) execution shards the out dim; replica-dim
handling (linear.cc:132-200) is carried by the PCG's ParallelTensor dims, not
by the kernel.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp

from ..ff_types import ActiMode, DataType, OperatorType, RegularizerMode
from .common import apply_activation
from .registry import WeightSpec, register_op


@dataclasses.dataclass(frozen=True)
class LinearParams:
    """reference: include/flexflow/ops/linear_params.h"""

    out_channels: int
    use_bias: bool = True
    activation: ActiMode = ActiMode.AC_MODE_NONE
    data_type: DataType = DataType.DT_FLOAT
    kernel_reg_lambda: float = 0.0
    kernel_reg_type: RegularizerMode = RegularizerMode.REG_MODE_NONE


def _infer(params: LinearParams, in_shapes, in_dtypes):
    (s,) = in_shapes
    out = tuple(s[:-1]) + (params.out_channels,)
    return [out], [params.data_type if params.data_type else in_dtypes[0]]


def _weights(params: LinearParams, in_shapes, in_dtypes):
    (s,) = in_shapes
    in_dim = s[-1]
    ws = [
        WeightSpec(
            "kernel",
            (in_dim, params.out_channels),
            params.data_type,
            "glorot_uniform",
            parallel_dim_tags=("in_channel", "out_channel"),
        )
    ]
    if params.use_bias:
        ws.append(
            WeightSpec(
                "bias",
                (params.out_channels,),
                params.data_type,
                "zero",
                parallel_dim_tags=("out_channel",),
            )
        )
    return ws


def _forward(params: LinearParams, weights, inputs, ctx):
    (x,) = inputs
    kernel = weights["kernel"]
    cdt = ctx.compute_dtype
    if cdt is not None:
        x = x.astype(cdt)
        kernel = kernel.astype(cdt)
    # preferred_element_type keeps the MXU accumulating in f32 even for bf16 in.
    y = jnp.dot(x, kernel, preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if params.use_bias:
        y = y + weights["bias"].astype(y.dtype)
    return [apply_activation(params.activation, y)]


register_op(
    OperatorType.OP_LINEAR,
    "Dense",
    infer=_infer,
    weights=_weights,
    forward=_forward,
    num_inputs=1,
    seq_pointwise=True,
)
