"""NMT seq2seq model (embedding + stacked LSTM encoder/decoder + projection).

Same capability as the reference's standalone NMT example (nmt/nmt.cc,
~3.3k LoC of custom CUDA LSTM/embed/linear/softmax predating FFModel —
SURVEY §1 row 12), built on the framework's first-class ops instead.
"""
from __future__ import annotations

from ..core.model import FFModel
from ..ff_types import AggrMode, DataType


def build_nmt(
    model: FFModel,
    batch_size: int,
    src_vocab: int = 32000,
    tgt_vocab: int = 32000,
    src_len: int = 32,
    tgt_len: int = 32,
    embed_dim: int = 256,
    hidden: int = 512,
    num_layers: int = 2,
):
    """reference: nmt.cc top_level_task — encoder LSTM stack over source
    embeddings, decoder LSTM stack (teacher-forced), vocab projection +
    softmax."""
    src = model.create_tensor((batch_size, src_len), DataType.DT_INT32, name="src")
    tgt = model.create_tensor((batch_size, tgt_len), DataType.DT_INT32, name="tgt")
    enc = model.embedding(src, src_vocab, embed_dim, AggrMode.AGGR_MODE_NONE)
    for _ in range(num_layers):
        enc = model.lstm(enc, hidden, return_sequences=True)
    # final encoder state broadcast to the decoder via concat conditioning
    enc_last = model.lstm(enc, hidden, return_sequences=False)  # (b, h)
    dec = model.embedding(tgt, tgt_vocab, embed_dim, AggrMode.AGGR_MODE_NONE)
    for _ in range(num_layers):
        dec = model.lstm(dec, hidden, return_sequences=True)
    # condition decoder states on the encoder summary
    enc_cond = model.reshape(enc_last, (batch_size, 1, hidden))
    # broadcast add over target positions
    dec = model.add(dec, enc_cond)
    logits = model.dense(dec, tgt_vocab)
    probs = model.softmax(logits)
    return [src, tgt], probs
