"""PCG executor: lowers a parallelized PCG to one jitted XLA program.

This replaces the reference's entire execution stack — Legion IndexLaunchers
per op (src/ops/*.cc forward()/backward()), FFMapper placement
(src/mapper/mapper.cc), Realm data movement, and NCCL gradient allreduce
(src/runtime/optimizer.cc nccl_update_task) — with a single SPMD program:

  * op forwards run in topo order inside one traced function,
  * ParallelTensor shardings become with_sharding_constraint, so the XLA
    partitioner inserts the collectives the reference's parallel ops and
    NCCL calls perform,
  * jax.grad generates every backward task,
  * the optimizer update is fused into the same program (the reference's
    overlap_backward_update, config.h:133, is automatic here),
  * Legion trace replay (begin/end_trace) ≈ the jit cache.
"""
from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core import losses as losses_mod
from ..core.initializers import get_initializer
from ..core.metrics import Metrics
from ..core.optimizers import Optimizer
from ..ff_types import (
    CompMode,
    DataType,
    LossType,
    OperatorType,
    RegularizerMode,
)
from ..ops.registry import FwdCtx, get_op_def
from ..pcg.graph import Graph
from ..pcg.op import PCGOp
from .mesh import pspec_for_parallel_tensor, sharding_for_parallel_tensor
from . import parallel_ops as par_ops

# Ops whose forward allocates large internal residuals worth recomputing in
# the backward (reference has no equivalent — cuDNN owns these residuals;
# XLA lets us trade FLOPs for HBM via jax.checkpoint). MoE ops are excluded:
# their forward appends aux losses, which must trace exactly once.
_REMAT_OPS = frozenset({OperatorType.OP_MULTIHEAD_ATTENTION})


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GuardState:
    """Device-resident step-guard counters (runtime/resilience.py
    StepGuardConfig): dynamic loss scale + skip bookkeeping, advanced
    inside the jitted train step so guarded training stays one dispatch."""

    loss_scale: jax.Array        # f32 scalar
    good_steps: jax.Array        # i32: consecutive finite steps (regrowth)
    consecutive_skips: jax.Array  # i32: fit() hard-fails past the config max
    total_skips: jax.Array       # i32: run-lifetime skipped steps


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """All device-resident state of a compiled model."""

    params: Dict[str, Dict[str, jax.Array]]
    opt_state: Any
    step: int = 0
    # non-trainable cross-batch buffers (BN running stats, Cache op);
    # keyed op.name -> buffer name -> array
    net_state: Dict[str, Dict[str, jax.Array]] = dataclasses.field(
        default_factory=dict
    )
    # step-guard counters; None when the guard is off (the default)
    guard: Optional[GuardState] = None


def global_grad_norm(grads) -> jax.Array:
    """L2 norm over every gradient leaf, accumulated in f32 (bf16 grads
    would overflow the squares). NaN/Inf anywhere in any leaf surfaces
    here as a non-finite norm — one scalar finiteness check covers the
    whole gradient pytree."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads) if g is not None]
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    return jnp.sqrt(total)


def _tree_select(pred, new, old):
    """Leafwise where(pred, new, old) tolerating None leaves (SGD without
    momentum keeps {"v": None}) — used to carry params/opt state through
    unchanged on a skipped step."""
    def sel(n, o):
        if n is None or o is None:
            return n if o is None else o
        return jnp.where(pred, n, o)

    return jax.tree_util.tree_map(
        sel, new, old, is_leaf=lambda x: x is None
    )


def truncate_labels(labels, logits, seq_length: int = 0):
    """Per-iteration seq truncation must hit the LABELS too: with
    forward(seq_length=N) the logits lose positions, and a loss/metric
    against full-length labels shape-errors. Slices every label axis that
    is LONGER than the logits' (seq axes shrink; a sparse label's trailing
    1 stays — it's never longer than the vocab axis)."""
    if labels.ndim != logits.ndim:
        return labels
    for ax in range(1, labels.ndim):
        if labels.shape[ax] > logits.shape[ax]:
            labels = jax.lax.slice_in_dim(labels, 0, logits.shape[ax], axis=ax)
    return labels


class PCGExecutor:
    """Builds and caches the jitted step functions for a PCG."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh,
        optimizer: Optimizer,
        loss_type: LossType,
        metrics: Metrics,
        *,
        compute_dtype=None,
        grad_dtype=None,
        seed: int = 0,
        input_order: Optional[List] = None,
        remat: bool = False,
        constants: Optional[Dict] = None,
        plan_cost_model=None,
        overlap_grad_sync: bool = False,
    ):
        self.graph = graph
        self.mesh = mesh
        # cost oracle for pipeline stage planning (the same calibrated
        # model the strategy search uses; None = default v5e constants)
        self._plan_cost_model = plan_cost_model
        self.remat = remat
        # guid -> (ParallelTensor, python float OR baked np.ndarray):
        # materialized as jnp.full / jnp.asarray at trace time, excluded
        # from batch inputs (reference: flexflow_constant_create,
        # flexflow_cffi.py:941)
        self.constants = constants or {}
        self.optimizer = optimizer
        self.loss_type = loss_type
        self.loss_fn = losses_mod.get_loss_fn(loss_type)
        self.metrics = metrics
        self.compute_dtype = compute_dtype
        # Gradient storage dtype (None = param dtype). bf16 under mixed
        # precision: converts fuse into the grad matmuls' epilogues, so
        # grads hit HBM (and any cross-chip reduction) at half width —
        # the AMP recipe (half-width grads + f32 master weights). The
        # optimizer update reads them back with f32 promotion.
        self.grad_dtype = grad_dtype
        self.seed = seed
        self.topo = graph.topo_order()
        # User-facing input order is tensor *creation* order (the order of
        # FFModel.create_tensor calls), not graph consumption order —
        # multi-input models (DLRM dense+sparse, enc-dec) depend on it.
        self.input_pts = (
            list(input_order) if input_order is not None else graph.input_tensors()
        )
        outs = graph.output_tensors()
        assert outs, "graph has no output tensor"
        self.logits_pt = outs[-1]
        # Comm/compute-overlapped gradient sync (the reference's
        # overlap_backward_update, config.h:133): decompose the implicit
        # data-parallel grad all-reduce into per-weight reduce-scatter +
        # sharded optimizer update + all-gather of the updated params
        # (set_overlap_grad_sync / config.overlap_backward_update).
        self.overlap_grad_sync = overlap_grad_sync
        self._overlap_spec_cache = None
        # NaN/Inf step guard (runtime/resilience.py StepGuardConfig);
        # None = unguarded step (the default). Changing it invalidates
        # the cached train step (set_step_guard).
        self.step_guard = None
        # extra per-step outputs folded into the metric partials
        # (set_step_metrics; telemetry feed, e.g. "grad_norm")
        self.step_metrics: tuple = ()
        self._train_step = None
        self._train_step_nodonate = None
        self._train_scan = None
        self._grad_step = None
        self._eval_step = None
        self._fwd = None
        self._decode_builds = {}
        self._seq_len_cache = {}  # ("fwd"|"grad", seq_length) -> jitted fn
        # generalized pipeline: a pipe mesh axis with no block-stack op
        # means the graph itself must be stage-partitioned (CNNs,
        # non-uniform transformers — parallel/pipeline.py gpipe_pcg)
        self.pipeline_plan = None
        pipe = mesh.shape.get("pipe", 1) if mesh is not None else 1
        if pipe > 1 and not any(
            op.op_type == OperatorType.OP_BLOCK_STACK for op in self.topo
        ):
            self.pipeline_plan = self._plan_pcg_pipeline(pipe)

    # -- generalized pipeline planning --------------------------------------
    def _plan_pcg_pipeline(self, n_stages: int):
        """Partition the compute graph into `n_stages` contiguous stages
        balanced by analytic op cost ("the search proposes the cut"), and
        describe each cut's boundary tensors. Falls back to None (warn)
        when the graph can't be pipelined exactly."""
        import warnings

        from ..search.cost_model import op_bytes, op_flops
        from ..search.machine_model import MachineModel
        from .pipeline import PcgPipelinePlan, balanced_linear_partition

        ops = [o for o in self.topo if not o.is_parallel_op]
        if len(ops) < n_stages:
            warnings.warn("pipeline: fewer compute ops than stages — "
                          "running unpipelined")
            return None
        for op in ops:
            d = get_op_def(op.op_type)
            if d.state_spec is not None or op.op_type in (
                OperatorType.OP_GROUP_BY, OperatorType.OP_AGGREGATE,
                OperatorType.OP_AGG_SPEC, OperatorType.OP_CACHE,
            ):
                warnings.warn(
                    f"pipeline: {op.op_type.name} (stateful/aux-loss op) "
                    "can't cross the GPipe schedule — running unpipelined"
                )
                return None
        if self._plan_cost_model is not None:
            from ..pcg.machine_view import MachineView

            v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
            costs = [
                self._plan_cost_model.measure_operator_cost(o, v1).total_time
                for o in ops
            ]
        else:
            machine = MachineModel()
            costs = [
                machine.compute_cost(op_flops(o), op_bytes(o)) for o in ops
            ]
        bounds = balanced_linear_partition(costs, n_stages)
        stages = [ops[bounds[i]:bounds[i + 1]]
                  for i in range(len(bounds) - 1)]
        stages = [s for s in stages if s]
        if len(stages) < n_stages:
            warnings.warn("pipeline: degenerate stage partition — "
                          "running unpipelined")
            return None

        stage_of = {}
        for si, sops in enumerate(stages):
            for o in sops:
                stage_of[o.guid] = si
        # parallel ops (degree bookkeeping) are identity device-local:
        # resolve their outputs back to the producing compute tensor
        alias: Dict[int, int] = {}
        for op in self.topo:
            if op.is_parallel_op:
                src = alias.get(op.inputs[0].guid, op.inputs[0].guid)
                for t in op.outputs:
                    alias[t.guid] = src

        def resolve(g):
            return alias.get(g, g)

        # graph inputs must all enter at stage 0 (they are injected there)
        input_guids = {p.guid for p in self.input_pts}
        for op in ops:
            for t in op.inputs:
                if resolve(t.guid) in input_guids and stage_of[op.guid] != 0:
                    warnings.warn(
                        "pipeline: a graph input is consumed past stage 0 "
                        "— running unpipelined"
                    )
                    return None

        batch = self.input_pts[0].material_shape()[0]
        consumers_stage: Dict[int, int] = {}
        for op in ops:
            for t in op.inputs:
                g = resolve(t.guid)
                consumers_stage[g] = max(
                    consumers_stage.get(g, -1), stage_of[op.guid]
                )
        cuts = []
        buf_elems = 0
        for s in range(len(stages) - 1):
            cut = []
            total = 0
            for op in ops:
                if stage_of[op.guid] > s:
                    continue
                for t in op.outputs:
                    if consumers_stage.get(t.guid, -1) <= s:
                        continue
                    shape = tuple(t.material_shape())
                    if not shape or shape[0] != batch:
                        warnings.warn(
                            "pipeline: a cut tensor is not batch-leading "
                            "— running unpipelined"
                        )
                        return None
                    if not np.issubdtype(t.data_type.np_dtype, np.floating):
                        warnings.warn(
                            "pipeline: non-float cut tensor — running "
                            "unpipelined"
                        )
                        return None
                    cut.append((t.guid, shape[1:], t.data_type.jnp_dtype))
                    n = 1
                    for d_ in shape[1:]:
                        n *= d_
                    total += n
            cuts.append(cut)
            buf_elems = max(buf_elems, total)
        out_pt = self.logits_pt
        return PcgPipelinePlan(
            stages=stages,
            cuts=cuts,
            buf_elems=buf_elems,
            out_guid=resolve(out_pt.guid),
            out_shape=tuple(out_pt.material_shape()),
            out_dtype=out_pt.data_type.jnp_dtype,
            n_stages=len(stages),
            alias=alias,
        )

    def _pipeline_stage_runners(self, training: bool, rng):
        """One runner per stage: executes that stage's ops exactly like
        apply()'s walk, minus sharding constraints (runners execute inside
        shard_map on device-local values)."""
        compute_index = {}
        idx = 0
        for op in self.topo:
            if not op.is_parallel_op:
                compute_index[op.guid] = idx
                idx += 1

        alias = getattr(self.pipeline_plan, "alias", {})

        def make_runner(sops):
            def run(params, vals, tick):
                consts = {}
                for guid, (pt, value) in self.constants.items():
                    if isinstance(value, np.ndarray):
                        consts[guid] = jnp.asarray(
                            value, pt.data_type.jnp_dtype
                        )
                    else:
                        consts[guid] = jnp.full(
                            pt.material_shape(), value,
                            pt.data_type.jnp_dtype,
                        )
                vals = dict(vals)
                for op in sops:
                    d = get_op_def(op.op_type)
                    ins = []
                    for t in op.inputs:
                        g = alias.get(t.guid, t.guid)
                        if g in vals:
                            ins.append(vals[g])
                        else:
                            ins.append(consts[g])
                    # fold the tick too: each micro-batch must draw its own
                    # dropout mask (one shared mask would correlate the
                    # micro-batches vs the unpipelined path)
                    op_rng = (
                        jax.random.fold_in(
                            jax.random.fold_in(rng, compute_index[op.guid]),
                            tick,
                        )
                        if rng is not None else None
                    )
                    ctx = FwdCtx(
                        training=training, rng=op_rng, seq_length=-1,
                        compute_dtype=self.compute_dtype, aux_losses=None,
                        n_devices=1, mesh=None,  # device-local inside shard_map
                        op_name=op.name,
                    )
                    outs = d.forward(
                        op.params, params.get(op.name, {}), ins, ctx
                    )
                    for t, v in zip(op.outputs, outs):
                        vals[t.guid] = v
                return vals
            return run

        return [make_runner(s) for s in self.pipeline_plan.stages]

    def _apply_pipelined(self, params, inputs: Dict[int, jax.Array], *,
                         training: bool, rng):
        """Forward through the generalized GPipe schedule; returns
        {logits_guid: value} (micro-batched stages; weights replicated
        over the pipe axis)."""
        from .pipeline import gpipe_pcg

        plan = self.pipeline_plan
        # input value order: resolve via a guid->value map; parallel ops
        # on inputs (degree bookkeeping) are identity device-local
        guids = [pt.guid for pt in self.input_pts]
        arrays = [inputs[g] for g in guids]
        out = gpipe_pcg(
            plan,
            self._pipeline_stage_runners(training, rng),
            params,
            arrays,
            guids,
            self.mesh,
        )
        return {plan.out_guid: out, self.logits_pt.guid: out}

    # -- parameter init (reference: initializer Legion tasks per weight) ----
    def init_params(self) -> Dict[str, Dict[str, jax.Array]]:
        key = jax.random.PRNGKey(self.seed)
        params: Dict[str, Dict[str, jax.Array]] = {}
        # local_devices: under multi-host, devices()[0] belongs to process
        # 0 — every other rank would compute init on a non-addressable
        # device. Same seed everywhere => identical draws on each host.
        with jax.default_device(jax.local_devices()[0]):
            for op in self.topo:
                if not op.weights:
                    continue
                wd: Dict[str, jax.Array] = {}
                for name, wpt in zip(op.weight_names, op.weights):
                    key, sub = jax.random.split(key)
                    init = get_initializer(op.initializers.get(name, "glorot_uniform"))
                    arr = init(sub, wpt.material_shape(), wpt.data_type.jnp_dtype)
                    sharding = sharding_for_parallel_tensor(wpt, self.mesh)
                    # via host numpy: under multi-host every process draws
                    # the SAME init (same seed) and contributes its local
                    # shards — a device-committed array cannot be reshard
                    # onto a mesh spanning other processes
                    if jax.process_count() > 1:
                        arr = np.asarray(arr)
                    wd[name] = jax.device_put(arr, sharding)
                params[op.name] = wd
        # PM_MERGE substitutions rebuild weights fresh from initializer
        # specs — running one after this point would discard trained
        # values (search/substitution_loader.py asserts on this flag)
        self.graph.weights_materialized = True
        return params

    def init_net_state(self) -> Dict[str, Dict[str, jax.Array]]:
        """Zero/one-filled cross-batch buffers for stateful ops (reference:
        cuDNN BN running stats init, Cache's first-batch fill)."""
        net: Dict[str, Dict[str, jax.Array]] = {}
        for op in self.topo:
            if op.is_parallel_op:
                continue
            d = get_op_def(op.op_type)
            if d.state_spec is None:
                continue
            specs = d.state_spec(
                op.params,
                [t.material_shape() for t in op.inputs],
                [t.data_type for t in op.inputs],
            )
            bufs = {}
            for spec in specs:
                fill = 1.0 if spec.initializer == "one" else 0.0
                arr = np.full(spec.shape, fill, spec.dtype.np_dtype)
                if self.mesh is not None:
                    bufs[spec.name] = jax.device_put(
                        arr, NamedSharding(self.mesh, PartitionSpec())
                    )
                else:
                    bufs[spec.name] = jnp.asarray(arr)
            net[op.name] = bufs
        return net

    def init_state(self) -> TrainState:
        params = self.init_params()
        opt_state = self.optimizer.init_state(params)
        # overlapped grad sync stores optimizer state sharded over the
        # data axis (ZeRO-1): the sharded update then never gathers it
        opt_state = self._place_opt_state_sharded(opt_state)
        return TrainState(params=params, opt_state=opt_state,
                          net_state=self.init_net_state())

    # -- forward ------------------------------------------------------------
    def _constrain(self, val, pt):
        spec = pspec_for_parallel_tensor(pt, self.mesh)
        if any(s is not None for s in spec):
            return jax.lax.with_sharding_constraint(
                val, NamedSharding(self.mesh, spec)
            )
        return val

    def apply(
        self,
        params,
        inputs: Dict[int, jax.Array],
        *,
        training: bool,
        rng: Optional[jax.Array],
        seq_length: int = -1,
        aux_out: Optional[list] = None,
        net_state: Optional[Dict] = None,
        net_out: Optional[Dict] = None,
    ) -> Dict[int, jax.Array]:
        """Walk the PCG and compute every tensor. Returns guid -> value.
        Differentiable aux losses (MoE balance) are appended to aux_out;
        stateful ops read net_state and write updates into net_out (the
        train step threads both; eval passes net_state read-only)."""
        if self.pipeline_plan is not None:
            if seq_length >= 0:
                raise NotImplementedError(
                    "per-iteration seq_length truncation changes the cut "
                    "tensor shapes and is not supported with the "
                    "generalized pipeline (pipeline_parallel_degree > 1 on "
                    "a non-block-stack graph)"
                )
            # generalized GPipe over the stage-partitioned graph; returns
            # only the output tensor (stage internals live per-device)
            return self._apply_pipelined(
                params, inputs, training=training, rng=rng
            )
        vals: Dict[int, jax.Array] = dict(inputs)
        for guid, (pt, value) in self.constants.items():
            if isinstance(value, np.ndarray):  # baked array constant
                vals[guid] = jnp.asarray(value, pt.data_type.jnp_dtype)
            else:
                vals[guid] = jnp.full(
                    pt.material_shape(), value, pt.data_type.jnp_dtype
                )
        compute_idx = 0
        for op in self.topo:
            ins = [vals[t.guid] for t in op.inputs]
            if op.is_parallel_op:
                outs = par_ops.execute(op, ins, self.mesh)
            else:
                opdef = get_op_def(op.op_type)
                # fold in the op's index among COMPUTE ops, not its guid
                # (process-global counter — a rebuilt model would draw
                # different dropout masks for the same seed) and not its
                # raw topo position (the search inserts partition/combine
                # ops per mesh, which would make masks mesh-dependent)
                op_rng = (
                    jax.random.fold_in(rng, compute_idx)
                    if rng is not None else None
                )
                compute_idx += 1
                ctx = FwdCtx(
                    training=training,
                    rng=op_rng,
                    seq_length=seq_length,
                    compute_dtype=self.compute_dtype,
                    aux_losses=aux_out,
                    n_devices=self.mesh.size,
                    mesh=self.mesh,
                    op_name=op.name,
                )
                w = params.get(op.name, {})
                if training and self.remat and op.op_type in _REMAT_OPS:
                    # Rematerialize in the backward instead of saving the
                    # op's internals — for attention that drops the stored
                    # s_q x s_kv scores/probs (the dominant HBM residual;
                    # measured 30x+ train-step speedup at seq 512 where the
                    # saved probs otherwise thrash HBM). Exact: same math,
                    # recomputed. RNG is closed over, so recompute is
                    # deterministic.
                    outs = jax.checkpoint(
                        lambda w_, ins_, _od=opdef, _p=op.params, _c=ctx: (
                            _od.forward(_p, w_, ins_, _c)
                        )
                    )(w, ins)
                elif opdef.forward_stateful is not None:
                    st = (net_state or {}).get(op.name, {})
                    outs, new_st = opdef.forward_stateful(
                        op.params, w, st, ins, ctx
                    )
                    if net_out is not None:
                        # buffers are statistics, not a gradient path
                        net_out[op.name] = jax.tree_util.tree_map(
                            jax.lax.stop_gradient, new_st
                        )
                else:
                    outs = opdef.forward(op.params, w, ins, ctx)
            for t, o in zip(op.outputs, outs):
                vals[t.guid] = self._constrain(o, t)
        return vals

    # -- step functions -----------------------------------------------------
    def _input_vals(self, batch_arrays: List[jax.Array]) -> Dict[int, jax.Array]:
        assert len(batch_arrays) == len(self.input_pts), (
            f"model takes {len(self.input_pts)} inputs, got {len(batch_arrays)}"
        )
        return {pt.guid: a for pt, a in zip(self.input_pts, batch_arrays)}

    def _reg_penalty(self, params):
        """Weight-regularizer loss terms (reference applies L2 directly in
        the kernel-grad GEMM, linear_kernels.cu:333-350 grad += lambda*w;
        here the equivalent penalty lambda/2*||w||^2 joins the loss so
        jax.grad produces that same gradient)."""
        terms = []
        for op in self.topo:
            lam = getattr(op.params, "kernel_reg_lambda", 0.0)
            if not lam:
                continue
            w = params.get(op.name, {}).get("kernel")
            if w is None:
                continue
            mode = getattr(op.params, "kernel_reg_type", None)
            wf = w.astype(jnp.float32)
            if mode == RegularizerMode.REG_MODE_L1:
                terms.append(lam * jnp.sum(jnp.abs(wf)))
            else:
                terms.append(0.5 * lam * jnp.sum(wf * wf))
        return terms

    def mesh_is_live(self) -> bool:
        """Whether every device this executor's mesh spans is still in
        `jax.devices()`. False after a host loss / device shrink
        (runtime/elastic.py) — any further dispatch onto the stale mesh
        would hang or crash, so fit(elastic=True) recompiles the model
        for the surviving topology (FFModel.recompile_for_topology)
        before touching device state."""
        try:
            live = set(jax.devices())
        except Exception:
            return False
        return all(d in live for d in self.mesh.devices.flat)

    def note_step_duration(self, dur_s: float) -> None:
        """Feed the step-time EMA behind `drain_window_s`. fit() calls
        this only for SYNCED steps (health monitor / drain mode), where
        the wall time measured a whole step rather than an async
        dispatch."""
        if dur_s <= 0:
            return
        ema = getattr(self, "_step_dur_ema", None)
        self._step_dur_ema = (dur_s if ema is None
                              else 0.5 * ema + 0.5 * dur_s)

    @property
    def step_dur_ema(self) -> Optional[float]:
        """The measured synced-step wall-time EMA (None until fed). The
        StrategyTuner's drift watch and post-swap guard window read this
        (runtime/tuner.py)."""
        return getattr(self, "_step_dur_ema", None)

    def reset_step_duration(self) -> None:
        """Forget the step-time EMA. A strategy hot-swap installs a new
        executor whose steps must not be averaged against the pre-swap
        strategy's timings (runtime/tuner.py)."""
        self._step_dur_ema = None

    def drain_window_s(self, checkpoint_s: Optional[float] = None,
                       safety: float = 2.0) -> float:
        """How much of a preemption deadline must remain for fit() to
        risk ONE more step: the expected step time plus the expected
        checkpoint flush, with a safety factor (steps and flushes
        jitter; blowing the deadline means a hard kill mid-write, which
        costs a whole checkpoint interval of replay). The drain protocol
        keeps training while deadline_remaining() > this window, then
        flushes and leaves."""
        step = getattr(self, "_step_dur_ema", None) or 0.0
        ckpt = checkpoint_s or 0.0
        return safety * (step + ckpt) + 0.25

    def invalidate_step_cache(self, train_only: bool = False) -> None:
        """Drop cached jitted steps so the next build re-traces.

        Needed when a traced-as-constant hyperparameter changes (e.g. the
        learning rate from a keras LearningRateScheduler) — the Legion
        analogy is ending a captured trace when the task graph changes.
        `train_only` keeps the eval/forward traces, which don't see the
        optimizer's hyperparameters."""
        self._train_step = None
        self._train_step_nodonate = None
        self._train_scan = None
        self._grad_step = None
        for k in list(self._seq_len_cache):
            if k[0] == "grad" or not train_only:
                del self._seq_len_cache[k]
        if not train_only:
            self._eval_step = None
            self._fwd = None

    def _cast_grads(self, grads):
        """Half-width gradient storage (config.bf16_grads): cast every
        float grad leaf to grad_dtype. Integer/bool leaves (none today)
        and None pass through."""
        if self.grad_dtype is None:
            return grads
        return jax.tree_util.tree_map(
            lambda g: g.astype(self.grad_dtype)
            if jnp.issubdtype(g.dtype, jnp.floating) else g,
            grads,
        )

    def set_step_guard(self, cfg) -> None:
        """Enable/disable the NaN/Inf step guard (a
        resilience.StepGuardConfig or None). Invalidates the cached train
        step when the config actually changes — the guard is traced into
        the step program."""
        if cfg != self.step_guard:
            self.step_guard = cfg
            self._train_step = None
            self._train_step_nodonate = None
            self._train_scan = None

    def set_step_metrics(self, names) -> None:
        """Request extra per-step outputs in the metric partials
        (obs telemetry feed). Supported: ``"grad_norm"`` — the global
        gradient norm, already present whenever the step guard is armed,
        computed on demand otherwise. Traced into the step program, so a
        change invalidates the cached steps like set_step_guard."""
        names = tuple(names or ())
        unknown = [n for n in names if n != "grad_norm"]
        assert not unknown, f"unsupported step metrics: {unknown}"
        if names != self.step_metrics:
            self.step_metrics = names
            self._train_step = None
            self._train_step_nodonate = None
            self._train_scan = None

    # -- comm/compute-overlapped gradient sync ------------------------------
    def set_overlap_grad_sync(self, flag: bool) -> None:
        """Enable/disable the reduce-scatter + sharded-update + all-gather
        step decomposition. Traced into the step program, so a change
        invalidates the cached train steps (like set_step_guard)."""
        flag = bool(flag)
        if flag != self.overlap_grad_sync:
            self.overlap_grad_sync = flag
            self._overlap_spec_cache = None
            self._train_step = None
            self._train_step_nodonate = None
            self._train_scan = None

    def _overlap_specs(self) -> Dict:
        """(op name, weight name) -> (data-sharded, canonical) NamedSharding
        for every weight eligible for the overlapped update.

        The transform: constrain the weight's GRADIENT to a spec that
        additionally shards one replicated dim over the "data" axis — the
        XLA partitioner then lowers the pending cross-replica psum as a
        reduce-scatter instead of an all-reduce — run the (elementwise)
        optimizer update on the owned 1/d shard, and constrain the new
        param back to its canonical spec (an all-gather of UPDATED
        values). Wire bytes match the all-reduce exactly (RS + AG ==
        2(d-1)/d), but each weight's reduce-scatter depends only on that
        weight's gradient, so XLA's async-collective scheduler can
        overlap layer i's collective with layer i-1's backward matmuls —
        the reference's overlap_backward_update (config.h:133), with the
        optimizer state sharded ZeRO-1 style as a bonus (it never needs
        gathering; see init_state).

        Ineligible (left on the plain all-reduce path): weights already
        touching the data or fsdp axes (FSDP reduce-scatters on its own),
        and weights with no dim divisible by the data-axis size."""
        if self._overlap_spec_cache is not None:
            return self._overlap_spec_cache
        out: Dict = {}
        dsize = self.mesh.shape.get("data", 1) if self.mesh is not None else 1
        if not self.overlap_grad_sync or dsize <= 1:
            self._overlap_spec_cache = out
            return out
        for op in self.topo:
            for wname, wpt in zip(op.weight_names, op.weights):
                shape = tuple(wpt.material_shape())
                spec = list(pspec_for_parallel_tensor(wpt, self.mesh))
                spec += [None] * (len(shape) - len(spec))
                flat = set()
                for e in spec:
                    if isinstance(e, (tuple, list)):
                        flat.update(e)
                    elif e is not None:
                        flat.add(e)
                if "data" in flat or "fsdp" in flat:
                    continue
                for di, size in enumerate(shape):
                    if spec[di] is None and size >= dsize \
                            and size % dsize == 0:
                        sharded = list(spec)
                        sharded[di] = "data"
                        out[(op.name, wname)] = (
                            NamedSharding(self.mesh,
                                          PartitionSpec(*sharded)),
                            NamedSharding(self.mesh, PartitionSpec(*spec)),
                        )
                        break
        self._overlap_spec_cache = out
        return out

    def overlap_schedule(self):
        """Schedule-introspection hook for the static analyzer
        (analysis/schedule.py): the per-weight task chains this
        executor's overlapped step actually traces — backward →
        reduce-scatter(grad) → sharded update (donating opt state) →
        all-gather of updated params (donating the old param storage) —
        as an ``OverlapSchedule`` the FFA502 race detector can walk.
        Returns None when the overlapped path is off or inert (data
        degree 1 leaves ``_overlap_specs`` empty), matching the step
        the jit actually runs."""
        from ..analysis.schedule import build_overlap_schedule

        omap = self._overlap_specs()
        if not omap:
            return None
        return build_overlap_schedule(self.graph, set(omap.keys()))

    def _constrain_weight_tree(self, tree, omap, *, sharded: bool):
        """Apply the overlap shardings to a params-shaped
        {op: {weight: array}} tree (grads, params, or updated params)."""
        if not omap:
            return tree
        idx = 0 if sharded else 1
        return {
            op: {
                w: (jax.lax.with_sharding_constraint(v, omap[(op, w)][idx])
                    if (op, w) in omap and v is not None else v)
                for w, v in d.items()
            }
            for op, d in tree.items()
        }

    def _constrain_opt_state(self, tree, omap):
        """Constrain weight-shaped optimizer-state leaves to the sharded
        spec of the weight they mirror (identified by the leaf's trailing
        (op name, weight name) dict path — SGD's {"v": params-like},
        Adam's {"m"/"v": params-like}; scalars pass through)."""
        if not omap:
            return tree

        def f(path, leaf):
            if leaf is None or not hasattr(leaf, "shape"):
                return leaf
            keys = [p.key for p in path
                    if isinstance(p, jax.tree_util.DictKey)]
            if len(keys) >= 2 and (keys[-2], keys[-1]) in omap:
                return jax.lax.with_sharding_constraint(
                    leaf, omap[(keys[-2], keys[-1])][0]
                )
            return leaf

        return jax.tree_util.tree_map_with_path(
            f, tree, is_leaf=lambda x: x is None
        )

    def _place_opt_state_sharded(self, opt_state):
        """Host-side placement of fresh optimizer state on the overlap
        shardings: the sharded update reads and writes 1/d-sized state
        shards, so the state LIVES sharded across steps (ZeRO-1) — no
        all-gather of m/v ever happens, and opt-state HBM divides by the
        data degree. Checkpointing host-gathers shards transparently."""
        omap = self._overlap_specs()
        if not omap:
            return opt_state

        def f(path, leaf):
            if leaf is None or not hasattr(leaf, "shape"):
                return leaf
            keys = [p.key for p in path
                    if isinstance(p, jax.tree_util.DictKey)]
            if len(keys) >= 2 and (keys[-2], keys[-1]) in omap:
                return jax.device_put(leaf, omap[(keys[-2], keys[-1])][0])
            return leaf

        return jax.tree_util.tree_map_with_path(
            f, opt_state, is_leaf=lambda x: x is None
        )

    def init_guard_state(self) -> GuardState:
        assert self.step_guard is not None, "set_step_guard() first"
        cfg = self.step_guard
        return GuardState(
            loss_scale=jnp.asarray(cfg.init_loss_scale, jnp.float32),
            good_steps=jnp.asarray(0, jnp.int32),
            consecutive_skips=jnp.asarray(0, jnp.int32),
            total_skips=jnp.asarray(0, jnp.int32),
        )

    def _make_step(self):
        guard = self.step_guard
        # overlap shardings are trace-time constants of the step program
        omap = self._overlap_specs()

        def step(state: TrainState, batch_inputs, labels, rng, *extra):
            def loss_of(params):
                aux: list = []
                net_out: dict = {}
                vals = self.apply(
                    params, self._input_vals(batch_inputs), training=True, rng=rng,
                    aux_out=aux, net_state=state.net_state, net_out=net_out,
                )
                logits = vals[self.logits_pt.guid]
                loss = self.loss_fn(logits, labels)
                for a in aux:
                    loss = loss + a
                for r in self._reg_penalty(params):
                    loss = loss + r
                if guard is not None:
                    # dynamic loss scaling: grads come out scaled and are
                    # unscaled below; the reported loss stays unscaled
                    return loss * state.guard.loss_scale, (loss, logits, net_out)
                return loss, (loss, logits, net_out)

            (_, (loss, logits, net_out)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(state.params)
            grads = self._cast_grads(grads)
            if omap:
                # overlapped grad sync: pin each eligible gradient to a
                # data-sharded layout, turning the pending cross-replica
                # psum into a per-weight reduce-scatter. Each weight's
                # collective depends only on that weight's gradient, so
                # the async-collective scheduler hides layer i's ICI
                # traffic behind layer i-1's backward matmuls. The guard
                # norm and the optimizer update below then run on the
                # owned 1/d shards (partial norms psum to one scalar —
                # no second full-tree traversal), and only the UPDATED
                # params all-gather back (see _overlap_specs).
                grads = self._constrain_weight_tree(grads, omap,
                                                    sharded=True)
            upd_src_params = (
                self._constrain_weight_tree(state.params, omap,
                                            sharded=True)
                if omap else state.params
            )
            new_net = dict(state.net_state)
            new_net.update(net_out)
            if guard is None:
                new_params, new_opt = self.optimizer.update(
                    upd_src_params, grads, state.opt_state
                )
                if omap:
                    new_params = self._constrain_weight_tree(
                        new_params, omap, sharded=False
                    )
                    new_opt = self._constrain_opt_state(new_opt, omap)
                new_guard = state.guard
                partials = self.metrics.compute(logits, labels)
                partials["loss"] = loss
                if "grad_norm" in self.step_metrics:
                    # telemetry feed (set_step_metrics): the guard path
                    # below always computes this; here it is opt-in
                    partials["grad_norm"] = global_grad_norm(grads)
            else:
                # -- NaN/Inf step guard (resilience.StepGuardConfig) ----
                # fit()'s fault-injection seam: extra[0] is a grad poison
                # multiplier (1.0 normally, NaN to simulate a bad batch)
                poison = extra[0] if extra else jnp.asarray(1.0, jnp.float32)
                inv = (poison / state.guard.loss_scale).astype(jnp.float32)
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                    grads,
                )
                # under overlap the grads are data-sharded here, so this
                # is a per-shard partial sum-of-squares + one scalar psum
                # — the guard's old extra full-tree traversal is gone
                gnorm = global_grad_norm(grads)
                finite = jnp.isfinite(gnorm)
                upd_params, upd_opt = self.optimizer.update(
                    upd_src_params, grads, state.opt_state
                )
                # a skipped step carries params AND opt state through
                # unchanged — momentum/bias-correction must not advance
                # on a discarded gradient
                new_params = _tree_select(finite, upd_params,
                                          upd_src_params)
                new_opt = _tree_select(finite, upd_opt, state.opt_state)
                if omap:
                    new_params = self._constrain_weight_tree(
                        new_params, omap, sharded=False
                    )
                    new_opt = self._constrain_opt_state(new_opt, omap)
                g = state.guard
                cap = jnp.asarray(
                    guard.max_loss_scale
                    if guard.max_loss_scale is not None
                    else guard.init_loss_scale,
                    jnp.float32,
                )
                good = jnp.where(finite, g.good_steps + 1, 0)
                grow = finite & (good >= guard.growth_interval)
                backed = jnp.maximum(
                    g.loss_scale * guard.backoff_factor, guard.min_loss_scale
                )
                scale = jnp.where(
                    finite,
                    jnp.where(
                        grow,
                        jnp.minimum(g.loss_scale * guard.growth_factor, cap),
                        g.loss_scale,
                    ),
                    backed,
                )
                new_guard = GuardState(
                    loss_scale=scale,
                    good_steps=jnp.where(grow, 0, good).astype(jnp.int32),
                    consecutive_skips=jnp.where(
                        finite, 0, g.consecutive_skips + 1
                    ).astype(jnp.int32),
                    total_skips=(
                        g.total_skips + (1 - finite.astype(jnp.int32))
                    ),
                )
                # skipped steps contribute nothing to epoch metrics (their
                # logits/loss are NaN — summing would poison the epoch)
                partials = self.metrics.compute(logits, labels)
                partials["loss"] = loss
                partials = jax.tree_util.tree_map(
                    lambda v: jnp.where(finite, v, jnp.zeros_like(v)), partials
                )
                partials["skipped"] = 1.0 - finite.astype(jnp.float32)
                partials["grad_norm"] = jnp.where(finite, gnorm, 0.0)
            if self.mesh is not None:
                # pin metric partials replicated over the FULL mesh: under
                # multi-host, XLA may otherwise place these tiny outputs on
                # one process's devices, making them unfetchable elsewhere
                rep = NamedSharding(self.mesh, PartitionSpec())
                partials = {
                    k: jax.lax.with_sharding_constraint(v, rep)
                    for k, v in partials.items()
                }
                if guard is not None:
                    # guard counters are fetched per-step by fit's skip
                    # monitor — same multi-host placement concern
                    new_guard = jax.tree_util.tree_map(
                        lambda v: jax.lax.with_sharding_constraint(v, rep),
                        new_guard,
                    )
            return (
                TrainState(params=new_params, opt_state=new_opt,
                           step=state.step + 1, net_state=new_net,
                           guard=new_guard),
                partials,
            )

        return step

    def _donate_state(self) -> tuple:
        """donate_argnums for the train state: donate on accelerators,
        where in-place buffer reuse halves peak weight/opt-state HBM —
        but NOT on CPU. On the CPU backend, an executable deserialized
        from the persistent compilation cache can lose the input/output
        aliasing metadata for donated buffers (observed on jax 0.4.37:
        the final state's buffers get reclaimed while still referenced,
        and live `model.state` arrays read back garbage once a later
        computation reuses the memory). CPU donation buys nothing —
        host RAM is not the scarce resource — so the safe choice costs
        nothing where it applies."""
        return (0,) if jax.default_backend() != "cpu" else ()

    def build_train_step(self, donate: bool = True) -> Callable:
        """donate=False builds a variant that never donates the input
        state, whatever the backend — required by the SDC/determinism
        canary (runtime/verify.py), which re-executes a step from the
        pre-step state: donation would have already reclaimed those
        buffers on accelerators."""
        if not donate:
            if self._train_step_nodonate is None:
                self._train_step_nodonate = jax.jit(self._make_step())
            return self._train_step_nodonate
        if self._train_step is None:
            self._train_step = jax.jit(self._make_step(),
                                       donate_argnums=self._donate_state())
        return self._train_step

    def time_train_step(self, state, batch_inputs, labels, rng, *,
                        repeats: int = 3, warmup: int = 1) -> float:
        """Wall-clock the REAL fused jitted training step (the step
        observatory's in-situ probe, obs/step_profile.py): mean seconds
        per step over `repeats` timed runs after `warmup` untimed ones.
        Uses the non-donating step variant so the caller's state (and
        the model's live params) survive the measurement untouched."""
        step = self.build_train_step(donate=False)
        parts = None
        for _ in range(max(1, warmup)):
            _, parts = step(state, batch_inputs, labels, rng)
            jax.block_until_ready(parts["loss"])  # fflint: disable=FFL103 — timing harness, the sync IS the measurement
        t0 = time.perf_counter()
        for _ in range(max(1, repeats)):
            _, parts = step(state, batch_inputs, labels, rng)
        jax.block_until_ready(parts["loss"])  # fflint: disable=FFL103 — timing harness, the sync IS the measurement
        return (time.perf_counter() - t0) / max(1, repeats)

    def build_train_scan(self) -> Callable:
        """Multi-step driver: lax.scan over pre-staged batches in ONE XLA
        program — the TPU-native analog of the reference's Legion trace
        replay around each training iteration (flexflow_cffi.py:2093-2102
        begin_trace/end_trace), amortizing per-step host dispatch. Takes
        (state, stacked_inputs, stacked_labels, rngs) where every batch
        array AND the rng keys carry a leading steps axis — the caller
        supplies one key per step, so stochastic ops (dropout) see the
        exact same streams as the one-dispatch-per-step path. Returns the
        final state and per-step-stacked metric partials."""
        if self._train_scan is not None:
            return self._train_scan
        assert self.step_guard is None, (
            "the fused multi-step scan driver does not take the step "
            "guard's per-step poison/skip monitoring; resilient fit() "
            "dispatches stepwise (build_train_step)"
        )
        step = self._make_step()

        def multi(state, stacked_inputs, stacked_labels, rngs):
            def body(st, xs):
                ins, lab, key = xs
                st2, partials = step(st, ins, lab, key)
                return st2, partials

            state, partials = jax.lax.scan(
                body, state, (list(stacked_inputs), stacked_labels, rngs)
            )
            return state, partials

        self._train_scan = jax.jit(multi,
                                   donate_argnums=self._donate_state())
        return self._train_scan

    def build_grad_step(self, seq_length: int = -1) -> Callable:
        """Gradient-only step for the cffi-parity stepwise loop
        (FFModel.backward). Uses the SAME loss as the fused train step —
        including MoE aux losses and regularizer penalties — so stepwise
        training matches fit() exactly."""
        if seq_length < 0 and self._grad_step is not None:
            return self._grad_step
        if seq_length >= 0 and ("grad", seq_length) in self._seq_len_cache:
            return self._seq_len_cache[("grad", seq_length)]

        def grad_of(params, batch_inputs, labels, net_state=None):
            def loss_of(p):
                aux: list = []
                net_out: dict = {}
                vals = self.apply(
                    p, self._input_vals(batch_inputs), training=True,
                    rng=None, aux_out=aux, seq_length=seq_length,
                    net_state=net_state, net_out=net_out,
                )
                logits = vals[self.logits_pt.guid]
                loss = self.loss_fn(logits, truncate_labels(labels, logits))
                for a in aux:
                    loss = loss + a
                for r in self._reg_penalty(p):
                    loss = loss + r
                return loss, net_out

            grads, net_out = jax.grad(loss_of, has_aux=True)(params)
            return self._cast_grads(grads), net_out

        fn = jax.jit(grad_of)
        if seq_length < 0:
            self._grad_step = fn
        else:
            self._seq_len_cache[("grad", seq_length)] = fn
        return fn

    def build_eval_step(self) -> Callable:
        if self._eval_step is not None:
            return self._eval_step

        def step(params, batch_inputs, labels, net_state=None):
            vals = self.apply(
                params, self._input_vals(batch_inputs), training=False,
                rng=None, net_state=net_state,
            )
            logits = vals[self.logits_pt.guid]
            partials = self.metrics.compute(logits, labels)
            partials["loss"] = self.loss_fn(logits, labels)
            return logits, partials

        self._eval_step = jax.jit(step)
        return self._eval_step

    def build_forward(self, seq_length: int = -1) -> Callable:
        """seq_length >= 0 truncates seq-aware ops per iteration (reference:
        FFIterationConfig.seq_length, forward(seq_length) model.h:771 —
        BatchMatmul a/b_seq_length_dim slicing). Each distinct value is its
        own compiled executable, like the reference re-runs its tasks with
        the iteration config."""
        if seq_length < 0:
            if self._fwd is not None:
                return self._fwd
        elif ("fwd", seq_length) in self._seq_len_cache:
            return self._seq_len_cache[("fwd", seq_length)]

        def fwd(params, batch_inputs, net_state=None):
            vals = self.apply(
                params, self._input_vals(batch_inputs), training=False,
                rng=None, seq_length=seq_length, net_state=net_state,
            )
            return vals[self.logits_pt.guid]

        fn = jax.jit(fwd)
        if seq_length < 0:
            self._fwd = fn
        else:
            self._seq_len_cache[("fwd", seq_length)] = fn
        return fn

    # -- incremental decode (serving KV cache) ------------------------------
    def build_decode(self, batch: int, max_len: int, cache_dtype=None,
                     decode_input: Optional[int] = None,
                     assume_causal: bool = False):
        """(init_caches, step) for KV-cache autoregressive decoding over an
        arbitrary causal decoder or encoder-decoder PCG (the liveness/
        prefix analysis in parallel/decode.py — graphs imported from HF
        build attention from primitive batch_matmul/softmax/mask ops and
        still decode O(1)/token).

        init_caches(params=None, static_inputs=()) computes the static
        (encoder-side) subgraph once and zero-fills the prefix/KV caches;
        decoder-only graphs keep the old zero-arg call. step(params,
        caches, t, [token_block]) runs the newest positions: seq-pointwise
        ops execute on the (batch, s0, ...) slice, attention appends this
        block's K/V and attends against the prefix, cross-attention
        attends the precomputed encoder K/V, and static/constant operands
        (positional tables, masks) are sliced per step.

        step's `t` may be a scalar (the generate APIs: every row at the
        same position) or a (batch,) int vector of per-row positions —
        the continuous-batching contract (runtime/serving.py): each slot
        of a running decode batch advances through its own sequence, so
        K/V appends and causality masks are applied per row.

        Build-time validation rejects graphs the scheme can't prove exact:
        ops mixing sequence positions without a decode rule, non-causal
        self-attention, softmax over the live axis."""
        from . import decode as dec
        from ..ops.attention import cross_decode_kv, init_decode_cache

        key = (batch, max_len, cache_dtype, decode_input, assume_causal)
        cached = self._decode_builds.get(key)
        if cached is not None:
            return cached

        plan = dec.build_plan(self.topo, self.input_pts, self.constants,
                              decode_input, assume_causal=assume_causal)
        # prefix caches patch ONLY axis 0 to the decode batch; a graph that
        # folds batch with heads on axis 0 (B*H, ...) would get a
        # wrong-sized cache when decoding at a different batch than
        # compile (beam search at num_beams) — reject at build like the
        # other exactness checks
        compile_batch = plan.decode_pt.material_shape()[0]
        for g in plan.cached_guids:
            pt = next(x for op in plan.live_ops for x in op.outputs
                      if x.guid == g)
            if plan.info[g].live != 0 and \
                    pt.material_shape()[0] != compile_batch:
                raise NotImplementedError(
                    f"cached tensor guid {g} has axis-0 size "
                    f"{pt.material_shape()[0]} != compiled batch "
                    f"{compile_batch}: its batch dim is folded with "
                    "another axis, so decoding at a different batch "
                    "would mis-size the cache"
                )
        if plan.requires_cap_le_live_len and max_len > plan.live_len:
            raise NotImplementedError(
                f"max_len {max_len} > compiled decoder length "
                f"{plan.live_len}: the graph bakes full-length constants "
                "(masks/position tables) that can't be extended"
            )
        if not plan.info.get(self.logits_pt.guid, dec.AxisInfo()).is_live:
            raise NotImplementedError(
                "the graph output does not depend on the decode input"
            )
        cdt = cache_dtype or self.compute_dtype or jnp.float32
        static_pts = [pt for pt in self.input_pts
                      if pt.guid != plan.decode_pt.guid]
        ctx = FwdCtx(
            training=False, rng=None, seq_length=-1,
            compute_dtype=self.compute_dtype, aux_losses=None,
            n_devices=1, mesh=None,  # decode is device-local
        )

        # MHA classification: self-attention (live k/v -> per-op KV cache)
        # vs cross-attention (static k/v -> precomputed encoder K/V)
        mha_self, mha_cross = [], []
        for op in plan.live_ops:
            if op.is_parallel_op:
                continue
            if op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION:
                if plan.info.get(op.inputs[1].guid, dec.AxisInfo()).is_live:
                    mha_self.append(op)
                else:
                    mha_cross.append(op)

        def _materialize_constants():
            """Baked constants, with batch-uniform leading axes collapsed
            to 1: decode may run at a different batch than compile (beam
            search runs at num_beams), and constants like HF's extended
            attention masks carry the compiled batch size — when every
            row is identical (no per-sample padding was traced) a
            broadcastable row-1 constant is exact."""
            vals = {}
            for guid, (pt, value) in self.constants.items():
                shape = tuple(pt.material_shape())
                if isinstance(value, np.ndarray):
                    arr = value
                    if (arr.ndim >= 1 and arr.shape[0] not in (1, batch)
                            and np.array_equal(arr, np.broadcast_to(
                                arr[:1], arr.shape), equal_nan=True)):
                        arr = arr[:1]
                    vals[guid] = jnp.asarray(arr, pt.data_type.jnp_dtype)
                else:
                    if len(shape) >= 1 and shape[0] not in (1, batch):
                        shape = (1,) + shape[1:]
                    vals[guid] = jnp.full(
                        shape, value, pt.data_type.jnp_dtype
                    )
            return vals

        def _compute_statics(params, static_arrays):
            vals = _materialize_constants()
            for pt, arr in zip(static_pts, static_arrays):
                vals[pt.guid] = jnp.asarray(arr, pt.data_type.jnp_dtype)
            for op in plan.static_ops:
                if op.is_parallel_op:
                    vals[op.outputs[0].guid] = vals[op.inputs[0].guid]
                    continue
                d = get_op_def(op.op_type)
                ins = [vals[x.guid] for x in op.inputs]
                w = (params or {}).get(op.name, {})
                if (op.op_type == OperatorType.OP_RESHAPE
                        and tuple(ins[0].shape)
                        != tuple(op.inputs[0].material_shape())):
                    # traced reshape params bake the compiled batch size;
                    # decode may run at a different batch (beam search) —
                    # recompute the batch axis
                    target = list(op.outputs[0].material_shape())
                    target[0] = -1
                    outs = [jnp.reshape(ins[0], target)]
                else:
                    outs = d.forward(op.params, w, ins, ctx)
                for x, v in zip(op.outputs, outs):
                    vals[x.guid] = v
            return vals

        needs_params = bool(mha_cross) or any(
            op.weights for op in plan.static_ops if not op.is_parallel_op
        )

        # static values whose ONLY live consumers are cross-attention k/v
        # slots are folded into the precomputed K/V — keeping the raw
        # encoder hidden states in the cache would waste HBM per layer
        cross_kv_guids = {op.inputs[i].guid
                          for op in mha_cross for i in (1, 2)}
        other_uses = set()
        for op in plan.live_ops:
            if op.is_parallel_op or id(op) in {id(o) for o in mha_cross}:
                continue
            for x in op.inputs:
                other_uses.add(x.guid)
        for op in mha_cross:
            other_uses.add(op.inputs[0].guid)
        static_kept = [g for g in plan.static_needed
                       if g not in cross_kv_guids or g in other_uses]

        def init_caches(params=None, static_inputs=()):
            assert len(static_inputs) == len(static_pts), (
                f"need {len(static_pts)} static (non-decode) input arrays, "
                f"got {len(static_inputs)}"
            )
            assert params is not None or not needs_params, (
                "this graph has encoder-side ops: call "
                "init_caches(params, static_inputs)"
            )
            svals = _compute_statics(params, static_inputs)
            caches = {
                "static": {g: svals[g] for g in static_kept},
                "prefix": {},
                "mha": {},
                # beam-invariant per-op statics (cross-attention encoder
                # K/V): separate key so serving's beam reorder can skip
                # gathering them
                "mha_static": {},
            }
            for g in plan.cached_guids:
                pt = next(x for op in plan.live_ops for x in op.outputs
                          if x.guid == g)
                shape = list(pt.material_shape())
                shape[plan.info[g].live] = max_len
                if plan.info[g].live != 0:
                    shape[0] = batch  # decode batch, not compile batch
                caches["prefix"][g] = jnp.zeros(
                    shape, pt.data_type.jnp_dtype
                )
            for op in mha_self:
                caches["mha"][op.name] = init_decode_cache(
                    op.params, batch, max_len, cdt
                )
            for op in mha_cross:
                caches["mha_static"][op.name] = cross_decode_kv(
                    op.params, params.get(op.name, {}),
                    svals[op.inputs[1].guid], svals[op.inputs[2].guid],
                    ctx,
                )
            return caches

        info = plan.info
        cached_set = set(plan.cached_guids)
        mha_cross_set = {id(op) for op in mha_cross}
        mha_self_set = {id(op) for op in mha_self}

        def step(params, caches, t, batch_inputs):
            (tok,) = batch_inputs
            tok = jnp.asarray(tok, plan.decode_pt.data_type.jnp_dtype)
            s0 = tok.shape[1]
            # t may be a scalar (all rows at the same position) or a (b,)
            # vector of per-row positions (continuous batching: each slot
            # of a running decode batch is mid-way through its own
            # sequence — runtime/serving.ContinuousBatcher)
            per_row_t = getattr(t, "ndim", 0) == 1
            if per_row_t and tok.shape[0] != t.shape[0]:
                raise NotImplementedError(
                    f"per-row positions: {t.shape[0]} positions for "
                    f"{tok.shape[0]} rows"
                )
            consts = _materialize_constants()
            statics = dict(caches["static"])
            vals = {plan.decode_pt.guid: tok}
            new_caches = {
                "static": caches["static"],
                "prefix": dict(caches["prefix"]),
                "mha": dict(caches["mha"]),
                "mha_static": caches["mha_static"],
            }

            def get_static(g):
                if g in statics:
                    return statics[g]
                return consts[g]

            def aligned_input(x, out_rank, out_info, site=""):
                """A live op's input value: live tensors yield their
                current slice; static/constant operands are sliced where
                their full-length axes align with the live/prefix axes."""
                g = x.guid
                if g in vals:
                    return vals[g]
                full = get_static(g)
                # runtime shape, not the compiled ParallelTensor's — a
                # batch-collapsed constant differs on axis 0
                amap = dec._static_alignment(
                    tuple(full.shape), out_rank, out_info, plan.live_len,
                )
                return dec._slice_aligned(full, amap, t, s0, max_len,
                                          out_rank=out_rank, site=site)

            for op in plan.live_ops:
                if op.is_parallel_op:
                    vals[op.outputs[0].guid] = vals[op.inputs[0].guid]
                    continue
                d = get_op_def(op.op_type)
                w = params.get(op.name, {})
                ot = op.op_type
                out_info = info.get(op.outputs[0].guid, dec.AxisInfo())

                if id(op) in mha_self_set:
                    ins = [vals[x.guid] for x in op.inputs]
                    outs, new_caches["mha"][op.name] = d.forward_decode(
                        op.params, w, ins, ctx, caches["mha"][op.name], t
                    )
                elif id(op) in mha_cross_set:
                    from ..ops.attention import _forward_decode_cross

                    outs = _forward_decode_cross(
                        op.params, w, vals[op.inputs[0].guid], ctx,
                        caches["mha_static"][op.name],
                    )
                elif ot == OperatorType.OP_BATCHMATMUL:
                    a_pt, b_pt = op.inputs
                    # lhs may itself be static (live operand on the rhs)
                    a = (vals[a_pt.guid] if a_pt.guid in vals
                         else get_static(a_pt.guid))
                    b_info = info.get(b_pt.guid, dec.AxisInfo())
                    if b_pt.guid in cached_set:
                        b = new_caches["prefix"][b_pt.guid]
                    elif b_info.is_live:
                        b = vals[b_pt.guid]
                    else:
                        b_full = get_static(b_pt.guid)
                        a_info = info.get(a_pt.guid, dec.AxisInfo())
                        rb = b_full.ndim
                        if a_info.prefix == len(a_pt.material_shape()) - 1:
                            # probs @ static V of compiled length: keep
                            # only the cap positions the cache covers
                            b_full = jax.lax.slice_in_dim(
                                b_full, 0, max_len, axis=rb - 2
                            )
                        b = b_full
                    outs = [jnp.matmul(
                        a, b, preferred_element_type=jnp.float32
                    ).astype(a.dtype)]
                elif ot == OperatorType.OP_SOFTMAX:
                    x = vals[op.inputs[0].guid]
                    nd = x.ndim
                    dim = op.params.dim % nd
                    a_info = info[op.inputs[0].guid]
                    if a_info.prefix is not None and dim == a_info.prefix:
                        # attention row softmax over the prefix axis:
                        # inject the causality/validity mask (hides the
                        # cache's unwritten tail; for causal models this
                        # matches the graph's own mask)
                        assert a_info.live is not None, (
                            "prefix softmax without a live query axis"
                        )
                        kv = jax.lax.broadcasted_iota(jnp.int32, x.shape, dim)
                        if per_row_t:
                            if x.shape[0] != t.shape[0]:
                                raise NotImplementedError(
                                    f"per-row positions: attention scores "
                                    f"fold batch with another axis "
                                    f"(axis 0 is {x.shape[0]}, batch "
                                    f"{t.shape[0]})"
                                )
                            t_rows = t.reshape(
                                (t.shape[0],) + (1,) * (x.ndim - 1)
                            )
                            qp = t_rows + jax.lax.broadcasted_iota(
                                jnp.int32, x.shape, a_info.live
                            )
                        else:
                            qp = t + jax.lax.broadcasted_iota(
                                jnp.int32, x.shape, a_info.live
                            )
                        x = jnp.where(kv <= qp, x, dec.NEG_INF)
                    outs = [jax.nn.softmax(x, axis=dim)]
                elif ot in (OperatorType.OP_RESHAPE, OperatorType.OP_FLAT):
                    x = vals[op.inputs[0].guid]
                    target = list(op.outputs[0].material_shape())
                    if out_info.live is not None:
                        target[out_info.live] = s0
                    if out_info.live != 0:
                        target[0] = -1  # batch may differ from compile
                    outs = [jnp.reshape(x, target)]
                else:
                    out_rank = len(op.outputs[0].material_shape())
                    ins = [aligned_input(x, out_rank, out_info, op.name)
                           for x in op.inputs]
                    outs = d.forward(op.params, w, ins, ctx)

                for x, v in zip(op.outputs, outs):
                    vals[x.guid] = v
                    if x.guid in cached_set:
                        ax = info[x.guid].live
                        cache = caches["prefix"][x.guid]
                        if per_row_t:
                            if ax == 0 or cache.shape[0] != t.shape[0]:
                                raise NotImplementedError(
                                    f"per-row positions: prefix cache guid "
                                    f"{x.guid} has no batch-leading axis "
                                    f"(live axis {ax}, axis 0 "
                                    f"{cache.shape[0]})"
                                )
                            new_caches["prefix"][x.guid] = jax.vmap(
                                lambda c, vv, tt, _ax=ax:
                                jax.lax.dynamic_update_slice_in_dim(
                                    c, vv, tt, axis=_ax - 1
                                )
                            )(cache, v.astype(cache.dtype), t)
                        else:
                            new_caches["prefix"][x.guid] = (
                                jax.lax.dynamic_update_slice_in_dim(
                                    cache, v.astype(cache.dtype), t, axis=ax
                                )
                            )
            return vals[self.logits_pt.guid], new_caches

        built = (init_caches, jax.jit(step))
        self._decode_builds[key] = built
        return built

    # -- data placement -----------------------------------------------------
    def shard_batch(self, pt, array) -> jax.Array:
        sharding = sharding_for_parallel_tensor(pt, self.mesh)
        return jax.device_put(array, sharding)

    def shard_batch_stack(self, pt, array) -> jax.Array:
        """Place a (steps, *batch_shape) stack for build_train_scan: the
        leading steps axis is unsharded, per-step dims shard as usual."""
        spec = pspec_for_parallel_tensor(pt, self.mesh)
        return jax.device_put(
            array, NamedSharding(self.mesh, PartitionSpec(None, *spec))
        )

    def put_replicated(self, array) -> jax.Array:
        """Place host data replicated over the FULL mesh. Required under
        multi-host (runtime/distributed.py): a plain jnp.asarray commits to
        one local device, and jit cannot reshard a single-device-committed
        array onto a mesh spanning other processes — labels and rng keys
        must enter as global arrays."""
        if self.mesh is None:
            return jnp.asarray(array)
        return jax.device_put(array, NamedSharding(self.mesh, PartitionSpec()))
