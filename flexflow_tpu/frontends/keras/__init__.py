"""Keras-compatible frontend (reference: python/flexflow/keras/)."""
from . import (  # noqa: F401
    backend,
    callbacks,
    datasets,
    initializers,
    layers,
    losses,
    metrics,
    optimizers,
    regularizers,
)
from .layers import (  # noqa: F401
    Permute,
    Activation,
    Add,
    AveragePooling2D,
    BatchNormalization,
    Concatenate,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Input,
    LayerNormalization,
    Maximum,
    MaxPooling2D,
    Minimum,
    MultiHeadAttention,
    Multiply,
    Reshape,
    Subtract,
)
from .models import Model, Sequential  # noqa: F401
from .optimizers import SGD, Adam  # noqa: F401
