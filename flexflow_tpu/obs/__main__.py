"""Telemetry artifact CLI.

Usage:
    python -m flexflow_tpu.obs trace   <events.jsonl> [-o trace.json]
    python -m flexflow_tpu.obs summary <events.jsonl>
    python -m flexflow_tpu.obs prom    <metrics.jsonl> [-o metrics.prom]
    python -m flexflow_tpu.obs explain [--top N] [model shape flags]

``trace`` converts a structured event log to Chrome-trace JSON (open at
https://ui.perfetto.dev). ``summary`` schema-validates the log and
prints per-category/event counts plus step/search aggregates.
``prom`` re-renders the last metrics.jsonl snapshot as Prometheus text.
``explain`` compiles the benchmark Transformer (CPU-sized by default;
pass --seq/--hidden/... for the real bench shape on a TPU host), joins
the cost model against on-device profile_ops measurements and prints
the miscalibrated-op kernel worklist — each perf round starts from this
list (docs/performance.md).

This module is a CLI entry point: bare print() is its job (fflint FFL201
allowlists __main__ modules).
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter

from .tracer import read_events_jsonl, to_chrome_trace


def _cmd_trace(args) -> int:
    events, problems = read_events_jsonl(args.events)
    for p in problems:
        print(f"warning: {p}", file=sys.stderr)
    out = args.output or "trace.json"
    with open(out, "w") as f:
        json.dump(to_chrome_trace(events), f)
    print(f"wrote {out}: {len(events)} event(s) "
          f"({len(problems)} malformed line(s) skipped)")
    return 0


def _cmd_summary(args) -> int:
    events, problems = read_events_jsonl(args.events)
    if problems:
        for p in problems:
            print(f"schema: {p}", file=sys.stderr)
    by_name = Counter((e["cat"], e["name"]) for e in events)
    print(f"{args.events}: {len(events)} event(s), "
          f"{len(problems)} malformed line(s)")
    for (cat, name), n in sorted(by_name.items()):
        print(f"  {cat:<12} {name:<24} {n}")
    steps = [e for e in events
             if e["name"] == "step" and e["ph"] == "X"]
    if steps:
        total = sum(e["dur"] for e in steps)
        print(f"steps: {len(steps)}, total {total:.3f}s, "
              f"mean {total / len(steps) * 1e3:.2f}ms")
    mcmc = [e for e in events if e["name"] == "mcmc_iter"]
    if mcmc:
        acc = sum(1 for e in mcmc if e.get("args", {}).get("accept"))
        print(f"mcmc: {len(mcmc)} proposal(s), {acc} accepted "
              f"({100.0 * acc / len(mcmc):.1f}%)")
    cands = [e for e in events if e["name"] == "xfer_candidate"]
    if cands:
        best = sum(1 for e in cands if e.get("args", {}).get("best"))
        print(f"substitutions: {len(cands)} candidate(s), "
              f"{best} improved the best strategy")
    return 1 if problems else 0


def _cmd_prom(args) -> int:
    from .metrics import MetricsRegistry

    reg = MetricsRegistry()
    with open(args.metrics) as f:
        records = [json.loads(line) for line in f if line.strip()]
    # keep only the newest snapshot per (name, labels)
    latest = {}
    for r in records:
        latest[(r["name"], tuple(sorted(r["labels"].items())))] = r
    for r in latest.values():
        labels = dict(r["labels"])
        if r["kind"] == "counter":
            reg.counter(r["name"], **labels).inc(r["value"])
        elif r["kind"] == "gauge":
            reg.gauge(r["name"], **labels).set(r["value"])
        else:  # histogram snapshots only carry aggregates; re-emit sum
            h = reg.histogram(r["name"], **labels)
            h.sum, h.count = r.get("sum", 0.0), r.get("count", 0)
    text = reg.to_prometheus()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {args.output}")
    else:
        print(text, end="")
    return 0


def _cmd_explain(args) -> int:
    from .. import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from ..models.transformer import build_transformer
    from .explain import explain_strategy

    cfg = FFConfig()
    cfg.batch_size = args.batch
    cfg.allow_mixed_precision = args.bf16
    model = FFModel(cfg)
    build_transformer(
        model, batch_size=args.batch, seq_length=args.seq,
        hidden_size=args.hidden, num_heads=args.heads,
        num_layers=args.layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    exp = explain_strategy(model, repeats=args.repeats)
    print(exp.summary(args.top))
    print(f"kernel worklist (top {args.top} by |simulated - measured|):")
    for w in exp.worklist(args.top):
        verdict = ("cost model optimistic — fuse/speed up this kernel"
                   if w["ratio"] > 1.0 else
                   "cost model pessimistic — recalibrate this class")
        print(f"  #{w['rank']} {w['name']} [{w['op_type']}] "
              f"meas {w['meas_total_s'] * 1e3:.4f} ms vs "
              f"sim {w['sim_total_s'] * 1e3:.4f} ms "
              f"(x{w['ratio']:.2f}) — {verdict}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.obs",
        description=__doc__.split("\n\n")[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("trace", help="events.jsonl -> Chrome/Perfetto trace")
    t.add_argument("events")
    t.add_argument("-o", "--output")
    s = sub.add_parser("summary", help="validate + summarize an event log")
    s.add_argument("events")
    m = sub.add_parser("prom", help="metrics.jsonl -> Prometheus text")
    m.add_argument("metrics")
    m.add_argument("-o", "--output")
    e = sub.add_parser(
        "explain",
        help="print the miscalibrated-op kernel worklist for the "
             "benchmark Transformer on this host's device",
    )
    e.add_argument("--top", type=int, default=3)
    e.add_argument("--batch", type=int, default=2)
    e.add_argument("--seq", type=int, default=64)
    e.add_argument("--hidden", type=int, default=128)
    e.add_argument("--heads", type=int, default=4)
    e.add_argument("--layers", type=int, default=2)
    e.add_argument("--repeats", type=int, default=1)
    e.add_argument("--bf16", action="store_true")
    args = p.parse_args(argv)
    return {"trace": _cmd_trace, "summary": _cmd_summary,
            "prom": _cmd_prom, "explain": _cmd_explain}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
