#!/usr/bin/env python
"""fflint — project-level AST lints distilled from real shipped bugs.

Each rule encodes a bug class this repo actually shipped and fixed; the
linter makes the fix mechanical instead of tribal knowledge. Stdlib
only (ast) so CI can run it before any heavy install.

Rules
-----
FFL001  bare `except:`
        Swallows KeyboardInterrupt/SystemExit too. Never shipped here,
        banned so it never is.
FFL002  silent `except Exception` (handler body is only pass/continue)
        Historical: silent except-Exception blocks in the checkpoint
        restore path masked corrupted tensors until PR 3 narrowed them
        to typed exceptions with logged warnings. A handler must raise,
        log, warn, or produce a fallback value — not just swallow.
FFL101  `np.asarray(jax.device_get(...))` (or np.array without copy)
        Historical: on the CPU backend device_get returns a ZERO-COPY
        view into the live buffer; with donated train steps the next
        dispatch reuses that memory and the "snapshot" silently mutates
        — PR 2's checkpoint-corruption bug. Use
        `np.array(..., copy=True)` (or `.copy()`).
FFL102  reuse of a donated state after a donated step call
        Historical: the same PR 2 class — a variable passed into a
        `build_train_step()` callable (donating by default) is dead
        after the call; reading it again observes reused buffers.
        Rebind it from the step's return value first.
FFL103  host-sync call inside a step-path function of parallel/ or
        kernels/ modules
        The per-step dispatch path (the traced `step`/`loss_of`/...
        closures and the `*_kernel` bodies) must never synchronize with
        the host: `block_until_ready` / `jax.device_get` stall the
        async dispatch queue (the Perfetto traces show the step pipeline
        draining), and `np.asarray`/`np.array` on a traced value either
        raises under jit or, on concrete per-step values, forces a
        device->host round-trip per step. Hoist host reads out of the
        step path, or pragma genuinely host-side helpers.
FFL301  float64 creep inside a step-path function of parallel/ or
        kernels/ modules
        An `np.float64`/`jnp.float64` reference, a `dtype="float64"`
        keyword, or a dtype-less `np.array(...)` (which defaults to
        float64 for Python floats) inside the traced per-step closures
        silently widens the whole downstream flow to fp64 — the TPU
        has no fp64 MXU path, so XLA either software-emulates it
        (order-of-magnitude slowdown) or demotes it, and either way the
        static precision story (analysis/precision.py FFA7xx) no longer
        matches the executed math. Pin an explicit narrow dtype, or
        pragma genuinely host-side float64 math (e.g. accumulating
        telemetry counters).
FFL201  bare `print()` inside flexflow_tpu/ library code
        Historical: fit/eval reported progress via bare print()s —
        invisible to telemetry, unredirectable, and uncapturable. Route
        output through the structured sink (flexflow_tpu.obs.progress:
        same human-readable line, plus a structured event when a
        telemetry session is active). Only applies to files under a
        `flexflow_tpu` package directory; `__main__.py` CLI modules are
        allowlisted automatically, other CLI entry points via the
        file-level pragma below.

Suppression: append `# fflint: disable=FFL002` (comma-list) to the
offending line (for except-handlers: to the `except` line). A module
whose job is terminal output (CLIs, debug dumpers) can opt out of a
rule wholesale with `# fflint: disable-file=FFL201` on any line.

Usage:  python tools/fflint.py [--list-rules] PATH [PATH...]
Exit codes: 0 clean, 1 findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import ast
import os
import re
import sys
from typing import Dict, List, Set

RULES = {
    "FFL001": "bare `except:` clause",
    "FFL002": "silent `except Exception:` handler (body only "
              "pass/continue)",
    "FFL101": "np.asarray/np.array without copy=True on "
              "jax.device_get(...) output",
    "FFL102": "donated train-step input read again after the step call",
    "FFL103": "host-sync call (block_until_ready / jax.device_get / "
              "np.asarray) inside a step-path function of parallel/ or "
              "kernels/",
    "FFL201": "bare print() in flexflow_tpu/ library code (use "
              "flexflow_tpu.obs.progress; __main__ modules exempt)",
    "FFL301": "float64 creep (np.float64 / dtype='float64' / dtype-less "
              "np.array) inside a step-path function of parallel/ or "
              "kernels/",
}

_PRAGMA = re.compile(r"#\s*fflint:\s*disable=([A-Z0-9,\s]+)")
_FILE_PRAGMA = re.compile(r"#\s*fflint:\s*disable-file=([A-Z0-9,\s]+)")


class Finding:
    def __init__(self, path: str, line: int, col: int, code: str, msg: str):
        self.path, self.line, self.col = path, line, col
        self.code, self.msg = code, msg

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.msg}"


def _pragmas(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), 1):
        m = _PRAGMA.search(text)
        if m:
            out[i] = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return out


def _dotted(node: ast.AST) -> str:
    """best-effort dotted-name rendering of Name/Attribute chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ----------------------------------------------------------------------
# FFL001 / FFL002 — exception-handler rules
# ----------------------------------------------------------------------
def _check_excepts(tree: ast.AST, path: str, findings: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "FFL001",
                "bare `except:` also catches KeyboardInterrupt/SystemExit; "
                "catch a concrete exception type",
            ))
            continue
        names = []
        if isinstance(node.type, (ast.Name, ast.Attribute)):
            names = [_dotted(node.type)]
        elif isinstance(node.type, ast.Tuple):
            names = [_dotted(e) for e in node.type.elts]
        if not any(n in ("Exception", "BaseException") for n in names):
            continue
        if all(isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
            findings.append(Finding(
                path, node.lineno, node.col_offset, "FFL002",
                "except Exception that only swallows (pass/continue): "
                "raise a typed error, log, or produce a fallback "
                "(historical: silent restore-path excepts masked "
                "checkpoint corruption)",
            ))


# ----------------------------------------------------------------------
# FFL101 — zero-copy view of device memory
# ----------------------------------------------------------------------
def _is_device_get(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and \
        _dotted(node.func).split(".")[-1] == "device_get"


def _check_asarray(tree: ast.AST, path: str, findings: List[Finding]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _dotted(node.func)
        leaf = fn.split(".")[-1]
        if leaf not in ("asarray", "array") or not node.args:
            continue
        if not _is_device_get(node.args[0]):
            continue
        if leaf == "array":
            copy_kw = next((k for k in node.keywords if k.arg == "copy"),
                           None)
            if copy_kw is not None and \
                    getattr(copy_kw.value, "value", None) is True:
                continue
        findings.append(Finding(
            path, node.lineno, node.col_offset, "FFL101",
            f"{fn}(jax.device_get(...)) may be a zero-copy view of a "
            "live (donatable) device buffer; use np.array(..., copy=True) "
            "(historical: donated-step aliasing corrupted checkpoints)",
        ))


# ----------------------------------------------------------------------
# FFL102 — donated buffer reused after the step
# ----------------------------------------------------------------------
def _check_donated_reuse(tree: ast.AST, path: str,
                         findings: List[Finding]) -> None:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # step-fn variables: x = <...>.build_train_step(...) without
        # donate=False
        step_fns: Set[str] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            callee = _dotted(node.value.func)
            if not callee.endswith("build_train_step"):
                continue
            donate_off = any(
                k.arg == "donate"
                and getattr(k.value, "value", None) is False
                for k in node.value.keywords
            )
            # donate=(expr) that may be False at runtime: trust it only
            # when literally False
            if donate_off:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    step_fns.add(tgt.id)
        if not step_fns:
            continue
        # calls step(arg0, ...): arg0 is donated; flag loads of arg0's
        # expression after the call line and before a re-store of it
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in step_fns and node.args):
                continue
            target = _dotted(node.args[0])
            if not target:
                continue
            stores = [
                n.lineno for n in ast.walk(fn)
                if isinstance(n, (ast.Name, ast.Attribute))
                and isinstance(getattr(n, "ctx", None), ast.Store)
                and _dotted(n) == target and n.lineno >= node.lineno
            ]  # >=: `state, out = step_fn(state, ...)` rebinds in place
            rebound = min(stores) if stores else None
            for n in ast.walk(fn):
                if not isinstance(n, (ast.Name, ast.Attribute)):
                    continue
                if not isinstance(getattr(n, "ctx", None), ast.Load):
                    continue
                if _dotted(n) != target or n.lineno <= node.lineno:
                    continue
                if rebound is not None and n.lineno >= rebound:
                    continue
                if n.end_col_offset is not None and \
                        n.lineno == node.lineno:
                    continue
                findings.append(Finding(
                    path, n.lineno, n.col_offset, "FFL102",
                    f"`{target}` was donated to `{node.func.id}(...)` on "
                    f"line {node.lineno} and is read again before being "
                    "rebound — donated buffers are reused by the next "
                    "dispatch (historical: stale-state reads after "
                    "donation)",
                ))
                break  # one finding per donated call is enough


# ----------------------------------------------------------------------
# FFL103 — host sync on the step path
# ----------------------------------------------------------------------
# The traced / per-step-dispatch closures of the executor and the Pallas
# kernel bodies. A call is attributed to its INNERMOST enclosing
# function: build-time code in `build_decode` stays exempt while the
# `step` closure it returns is covered.
_STEP_PATH_NAMES = frozenset({
    "step", "loss_of", "grad_of", "fwd", "body", "run", "multi",
})


def _is_step_path_fn(name: str) -> bool:
    return (name in _STEP_PATH_NAMES or name.endswith("_step")
            or name.startswith("step_") or name.endswith("_kernel"))


def _in_step_path_module(path: str) -> bool:
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    if "flexflow_tpu" not in parts[:-1]:
        return False
    return "parallel" in parts[:-1] or "kernels" in parts[:-1]


def _walk_innermost_fn(node: ast.AST, fn_name: str = ""):
    """Yield (node, innermost enclosing function name) pairs."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield child, fn_name
            yield from _walk_innermost_fn(child, child.name)
        else:
            yield child, fn_name
            yield from _walk_innermost_fn(child, fn_name)


def _host_sync_reason(call: ast.Call) -> str:
    fn = _dotted(call.func)
    leaf = fn.split(".")[-1]
    root = fn.split(".")[0]
    if leaf == "block_until_ready":
        return f"{fn}() blocks the host until the device drains"
    if leaf == "device_get":
        return f"{fn}() is a device->host transfer"
    if leaf in ("asarray", "array") and root in ("np", "numpy"):
        return (f"{fn}() on a device value forces a host round-trip "
                "(or raises under jit)")
    return ""


def _check_step_path_sync(tree: ast.AST, path: str,
                          findings: List[Finding]) -> None:
    if not _in_step_path_module(path):
        return
    for node, fn_name in _walk_innermost_fn(tree):
        if not isinstance(node, ast.Call) or not _is_step_path_fn(fn_name):
            continue
        reason = _host_sync_reason(node)
        if reason:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "FFL103",
                f"host sync inside step-path function `{fn_name}`: "
                f"{reason}; hoist it out of the per-step path "
                "(historical: per-step host syncs serialized async "
                "dispatch and flattened bench throughput)",
            ))


# ----------------------------------------------------------------------
# FFL301 — float64 creep on the step path
# ----------------------------------------------------------------------
_F64_NAMES = frozenset({
    "np.float64", "numpy.float64", "jnp.float64", "jax.numpy.float64",
})


def _check_float64(tree: ast.AST, path: str,
                   findings: List[Finding]) -> None:
    if not _in_step_path_module(path):
        return
    for node, fn_name in _walk_innermost_fn(tree):
        if not _is_step_path_fn(fn_name):
            continue
        if isinstance(node, ast.Attribute) and _dotted(node) in _F64_NAMES:
            findings.append(Finding(
                path, node.lineno, node.col_offset, "FFL301",
                f"`{_dotted(node)}` inside step-path function "
                f"`{fn_name}` widens the traced flow to fp64 (no TPU "
                "fp64 MXU path, and the FFA7xx static precision story "
                "no longer matches the executed math); pin bf16/f32",
            ))
            continue
        if not isinstance(node, ast.Call):
            continue
        fn = _dotted(node.func)
        leaf = fn.split(".")[-1]
        root = fn.split(".")[0]
        for kw in node.keywords:
            if kw.arg == "dtype" and \
                    getattr(kw.value, "value", None) in ("float64",
                                                         "double"):
                findings.append(Finding(
                    path, kw.value.lineno, kw.value.col_offset, "FFL301",
                    f"dtype='float64' inside step-path function "
                    f"`{fn_name}`: fp64 has no TPU MXU path; pin "
                    "bf16/f32 or pragma host-side math",
                ))
        if leaf in ("array", "asarray") and root in ("np", "numpy") \
                and not any(k.arg == "dtype" for k in node.keywords):
            findings.append(Finding(
                path, node.lineno, node.col_offset, "FFL301",
                f"dtype-less {fn}() inside step-path function "
                f"`{fn_name}` defaults Python floats to float64; pass "
                "an explicit dtype",
            ))


# ----------------------------------------------------------------------
# FFL201 — bare print() in library code
# ----------------------------------------------------------------------
def _in_flexflow_tpu(path: str) -> bool:
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    return "flexflow_tpu" in parts[:-1]


def _check_prints(tree: ast.AST, path: str,
                  findings: List[Finding]) -> None:
    if not _in_flexflow_tpu(path):
        return  # tools/, tests/, examples/ may print freely
    if os.path.basename(path) == "__main__.py":
        return  # CLI entry points: printing is the job
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            findings.append(Finding(
                path, node.lineno, node.col_offset, "FFL201",
                "bare print() in library code bypasses the structured "
                "logger/telemetry sink; use flexflow_tpu.obs.progress "
                "(same human-readable line + an event when telemetry is "
                "on), or pragma-allowlist genuine CLI/dump modules",
            ))


# ----------------------------------------------------------------------
def lint_source(source: str, path: str) -> List[Finding]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "FFL000",
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = []
    _check_excepts(tree, path, findings)
    _check_asarray(tree, path, findings)
    _check_donated_reuse(tree, path, findings)
    _check_step_path_sync(tree, path, findings)
    _check_float64(tree, path, findings)
    _check_prints(tree, path, findings)
    pragmas = _pragmas(source)
    file_off: Set[str] = set()
    for m in _FILE_PRAGMA.finditer(source):
        file_off |= {c.strip() for c in m.group(1).split(",") if c.strip()}
    return [
        f for f in findings
        if f.code not in pragmas.get(f.line, set())
        and f.code not in file_off
    ]


def lint_path(path: str) -> List[Finding]:
    findings: List[Finding] = []
    if os.path.isfile(path):
        files = [path]
    else:
        files = []
        for root, dirs, names in os.walk(path):
            dirs[:] = [d for d in dirs
                       if d not in (".git", "__pycache__", ".jax_cache")]
            files.extend(os.path.join(root, n) for n in sorted(names)
                         if n.endswith(".py"))
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            findings.append(Finding(f, 0, 0, "FFL000", f"unreadable: {e}"))
            continue
        findings.extend(lint_source(src, f))
    return findings


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="fflint", description=__doc__.split("\n\n")[0])
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    if not args.paths:
        p.print_usage()
        return 2
    findings: List[Finding] = []
    for path in args.paths:
        if not os.path.exists(path):
            print(f"fflint: no such path: {path}", file=sys.stderr)
            return 2
        findings.extend(lint_path(path))
    for f in findings:
        print(f.format())
    if findings:
        print(f"fflint: {len(findings)} finding(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
