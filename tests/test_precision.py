"""FFA7xx precision-flow analyzer tests (flexflow_tpu/analysis/precision.py).

Covers: compute/accum dtype as first-class ParallelTensor state, the
registry-driven annotation pass, each FFA701-705 check on a seeded-defect
PCG, FFA407 in the substitution-rule lint plus PM_PRECISION match/apply in
the loader, effective-dtype byte accounting (collectives + cost model +
KV cache), strategy_io/artifact-store round-trips preserving dtypes, the
verify-tolerance-from-drift-budget derivation (tightening the budget
flips a borderline strategy to a typed failure), a mixed-precision clean
zoo sweep (zero FFA7xx errors on searched strategies), and the FFL301
float64-creep fflint rule. scripts/precision_check.sh re-runs this file
plus the analyzer CLI standalone."""
import dataclasses
import json
import os
import sys

import numpy as np
import pytest

import jax

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
    Severity,
    analyze_model,
)
from flexflow_tpu.analysis import analyze_rules_path, strategy_violations
from flexflow_tpu.analysis.precision import (
    DEFAULT_DRIFT_BUDGET,
    RING_DEGREE_THRESHOLD,
    annotate_graph_precision,
    estimate_drift,
    precision_diagnostics,
)
from flexflow_tpu.ff_types import OperatorType
from flexflow_tpu.ops.elementwise import (
    ElementBinaryParams,
    ElementUnaryParams,
)
from flexflow_tpu.ops.linear import LinearParams
from flexflow_tpu.ops.tensor_ops import CastParams
from flexflow_tpu.parallel.parallel_ops import ReductionParams
from flexflow_tpu.pcg.graph import Graph
from flexflow_tpu.pcg.op import PCGOp
from flexflow_tpu.pcg.parallel_tensor import ParallelTensor, make_dims
from flexflow_tpu.runtime.resilience import StepGuardConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# graph-building helpers (no compile, no devices)
# ----------------------------------------------------------------------
def pt(sizes, degrees=None, replicas=None, dtype=DataType.DT_FLOAT):
    return ParallelTensor(dims=make_dims(sizes, degrees, replicas),
                          data_type=dtype)


def add_op(graph, op_type, params, inputs, out):
    op = PCGOp(op_type, params, inputs)
    out.owner_op = op
    op.outputs.append(out)
    graph.add_op(op)
    return op


def bf16(t):
    t.compute_dtype = DataType.DT_BF16
    return t


# ----------------------------------------------------------------------
# tentpole core: dtype as first-class ParallelTensor state
# ----------------------------------------------------------------------
def test_parallel_tensor_precision_fields():
    t = pt([8, 16])
    assert t.compute_dtype is None and t.accum_dtype is None
    assert t.effective_dtype() is DataType.DT_FLOAT
    assert t.effective_itemsize() == 4
    t.compute_dtype = DataType.DT_BF16
    assert t.effective_dtype() is DataType.DT_BF16
    assert t.effective_itemsize() == 2
    # like axis_tag, precision annotations must NOT perturb the shape
    # key (cost-model caches key on it)
    u = pt([8, 16])
    assert t.shape_key() == u.shape_key()


def test_annotate_graph_precision_flow_and_idempotence():
    g = Graph()
    x = pt([8, 16])
    h = pt([8, 32])
    lin = add_op(g, OperatorType.OP_LINEAR, LinearParams(32), [x], h)
    w = pt([16, 32])
    lin.weights.append(w)
    y = pt([8, 32])
    add_op(g, OperatorType.OP_RELU,
           ElementUnaryParams(op_type=OperatorType.OP_RELU), [h], y)
    annotate_graph_precision(g, compute_dtype=DataType.DT_BF16)
    # outputs annotated: graph inputs enter through the AMP cast, so the
    # whole flow runs bf16 with an fp32 accumulator on the matmul
    assert h.compute_dtype is DataType.DT_BF16
    assert h.accum_dtype is DataType.DT_FLOAT
    assert y.compute_dtype is DataType.DT_BF16
    # weights are NEVER annotated (fp32 master storage keeps data_type
    # width in every memory account)
    assert w.compute_dtype is None and w.effective_itemsize() == 4
    # None clears: re-annotation is idempotent
    annotate_graph_precision(g, compute_dtype=None)
    assert h.compute_dtype is None and h.accum_dtype is None
    assert y.compute_dtype is None


def test_cast_op_redirects_the_flow():
    g = Graph()
    x = pt([8, 16])
    c = pt([8, 16])
    add_op(g, OperatorType.OP_CAST, CastParams(dtype=DataType.DT_FLOAT),
           [x], c)
    y = pt([8, 16])
    add_op(g, OperatorType.OP_RELU,
           ElementUnaryParams(op_type=OperatorType.OP_RELU), [c], y)
    annotate_graph_precision(g, compute_dtype=DataType.DT_BF16)
    # the explicit cast promotes back to fp32 and downstream follows
    assert c.compute_dtype is None  # == data_type, stored as None
    assert y.compute_dtype is None


# ----------------------------------------------------------------------
# FFA701-705 on seeded defects
# ----------------------------------------------------------------------
def test_ffa701_boundary_mix_flags_and_cast_fixes():
    g = Graph()
    a, b = pt([8, 16]), bf16(pt([8, 16]))
    s = pt([8, 16])
    add_op(g, OperatorType.OP_EW_ADD,
           ElementBinaryParams(op_type=OperatorType.OP_EW_ADD), [a, b], s)
    rep = precision_diagnostics(g)
    assert [d.code for d in rep.errors] == ["FFA701"]
    assert "DT_BF16" in rep.errors[0].message
    # the fix: cast the narrow operand up, boundary becomes clean
    g2 = Graph()
    a2, b2 = pt([8, 16]), bf16(pt([8, 16]))
    c2 = pt([8, 16])
    add_op(g2, OperatorType.OP_CAST,
           CastParams(dtype=DataType.DT_FLOAT), [b2], c2)
    s2 = pt([8, 16])
    add_op(g2, OperatorType.OP_EW_ADD,
           ElementBinaryParams(op_type=OperatorType.OP_EW_ADD),
           [a2, c2], s2)
    assert precision_diagnostics(g2).ok


def test_ffa702_low_precision_accumulation():
    g = Graph()
    x = pt([8, 256])
    h = pt([8, 32])
    add_op(g, OperatorType.OP_LINEAR, LinearParams(32), [x], h)
    h.compute_dtype = DataType.DT_BF16
    h.accum_dtype = None  # seeded defect: bf16 accumulate, no fp32 master
    rep = precision_diagnostics(g, drift_budget=1e9)
    codes = [d.code for d in rep.errors]
    assert codes == ["FFA702"]
    assert "256" in rep.errors[0].message  # names the reduction width
    # the default inference never produces this state
    h.accum_dtype = DataType.DT_FLOAT
    assert precision_diagnostics(g, drift_budget=1e9).ok


def test_ffa703_low_precision_ring_reduction_names_degree():
    g = Graph()
    x = bf16(pt([8, 16], replicas=[8]))
    y = pt([8, 16])
    add_op(g, OperatorType.OP_REDUCTION,
           ReductionParams(reduction_dim=0, reduction_degree=8), [x], y)
    rep = precision_diagnostics(g, drift_budget=1e9)
    warns = rep.by_code("FFA703")
    assert len(warns) == 1 and warns[0].severity is Severity.WARNING
    assert "degree 8" in warns[0].message
    # narrow rings stay quiet
    g2 = Graph()
    x2 = bf16(pt([8, 16], replicas=[2]))
    y2 = pt([8, 16])
    add_op(g2, OperatorType.OP_REDUCTION,
           ReductionParams(reduction_dim=0, reduction_degree=2), [x2], y2)
    assert not precision_diagnostics(g2, drift_budget=1e9).by_code("FFA703")
    assert RING_DEGREE_THRESHOLD == 4


def test_ffa703_implicit_weight_grad_sync_aggregate_warning():
    g = Graph()
    x = pt([8, 16])
    h = pt([8, 32])
    lin = add_op(g, OperatorType.OP_LINEAR, LinearParams(32), [x], h)
    lin.weights.append(pt([16, 32]))
    rep = precision_diagnostics(g, num_devices=8,
                                grad_dtype=DataType.DT_BF16,
                                drift_budget=1e9)
    warns = rep.by_code("FFA703")
    assert len(warns) == 1
    assert "degree 8" in warns[0].message and "DT_BF16" in warns[0].message
    # fp32 grads: no warning
    assert not precision_diagnostics(
        g, num_devices=8, grad_dtype=None, drift_budget=1e9
    ).by_code("FFA703")


def test_ffa704_guard_range_vs_dtype():
    g = Graph()
    x = pt([8, 16], dtype=DataType.DT_HALF)
    y = pt([8, 16], dtype=DataType.DT_HALF)
    add_op(g, OperatorType.OP_RELU,
           ElementUnaryParams(op_type=OperatorType.OP_RELU), [x], y)
    # f16 with no loss scaling at all
    rep = precision_diagnostics(g, drift_budget=1e9)
    assert any("loss scaling" in d.message
               for d in rep.by_code("FFA704"))
    # ceiling above f16's max finite value (~6.5e4)
    guard = StepGuardConfig(init_loss_scale=2.0 ** 20)
    rep2 = precision_diagnostics(g, step_guard=guard, drift_budget=1e9)
    assert any("overflow" in d.message for d in rep2.by_code("FFA704"))
    # a sane guard is quiet
    guard3 = StepGuardConfig(init_loss_scale=2.0 ** 15,
                             min_loss_scale=2.0 ** -13)
    assert not precision_diagnostics(
        g, step_guard=guard3, drift_budget=1e9
    ).by_code("FFA704")


def test_ffa705_drift_budget_and_fix_hint():
    g = Graph()
    x = pt([8, 16384])
    h = pt([8, 32])
    add_op(g, OperatorType.OP_LINEAR, LinearParams(32), [x], h)
    h.compute_dtype = DataType.DT_BF16  # bf16 accumulate over 16384 terms
    total, contrib = estimate_drift(g)
    assert total > DEFAULT_DRIFT_BUDGET
    rep = precision_diagnostics(g)
    errs = rep.by_code("FFA705")
    assert len(errs) == 1
    # the fix_hint names the op to promote and the config knob
    assert errs[0].fix_hint and "precision_drift_budget" in errs[0].fix_hint
    assert errs[0].op_name
    # raising the budget (the documented escape hatch) silences it
    assert not precision_diagnostics(
        g, drift_budget=total + 1.0
    ).by_code("FFA705")
    # the proper fix — fp32 accumulator — brings the estimate under
    h.accum_dtype = DataType.DT_FLOAT
    total_fixed, _ = estimate_drift(g)
    assert total_fixed < DEFAULT_DRIFT_BUDGET
    assert precision_diagnostics(g).by_code("FFA705") == []


def test_estimate_drift_fp32_graph_is_negligible():
    g = Graph()
    x = pt([8, 1024])
    h = pt([8, 64])
    add_op(g, OperatorType.OP_LINEAR, LinearParams(64), [x], h)
    total, _ = estimate_drift(g)
    assert total < 1e-5  # fp32 eps-scale, nowhere near any budget


# ----------------------------------------------------------------------
# FFA407 + PM_PRECISION in the substitution loader
# ----------------------------------------------------------------------
def _precision_rule(src_para=(), dst_para=(), name="prec_rule"):
    return {"rule": [{
        "name": name,
        "srcOp": [{"type": "OP_LINEAR",
                   "input": [{"opId": -1, "tsId": 0}],
                   "para": [dict(p) for p in src_para]}],
        "dstOp": [{"type": "OP_LINEAR",
                   "input": [{"opId": -1, "tsId": 0}],
                   "para": [dict(p) for p in dst_para]}],
        "mappedOutput": [{"srcOpId": 0, "srcTsId": 0,
                          "dstOpId": 0, "dstTsId": 0}],
    }]}


def test_ffa407_rejects_non_float_precision_value(tmp_path):
    bad = _precision_rule(
        dst_para=[{"key": "PM_PRECISION",
                   "value": int(DataType.DT_INT32)}],
        name="int_precision")
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    rep = analyze_rules_path(str(p))
    errs = rep.by_code("FFA407")
    assert errs and "float DataType" in errs[0].message


def test_ffa407_low_precision_accumulating_dst_needs_accum(tmp_path):
    bad = _precision_rule(
        dst_para=[{"key": "PM_PRECISION",
                   "value": int(DataType.DT_BF16)}],
        name="bf16_no_accum")
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    rep = analyze_rules_path(str(p))
    errs = rep.by_code("FFA407")
    assert len(errs) == 1
    assert "PM_ACCUM_PRECISION" in (errs[0].fix_hint or "")
    # declaring the accumulator makes the rule sound
    good = _precision_rule(
        dst_para=[{"key": "PM_PRECISION", "value": int(DataType.DT_BF16)},
                  {"key": "PM_ACCUM_PRECISION",
                   "value": int(DataType.DT_FLOAT)}],
        name="bf16_with_accum")
    p2 = tmp_path / "good.json"
    p2.write_text(json.dumps(good))
    assert analyze_rules_path(str(p2)).ok


def test_pm_precision_gates_matching_and_stamps_dst():
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.substitution_loader import (
        apply_rule,
        load_rule_collection,
    )

    rules = load_rule_collection(_precision_rule(
        src_para=[{"key": "PM_PRECISION", "value": int(DataType.DT_BF16)}],
        dst_para=[{"key": "PM_PRECISION", "value": int(DataType.DT_BF16)},
                  {"key": "PM_ACCUM_PRECISION",
                   "value": int(DataType.DT_FLOAT)}],
        name="bf16_gate"))
    model = FFModel(FFConfig())
    x = model.create_tensor((64, 32), DataType.DT_FLOAT)
    model.dense(x, 16)
    graph, _ = layers_to_pcg(model.layers)
    # the un-annotated fp32 graph does NOT match a bf16 pattern
    assert list(apply_rule(graph, rules[0])) == []
    # annotate the site bf16 -> the rule fires and stamps the dst op
    lin = next(op for op in graph.ops
               if op.op_type == OperatorType.OP_LINEAR)
    lin.outputs[0].compute_dtype = DataType.DT_BF16
    cands = list(apply_rule(graph, rules[0]))
    assert len(cands) == 1
    out = next(op for op in cands[0].ops
               if op.op_type == OperatorType.OP_LINEAR).outputs[0]
    assert out.compute_dtype is DataType.DT_BF16
    assert out.accum_dtype is DataType.DT_FLOAT


# ----------------------------------------------------------------------
# satellite: effective-dtype byte accounting
# ----------------------------------------------------------------------
def test_collective_bytes_use_effective_dtype():
    from flexflow_tpu.analysis.collectives import estimate_collective_bytes

    def reduction_graph(annotate):
        g = Graph()
        x = pt([8, 16], replicas=[4])
        if annotate:
            bf16(x)
        y = pt([8, 16])
        add_op(g, OperatorType.OP_REDUCTION,
               ReductionParams(reduction_dim=0, reduction_degree=4),
               [x], y)
        return g

    full = estimate_collective_bytes(reduction_graph(False))
    half = estimate_collective_bytes(reduction_graph(True))
    assert len(full) == 1 and len(half) == 1
    # the bf16 wire moves exactly half the fp32 bytes: the historical
    # 2x over-pricing of bf16 graphs is gone
    assert half[0]["bytes"] * 2 == full[0]["bytes"]


def test_cost_model_bytes_use_effective_dtype_weights_stay_wide():
    from flexflow_tpu.search.cost_model import op_bytes, op_decode_bytes

    def linear_op(annotate):
        g = Graph()
        x = pt([8, 16])
        h = pt([8, 32])
        op = add_op(g, OperatorType.OP_LINEAR, LinearParams(32), [x], h)
        op.weights.append(pt([16, 32]))
        if annotate:
            annotate_graph_precision(g, compute_dtype=DataType.DT_BF16)
        return op

    wide, narrow = linear_op(False), linear_op(True)
    w_bytes = 16 * 32 * 4  # fp32 master weights in BOTH accounts
    # the graph-entry tensor keeps its storage dtype (only op outputs
    # carry annotations); the bf16 output streams at half width
    assert op_bytes(wide) == w_bytes + (8 * 16 + 8 * 32) * 4
    assert op_bytes(narrow) == w_bytes + 8 * 16 * 4 + 8 * 32 * 2
    assert op_decode_bytes(narrow) < op_decode_bytes(wide)


def test_kv_page_bytes_explicit_dtype_and_session_capacity():
    from flexflow_tpu.runtime.kvcache import (
        KVCacheConfig,
        KVCacheExhaustedError,
        PagePool,
    )

    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 16, 32), DataType.DT_FLOAT)
    t = m.multihead_attention(x, x, x, 32, 4)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    from flexflow_tpu.runtime.kvcache import kv_page_bytes

    pb32 = kv_page_bytes(m, 16, kv_dtype="float32")
    pb16 = kv_page_bytes(m, 16, kv_dtype="float16")
    pb8 = kv_page_bytes(m, 16, kv_dtype="int8")
    assert pb32 == 4 * pb8 and pb16 == 2 * pb8
    # default keeps the executor-compute-dtype derivation
    assert kv_page_bytes(m, 16) == pb32  # fp32 compile

    # regression: in one fixed byte budget, a quantized int8 pool admits
    # (at least) 2x the sessions an fp32 pool does
    budget = 64 * pb32  # 64 fp32 pages' worth of HBM

    def sessions(kv_dtype):
        page_bytes = kv_page_bytes(m, 16, kv_dtype=kv_dtype)
        pool = PagePool(KVCacheConfig(num_pages=budget // page_bytes,
                                      page_size=16, kv_dtype=kv_dtype))
        n = 0
        while True:
            try:
                pool.reserve(f"s{n}", 64)  # 4 pages per session
            except KVCacheExhaustedError:
                return n
            n += 1

    assert sessions("int8") >= 2 * sessions("float32")
    assert KVCacheConfig(num_pages=4, kv_dtype="int8").kv_dtype == "int8"
    with pytest.raises(ValueError, match="kv_dtype"):
        KVCacheConfig(num_pages=4, kv_dtype="not_a_dtype")


# ----------------------------------------------------------------------
# strategy_io / artifact-store round-trips preserve dtypes
# ----------------------------------------------------------------------
def _mixed_model(store=None, budget=4):
    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = budget
    cfg.allow_mixed_precision = True
    m = FFModel(cfg)
    x = m.create_tensor((32, 4), DataType.DT_FLOAT)
    t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY], artifact_store=store)
    return m


def _dtype_map(graph):
    return {
        op.name: [(t.data_type.name,
                   t.compute_dtype.name if t.compute_dtype else None,
                   t.accum_dtype.name if t.accum_dtype else None)
                  for t in op.outputs]
        for op in graph.ops
    }


def test_strategy_io_round_trip_preserves_dtypes(tmp_path):
    from flexflow_tpu.runtime.strategy_io import (
        apply_imported_strategy,
        export_strategy,
        import_strategy,
    )

    m = _mixed_model()
    before = _dtype_map(m.graph)
    assert any(c == "DT_BF16" for recs in before.values()
               for (_, c, _) in recs), "mixed compile must annotate bf16"
    path = str(tmp_path / "strategy.json")
    export_strategy(m.graph, None, path)
    strategy = import_strategy(path)
    # wipe the annotations, re-apply from the file: dim-for-dim identical
    for op in m.graph.ops:
        for t in op.outputs:
            t.compute_dtype = None
            t.accum_dtype = None
    apply_imported_strategy(m.graph, strategy)
    assert _dtype_map(m.graph) == before


def test_strategy_io_rejects_prev3_with_precision_state(tmp_path):
    from flexflow_tpu.runtime.strategy_io import (
        StrategyImportError,
        export_strategy,
        import_strategy,
    )

    m = _mixed_model()
    path = str(tmp_path / "strategy.json")
    export_strategy(m.graph, None, path)
    with open(path) as f:
        blob = json.load(f)
    blob["version"] = 2  # pre-precision reader's schema
    with open(path, "w") as f:
        json.dump(blob, f)
    with pytest.raises(StrategyImportError, match="precision"):
        import_strategy(path)


def test_artifact_cache_hit_replays_with_precision_intact(tmp_path):
    from flexflow_tpu.runtime.artifact_store import ArtifactStore

    st = ArtifactStore(str(tmp_path))
    m1 = _mixed_model(store=st)
    assert m1.strategy_provenance["source"] == "search"
    m2 = _mixed_model(store=st)
    assert m2.strategy_provenance["source"] == "artifact_cache"
    d1, d2 = _dtype_map(m1.graph), _dtype_map(m2.graph)
    assert d1 == d2
    assert any(c == "DT_BF16" for recs in d2.values()
               for (_, c, _) in recs)
    # and the stored payload itself carries the annotations (schema v4)
    payload = st.get(m1._artifact_key)
    assert payload["strategy_schema"] == 4
    stored = [o.get("compute_dtype") for n in payload["nodes"]
              for o in n["outputs"]]
    assert "DT_BF16" in stored


# ----------------------------------------------------------------------
# verify tolerances derive from the drift budget
# ----------------------------------------------------------------------
def test_tolerance_from_budget_derivation():
    from flexflow_tpu.runtime.verify import (
        DRIFT_TO_TOLERANCE,
        DTYPE_TOLERANCES,
        tolerance_from_budget,
    )

    # at the default budget the cap lands exactly on the bf16 table row,
    # so existing behavior is unchanged
    assert DEFAULT_DRIFT_BUDGET * DRIFT_TO_TOLERANCE == \
        DTYPE_TOLERANCES["bfloat16"][0]
    assert tolerance_from_budget("bfloat16", None) == \
        DTYPE_TOLERANCES["bfloat16"]
    assert tolerance_from_budget("float32", None) == \
        DTYPE_TOLERANCES["float32"]
    # tightening the budget tightens the tolerance with it
    rt, at = tolerance_from_budget("bfloat16", 0.01)
    assert rt == at == 0.01 * DRIFT_TO_TOLERANCE
    rt32, _ = tolerance_from_budget("float32", 1e-12)
    assert rt32 == 1e-12 * DRIFT_TO_TOLERANCE


def test_tight_budget_flips_borderline_strategy_to_typed_failure():
    """Acceptance: a strategy whose drift passes at the default budget
    becomes a typed StrategyDivergenceError when the budget tightens —
    the runtime check and FFA705 share FFConfig.precision_drift_budget."""
    from flexflow_tpu.runtime.verify import (
        StrategyDivergenceError,
        verify_strategy,
    )

    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = 4
    m = FFModel(cfg)
    x = m.create_tensor((32, 4), DataType.DT_FLOAT)
    t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
    t = m.scalar_multiply(t, 1.0)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    # seed a BORDERLINE drift into the strategy side only: a 1e-6
    # multiplicative nudge, far under the fp32 table tolerance (2e-4)
    sm = next(op for op in m.graph.ops
              if op.op_type == OperatorType.OP_SCALAR_MULTIPLY)
    sm.params = dataclasses.replace(sm.params, scalar=1.0 + 1e-6)
    m.executor.invalidate_step_cache()
    rng = np.random.RandomState(0)
    xd = rng.randn(64, 4).astype(np.float32)
    yd = rng.randint(0, 3, (64, 1)).astype(np.int32)
    v = verify_strategy(m, (xd, yd), steps=2, batch_size=32)
    assert v.ok, v.summary()  # borderline PASS at the default budget
    m.config.precision_drift_budget = 1e-10
    with pytest.raises(StrategyDivergenceError):
        verify_strategy(m, (xd, yd), steps=2, batch_size=32,
                        raise_on_divergence=True)


# ----------------------------------------------------------------------
# clean zoo sweep: zero FFA7xx errors on searched mixed strategies
# ----------------------------------------------------------------------
def mixed_mlp():
    return _mixed_model()


def mixed_cnn():
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = 3
    cfg.allow_mixed_precision = True
    m = FFModel(cfg)
    x = m.create_tensor((8, 3, 16, 16), DataType.DT_FLOAT)
    t = m.conv2d(x, 8, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = m.flat(t)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


def mixed_attention():
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = 3
    cfg.allow_mixed_precision = True
    m = FFModel(cfg)
    x = m.create_tensor((8, 16, 32), DataType.DT_FLOAT)
    t = m.multihead_attention(x, x, x, 32, 4)
    t = m.dense(t, 32, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


def mixed_moe():
    from flexflow_tpu import models as zoo

    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.search_budget = 2
    cfg.allow_mixed_precision = True
    m = FFModel(cfg)
    zoo.build_moe(m, 16, input_dim=32, num_classes=4, num_exp=4,
                  num_select=2, hidden=16)
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


def mixed_fsdp():
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.allow_mixed_precision = True
    cfg.fsdp_degree = len(jax.devices())  # manual ZeRO lowering, no search
    m = FFModel(cfg)
    x = m.create_tensor((8, 16), DataType.DT_FLOAT)
    t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


def mixed_longctx():
    cfg = FFConfig()
    cfg.batch_size = 2
    cfg.search_budget = 2
    cfg.allow_mixed_precision = True
    m = FFModel(cfg)
    x = m.create_tensor((2, 128, 32), DataType.DT_FLOAT)
    t = m.multihead_attention(x, x, x, 32, 4, causal=True)
    t = m.dense(t, 32, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


def mixed_decode():
    from flexflow_tpu import AggrMode

    cfg = FFConfig()
    cfg.batch_size = 2
    cfg.search_budget = 1
    cfg.allow_mixed_precision = True
    m = FFModel(cfg)
    ids = m.create_tensor((2, 16), DataType.DT_INT32)
    t = m.embedding(ids, 32, 16, AggrMode.AGGR_MODE_NONE)
    t = m.multihead_attention(t, t, t, 16, 2, causal=True)
    t = m.softmax(m.dense(t, 32))
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    m.compile_decode()
    return m


@pytest.mark.parametrize("builder", [mixed_mlp, mixed_cnn,
                                     mixed_attention, mixed_moe,
                                     mixed_fsdp, mixed_longctx,
                                     mixed_decode])
def test_mixed_zoo_sweep_zero_ffa7xx_errors(builder):
    """Searched mixed-precision zoo strategies must come back with ZERO
    FFA7xx errors: the default inference (bf16 compute, fp32 accum) is
    clean by construction."""
    m = builder()
    # compile annotated the graph; the full analyzer stack must be clean
    rep = analyze_model(m)
    assert not [d for d in rep.errors if d.code.startswith("FFA7")], \
        rep.summary()
    ndev = min(m.config.numWorkers, len(jax.devices()))
    assert strategy_violations(
        m.graph, getattr(m, "searched_views", None), ndev) == []
    # the trajectory records the precision vetting
    kinds = [e["kind"] for e in m.search_trajectory.events]
    assert "precision_lint" in kinds


# ----------------------------------------------------------------------
# FFL301: float64 creep on the step path
# ----------------------------------------------------------------------
def _fflint(src, path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from fflint import lint_source
    finally:
        sys.path.pop(0)
    return lint_source(src, path)


def test_ffl301_flags_float64_creep_in_step_paths():
    src = (
        "import numpy as np\n"
        "def step(state, batch):\n"
        "    a = np.array(batch)\n"
        "    b = np.float64(0.0)\n"
        "    c = np.zeros((2,), dtype='float64')\n"
        "    return a, b, c\n"
    )
    hits = [f for f in _fflint(
        src, os.path.join(REPO, "flexflow_tpu", "parallel", "x.py"))
        if f.code == "FFL301"]
    assert len(hits) == 3
    # outside step-path modules the rule is silent
    assert not [f for f in _fflint(
        src, os.path.join(REPO, "flexflow_tpu", "core", "x.py"))
        if f.code == "FFL301"]
    # explicit narrow dtype and pragma both satisfy it
    clean = (
        "import numpy as np\n"
        "def step(state):\n"
        "    a = np.zeros((2,), dtype=np.float32)\n"
        "    b = np.float64(0.0)  # fflint: disable=FFL301\n"
        "    return a, b\n"
    )
    assert not [f for f in _fflint(
        clean, os.path.join(REPO, "flexflow_tpu", "parallel", "x.py"))
        if f.code == "FFL301"]


def test_fflint_tree_is_clean_including_ffl301():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from fflint import lint_path
    finally:
        sys.path.pop(0)
    findings = []
    for sub in ("flexflow_tpu", "tools", "tests"):
        findings.extend(lint_path(os.path.join(REPO, sub)))
    assert findings == [], [f.format() for f in findings]
