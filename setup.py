"""Packaging entry (reference: setup.py + cmake; SURVEY §2.7).

Builds the native C++ runtime library (dataloader + task-graph simulator,
flexflow_tpu/native/src) at install time when a toolchain is present; the
package also self-builds it lazily at runtime (native/__init__.py), so a
pure-Python install still works everywhere.
"""
import subprocess
import sys
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildPyWithNative(build_py):
    def run(self):
        super().run()
        try:
            sys.path.insert(0, str(Path(__file__).parent))
            from flexflow_tpu import native

            lib = native.build(force=True)
            if lib:
                dest = Path(self.build_lib) / "flexflow_tpu" / "native"
                dest.mkdir(parents=True, exist_ok=True)
                self.copy_file(lib, str(dest / Path(lib).name))
        except Exception as exc:  # toolchain-less install is fine
            print(f"[setup] skipping native build: {exc}")


setup(cmdclass={"build_py": BuildPyWithNative})
