"""MultiHeadAttention operator.

TPU-native equivalent of reference src/ops/attention.cc (926 LoC, cuDNN
`cudnnMultiHeadAttnForward` with packed qkv weights). Here attention is
expressed as einsum chains that XLA maps onto the MXU; a Pallas
flash-attention kernel (kernels/flash_attention.py) is used for long
sequences where the O(s^2) score tensor would blow HBM.

Head-dim parallelism: the reference partitions weights per-head
(attention.cc:214 — "attribute parallelism over heads"); our PCG carries that
as a degree on the heads dim, which lowers to sharding the (num_heads,...)
weight axes over the mesh's model axis.

Inputs are (batch, seq, embed) like the reference's (N, L, E).
"""
from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from ..ff_types import DataType, OperatorType
from .registry import WeightSpec, register_op


# Dropout-fallback bookkeeping: the "dropout forces the dense path" warning
# used to fire on EVERY traced forward (once per layer per trace — dozens of
# identical lines per compile). Now each distinct (impl, layer, reason)
# warns once per process, and every occurrence is counted in the
# ff_attention_fallback_total{reason=...} metric instead (obs.count — a
# no-op without an active telemetry session).
_FALLBACK_WARNED: set = set()


def reset_attention_fallback_warnings() -> None:
    """Forget which (impl, layer, reason) fallbacks already warned
    (tests; a fresh process starts empty)."""
    _FALLBACK_WARNED.clear()


def _dropout_fallback(impl: str, op_name: str, reason: str) -> None:
    from .. import obs

    obs.count("ff_attention_fallback_total",
              help="attention ops that fell back to the dense path",
              reason=reason)
    key = (impl, op_name, reason)
    if key in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(key)
    detail = {
        "kernel": f"FF_ATTENTION_IMPL={impl} does not thread the dropout "
                  "rng (only the fused flash kernels do)",
        "mesh": "the flash dropout kernel runs device-local; sharded "
                "meshes keep the dense path",
        "backend": "the fused Pallas kernel needs the TPU backend",
        "seq": "the sequence exceeds the fused kernel's VMEM tile",
        # sequence-parallel (ring/ulysses) fallbacks: the requested SP
        # impl cannot engage, so XLA all-gathers the full K/V instead
        "sp_mesh": f"FF_ATTENTION_IMPL={impl} needs a seq-sharded mesh "
                   "(sequence_parallel_degree > 1)",
        "sp_shape": "ring/ulysses need self-attention with batch, heads "
                    "and seq divisible by their mesh degrees",
        "sp_heads": "ulysses needs the per-device head count divisible "
                    "by the seq axis (heads scatter over it)",
        # paged flash-decode fallbacks (serving): the requested paged
        # kernel cannot prove exactness for this step, so the dense
        # per-row masked path runs instead
        "paged_pallas": "the paged flash-decode kernel needs Pallas "
                        "(jax.experimental.pallas unavailable)",
        "paged_block": "the paged flash-decode kernel attends ONE query "
                       "token per slot; multi-token blocks (prefill) "
                       "keep the dense masked path",
    }[reason]
    kind = "dropout" if reason in ("kernel", "mesh", "backend", "seq") \
        else "paged decode" if reason.startswith("paged_") \
        else "sequence parallelism"
    knob = "FF_DECODE_IMPL" if reason.startswith("paged_") \
        else "FF_ATTENTION_IMPL"
    warnings.warn(
        f"attention {kind} on {op_name or 'a MHA op'} "
        f"({knob}={impl}) falls back to the dense path: "
        f"{detail}"
    )


@dataclasses.dataclass(frozen=True)
class MultiHeadAttentionParams:
    """reference: include/flexflow/ops/attention_params.h"""

    embed_dim: int
    num_heads: int
    kdim: int = 0  # 0 = embed_dim
    vdim: int = 0
    dropout: float = 0.0
    bias: bool = True
    add_bias_kv: bool = False
    add_zero_attn: bool = False
    causal: bool = False  # TPU addition: causal masking for decoder models

    # reference semantics (attention.cc:86): kdim/vdim are PER-HEAD
    # projection sizes (qProjSize = kdim); 0 means embed_dim/num_heads.
    @property
    def qk_head_dim(self):
        return self.kdim or self.embed_dim // self.num_heads

    @property
    def v_head_dim(self):
        return self.vdim or self.embed_dim // self.num_heads

    @property
    def head_dim(self):
        return self.qk_head_dim


def _infer(params: MultiHeadAttentionParams, in_shapes, in_dtypes):
    q, k, v = in_shapes
    out = (q[0], q[1], params.embed_dim)
    return [out], [in_dtypes[0]]


def _weights(params: MultiHeadAttentionParams, in_shapes, in_dtypes):
    q, k, v = in_shapes
    h = params.num_heads
    dqk, dv = params.qk_head_dim, params.v_head_dim
    dt = in_dtypes[0]
    ws = [
        WeightSpec("wq", (q[-1], h, dqk), dt, "glorot_uniform", ("", "head", "")),
        WeightSpec("wk", (k[-1], h, dqk), dt, "glorot_uniform", ("", "head", "")),
        WeightSpec("wv", (v[-1], h, dv), dt, "glorot_uniform", ("", "head", "")),
        WeightSpec("wo", (h, dv, params.embed_dim), dt, "glorot_uniform", ("head", "", "")),
    ]
    if params.bias:
        ws.append(WeightSpec("bias_o", (params.embed_dim,), dt, "zero"))
    return ws


def _forward(params: MultiHeadAttentionParams, weights, inputs, ctx):
    q_in, k_in, v_in = inputs
    cdt = ctx.compute_dtype
    if cdt is not None:
        q_in, k_in, v_in = (t.astype(cdt) for t in (q_in, k_in, v_in))
    wq, wk, wv, wo = (
        weights["wq"], weights["wk"], weights["wv"], weights["wo"],
    )
    if cdt is not None:
        wq, wk, wv, wo = (w.astype(cdt) for w in (wq, wk, wv, wo))
    b, seq_len, _ = q_in.shape
    kv_len = k_in.shape[1]
    h = params.num_heads
    use_dropout = params.dropout > 0.0 and ctx.training and ctx.rng is not None
    seq_degree = data_degree = model_degree = expert_degree = 1
    if ctx.mesh is not None:
        seq_degree = ctx.mesh.shape.get("seq", 1)
        data_degree = ctx.mesh.shape.get("data", 1)
        model_degree = ctx.mesh.shape.get("model", 1)
        # under the expert merge (parallel/strategies.py assign_mesh_axes)
        # the batch rides the RENAMED data axis, so a nontrivial expert
        # axis must gate the device-local fast paths exactly like data
        expert_degree = ctx.mesh.shape.get("expert", 1)
    # Only the mesh axes that actually shard the score tensor's dims count
    # toward the per-chip footprint: data (batch), model (heads), seq
    # (query positions). The pipe axis doesn't divide this op's footprint.
    shard = ctx.n_devices
    if ctx.mesh is not None:
        shard = data_degree * model_degree * seq_degree
    score_bytes = 4 * b * h * seq_len * kv_len // max(1, shard)
    # FF_ATTENTION_IMPL ∈ {auto, dense, flash, chunked, ring, ulysses}
    # overrides the size-based dispatch (like picking a cuDNN MHA algo by
    # hand).
    impl = os.environ.get("FF_ATTENTION_IMPL", "auto")
    if impl not in ("auto", "dense", "flash", "chunked", "ring", "ulysses"):
        raise ValueError(
            f"FF_ATTENTION_IMPL={impl!r}: "
            "expected auto|dense|flash|chunked|ring|ulysses"
        )
    from ..kernels.attention import flash_supported

    # RNG-threaded flash dropout: the fused Pallas kernels regenerate a
    # counter-based keep-mask per VMEM tile (kernels/attention.py), so
    # dropout > 0 no longer forces the dense-materialized path wherever
    # the fused kernel is eligible. The other streaming kernels
    # (chunked/ring/ulysses) and sharded meshes still fall back to dense
    # — warn once per (impl, layer, reason), count every occurrence.
    flash_dropout_ok = (
        use_dropout
        and impl in ("auto", "flash")
        and jax.default_backend() == "tpu"
        and flash_supported(seq_len, kv_len)
        and data_degree * model_degree * seq_degree * expert_degree == 1
    )
    if use_dropout and not flash_dropout_ok:
        if impl in ("chunked", "ring", "ulysses"):
            _dropout_fallback(impl, ctx.op_name, "kernel")
        elif impl == "flash" or (
                impl == "auto"
                and (jax.default_backend() == "tpu"
                     or score_bytes > 256 * 1024 * 1024)):
            # without dropout this call would have streamed
            if jax.default_backend() != "tpu":
                _dropout_fallback(impl, ctx.op_name, "backend")
            elif not flash_supported(seq_len, kv_len):
                _dropout_fallback(impl, ctx.op_name, "seq")
            else:
                _dropout_fallback(impl, ctx.op_name, "mesh")

    # Single-chip/unsharded fast path: project q/k/v straight into the
    # kernel's folded (b*h, s, d) layout — the head transpose rides the
    # projection einsum for free instead of costing a per-layer HBM
    # round-trip each way (fold + unfold, fwd and bwd).
    if (impl in ("auto", "flash")
            and jax.default_backend() == "tpu"
            and (not use_dropout or flash_dropout_ok)
            and flash_supported(seq_len, kv_len)
            and data_degree * model_degree * seq_degree * expert_degree
            == 1):
        from ..kernels.attention import dropout_seeds, flash_attention_folded

        dqk, dv = params.qk_head_dim, params.v_head_dim
        qf = jnp.einsum("bse,ehd->bhsd", q_in, wq,
                        preferred_element_type=jnp.float32)
        kf = jnp.einsum("bse,ehd->bhsd", k_in, wk,
                        preferred_element_type=jnp.float32)
        vf = jnp.einsum("bse,ehd->bhsd", v_in, wv,
                        preferred_element_type=jnp.float32)
        qf = qf.astype(q_in.dtype).reshape(b * h, seq_len, dqk)
        kf = kf.astype(q_in.dtype).reshape(b * h, kv_len, dqk)
        vf = vf.astype(q_in.dtype).reshape(b * h, kv_len, dv)
        attn = flash_attention_folded(
            qf, kf, vf, params.causal,
            dropout=params.dropout if use_dropout else 0.0,
            seeds=dropout_seeds(ctx.rng) if use_dropout else None,
        )
        out = jnp.einsum(
            "bhsd,hde->bse", attn.reshape(b, h, seq_len, dv), wo,
            preferred_element_type=jnp.float32,
        ).astype(q_in.dtype)
        if params.bias:
            out = out + weights["bias_o"].astype(out.dtype)
        return [out]

    # (b, s, e) @ (e, h, d) -> (b, s, h, d). Three separate gemms: packing
    # q/k/v into one gemm against a concatenated weight (cuDNN-MHA style)
    # was tried and wins ~4.5% in isolation but loses ~6% inside the full
    # jitted train step (the per-step concat + slices cost XLA more in
    # layout/fusion than the bigger gemm saves).
    q = jnp.einsum("bse,ehd->bshd", q_in, wq, preferred_element_type=jnp.float32)
    k = jnp.einsum("bse,ehd->bshd", k_in, wk, preferred_element_type=jnp.float32)
    v = jnp.einsum("bse,ehd->bshd", v_in, wv, preferred_element_type=jnp.float32)
    q = q.astype(q_in.dtype)
    k = k.astype(q_in.dtype)
    v = v.astype(q_in.dtype)

    # Dispatch: on TPU the fused Pallas kernel (fwd + bwd in VMEM,
    # kernels/attention.py) wins whenever its score tile fits — measured
    # 416 vs 313 samples/s against the XLA dense path on the bench config
    # (seq 512, hidden 1024 — the dense path moves 134 MB of f32 scores
    # per layer through HBM). The dense path remains for dropout (rng
    # threading), non-TPU backends, and as the general fallback; past a
    # per-chip score-byte budget the O(seq)-memory chunked/ring kernels
    # take over regardless. Shapes here are global; batch/head axes shard
    # over the mesh, so the per-chip footprint divides by n_devices.

    # pallas_call has no GSPMD partitioning rule: on a non-trivial mesh the
    # fused kernel must run under shard_map over the batch/head axes (each
    # program is independent per (batch, head)); when the seq axis shards
    # the queries, the ring/ulysses paths own the problem instead.
    mesh_nontrivial = (
        data_degree * model_degree * seq_degree * expert_degree > 1
    )
    flash_shardable = (
        seq_degree == 1
        and expert_degree == 1  # batch rides the expert axis when merged
        and b % data_degree == 0
        and h % model_degree == 0
    )
    # A seq-sharded mesh still wants streaming: the ring path intercepts
    # below (keeping K/V sharded), and its indivisible fallback lands on
    # chunked — never on a GSPMD-sharded pallas_call.
    prefer_flash = (
        impl == "auto"
        and jax.default_backend() == "tpu"
        and flash_supported(seq_len, kv_len)
        and (not mesh_nontrivial or flash_shardable or seq_degree > 1)
    )
    use_streaming = (
        impl in ("flash", "chunked", "ring", "ulysses")
        or (impl == "auto"
            and (prefer_flash or score_bytes > 256 * 1024 * 1024))
    ) and not use_dropout
    # Sequence/context parallelism: with the seq axis sharded, the dense
    # and flash paths would make XLA all-gather the full K/V on every chip;
    # ring attention keeps K/V resident and rotates shards over ICI
    # (kernels/attention.py). Chosen whenever streaming kicks in on a
    # seq-sharded mesh, or forced via FF_ATTENTION_IMPL=ring. shard_map
    # needs every sharded dim divisible (GSPMD tolerates uneven shards,
    # the explicit specs here don't) — otherwise fall back to streaming.
    sp_shardable = (
        seq_degree > 1
        and use_streaming
        and kv_len == seq_len
        and seq_len % seq_degree == 0
        and b % data_degree == 0
        and h % model_degree == 0
    )
    # Ulysses (all_to_all head scatter) additionally needs the local head
    # count to divide the seq axis; ring has no such constraint, so auto
    # keeps ring as the SP default and ulysses is opt-in.
    use_ulysses = (
        sp_shardable
        and impl == "ulysses"
        and (h // max(1, model_degree)) % seq_degree == 0
    )
    use_ring = sp_shardable and impl in ("auto", "ring")
    if impl in ("ring", "ulysses") and not (use_ring or use_ulysses) \
            and not use_dropout:
        # same dedup + ff_attention_fallback_total{reason} accounting as
        # the dropout fallbacks: warn once per (impl, layer, reason),
        # count every traced occurrence
        if seq_degree <= 1:
            reason = "sp_mesh"
        elif impl == "ulysses" and sp_shardable:
            reason = "sp_heads"
        else:
            reason = "sp_shape"
        _dropout_fallback(impl, ctx.op_name, reason)
    if use_ring or use_ulysses:
        import functools

        from jax.sharding import PartitionSpec as P

        from ..kernels.attention import ring_attention, ulysses_attention
        from ..parallel.pipeline import shard_map

        if use_ulysses:
            fn = functools.partial(
                ulysses_attention, axis_name="seq", causal=params.causal,
                interpret=jax.default_backend() != "tpu",
            )
        else:
            fn = functools.partial(
                ring_attention, axis_name="seq", causal=params.causal
            )
        spec = P("data", "seq", "model", None)
        attn = shard_map(
            fn,
            mesh=ctx.mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)
    elif use_streaming:
        # Long sequences: O(seq) memory kernels instead of the s×s score
        # tensor — Pallas flash attention on TPU, chunked scan elsewhere
        # (kernels/attention.py; replaces cuDNN MHA's internal algorithm).
        import functools

        from ..kernels.attention import chunked_attention, local_attention

        if impl == "flash" and not flash_supported(seq_len, kv_len):
            warnings.warn(
                "FF_ATTENTION_IMPL=flash ignored: "
                f"{seq_len}x{kv_len} scores exceed the fused kernel's "
                "VMEM tile — using chunked attention"
            )
        if impl == "chunked":
            attn = chunked_attention(q, k, v, causal=params.causal)
        elif mesh_nontrivial:
            # On a sharded mesh the Pallas kernel can only run on per-chip
            # shards: shard_map over batch (data) and heads (model) — each
            # (batch, head) program is independent, so no collectives. When
            # those dims don't divide the mesh, chunked attention (plain
            # jnp, GSPMD-partitionable) is the safe path.
            if flash_shardable:
                from jax.sharding import PartitionSpec as P

                from ..parallel.pipeline import shard_map

                spec = P("data", None, "model", None)
                attn = shard_map(
                    functools.partial(local_attention, causal=params.causal),
                    mesh=ctx.mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                )(q, k, v)
            else:
                if impl == "flash":
                    warnings.warn(
                        "FF_ATTENTION_IMPL=flash ignored: batch/heads don't "
                        "divide the data/model mesh axes (or the seq axis is "
                        "sharded) — using chunked attention"
                    )
                attn = chunked_attention(q, k, v, causal=params.causal)
        else:
            attn = local_attention(q, k, v, causal=params.causal)
    else:
        scale = 1.0 / jnp.sqrt(jnp.asarray(params.head_dim, jnp.float32))
        scores = jnp.einsum(
            "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
        )
        scores = scores * scale
        if params.causal:
            s_len, t_len = scores.shape[-2], scores.shape[-1]
            mask = jnp.tril(jnp.ones((s_len, t_len), bool))
            scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        if use_dropout:
            # same counter-based mask the flash kernels regenerate
            # blockwise in VMEM — the two paths draw IDENTICAL masks from
            # the same rng, so flash-with-dropout is testable against
            # dense-with-dropout (and switching paths between compiles
            # doesn't change the dropout stream)
            from ..kernels.attention import (
                attention_dropout_mask,
                dropout_seeds,
            )

            keep = attention_dropout_mask(
                dropout_seeds(ctx.rng), params.dropout,
                probs.shape[0] * probs.shape[1],
                probs.shape[2], probs.shape[3],
            ).reshape(probs.shape)
            probs = jnp.where(
                keep, probs * (1.0 / (1.0 - params.dropout)), 0
            ).astype(probs.dtype)
        attn = jnp.einsum(
            "bhst,bthd->bshd", probs, v, preferred_element_type=jnp.float32
        )
        attn = attn.astype(q.dtype)
    out = jnp.einsum("bshd,hde->bse", attn, wo, preferred_element_type=jnp.float32)
    out = out.astype(q_in.dtype)
    if params.bias:
        out = out + weights["bias_o"].astype(out.dtype)
    return [out]


def _forward_decode(params, weights, inputs, ctx, cache, t):
    """Incremental decode step with a KV cache (serving path,
    executor.build_decode). Inputs are the NEW positions' slices
    (b, s0, e) starting at position t (s0 = 1 for token-by-token decode,
    s0 = prompt_len for one-shot prefill); cache holds (k, v) of shape
    (b, max_len, h, d) with positions < t valid. Appends the block's K/V
    and attends its queries against the prefix with intra-block causal
    masking — cache-width attention rows per token instead of the full
    O(L²) forward the reference's serving prototype would re-run (it has
    no KV cache; triton/README.md calls it an incomplete prototype).

    Requires self-attention (q_in is k_in is v_in upstream) — the decode
    builder rejects cross-attention graphs.

    `t` may be a scalar (every row at the same position — the generate
    APIs) or a (b,) vector of per-row positions (continuous batching,
    runtime/serving.py: each slot of a running decode batch is mid-way
    through its own sequence). The vector path appends each row's K/V at
    its own offset (a vmapped per-row update) and masks each row's
    attention against its own position."""
    q_in, k_in, v_in = inputs
    cdt = ctx.compute_dtype
    if cdt is not None:
        q_in, k_in, v_in = (x.astype(cdt) for x in (q_in, k_in, v_in))
    wq, wk, wv, wo = (
        weights["wq"], weights["wk"], weights["wv"], weights["wo"],
    )
    if cdt is not None:
        wq, wk, wv, wo = (w.astype(cdt) for w in (wq, wk, wv, wo))
    q = jnp.einsum("bse,ehd->bshd", q_in, wq,
                   preferred_element_type=jnp.float32).astype(q_in.dtype)
    k_new = jnp.einsum("bse,ehd->bshd", k_in, wk,
                       preferred_element_type=jnp.float32).astype(q_in.dtype)
    v_new = jnp.einsum("bse,ehd->bshd", v_in, wv,
                       preferred_element_type=jnp.float32).astype(q_in.dtype)
    k_cache, v_cache = cache
    per_row_t = getattr(t, "ndim", 0) == 1
    if per_row_t:
        row_update = jax.vmap(
            lambda c, n, tt: jax.lax.dynamic_update_slice(c, n, (tt, 0, 0))
        )
        k_cache = row_update(k_cache, k_new.astype(k_cache.dtype), t)
        v_cache = row_update(v_cache, v_new.astype(v_cache.dtype), t)
    else:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k_new.astype(k_cache.dtype), (0, t, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v_new.astype(v_cache.dtype), (0, t, 0, 0)
        )
    # FF_DECODE_IMPL ∈ {auto, dense, paged}: "paged" routes single-token
    # steps through the Pallas paged flash-decode kernel
    # (kernels/decode.py — the dense per-slot cache viewed as a paged
    # pool, online softmax over pages, dead pages skipped); "auto"
    # engages it only where the compiled kernel runs (TPU backend);
    # "dense" pins the per-row masked reference path. Ineligible "paged"
    # requests fall back dense with the shared
    # ff_attention_fallback_total{reason} counter + one warning.
    impl = os.environ.get("FF_DECODE_IMPL", "auto")
    if impl not in ("auto", "dense", "paged"):
        raise ValueError(
            f"FF_DECODE_IMPL={impl!r}: expected one of auto|dense|paged")
    use_paged = False
    if impl != "dense":
        from ..kernels.attention import HAS_PALLAS
        if impl == "paged":
            if not HAS_PALLAS:
                _dropout_fallback(impl, ctx.op_name, "paged_pallas")
            elif q.shape[1] != 1:
                _dropout_fallback(impl, ctx.op_name, "paged_block")
            else:
                use_paged = True
        else:  # auto: interpret mode on CPU would lose to the XLA dense
            use_paged = (HAS_PALLAS and q.shape[1] == 1
                         and jax.default_backend() == "tpu")
    if use_paged:
        from ..kernels.decode import (
            decode_page_size,
            paged_flash_decode,
            paged_view_of_cache,
        )
        b = q.shape[0]
        kp, vp, table = paged_view_of_cache(
            k_cache.astype(q.dtype), v_cache.astype(q.dtype),
            decode_page_size(k_cache.shape[1]),
        )
        lengths = (t.astype(jnp.int32) if per_row_t
                   else jnp.full((b,), t, jnp.int32)) + 1
        attn = paged_flash_decode(
            q[:, 0], kp, vp, table, lengths,
            interpret=jax.default_backend() != "tpu",
        )[:, None]                     # (b, 1, h, dv)
    else:
        scale = 1.0 / jnp.sqrt(jnp.asarray(params.qk_head_dim, jnp.float32))
        scores = jnp.einsum(
            "bshd,bthd->bhst", q, k_cache.astype(q.dtype),
            preferred_element_type=jnp.float32,
        ) * scale                      # (b, h, s0, max_len)
        pos = jnp.arange(k_cache.shape[1])      # cache positions
        if per_row_t:
            q_pos = t[:, None] + jnp.arange(q.shape[1])[None, :]  # (b, s0)
            scores = jnp.where(
                pos[None, None, None, :] <= q_pos[:, None, :, None],
                scores, jnp.finfo(jnp.float32).min,
            )
        else:
            q_pos = t + jnp.arange(q.shape[1])  # this block's positions
            scores = jnp.where(
                pos[None, None, None, :] <= q_pos[None, None, :, None],
                scores, jnp.finfo(jnp.float32).min,
            )
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum(
            "bhst,bthd->bshd", probs, v_cache.astype(q.dtype),
            preferred_element_type=jnp.float32,
        ).astype(q.dtype)
    out = jnp.einsum("bshd,hde->bse", attn, wo,
                     preferred_element_type=jnp.float32)
    out = out.astype(q_in.dtype)  # post-cast dtype, same as _forward
    if params.bias:
        out = out + weights["bias_o"].astype(out.dtype)
    return [out], (k_cache, v_cache)


def cross_decode_kv(params: MultiHeadAttentionParams, weights, k_in, v_in,
                    ctx):
    """Precompute the FULL encoder-side K/V for cross-attention decode
    (executor.build_decode init): k_in/v_in are the static encoder
    outputs (b, s_enc, e). Computed once per sequence — each decode step
    then attends its query slice against these without re-projecting
    (the O(1)/token contract for enc-dec serving)."""
    cdt = ctx.compute_dtype
    if cdt is not None:
        k_in, v_in = k_in.astype(cdt), v_in.astype(cdt)
    wk, wv = weights["wk"], weights["wv"]
    if cdt is not None:
        wk, wv = wk.astype(cdt), wv.astype(cdt)
    k = jnp.einsum("bse,ehd->bshd", k_in, wk,
                   preferred_element_type=jnp.float32).astype(k_in.dtype)
    v = jnp.einsum("bse,ehd->bshd", v_in, wv,
                   preferred_element_type=jnp.float32).astype(k_in.dtype)
    return (k, v)


def _forward_decode_cross(params, weights, q_in, ctx, kv):
    """Cross-attention decode step: project this block's queries and
    attend over the precomputed full encoder K/V (cross_decode_kv). No
    causal mask — every decoder position sees the whole encoder sequence,
    exactly like the training forward."""
    cdt = ctx.compute_dtype
    if cdt is not None:
        q_in = q_in.astype(cdt)
    wq, wo = weights["wq"], weights["wo"]
    if cdt is not None:
        wq, wo = wq.astype(cdt), wo.astype(cdt)
    q = jnp.einsum("bse,ehd->bshd", q_in, wq,
                   preferred_element_type=jnp.float32).astype(q_in.dtype)
    k, v = kv
    scale = 1.0 / jnp.sqrt(jnp.asarray(params.qk_head_dim, jnp.float32))
    scores = jnp.einsum(
        "bshd,bthd->bhst", q, k.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    attn = jnp.einsum(
        "bhst,bthd->bshd", probs, v.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    out = jnp.einsum("bshd,hde->bse", attn, wo,
                     preferred_element_type=jnp.float32)
    out = out.astype(q_in.dtype)
    if params.bias:
        out = out + weights["bias_o"].astype(out.dtype)
    return [out]


def init_decode_cache(params: MultiHeadAttentionParams, batch: int,
                      max_len: int, dtype):
    """Fresh (k, v) cache for one MHA op."""
    h, dqk, dv = params.num_heads, params.qk_head_dim, params.v_head_dim
    return (
        jnp.zeros((batch, max_len, h, dqk), dtype),
        jnp.zeros((batch, max_len, h, dv), dtype),
    )


register_op(
    OperatorType.OP_MULTIHEAD_ATTENTION,
    "MultiHeadAttention",
    infer=_infer,
    weights=_weights,
    forward=_forward,
    num_inputs=3,
    forward_decode=_forward_decode,
)
