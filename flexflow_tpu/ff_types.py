"""Core enums and type maps for flexflow_tpu.

TPU-native re-design of the reference's enum vocabulary
(reference: include/flexflow/ffconst.h:1-200). We keep the same *names* so the
Python API surface is drop-in compatible, but values are our own.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.IntEnum):
    """Tensor element types (reference: ffconst.h:14-21)."""

    DT_BOOLEAN = 40
    DT_INT32 = 41
    DT_INT64 = 42
    DT_HALF = 43
    DT_FLOAT = 44
    DT_DOUBLE = 45
    DT_BF16 = 46  # TPU-native addition: bfloat16 is the native matmul type
    DT_NONE = 49

    @property
    def jnp_dtype(self):
        return _DT_TO_JNP[self]

    @property
    def np_dtype(self):
        return _DT_TO_NP[self]

    @property
    def size(self) -> int:
        return np.dtype(_DT_TO_NP[self]).itemsize


_DT_TO_JNP = {
    DataType.DT_BOOLEAN: jnp.bool_,
    DataType.DT_INT32: jnp.int32,
    DataType.DT_INT64: jnp.int64,
    DataType.DT_HALF: jnp.float16,
    DataType.DT_FLOAT: jnp.float32,
    DataType.DT_DOUBLE: jnp.float64,
    DataType.DT_BF16: jnp.bfloat16,
}

_DT_TO_NP = {
    DataType.DT_BOOLEAN: np.bool_,
    DataType.DT_INT32: np.int32,
    DataType.DT_INT64: np.int64,
    DataType.DT_HALF: np.float16,
    DataType.DT_FLOAT: np.float32,
    DataType.DT_DOUBLE: np.float64,
    DataType.DT_BF16: jnp.bfloat16,  # numpy via ml_dtypes
}


def to_data_type(x) -> DataType:
    if isinstance(x, DataType):
        return x
    d = np.dtype(x) if not hasattr(x, "name") else x
    name = getattr(d, "name", str(d))
    return {
        "bool": DataType.DT_BOOLEAN,
        "int32": DataType.DT_INT32,
        "int64": DataType.DT_INT64,
        "float16": DataType.DT_HALF,
        "float32": DataType.DT_FLOAT,
        "float64": DataType.DT_DOUBLE,
        "bfloat16": DataType.DT_BF16,
    }[name]


class ActiMode(enum.IntEnum):
    """Fused activation modes (reference: ffconst.h:23-29)."""

    AC_MODE_NONE = 10
    AC_MODE_RELU = 11
    AC_MODE_SIGMOID = 12
    AC_MODE_TANH = 13
    AC_MODE_GELU = 14


class AggrMode(enum.IntEnum):
    """Embedding aggregation (reference: ffconst.h:31-35)."""

    AGGR_MODE_NONE = 20
    AGGR_MODE_SUM = 21
    AGGR_MODE_AVG = 22


class RegularizerMode(enum.IntEnum):
    """Weight regularizers (reference: python/flexflow/type.py:12-15;
    linear_kernels.cu:333-350 applies L2 as grad += lambda * w)."""

    REG_MODE_NONE = 25
    REG_MODE_L1 = 26
    REG_MODE_L2 = 27


class PoolType(enum.IntEnum):
    """Pooling modes (reference: ffconst.h:37-40)."""

    POOL_MAX = 30
    POOL_AVG = 31


class LossType(enum.IntEnum):
    """Loss functions (reference: ffconst.h:47-53)."""

    LOSS_CATEGORICAL_CROSSENTROPY = 50
    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = 51
    LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE = 52
    LOSS_MEAN_SQUARED_ERROR_SUM_REDUCE = 53
    LOSS_IDENTITY = 54


class MetricsType(enum.IntEnum):
    """Metrics bitmask-ish ids (reference: ffconst.h:55-63)."""

    METRICS_ACCURACY = 1001
    METRICS_CATEGORICAL_CROSSENTROPY = 1002
    METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = 1004
    METRICS_MEAN_SQUARED_ERROR = 1008
    METRICS_ROOT_MEAN_SQUARED_ERROR = 1016
    METRICS_MEAN_ABSOLUTE_ERROR = 1032


class CompMode(enum.IntEnum):
    """Computation mode (reference: ffconst.h:65-67)."""

    COMP_MODE_TRAINING = 70
    COMP_MODE_INFERENCE = 71


class ParameterSyncType(enum.IntEnum):
    """Gradient sync strategy (reference: config.h:55-59).

    On TPU, PS has no meaning (no host parameter server); both map to XLA
    collectives over the mesh, but we keep the enum for API parity.
    """

    NONE = 80
    PS = 81
    NCCL = 82  # == XLA psum/reduce_scatter over mesh axes


class OperatorType(enum.IntEnum):
    """All operator types (reference: ffconst.h:69-163)."""

    OP_NOOP = 1000
    OP_INPUT = 1001
    OP_WEIGHT = 1002
    OP_CONV2D = 1010
    OP_DROPOUT = 1011
    OP_LINEAR = 1012
    OP_BATCHMATMUL = 1013
    OP_POOL2D = 1014
    OP_RELU = 1020
    OP_SIGMOID = 1021
    OP_TANH = 1022
    OP_ELU = 1023
    OP_FLAT = 1024
    OP_SOFTMAX = 1025
    OP_BATCHNORM = 1026
    OP_CONCAT = 1027
    OP_SPLIT = 1028
    OP_EMBEDDING = 1029
    OP_GROUP_BY = 1030
    OP_CACHE = 1031
    OP_AGGREGATE = 1032
    OP_AGG_SPEC = 1033
    OP_RESHAPE = 1040
    OP_REVERSE = 1041
    OP_TRANSPOSE = 1042
    OP_EW_ADD = 1043
    OP_EW_MUL = 1044
    OP_MATMUL = 1045
    OP_MUL = 1046
    OP_ENLARGE = 1047
    OP_SQUEEZE = 1048
    OP_UNSQUEEZE = 1049
    OP_EW_SUB = 1050
    OP_EW_DIV = 1051
    OP_EW_EQUAL = 1052
    OP_EW_GREATER = 1053
    OP_EW_LESS = 1054
    OP_EW_MAX = 1055
    OP_EW_MIN = 1056
    OP_REDUCE_ARGMAX = 1057
    OP_REDUCE_ARGMIN = 1058
    OP_REDUCE_MAX = 1059
    OP_REDUCE_MEAN = 1060
    OP_REDUCE_MIN = 1061
    OP_REDUCE_PROD = 1062
    OP_REDUCE_SUM = 1063
    OP_PAD = 1064
    OP_SHAPE = 1065
    OP_SIZE = 1066
    OP_TOPK = 1067
    OP_WHERE = 1068
    OP_CEIL = 1069
    OP_CAST = 1070
    OP_EXP = 1071
    OP_ROUND = 1072
    OP_LOG = 1073
    OP_LOGICAL_NOT = 1074
    OP_SQRT = 1075
    OP_SIN = 1076
    OP_COS = 1077
    OP_LEAKYRELU = 1078
    OP_SLICE = 1079
    OP_RESIZE = 1080
    OP_PRELU = 1081
    OP_GELU = 1082
    OP_MULTIHEAD_ATTENTION = 1090
    OP_FUSED = 1091
    OP_RSQRT = 1092
    OP_POW = 1093
    OP_MEAN = 1094
    OP_LAYERNORM = 1095
    OP_IDENTITY = 1096
    OP_GATHER = 1097
    OP_SCALAR_MULTIPLY = 1101
    OP_SCALAR_ADD = 1102
    OP_SCALAR_SUB = 1103
    OP_SCALAR_FLOOR_DIV = 1104
    OP_SCALAR_TRUE_DIV = 1105
    # TPU addition: stacked homogeneous transformer blocks executed as a
    # GPipe pipeline over the "pipe" mesh axis (the reference's OP_PIPELINE
    # is enum-only, ffconst.h:158 — no implementation exists there).
    OP_BLOCK_STACK = 1107
    # Parallel ops (reference: ffconst.h:152-160)
    OP_REPARTITION = 1110
    OP_COMBINE = 1111
    OP_REPLICATE = 1112
    OP_REDUCTION = 1113
    OP_PIPELINE = 1114
    OP_FUSED_PARALLEL = 1115
    # TPU-native additions (first-class sequence/context parallelism, SURVEY §7)
    OP_ALL_TO_ALL = 1120
    # FSDP/ZeRO weight sharding (parallel/weight_sharding.py): parameters +
    # optimizer state sharded over the "fsdp" mesh axis, all-gather-on-use,
    # reduce-scatter grads. No reference equivalent (the reference always
    # replicates weights within a model-parallel group).
    OP_WEIGHT_SHARD = 1121
    # recurrence (reference implements LSTM only in the standalone nmt/)
    OP_LSTM = 1130


PARALLEL_OP_TYPES = frozenset(
    {
        OperatorType.OP_REPARTITION,
        OperatorType.OP_COMBINE,
        OperatorType.OP_REPLICATE,
        OperatorType.OP_REDUCTION,
        OperatorType.OP_PIPELINE,
        OperatorType.OP_FUSED_PARALLEL,
        OperatorType.OP_ALL_TO_ALL,
        OperatorType.OP_WEIGHT_SHARD,
    }
)


class InitializerType(enum.IntEnum):
    INITIALIZER_GLOROT_UNIFORM = 2000
    INITIALIZER_ZERO = 2001
    INITIALIZER_CONSTANT = 2002
    INITIALIZER_UNIFORM = 2003
    INITIALIZER_NORM = 2004


MAX_TENSOR_DIM = 5  # reference: config MAX_TENSOR_DIM (include/flexflow/config.h)
