"""reference: python/flexflow/keras/backend/internal.py — rsqrt/gather
functional wrappers (+ the layer classes live in ..layers)."""
from ..layers import BatchMatmul, Cos, Exp, Gather, Pow, ReduceSum, Rsqrt, Sin  # noqa: F401


def rsqrt(x, name=""):
    return Rsqrt(name=name)(x)


def gather(x, indices, axis, name=""):
    return Gather(axis, name=name)([x, indices])
