"""AlexNet model builder.

Same network the reference trains for its CIFAR-10 bootcamp benchmark
(reference: examples/cpp/AlexNet/alexnet.cc:70-83 and
bootcamp_demo/ff_alexnet_cifar10.py), expressed through our FFModel API.
"""
from __future__ import annotations

from ..core.model import FFModel
from ..ff_types import ActiMode, DataType, PoolType


def build_alexnet(
    model: FFModel,
    batch_size: int,
    num_classes: int = 10,
    height: int = 229,
    width: int = 229,
):
    """reference topology: alexnet.cc:70-83 (conv 64k11s4p2 ... dense 4096)."""
    input_t = model.create_tensor(
        (batch_size, 3, height, width), DataType.DT_FLOAT, name="image"
    )
    t = model.conv2d(input_t, 64, 11, 11, 4, 4, 2, 2, ActiMode.AC_MODE_RELU)
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = model.conv2d(t, 192, 5, 5, 1, 1, 2, 2, ActiMode.AC_MODE_RELU)
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = model.conv2d(t, 384, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = model.conv2d(t, 256, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = model.pool2d(t, 3, 3, 2, 2, 0, 0)
    t = model.flat(t)
    t = model.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 4096, ActiMode.AC_MODE_RELU)
    t = model.dense(t, num_classes)
    t = model.softmax(t)
    return input_t, t
