"""FusedOp: executes a chain of ops as one unit.

TPU-native equivalent of reference src/ops/fused.cc (458 LoC + 922 LoC CUDA
dispatch loop). The reference packs consecutive non-parallel ops into a single
Legion task to amortize launch overhead (--fusion). Under XLA every jitted
step is already one fused program, so this op exists for (a) PCG parity —
the search/serializer can still produce OP_FUSED nodes — and (b) as the
attachment point for hand-written Pallas mega-kernels where XLA's automatic
fusion is insufficient (MoE routing chains).
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

from ..ff_types import OperatorType
from .registry import FwdCtx, get_op_def, register_op


@dataclasses.dataclass(frozen=True)
class FusedOpParams:
    """Chain of (op_type, params, input_slot_indices) triples.

    Slots: 0..len(inputs)-1 are fused-op inputs; len(inputs)+i is the output
    of chain step i (mirrors the reference's slot encoding in fused.cc).
    """

    chain: Tuple[Tuple[OperatorType, object, Tuple[int, ...]], ...]
    num_inputs: int
    output_slots: Tuple[int, ...]


def _fused_infer(params: FusedOpParams, in_shapes, in_dtypes):
    slots_s = list(in_shapes)
    slots_d = list(in_dtypes)
    for op_type, p, in_slots in params.chain:
        d = get_op_def(op_type)
        outs, dts = d.infer(p, [slots_s[i] for i in in_slots], [slots_d[i] for i in in_slots])
        slots_s.extend(outs)
        slots_d.extend(dts)
    return (
        [slots_s[i] for i in params.output_slots],
        [slots_d[i] for i in params.output_slots],
    )


def _fused_forward(params: FusedOpParams, weights, inputs, ctx: FwdCtx):
    slots = list(inputs)
    for step, (op_type, p, in_slots) in enumerate(params.chain):
        d = get_op_def(op_type)
        step_weights = {}
        if weights:
            # nested {"step0": {...}} or flat {"step0/kernel": ...} layouts
            step_weights = dict(weights.get(f"step{step}", {}))
            prefix = f"step{step}/"
            for k, v in weights.items():
                if isinstance(k, str) and k.startswith(prefix):
                    step_weights[k[len(prefix):]] = v
        outs = d.forward(p, step_weights, [slots[i] for i in in_slots], ctx)
        slots.extend(outs)
    return [slots[i] for i in params.output_slots]


register_op(
    OperatorType.OP_FUSED,
    "FusedOp",
    infer=_fused_infer,
    forward=_fused_forward,
    num_inputs=-1,
)
