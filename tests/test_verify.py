"""Numerical-trust layer tests (runtime/verify.py): differential
strategy-equivalence verification, checkpoint integrity checksums, the
SDC/determinism canary, per-step invariants, and the typed-error /
narrowed-except satellites.

Everything runs on the 8-device CPU mesh; the broader strategy sweep is
@pytest.mark.slow and runs standalone via scripts/verify_check.sh."""
import dataclasses
import os

import numpy as np
import pytest

import jax

from flexflow_tpu import (
    ActiMode,
    CanaryConfig,
    CanaryMismatchError,
    CheckpointCorruptionError,
    CheckpointManager,
    DataType,
    FFConfig,
    FFModel,
    FaultInjector,
    InvariantViolationError,
    LossType,
    MetricsType,
    NotCompiledError,
    SGDOptimizer,
    ServingConfigError,
    StrategyDivergenceError,
    verify_checkpoint,
    verify_strategy,
)
from flexflow_tpu.runtime import verify as vfy
from flexflow_tpu.runtime.checkpoint import (
    _put_resharded,
    restore_checkpoint,
    save_checkpoint,
)


def small_model(hidden=16, layers=2, batch=8, search_budget=None,
                features=4, classes=3):
    cfg = FFConfig()
    cfg.batch_size = batch
    if search_budget is not None:
        cfg.search_budget = search_budget
    m = FFModel(cfg)
    x = m.create_tensor((batch, features), DataType.DT_FLOAT)
    t = x
    for _ in range(layers - 1):
        t = m.dense(t, hidden, ActiMode.AC_MODE_RELU)
    t = m.dense(t, classes)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def dataset(n=64, seed=0, features=4, classes=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, features).astype(np.float32)
    y = rng.randint(0, classes, (n, 1)).astype(np.int32)
    return x, y


def params_of(m):
    return {
        name: {k: np.array(v, copy=True) for k, v in wd.items()}
        for name, wd in m.state.params.items()
    }


# ----------------------------------------------------------------------
# checksum primitives
# ----------------------------------------------------------------------
def test_tensor_checksums_stable_and_sensitive():
    tree = {"params": {"d": {"kernel": np.arange(12, dtype=np.float32)
                             .reshape(3, 4)}},
            "step": np.asarray(3)}
    a = vfy.tensor_checksums(tree)
    b = vfy.tensor_checksums(tree)
    assert a == b
    assert "params/d/kernel" in a
    assert a["params/d/kernel"]["dtype"] == "float32"
    assert a["params/d/kernel"]["shape"] == [3, 4]
    tree["params"]["d"]["kernel"][0, 0] += 1
    assert vfy.tensor_checksums(tree)["params/d/kernel"]["crc32"] \
        != a["params/d/kernel"]["crc32"]
    # None leaves (empty SGD momentum slots) are skipped, not hashed
    assert "opt" not in vfy.tensor_checksums({"opt": None})


def test_verify_checksums_names_the_corrupt_tensor():
    tree = {"params": {"d": {"kernel": np.ones(4, np.float32),
                             "bias": np.zeros(2, np.float32)}}}
    integrity = {"algo": "crc32", "tensors": vfy.tensor_checksums(tree)}
    vfy.verify_checksums(tree, integrity)  # intact: no raise
    tree["params"]["d"]["bias"][0] = 7.0
    with pytest.raises(CheckpointCorruptionError) as ei:
        vfy.verify_checksums(tree, integrity, path="/x")
    assert "params/d/bias" in str(ei.value)
    assert ei.value.tensors == ["params/d/bias"]


def test_bitflip_array_flips_exactly_one_bit():
    a = np.zeros(8, np.float32)
    b = vfy.bitflip_array(a, bit=6, index=3)
    assert (a != b).sum() == 1
    ab, bb = a.view(np.uint8), b.reshape(-1).view(np.uint8)
    diff = np.nonzero(ab != bb)[0]
    assert len(diff) == 1
    assert bin(int(ab[diff[0]]) ^ int(bb[diff[0]])).count("1") == 1


def test_fault_injector_fire_extras_matching():
    fi = FaultInjector()
    fi.inject("bitflip", at_step=3, target="disk")
    fi.inject("bitflip", at_step=3)
    # the state consumer (target=None) must not steal the disk plan
    plan = fi.fire("bitflip", 3, target=None)
    assert plan is not None and plan.get("target") is None
    plan = fi.fire("bitflip", 3, target="disk")
    assert plan is not None and plan["target"] == "disk"
    assert fi.fire("bitflip", 3) is None  # both consumed


# ----------------------------------------------------------------------
# checkpoint integrity end to end
# ----------------------------------------------------------------------
def test_checkpoint_audit_and_corruption_detection(tmp_path):
    m = small_model()
    x, y = dataset()
    path = str(tmp_path / "ck")
    save_checkpoint(m, path, step=0)
    rep = verify_checkpoint(path)
    assert rep["ok"] and rep["has_integrity"] and rep["checked"] >= 4
    corrupted = vfy.corrupt_checkpoint_tensor(path)
    rep2 = verify_checkpoint(path)
    assert not rep2["ok"]
    assert rep2["corrupt"] and corrupted.endswith(rep2["corrupt"][0]
                                                 .split("/", 1)[-1])
    m2 = small_model()
    with pytest.raises(CheckpointCorruptionError) as ei:
        restore_checkpoint(m2, path)
    assert rep2["corrupt"][0] in str(ei.value)


def test_restore_latest_falls_back_past_corrupt_newest(tmp_path):
    d = str(tmp_path / "ckpts")
    m = small_model()
    x, y = dataset()
    m.fit(x, y, epochs=2, verbose=False, checkpoint_dir=d,
          checkpoint_every_n_steps=4, resume=False)
    mgr = CheckpointManager(d)
    steps = mgr.list_steps()
    assert len(steps) >= 2
    vfy.corrupt_checkpoint_tensor(mgr.step_path(steps[-1]))
    m2 = small_model()
    with pytest.warns(UserWarning, match="falling back"):
        info = mgr.restore_latest(m2)
    assert info is not None and info.step == steps[-2]


def test_bitflip_disk_site_caught_by_checksum_on_restore(tmp_path):
    """Acceptance: FaultInjector(site='bitflip', target='disk') corrupts a
    just-written checkpoint AFTER its checksums were recorded; the
    restore-time integrity gate catches it and restore_latest falls back
    to the previous intact checkpoint."""
    d = str(tmp_path / "ckpts")
    m = small_model()
    x, y = dataset()
    # 16 total steps; cadence 5 -> saves at 5, 10, 15 and the final
    # done-save at 16. Arm the flip for step 16 so the NEWEST checkpoint
    # on disk is the corrupt one.
    fi = FaultInjector()
    fi.inject("bitflip", at_step=16, target="disk")
    m.fit(x, y, epochs=2, verbose=False, checkpoint_dir=d,
          checkpoint_every_n_steps=5, resume=False, fault_injector=fi)
    assert fi.fired.get("bitflip") == 1
    mgr = CheckpointManager(d)
    assert not verify_checkpoint(mgr.step_path(16))["ok"]
    m2 = small_model()
    with pytest.warns(UserWarning, match="falling back"):
        info = mgr.restore_latest(m2)
    assert info is not None and info.step == 15


def test_old_checkpoints_without_integrity_still_restore(tmp_path):
    import json

    m = small_model()
    path = str(tmp_path / "ck")
    save_checkpoint(m, path, step=0)
    meta_path = path + ".meta.json"
    with open(meta_path) as f:
        meta = json.load(f)
    meta.pop("integrity")
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    rep = verify_checkpoint(path)
    assert rep["ok"] and not rep["has_integrity"]
    m2 = small_model()
    restore_checkpoint(m2, path)  # no raise


# ----------------------------------------------------------------------
# SDC / determinism canary
# ----------------------------------------------------------------------
def test_canary_clean_run_matches_uncanaried_training():
    x, y = dataset()
    a = small_model()
    a.fit(x, y, epochs=1, verbose=False)
    b = small_model()
    b.fit(x, y, epochs=1, verbose=False,
          canary=CanaryConfig(every_n_steps=2, mode="determinism"))
    pa, pb = params_of(a), params_of(b)
    for name, wd in pa.items():
        for k, v in wd.items():
            np.testing.assert_allclose(pb[name][k], v, atol=1e-6,
                                       err_msg=f"{name}/{k}")


def test_canary_catches_bitflip_and_checkpoints(tmp_path):
    """Acceptance: the SDC canary catches a mid-run bitflip; escalation
    reverts to the pre-step state and flushes it as a checkpoint."""
    d = str(tmp_path / "ckpts")
    m = small_model()
    x, y = dataset()
    fi = FaultInjector()
    fi.inject("bitflip", at_step=4)
    with pytest.raises(CanaryMismatchError) as ei:
        m.fit(x, y, epochs=2, verbose=False, checkpoint_dir=d,
              checkpoint_every_n_steps=100, resume=False,
              fault_injector=fi,
              canary=CanaryConfig(every_n_steps=2, mode="determinism"))
    assert ei.value.step == 4
    assert ei.value.mismatches
    assert ei.value.checkpoint_path is not None
    assert os.path.isdir(ei.value.checkpoint_path)
    # the flushed checkpoint is the PRE-step (trusted) state and intact
    assert verify_checkpoint(ei.value.checkpoint_path)["ok"]


def test_canary_sdc_mode_catches_exponent_flip():
    m = small_model()
    x, y = dataset()
    fi = FaultInjector()
    fi.inject("bitflip", at_step=2, bit=6, index=3)  # exponent bit
    with pytest.raises(CanaryMismatchError):
        m.fit(x, y, epochs=1, verbose=False, fault_injector=fi,
              canary=CanaryConfig(every_n_steps=2, mode="sdc"))


def test_invariant_loss_delta_escalates(tmp_path):
    m = small_model()
    x, y = dataset()
    with pytest.raises(InvariantViolationError) as ei:
        m.fit(x, y, epochs=1, verbose=False,
              checkpoint_dir=str(tmp_path / "ck"),
              canary=CanaryConfig(every_n_steps=0, max_loss_delta=0.0))
    assert ei.value.invariant == "loss_delta"
    assert ei.value.checkpoint_path is not None


def test_canary_config_rejects_unknown_mode():
    with pytest.raises(ValueError, match="mode"):
        CanaryConfig(mode="paranoid")


# ----------------------------------------------------------------------
# differential strategy verifier
# ----------------------------------------------------------------------
def test_verify_strategy_searched_mlp():
    m = small_model(hidden=32, batch=32, search_budget=4, layers=3)
    x, y = dataset(n=64)
    v = verify_strategy(m, (x, y), steps=2, batch_size=32)
    assert v.ok, v.summary()
    assert v.steps == 2
    assert not v.param_mismatches


def test_verify_strategy_searched_cnn():
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = 3
    m = FFModel(cfg)
    x = m.create_tensor((8, 3, 16, 16), DataType.DT_FLOAT)
    t = m.conv2d(x, 8, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = m.flat(t)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    rng = np.random.RandomState(0)
    xc = rng.randn(16, 3, 16, 16).astype(np.float32)
    yc = rng.randint(0, 4, (16, 1)).astype(np.int32)
    v = verify_strategy(m, (xc, yc), steps=2)
    assert v.ok, v.summary()


def test_verify_strategy_searched_attention():
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = 3
    m = FFModel(cfg)
    x = m.create_tensor((8, 16, 32), DataType.DT_FLOAT)
    t = m.multihead_attention(x, x, x, 32, 4)
    t = m.dense(t, 32, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 4)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    rng = np.random.RandomState(0)
    xa = rng.randn(16, 16, 32).astype(np.float32)
    ya = rng.randint(0, 4, (16, 16, 1)).astype(np.int32)
    v = verify_strategy(m, (xa, ya), steps=2)
    assert v.ok, v.summary()


def _break_activation(m):
    """Simulate a broken substitution: the rewrite 'lost' an activation."""
    for op in m.graph.ops:
        if (op.op_type.name == "OP_LINEAR"
                and getattr(op.params, "activation", None)
                == ActiMode.AC_MODE_RELU):
            op.params = dataclasses.replace(
                op.params, activation=ActiMode.AC_MODE_NONE
            )
            return op.name
    raise AssertionError("no relu dense op to break")


def test_verify_strategy_names_broken_substitution_op():
    """Acceptance: a deliberately-broken substitution (dropped activation)
    must fail verification naming the diverging op."""
    m = small_model(hidden=32, batch=32, search_budget=4, layers=3)
    x, y = dataset(n=64)
    broken = _break_activation(m)
    m.executor.invalidate_step_cache()
    v = verify_strategy(m, (x, y), steps=2, batch_size=32)
    assert not v.ok
    assert v.diverging_op is not None and broken in v.diverging_op


def test_verify_strategy_names_wrong_reduction_axis():
    """Acceptance: a wrong reduction axis (softmax over the batch axis
    instead of the class axis) fails verification naming the op."""
    m = small_model(hidden=32, batch=32, search_budget=4)
    x, y = dataset(n=64)
    soft = [op for op in m.graph.ops if op.op_type.name == "OP_SOFTMAX"]
    assert soft
    soft[0].params = dataclasses.replace(soft[0].params, dim=0)
    m.executor.invalidate_step_cache()
    v = verify_strategy(m, (x, y), steps=2, batch_size=32)
    assert not v.ok
    assert v.diverging_op is not None and soft[0].name in v.diverging_op


def test_fit_preflight_verification(tmp_path):
    m = small_model(hidden=16, batch=8, search_budget=3)
    x, y = dataset()
    m.fit(x, y, epochs=1, verbose=False, verify_strategy="preflight")
    m2 = small_model(hidden=16, batch=8, search_budget=3)
    _break_activation(m2)
    m2.executor.invalidate_step_cache()
    with pytest.raises(StrategyDivergenceError) as ei:
        m2.fit(x, y, epochs=1, verbose=False, verify_strategy="preflight")
    assert ei.value.diverging_op is not None


def test_verify_strategy_does_not_mutate_live_state():
    m = small_model(hidden=16, batch=8)
    x, y = dataset()
    before = params_of(m)
    verify_strategy(m, (x, y), steps=2)
    after = params_of(m)
    for name, wd in before.items():
        for k, v in wd.items():
            np.testing.assert_array_equal(after[name][k], v)


def test_fit_rejects_unknown_verify_mode():
    m = small_model()
    x, y = dataset()
    with pytest.raises(ValueError, match="preflight"):
        m.fit(x, y, epochs=1, verbose=False, verify_strategy="postflight")


# ----------------------------------------------------------------------
# strategy-validator hook
# ----------------------------------------------------------------------
def test_strategy_validator_hook_runs_on_compile():
    from flexflow_tpu import search as search_mod

    seen = []

    def probe(graph, views, ndev):
        seen.append((len(graph.ops), ndev))
        return []

    search_mod.register_strategy_validator(probe)
    try:
        small_model(hidden=16, batch=8, search_budget=2)
    finally:
        search_mod._STRATEGY_VALIDATORS.remove(probe)
    assert seen and seen[0][1] >= 1


def test_validate_searched_strategy_flags_dead_devices():
    from flexflow_tpu.pcg.machine_view import MachineView

    m = small_model(hidden=16, batch=8, search_budget=2)
    views = dict(getattr(m, "searched_views", {}) or {})
    views[999] = MachineView(start_device_id=6, dim=(4,), stride=(1,))
    problems = vfy.validate_searched_strategy(m.graph, views, 4)
    assert any("999" in p for p in problems)


# ----------------------------------------------------------------------
# satellites: _put_resharded / _restore_report coverage
# ----------------------------------------------------------------------
def test_put_resharded_keeps_sharding_when_divisible():
    m = small_model()
    like = m.state.params["op_linear_0"]["kernel"]
    arr = np.random.RandomState(0).randn(*like.shape).astype(np.float32)
    out = _put_resharded(arr, like)
    assert out.sharding == like.sharding
    np.testing.assert_allclose(np.asarray(out), arr, atol=0)


def test_put_resharded_replicates_uneven_shapes(caplog):
    """An elastic restore can land a shard count the live mesh doesn't
    divide — the data must still arrive (replicated), with a warning."""
    import logging

    from jax.sharding import NamedSharding, PartitionSpec

    m = small_model()
    mesh = m.executor.mesh
    axis = mesh.axis_names[0]
    sharded_like = jax.device_put(
        np.zeros((mesh.shape[axis] * 2, 3), np.float32),
        NamedSharding(mesh, PartitionSpec(axis)),
    )
    # uneven last-shard shape: 6 rows across an 8-way axis
    arr = np.arange(6 * 3, dtype=np.float32).reshape(6, 3)
    with caplog.at_level(logging.WARNING,
                         logger="flexflow_tpu.runtime.checkpoint"):
        out = _put_resharded(arr, sharded_like)
    assert "replicating" in caplog.text
    np.testing.assert_allclose(np.asarray(out), arr, atol=0)
    spec = out.sharding.spec
    assert all(s is None for s in spec), spec


def test_restore_report_unmatched_tensor_paths(tmp_path):
    # checkpoint from a 3-layer model, restored into a 2-layer model:
    # the checkpoint's extra op lands in unmatched_checkpoint
    big = small_model(layers=3)
    path = str(tmp_path / "ck")
    save_checkpoint(big, path, step=0)
    small = small_model(layers=2)
    restore_checkpoint(small, path, strict_topology=False)
    rep = small._restore_report
    assert any("op_linear_2" in n for n in rep["unmatched_checkpoint"])
    # and the reverse: a model op missing from the checkpoint keeps its
    # fresh init and lands in unmatched_model
    small2 = small_model(layers=2)
    fresh = params_of(small2)
    path2 = str(tmp_path / "ck2")
    save_checkpoint(small2, path2, step=0)
    big2 = small_model(layers=3)
    restore_checkpoint(big2, path2, strict_topology=False)
    rep2 = big2._restore_report
    assert any("op_linear_2" in n for n in rep2["unmatched_model"])
    got = params_of(big2)
    for k, v in fresh["op_linear_0"].items():
        np.testing.assert_allclose(got["op_linear_0"][k], v, atol=1e-7)


# ----------------------------------------------------------------------
# satellites: typed errors replace bare asserts
# ----------------------------------------------------------------------
def test_uncompiled_apis_raise_not_compiled_error():
    m = FFModel(FFConfig())
    m.create_tensor((8, 4), DataType.DT_FLOAT)
    with pytest.raises(NotCompiledError):
        save_checkpoint(m, "/tmp/never-written")
    with pytest.raises(NotCompiledError):
        restore_checkpoint(m, "/tmp/never-written")
    with pytest.raises(NotCompiledError):
        m.fit(np.zeros((8, 4), np.float32), np.zeros((8, 1), np.int32),
              verbose=False)
    from flexflow_tpu import BatchScheduler

    with pytest.raises(NotCompiledError):
        BatchScheduler(m)
    from flexflow_tpu.runtime.serving import greedy_generate

    with pytest.raises(NotCompiledError):
        greedy_generate(m, np.zeros((8, 4), np.int32))


def test_serving_config_errors_are_typed():
    from flexflow_tpu.runtime.serving import incremental_generate

    m = small_model()
    with pytest.raises(ServingConfigError, match="max_len"):
        incremental_generate(m, np.zeros((8, 4), np.int32),
                             max_new_tokens=100, max_len=8)


# ----------------------------------------------------------------------
# slow sweep: model-zoo strategies at a larger search budget
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_verify_strategy_zoo_sweep():
    """scripts/verify_check.sh entry: the equivalence sweep at a larger
    budget, covering deeper zoo-shaped graphs than the tier-1 trio."""
    cases = []

    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.search_budget = 8
    m = FFModel(cfg)
    x = m.create_tensor((16, 64), DataType.DT_FLOAT)
    t = m.dense(x, 128, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 64, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    rng = np.random.RandomState(0)
    cases.append((m, rng.randn(32, 64).astype(np.float32),
                  rng.randint(0, 10, (32, 1)).astype(np.int32)))

    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 3, 32, 32), DataType.DT_FLOAT)
    t = m.conv2d(x, 16, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = m.conv2d(t, 16, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = m.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = m.flat(t)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    cases.append((m, rng.randn(16, 3, 32, 32).astype(np.float32),
                  rng.randint(0, 10, (16, 1)).astype(np.int32)))

    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 32, 64), DataType.DT_FLOAT)
    t = m.transformer_blocks(x, hidden_size=64, num_heads=8, num_layers=2)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    cases.append((m, rng.randn(16, 32, 64).astype(np.float32),
                  rng.randint(0, 10, (16, 32, 1)).astype(np.int32)))

    # FSDP/ZeRO weight sharding (parallel/weight_sharding.py): params +
    # optimizer state sharded over the fsdp axis, all-gather-on-use,
    # reduce-scatter grads — must be numerically equivalent to serial
    cfg = FFConfig()
    cfg.batch_size = 16
    cfg.fsdp_degree = len(jax.devices())
    m = FFModel(cfg)
    x = m.create_tensor((16, 64), DataType.DT_FLOAT)
    t = m.dense(x, 128, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 64, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.05, momentum=0.9),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    from flexflow_tpu.ff_types import OperatorType as _OT

    assert any(op.op_type == _OT.OP_WEIGHT_SHARD for op in m.graph.ops)
    cases.append((m, rng.randn(32, 64).astype(np.float32),
                  rng.randint(0, 10, (32, 1)).astype(np.int32)))

    for model, xd, yd in cases:
        v = verify_strategy(model, (xd, yd), steps=3)
        assert v.ok, v.summary()
