"""Profiling / tracing utilities.

TPU-native equivalents of the reference's profiling stack (SURVEY §5):
  * per-op cudaEvent timing behind `FFConfig.profiling`
    (kernels/linear_kernels.cu:94-117)      -> per-op wall timing via a
    non-jitted instrumented walk (XLA fuses ops, so per-op numbers come
    from running each op un-jitted — same caveat the simulator had)
  * Legion begin/end_trace replay            -> jit cache (free)
  * `-lg:prof` Legion profiler               -> jax.profiler traces viewable
    in TensorBoard/Perfetto, plus the obs/ structured tracer
    (flexflow_tpu.obs) for framework-level spans
  * simulator timeline export                -> export_simulated_timeline,
    emitting the SAME Chrome-trace schema as the obs tracer
    (obs/tracer.py to_chrome_trace) so a simulated schedule and a
    measured run overlay in one Perfetto view
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from typing import Dict, List, Union

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA/TPU profile (open in TensorBoard or Perfetto)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclasses.dataclass
class OpProfile:
    """Measured per-op wall times, in SECONDS (the same unit the cost
    model's CostMetrics and the simulated timeline use — keeping the
    units consistent is what lets obs.explain_strategy subtract them)."""

    forward_s: float
    backward_s: float = 0.0

    @property
    def total_s(self) -> float:
        return self.forward_s + self.backward_s


def profile_ops(
    model, batch_inputs, *, repeats: int = 3, warmup: int = 1,
    backward: bool = False,
) -> Union[Dict[str, float], Dict[str, "OpProfile"]]:
    """Per-op wall-times (reference: per-op event timing under
    FFConfig.profiling). Runs ops eagerly in topo order, `warmup`
    untimed runs first (the first eager call pays compilation/layout),
    then `repeats` timed runs averaged.

    Default return: {op name: forward seconds} (back-compat).
    `backward=True` additionally times each compute op's VJP (weights +
    float inputs) and returns {op name: OpProfile} — parallel ops and
    non-differentiable ops report backward_s=0.0."""
    ex = model.executor
    import jax.numpy as jnp

    vals = {pt.guid: jnp.asarray(a) for pt, a in zip(ex.input_pts, batch_inputs)}
    for guid, (pt, value) in ex.constants.items():
        vals[guid] = jnp.full(pt.material_shape(), value, pt.data_type.jnp_dtype)
    from ..ops.registry import FwdCtx, get_op_def
    from ..parallel import parallel_ops as par_ops

    times: Dict[str, OpProfile] = {}
    for op in ex.topo:
        ins = [vals[t.guid] for t in op.inputs]
        if op.is_parallel_op:
            fn = lambda: par_ops.execute(op, ins, ex.mesh)  # noqa: E731
            bwd_fn = None
        else:
            d = get_op_def(op.op_type)
            w = model.state.params.get(op.name, {})
            ctx = FwdCtx(training=False, rng=None)
            fn = lambda: d.forward(op.params, w, ins, ctx)  # noqa: E731
            bwd_fn = None
            if backward:
                diffable = [
                    i for i, a in enumerate(ins)
                    if jnp.issubdtype(a.dtype, jnp.floating)
                ]
                w_diff = {k: v for k, v in w.items()
                          if jnp.issubdtype(v.dtype, jnp.floating)}
                if diffable or w_diff:
                    def loss(ws, dins, _d=d, _op=op, _ins=ins,
                             _w=w, _idx=diffable, _ctx=ctx):
                        full = list(_ins)
                        for i, v in zip(_idx, dins):
                            full[i] = v
                        wall = dict(_w)
                        wall.update(ws)
                        outs = _d.forward(_op.params, wall, full, _ctx)
                        return sum(
                            jnp.sum(o.astype(jnp.float32)) for o in outs
                        )

                    grad = jax.grad(loss, argnums=(0, 1))
                    bwd_fn = (lambda _g=grad, _w=w_diff, _ins=ins,  # noqa: E731
                              _idx=diffable:
                              _g(_w, [_ins[i] for i in _idx]))
        outs = fn()
        jax.block_until_ready(outs)
        for _ in range(max(0, warmup - 1)):
            jax.block_until_ready(fn())
        t0 = time.perf_counter()
        for _ in range(repeats):
            outs = fn()
        jax.block_until_ready(outs)
        fwd_t = (time.perf_counter() - t0) / repeats
        bwd_t = 0.0
        if bwd_fn is not None:
            try:
                g = bwd_fn()
                jax.block_until_ready(g)
                for _ in range(max(0, warmup - 1)):
                    jax.block_until_ready(bwd_fn())
                t0 = time.perf_counter()
                for _ in range(repeats):
                    g = bwd_fn()
                jax.block_until_ready(g)
                # grad re-runs the forward: subtract it, floor at 10%
                # like search/measure.py so noise can't go negative
                total = (time.perf_counter() - t0) / repeats
                bwd_t = max(total - fwd_t, 0.1 * fwd_t)
            except (TypeError, ValueError, NotImplementedError):
                bwd_t = 0.0  # not differentiable standalone (int paths)
        times[op.name] = OpProfile(forward_s=fwd_t, backward_s=bwd_t)
        for t, o in zip(op.outputs, outs):
            vals[t.guid] = o
    if backward:
        return times
    return {name: p.forward_s for name, p in times.items()}


def measured_timeline_events(model, batch_inputs, *, repeats: int = 2,
                             warmup: int = 1) -> List[dict]:
    """The deterministic instrumented capture behind the step
    observatory's CPU fallback (obs/step_profile.py): the same eager
    chunked topo walk as `profile_ops`, but laid out as obs-tracer
    events — forward spans in topo order, per-op VJP backward spans
    (`<op>.bwd`) in reverse topo order after them, every span
    attributed to its PCG op guid with REAL perf_counter timestamps
    rebased to the capture's start. Timestamps tile the ops back to
    back (eager execution is serial), so the export reads as one
    measured step; `ts`/`dur` are seconds, cat is "measured", tid is
    the op's searched-view device (all of them, like the simulated
    export, so the tracks align in Perfetto)."""
    profs = profile_ops(model, batch_inputs, repeats=repeats,
                        warmup=warmup, backward=True)
    views = getattr(model, "searched_views", None) or {}
    topo = model.executor.topo
    events: List[dict] = []

    def tids(op):
        v = views.get(op.guid) or op.machine_view
        return v.device_ids() if v is not None else [0]

    cursor = 0.0
    for op in topo:
        p = profs.get(op.name)
        if p is None:
            continue
        for d in tids(op):
            events.append({
                "ts": cursor, "ph": "X", "name": op.name,
                "cat": "measured", "dur": p.forward_s, "tid": d,
                "args": {"op_type": op.op_type.name, "guid": op.guid,
                         "pass": "forward", "source": "instrumented"},
            })
        cursor += p.forward_s
    for op in reversed(topo):
        p = profs.get(op.name)
        if p is None or p.backward_s <= 0:
            continue
        for d in tids(op):
            events.append({
                "ts": cursor, "ph": "X", "name": f"{op.name}.bwd",
                "cat": "measured", "dur": p.backward_s, "tid": d,
                "args": {"op_type": op.op_type.name, "guid": op.guid,
                         "pass": "backward", "source": "instrumented"},
            })
        cursor += p.backward_s
    return events


def simulated_timeline_events(graph, views, cost_model,
                              *, backward: bool = False,
                              overlap_sync: bool = False) -> List[dict]:
    """The simulated schedule as obs-tracer events (the schema
    obs/tracer.py documents: ts/dur in seconds, cat "simulated", tid =
    device id) — export with obs.to_chrome_trace, or merge with a
    measured events.jsonl to overlay simulation against reality.

    overlap_sync=True additionally lays out the BACKWARD pass (reverse
    topo order after the forward makespan) with each statically
    overlappable weight-grad collective (analysis/collectives.
    overlappable_grad_syncs) as its own span on a dedicated comm-channel
    tid, concurrent with later backward compute spans — open the export
    in Perfetto and the collective/compute overlap the overlapped
    executor schedules is directly visible as parallel tracks."""
    events: List[dict] = []
    dev_free: Dict[int, float] = {}
    ready: Dict[int, float] = {}
    fwd_span: Dict[int, float] = {}
    topo = graph.topo_order()
    for op in topo:
        view = views[op.guid]
        cm = cost_model.measure_operator_cost(op, view)
        lb = max(
            (ready.get(t.guid, 0.0) for t in op.inputs), default=0.0
        )
        ids = view.device_ids()
        start = max([lb] + [dev_free.get(d, 0.0) for d in ids])
        dur = cm.forward_time + (
            cm.backward_time if backward and not overlap_sync else 0.0
        )
        end = start + dur
        for d in ids:
            dev_free[d] = end
            events.append({
                "ts": start,
                "ph": "X",
                "name": op.name,
                "cat": "simulated",
                "dur": dur,
                "tid": d,
                "args": {
                    "op_type": op.op_type.name,
                    "forward_s": cm.forward_time,
                    "backward_s": cm.backward_time,
                    "sync_s": cm.sync_time,
                },
            })
        for t in op.outputs:
            ready[t.guid] = end
        fwd_span[op.guid] = end
    if not overlap_sync:
        return events
    from ..analysis.collectives import overlappable_grad_syncs

    overlappable = overlappable_grad_syncs(graph)
    comm_tid = max(dev_free, default=0) + 1
    comm_free = 0.0
    cursor = max(dev_free.values()) if dev_free else 0.0
    for op in reversed(topo):
        view = views[op.guid]
        cm = cost_model.measure_operator_cost(op, view)
        start = cursor
        end = start + cm.backward_time
        cursor = end
        for d in view.device_ids():
            events.append({
                "ts": start, "ph": "X", "name": f"{op.name}.bwd",
                "cat": "simulated", "dur": cm.backward_time, "tid": d,
                "args": {"op_type": op.op_type.name, "pass": "backward"},
            })
        if cm.sync_time <= 0:
            continue
        if op.guid in overlappable:
            # the collective rides the comm channel while later backward
            # spans keep the devices busy — the overlap evidence
            s = max(comm_free, end)
            comm_free = s + cm.sync_time
            events.append({
                "ts": s, "ph": "X", "name": f"{op.name}.grad_sync",
                "cat": "simulated", "dur": cm.sync_time, "tid": comm_tid,
                "args": {"op_type": op.op_type.name,
                         "collective": "reduce_scatter+all_gather",
                         "overlapped": True},
            })
        else:
            for d in view.device_ids():
                events.append({
                    "ts": cursor, "ph": "X",
                    "name": f"{op.name}.grad_sync", "cat": "simulated",
                    "dur": cm.sync_time, "tid": d,
                    "args": {"op_type": op.op_type.name,
                             "collective": "all_reduce",
                             "overlapped": False},
                })
            cursor += cm.sync_time
    return events


def export_simulated_timeline(graph, views, cost_model, path: str, *,
                              overlap_sync: bool = False) -> None:
    """Export the simulated schedule as Chrome trace JSON (reference:
    Simulator::simulate_runtime's export_file_name, simulator.h:724),
    in the SAME schema as the runtime tracer's trace.json (categories as
    named processes, devices as tids) so both load into one Perfetto
    session and overlay. overlap_sync=True adds the backward pass with
    overlappable collectives on a comm-channel track (see
    simulated_timeline_events / docs/performance.md)."""
    from ..obs.tracer import to_chrome_trace

    with open(path, "w") as f:
        json.dump(
            to_chrome_trace(simulated_timeline_events(
                graph, views, cost_model, overlap_sync=overlap_sync,
            )),
            f,
        )
