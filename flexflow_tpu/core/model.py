"""FFModel: the user-facing model container and training driver.

TPU-native equivalent of the reference FFModel (include/flexflow/model.h:326,
src/runtime/model.cc:1160-3700) and its Python mirror
(python/flexflow/core/flexflow_cffi.py:883). API-call-for-API-call compatible:
each op method creates a deferred Layer; `compile()` lowers Layer graph → PCG,
applies/searches a parallelization strategy, and builds the jitted SPMD train
step; `fit()` runs the training loop (reference: flexflow_cffi.py:2058-2102
begin_trace → next_batch → forward → zero_gradients → backward → update →
end_trace — here one fused jitted step per iteration).
"""
from __future__ import annotations

import contextlib
import dataclasses
import time
import warnings
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..config import FFConfig, FFIterationConfig
from ..ff_types import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    PoolType,
    RegularizerMode,
    to_data_type,
)
from ..ops.attention import MultiHeadAttentionParams
from ..ops.batch_matmul import BatchMatmulParams
from ..ops.conv2d import Conv2DParams
from ..ops.dropout import DropoutParams
from ..ops.elementwise import ElementBinaryParams, ElementUnaryParams
from ..ops.embedding import EmbeddingParams
from ..ops.linear import LinearParams
from ..ops.moe import AggregateParams, AggregateSpecParams, CacheParams, GroupByParams
from ..ops.normalization import BatchNormParams, LayerNormParams
from ..ops.pool2d import Pool2DParams
from ..ops.reduce import ReduceParams, TopKParams
from ..ops.registry import get_op_def
from ..ops.softmax import SoftmaxParams
from ..ops.tensor_ops import (
    CastParams,
    ConcatParams,
    FlatParams,
    GatherParams,
    NoOpParams,
    PadParams,
    ReshapeParams,
    ReverseParams,
    SliceParams,
    SplitParams,
    TransposeParams,
)
from ..parallel import strategies
from ..parallel.executor import PCGExecutor, TrainState
from ..parallel.mesh import build_mesh
from ..pcg.lowering import layers_to_pcg
from .losses import to_loss_type
from .metrics import Metrics, PerfMetrics
from .optimizers import AdamOptimizer, Optimizer, SGDOptimizer
from .tensor import Layer, Tensor


_SHAPE_ONLY_OPS = (OperatorType.OP_RESHAPE, OperatorType.OP_FLAT,
                   OperatorType.OP_NOOP, OperatorType.OP_IDENTITY)


def _resolve_value_tail(op):
    """The op that produced an output's VALUES: unpack --fusion chains and
    skip shape-only steps."""
    steps = (
        [(s[0], s[1]) for s in op.params.chain]
        if op.op_type == OperatorType.OP_FUSED and op.params.chain
        else [(op.op_type, op.params)]
    )
    for op_type, params in reversed(steps):
        if op_type not in _SHAPE_ONLY_OPS:
            return op_type, params
    return steps[-1]


def _probability_like_tail(op_type, params) -> bool:
    """Does this value-producing tail op emit probabilities (in [0, 1])?"""
    if op_type in (OperatorType.OP_SOFTMAX, OperatorType.OP_SIGMOID):
        return True
    # fused activation inside the op (DLRM's final dense has
    # AC_MODE_SIGMOID, dlrm.cc create_mlp) keeps outputs in (0, 1)
    act = getattr(params, "activation", None)
    return act == ActiMode.AC_MODE_SIGMOID


def _fetch_global(v) -> np.ndarray:
    """Device value -> host numpy, multi-host safe: an array whose shards
    live on other processes can't be fetched directly (jax refuses), so
    allgather it first (runtime/distributed.py multi-host path — every
    process gets the full value, like the reference's CPU
    UPDATE_METRICS_TASK folding a future chain)."""
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        from jax.experimental import multihost_utils

        v = multihost_utils.process_allgather(v, tiled=True)
    return np.asarray(v)


class FFModel:
    """reference: model.h:326 FFModel / flexflow_cffi.py:883."""

    def __init__(self, ffconfig: Optional[FFConfig] = None):
        self.config = ffconfig or FFConfig()
        self.layers: List[Layer] = []
        self.input_tensors: List[Tensor] = []
        self.label_tensor: Optional[Tensor] = None
        self.optimizer: Optional[Optimizer] = None
        self.iter_config = FFIterationConfig()
        # compile products
        self.graph = None
        self.executor: Optional[PCGExecutor] = None
        # decode-objective second strategy (compile_decode): the same
        # layer graph re-searched under CostObjective.DECODE, carried
        # alongside the training strategy (Splitwise/DistServe
        # disaggregation within one model)
        self.decode_graph = None
        self.decode_executor: Optional[PCGExecutor] = None
        self.decode_searched_views: Dict[int, object] = {}
        self.decode_searched_cost: Optional[float] = None
        self.decode_trajectory = None
        self.state: Optional[TrainState] = None
        self.metrics_obj: Optional[Metrics] = None
        self.perf_metrics = PerfMetrics()
        self.loss_type: Optional[LossType] = None
        self.comp_mode = CompMode.COMP_MODE_TRAINING
        self._tensor_map: Dict[int, int] = {}
        self._pt_by_guid: Dict[int, object] = {}
        self._current_batch: Optional[Tuple] = None
        self._last_logits = None
        self._pending_grads = None
        self._dataloaders: List[object] = []
        # Tensor.guid -> scalar fill value OR baked np.ndarray contents
        self._constant_values: Dict[int, Union[float, np.ndarray]] = {}
        self._rng = jax.random.PRNGKey(self.config.seed)

    # ------------------------------------------------------------------
    # Graph building (reference: FFModel::create_tensor, model.cc)
    # ------------------------------------------------------------------
    def create_tensor(
        self,
        dims: Sequence[int],
        dtype: DataType = DataType.DT_FLOAT,
        create_grad: bool = True,
        name: str = "",
    ) -> Tensor:
        t = Tensor(tuple(dims), _to_dt(dtype), create_gradients=create_grad, name=name)
        t._model = self
        self.input_tensors.append(t)
        return t

    def _add_layer(
        self,
        op_type: OperatorType,
        params,
        inputs: List[Tensor],
        name: str = "",
        initializers: Optional[Dict[str, object]] = None,
    ) -> Union[Tensor, List[Tensor]]:
        # deterministic per-model names so checkpoints/strategies match
        # across processes (guid-based names differ run to run)
        if not name:
            name = f"{op_type.name.lower()}_{len(self.layers)}"
        layer = Layer(op_type, params, inputs, name=name)
        if initializers:
            layer.initializers.update(
                {k: v for k, v in initializers.items() if v is not None}
            )
        opdef = get_op_def(op_type)
        in_shapes = [t.dims for t in inputs]
        in_dtypes = [t.data_type for t in inputs]
        out_shapes, out_dtypes = opdef.infer(params, in_shapes, in_dtypes)
        for i, (s, dt) in enumerate(zip(out_shapes, out_dtypes)):
            out = Tensor(s, dt, owner_layer=layer, owner_idx=i)
            out._model = self
            layer.outputs.append(out)
        # expose weight tensors for get/set_weights parity
        for spec in opdef.weights(params, in_shapes, in_dtypes):
            wt = Tensor(spec.shape, spec.dtype, owner_layer=layer, name=spec.name)
            wt._model = self
            layer.weights.append(wt)
        self.layers.append(layer)
        if len(layer.outputs) == 1:
            return layer.outputs[0]
        return layer.outputs

    # -- op API (reference: flexflow_cffi.py FFModel methods) ----------
    def conv2d(
        self,
        input: Tensor,
        out_channels: int,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int,
        padding_w: int,
        activation: ActiMode = ActiMode.AC_MODE_NONE,
        groups: int = 1,
        use_bias: bool = True,
        shared_op=None,
        kernel_initializer=None,
        bias_initializer=None,
        name: str = "",
    ) -> Tensor:
        p = Conv2DParams(
            out_channels=out_channels,
            kernel_h=kernel_h,
            kernel_w=kernel_w,
            stride_h=stride_h,
            stride_w=stride_w,
            padding_h=padding_h,
            padding_w=padding_w,
            groups=groups,
            use_bias=use_bias,
            activation=_to_acti(activation),
        )
        return self._add_layer(
            OperatorType.OP_CONV2D,
            p,
            [input],
            name,
            {"kernel": kernel_initializer, "bias": bias_initializer},
        )

    def dense(
        self,
        input: Tensor,
        out_dim: int,
        activation: ActiMode = ActiMode.AC_MODE_NONE,
        use_bias: bool = True,
        datatype: DataType = DataType.DT_FLOAT,
        shared_op=None,
        kernel_initializer=None,
        bias_initializer=None,
        kernel_regularizer=None,
        name: str = "",
    ) -> Tensor:
        reg_type, reg_lambda = _to_regularizer(kernel_regularizer)
        p = LinearParams(
            out_channels=out_dim,
            use_bias=use_bias,
            activation=_to_acti(activation),
            data_type=_to_dt(datatype),
            kernel_reg_lambda=reg_lambda,
            kernel_reg_type=reg_type,
        )
        return self._add_layer(
            OperatorType.OP_LINEAR,
            p,
            [input],
            name,
            {"kernel": kernel_initializer, "bias": bias_initializer},
        )

    def embedding(
        self,
        input: Tensor,
        num_entries: int,
        out_dim: int,
        aggr: AggrMode = AggrMode.AGGR_MODE_NONE,
        dtype: DataType = DataType.DT_FLOAT,
        shared_op=None,
        kernel_initializer=None,
        name: str = "",
    ) -> Tensor:
        p = EmbeddingParams(
            num_entries=num_entries,
            out_channels=out_dim,
            aggr=aggr,
            data_type=_to_dt(dtype),
        )
        return self._add_layer(
            OperatorType.OP_EMBEDDING, p, [input], name, {"weight": kernel_initializer}
        )

    def pool2d(
        self,
        input: Tensor,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int,
        padding_w: int,
        pool_type: PoolType = PoolType.POOL_MAX,
        activation: ActiMode = ActiMode.AC_MODE_NONE,
        name: str = "",
    ) -> Tensor:
        p = Pool2DParams(
            kernel_h=kernel_h,
            kernel_w=kernel_w,
            stride_h=stride_h,
            stride_w=stride_w,
            padding_h=padding_h,
            padding_w=padding_w,
            pool_type=pool_type,
            activation=_to_acti(activation),
        )
        return self._add_layer(OperatorType.OP_POOL2D, p, [input], name)

    def batch_norm(self, input: Tensor, relu: bool = True, name: str = "") -> Tensor:
        return self._add_layer(
            OperatorType.OP_BATCHNORM, BatchNormParams(relu=relu), [input], name
        )

    def layer_norm(
        self,
        input: Tensor,
        axes: Sequence[int] = (-1,),
        elementwise_affine: bool = True,
        eps: float = 1e-5,
        name: str = "",
    ) -> Tensor:
        p = LayerNormParams(
            axes=tuple(axes), elementwise_affine=elementwise_affine, eps=eps
        )
        return self._add_layer(OperatorType.OP_LAYERNORM, p, [input], name)

    def batch_matmul(
        self,
        A: Tensor,
        B: Tensor,
        a_seq_length_dim: int = -1,
        b_seq_length_dim: int = -1,
        name: str = "",
    ) -> Tensor:
        p = BatchMatmulParams(a_seq_length_dim, b_seq_length_dim)
        return self._add_layer(OperatorType.OP_BATCHMATMUL, p, [A, B], name)

    def multihead_attention(
        self,
        query: Tensor,
        key: Tensor,
        value: Tensor,
        embed_dim: int,
        num_heads: int,
        kdim: int = 0,
        vdim: int = 0,
        dropout: float = 0.0,
        bias: bool = True,
        add_bias_kv: bool = False,
        add_zero_attn: bool = False,
        kernel_initializer=None,
        causal: bool = False,
        name: str = "",
    ) -> Tensor:
        p = MultiHeadAttentionParams(
            embed_dim=embed_dim,
            num_heads=num_heads,
            kdim=kdim,
            vdim=vdim,
            dropout=dropout,
            bias=bias,
            add_bias_kv=add_bias_kv,
            add_zero_attn=add_zero_attn,
            causal=causal,
        )
        inits = (
            {k: kernel_initializer for k in ("wq", "wk", "wv", "wo")}
            if kernel_initializer
            else None
        )
        return self._add_layer(
            OperatorType.OP_MULTIHEAD_ATTENTION, p, [query, key, value], name, inits
        )

    def transformer_blocks(
        self,
        input: Tensor,
        hidden_size: int,
        num_heads: int,
        num_layers: int,
        name: str = "",
    ) -> Tensor:
        """`num_layers` benchmark encoder blocks (MHA + 2 dense, the
        reference's transformer.cc:33-45 block) as ONE stacked op whose
        layer dim shards over the pipe mesh axis — pipeline parallelism as
        a sharding (TPU addition; reference's OP_PIPELINE is enum-only).
        Stage count comes from config.pipeline_parallel_degree."""
        from ..ops.pipeline import BlockStackParams

        p = BlockStackParams(
            hidden=hidden_size,
            num_heads=num_heads,
            num_layers=num_layers,
            num_stages=max(1, self.config.pipeline_parallel_degree),
            num_microbatches=self.config.num_microbatches,
        )
        return self._add_layer(OperatorType.OP_BLOCK_STACK, p, [input], name)

    # elementwise binary
    def _binary(self, t: OperatorType, x: Tensor, y: Tensor, name: str) -> Tensor:
        return self._add_layer(t, ElementBinaryParams(op_type=t), [x, y], name)

    def add(self, x, y, inplace_a=False, name=""):
        return self._binary(OperatorType.OP_EW_ADD, x, y, name)

    def subtract(self, x, y, inplace_a=False, name=""):
        return self._binary(OperatorType.OP_EW_SUB, x, y, name)

    def multiply(self, x, y, inplace_a=False, name=""):
        return self._binary(OperatorType.OP_EW_MUL, x, y, name)

    def divide(self, x, y, inplace_a=False, name=""):
        return self._binary(OperatorType.OP_EW_DIV, x, y, name)

    def max(self, x, y, inplace_a=False, name=""):
        return self._binary(OperatorType.OP_EW_MAX, x, y, name)

    def min(self, x, y, inplace_a=False, name=""):
        return self._binary(OperatorType.OP_EW_MIN, x, y, name)

    # elementwise unary
    def _unary(self, t: OperatorType, x: Tensor, name: str, scalar=0.0, inplace=False):
        p = ElementUnaryParams(op_type=t, inplace=inplace, scalar=scalar)
        return self._add_layer(t, p, [x], name)

    def exp(self, x, name=""):
        return self._unary(OperatorType.OP_EXP, x, name)

    def log(self, x, name=""):
        return self._unary(OperatorType.OP_LOG, x, name)

    def relu(self, x, inplace=True, name=""):
        return self._unary(OperatorType.OP_RELU, x, name, inplace=inplace)

    def sigmoid(self, x, name=""):
        return self._unary(OperatorType.OP_SIGMOID, x, name)

    def tanh(self, x, name=""):
        return self._unary(OperatorType.OP_TANH, x, name)

    def elu(self, x, inplace=True, name=""):
        return self._unary(OperatorType.OP_ELU, x, name, inplace=inplace)

    def gelu(self, x, name=""):
        return self._unary(OperatorType.OP_GELU, x, name)

    def identity(self, x, name=""):
        return self._unary(OperatorType.OP_IDENTITY, x, name)

    def rsqrt(self, x, name=""):
        return self._unary(OperatorType.OP_RSQRT, x, name)

    def sqrt(self, x, name=""):
        return self._unary(OperatorType.OP_SQRT, x, name)

    def sin(self, x, name=""):
        return self._unary(OperatorType.OP_SIN, x, name)

    def cos(self, x, name=""):
        return self._unary(OperatorType.OP_COS, x, name)

    def pow(self, x, exponent: float, name=""):
        return self._unary(OperatorType.OP_POW, x, name, scalar=exponent)

    def scalar_multiply(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OperatorType.OP_SCALAR_MULTIPLY, x, name, scalar=scalar)

    def scalar_add(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OperatorType.OP_SCALAR_ADD, x, name, scalar=scalar)

    def scalar_sub(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OperatorType.OP_SCALAR_SUB, x, name, scalar=scalar)

    def scalar_true_divide(self, x, scalar: float, inplace=True, name=""):
        return self._unary(OperatorType.OP_SCALAR_TRUE_DIV, x, name, scalar=scalar)

    # shape ops
    def concat(self, tensors: List[Tensor], axis: int, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_CONCAT, ConcatParams(axis=axis), list(tensors), name
        )

    def split(self, input: Tensor, sizes, axis: int, name="") -> List[Tensor]:
        if isinstance(sizes, int):
            assert input.dims[axis] % sizes == 0, (
                f"split: dim {input.dims[axis]} not divisible into {sizes} parts"
            )
            sizes = [input.dims[axis] // sizes] * sizes
        assert sum(sizes) == input.dims[axis], (
            f"split sizes {sizes} don't sum to dim {input.dims[axis]}"
        )
        out = self._add_layer(
            OperatorType.OP_SPLIT, SplitParams(tuple(sizes), axis), [input], name
        )
        return out if isinstance(out, list) else [out]

    def flat(self, input: Tensor, name="") -> Tensor:
        return self._add_layer(OperatorType.OP_FLAT, FlatParams(), [input], name)

    def softmax(self, input: Tensor, axis: int = -1, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_SOFTMAX, SoftmaxParams(dim=axis), [input], name
        )

    def reshape(self, input: Tensor, shape: Sequence[int], name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_RESHAPE, ReshapeParams(tuple(shape)), [input], name
        )

    def transpose(self, input: Tensor, perm: Sequence[int], name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_TRANSPOSE, TransposeParams(tuple(perm)), [input], name
        )

    def reverse(self, input: Tensor, axis: int, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_REVERSE, ReverseParams(axis=axis), [input], name
        )

    def cast(self, input: Tensor, dtype: DataType, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_CAST, CastParams(dtype=_to_dt(dtype)), [input], name
        )

    def squeeze(self, input: Tensor, axes=(), name="") -> Tensor:
        from ..ops.tensor_ops import SqueezeParams

        return self._add_layer(
            OperatorType.OP_SQUEEZE, SqueezeParams(tuple(axes)), [input], name
        )

    def unsqueeze(self, input: Tensor, axes, name="") -> Tensor:
        from ..ops.tensor_ops import UnsqueezeParams

        return self._add_layer(
            OperatorType.OP_UNSQUEEZE, UnsqueezeParams(tuple(axes)), [input], name
        )

    def where(self, cond: Tensor, x: Tensor, y: Tensor, name="") -> Tensor:
        from ..ops.tensor_ops import WhereParams

        return self._add_layer(
            OperatorType.OP_WHERE, WhereParams(), [cond, x, y], name
        )

    def resize(self, input: Tensor, out_shape, name="") -> Tensor:
        from ..ops.tensor_ops import ResizeParams

        return self._add_layer(
            OperatorType.OP_RESIZE, ResizeParams(tuple(out_shape)), [input], name
        )

    def prelu(self, input: Tensor, name="") -> Tensor:
        from ..ops.elementwise import PReluParams

        return self._add_layer(OperatorType.OP_PRELU, PReluParams(), [input], name)

    def dropout(self, input: Tensor, rate: float = 0.5, seed: int = 0, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_DROPOUT, DropoutParams(rate=rate, seed=seed), [input], name
        )

    def gather(self, input: Tensor, index: Tensor, dim: int = 0, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_GATHER, GatherParams(dim=dim), [input, index], name
        )

    def reduce_sum(self, input: Tensor, axes, keepdims=False, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_REDUCE_SUM,
            ReduceParams(tuple(axes), keepdims),
            [input],
            name,
        )

    def reduce_mean(self, input: Tensor, axes, keepdims=False, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_REDUCE_MEAN,
            ReduceParams(tuple(axes), keepdims),
            [input],
            name,
        )

    def mean(self, input: Tensor, dims, keepdims=False, name="") -> Tensor:
        return self._add_layer(
            OperatorType.OP_MEAN, ReduceParams(tuple(dims), keepdims), [input], name
        )

    def top_k(self, input: Tensor, k: int, sorted: bool = True, name="") -> List[Tensor]:
        out = self._add_layer(
            OperatorType.OP_TOPK, TopKParams(k=k, sorted=sorted), [input], name
        )
        return out

    def lstm(self, input: Tensor, hidden_size: int, return_sequences: bool = True,
             name="") -> Tensor:
        """reference: nmt/ standalone LSTM (SURVEY §1 row 12), promoted to a
        first-class op here."""
        from ..ops.lstm import LSTMParams

        return self._add_layer(
            OperatorType.OP_LSTM,
            LSTMParams(hidden_size=hidden_size, return_sequences=return_sequences),
            [input],
            name,
        )

    # MoE family (reference: moe.cc:20-44 FFModel::moe composite)
    def group_by(self, input: Tensor, assign: Tensor, n: int, alpha: float, name=""):
        return self._add_layer(
            OperatorType.OP_GROUP_BY, GroupByParams(n=n, alpha=alpha), [input, assign], name
        )

    def aggregate(self, tensors: List[Tensor], n: int, lambda_bal: float = 0.0, name=""):
        return self._add_layer(
            OperatorType.OP_AGGREGATE,
            AggregateParams(n=n, lambda_bal=lambda_bal),
            list(tensors),
            name,
        )

    def aggregate_spec(self, tensors: List[Tensor], n: int, lambda_bal: float = 0.0, name=""):
        return self._add_layer(
            OperatorType.OP_AGG_SPEC,
            AggregateSpecParams(n=n, lambda_bal=lambda_bal),
            list(tensors),
            name,
        )

    def cache(self, input: Tensor, num_batches: int = 1, name=""):
        """reference: FFModel::cache (src/ops/cache.cc) — cross-batch
        activation cache (MoE gating cache); see ops/moe.py CacheParams."""
        return self._add_layer(
            OperatorType.OP_CACHE,
            CacheParams(num_batches=num_batches),
            [input],
            name,
        )

    def moe(
        self,
        input: Tensor,
        num_exp: int,
        num_select: int,
        expert_hidden_size: int,
        alpha: float = 2.0,
        lambda_bal: float = 0.0,
    ) -> Tensor:
        """reference: src/ops/moe.cc:20-44 — gate -> top_k -> group_by ->
        per-expert dense -> aggregate."""
        gate_preds = self.dense(input, num_exp, ActiMode.AC_MODE_RELU)
        topk_out, topk_assign = self.top_k(gate_preds, num_select)
        exp_tensors = self.group_by(input, topk_assign, num_exp, alpha)
        if not isinstance(exp_tensors, list):
            exp_tensors = [exp_tensors]
        agg_inputs = [self.softmax(topk_out), topk_assign, topk_assign, gate_preds]
        for et in exp_tensors:
            agg_inputs.append(
                self.dense(et, expert_hidden_size, ActiMode.AC_MODE_RELU)
            )
        return self.aggregate(agg_inputs, num_exp, lambda_bal)

    # ------------------------------------------------------------------
    # compile (reference: model.cc:2803 FFModel::compile)
    # ------------------------------------------------------------------
    def set_optimizer(self, opt: Optimizer):
        self.optimizer = opt

    optimizer_setter = set_optimizer  # cffi property-style parity

    # pre-`set_optimizer` spellings (flexflow_c.cc
    # flexflow_model_set_sgd_optimizer / _set_adam_optimizer, used by
    # bootcamp_demo scripts)
    set_sgd_optimizer = set_optimizer
    set_adam_optimizer = set_optimizer

    def get_label_tensor(self):
        """Label tensor getter-method spelling (cffi exposes it as the
        `label_tensor` property, flexflow_cffi.py:2185). The label tensor is
        created by compile() — calling this earlier is an error, same as in
        the reference."""
        assert self.label_tensor is not None, (
            "label tensor exists after compile() — call compile() first"
        )
        return self.label_tensor

    def get_learning_rate(self) -> float:
        """Current learning rate, whatever the optimizer calls it
        (SGDOptimizer.lr, AdamOptimizer.alpha — optimizer.h:36-117)."""
        opt = self.optimizer
        return opt.alpha if hasattr(opt, "alpha") else opt.lr

    def set_learning_rate(self, lr: float) -> None:
        """Set the learning rate on the compiled optimizer and invalidate
        the jitted step (the rate is traced as a constant)."""
        opt = self.optimizer
        field = "alpha" if hasattr(opt, "alpha") else "lr"
        if getattr(opt, field) == lr:
            return
        setattr(opt, field, lr)
        if self.executor is not None:
            self.executor.invalidate_step_cache(train_only=True)

    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type=None,
        metrics: Sequence = (),
        comp_mode: CompMode = CompMode.COMP_MODE_TRAINING,
        calibration=None,
        artifact_store=None,
    ):
        if optimizer is not None:
            self.optimizer = optimizer
        if self.optimizer is None:
            self.optimizer = SGDOptimizer(lr=self.config.learning_rate)
        assert loss_type is not None, "compile() needs a loss_type"
        self.loss_type = to_loss_type(loss_type)
        self.comp_mode = comp_mode
        self.metrics_obj = Metrics(self.loss_type, metrics)
        # Persisted cost calibration (obs/calibration.py): an explicit
        # store/path — or the active telemetry session's store — resolves
        # to measured per-op (fwd, bwd) costs + cost-model globals BEFORE
        # the strategy search, so MCMC/DP price ops from measurement.
        # Rejected (stale/mismatched/empty) stores resolve to nothing and
        # the analytic roofline stands.
        from ..obs.calibration import resolve_calibration

        calib_table, calib_globals = resolve_calibration(calibration)
        if calib_table is not None and len(calib_table):
            self._profiled_op_costs = calib_table
        if calib_globals:
            self._calibration_globals = calib_globals
        # Every compile records what it did (phase timings + every search
        # decision) into a bounded in-memory trajectory; fit(telemetry=)
        # replays it into the event log and obs.explain_strategy joins it
        # with on-device measurements (obs/trajectory.py).
        self.search_trajectory = obs.SearchTrajectory()
        _t_phase = time.perf_counter()

        # 1. Layer graph -> PCG (reference: create_operators_from_layers)
        self.graph, self._tensor_map = layers_to_pcg(self.layers)
        if self.config.perform_fusion:
            # reference: apply_fusion (model.cc:2495, --fusion). Note:
            # per-layer weight get/set for non-head chain members is not
            # available on fused graphs (weights move under the fused op).
            from ..pcg.fusion import apply_fusion

            self.graph = apply_fusion(self.graph)
        self.search_trajectory.phase("lowering", _t_phase,
                                     ops=len(self.graph.ops))
        # 1.5 Artifact cache probe (runtime/artifact_store.py): a prior
        # compile of this exact (graph, topology, calibration) key already
        # paid for the Unity search — replay its winner instead of
        # re-searching. Store resolution: explicit arg > the store a
        # previous compile attached (recompile_for_topology reuses it) >
        # the process-ambient store (ReplicaSet wraps opaque model_fns in
        # store.ambient()). Corrupt/stale entries degrade to a fresh
        # search; the cause rides in strategy_provenance so
        # restore_elastic can count redundant searches.
        from ..runtime.artifact_store import get_ambient

        store = artifact_store or getattr(self, "artifact_store", None) \
            or get_ambient()
        self.artifact_store = store
        ndev = min(self.config.numWorkers, len(jax.devices()))
        search_enabled = (self.config.search_budget >= 0
                          and not self.config.only_data_parallel)
        self._artifact_key = None
        self._artifact_key_parts = None
        _cache_entry = None
        _research_cause = "no_store"
        if store is not None and search_enabled:
            _cache_entry, _research_cause = \
                self._probe_artifact_store(store, ndev)
        self._pt_by_guid = {}
        for op in self.graph.ops:
            for t in list(op.outputs) + list(op.weights):
                self._pt_by_guid[t.guid] = t
        for t in self.graph.input_tensors():
            self._pt_by_guid[t.guid] = t

        # 2. Parallelization strategy.
        #    - search_budget >= 0: Unity search (substitutions + DP view
        #      assignment, reference model.cc:2826 GRAPH_OPTIMIZE path).
        #    - else: manual degrees / pure data parallel (reference
        #      --only-data-parallel lowering).
        # Record user input order positionally BEFORE any search rewrite
        # (rewrites copy the graph with fresh tensor guids; graph input
        # order is stable under copy, so positions survive).
        pre_inputs = self.graph.input_tensors()
        pre_pos = {pt.guid: i for i, pt in enumerate(pre_inputs)}
        # one pass builds BOTH the positional map and the user-Tensor list
        # so attach_numpy_array / set_tensor slots stay element-wise
        # aligned with the executor's input order by construction
        _fit_pairs = [
            (t, pre_pos[self._tensor_map[t.guid]])
            for t in self.input_tensors
            if self._tensor_map.get(t.guid) in pre_pos
            and t.guid not in self._constant_values
        ]
        self._fit_input_tensors = [t for t, _ in _fit_pairs]
        self._input_positions = [i for _, i in _fit_pairs]
        self._constant_positions = {
            pre_pos[self._tensor_map[t.guid]]: self._constant_values[t.guid]
            for t in self.input_tensors
            if t.guid in self._constant_values
            and self._tensor_map.get(t.guid) in pre_pos
        }
        _t_phase = time.perf_counter()
        _pending_artifact_put = False
        if _cache_entry is not None:
            # artifact-cache hit: the stored winner replayed cleanly onto
            # the fresh lowering (degrees + views set, validators passed)
            # — rebuild the exact searched mesh and skip the search.
            views, mesh_axes, cost = _cache_entry
            self.searched_views = views
            self.searched_cost = cost
            if int(mesh_axes.get("pipe", 1)) > 1:
                self.searched_pipeline_degree = int(mesh_axes["pipe"])
            mesh = build_mesh(mesh_axes)
            self.strategy_provenance = {
                "source": "artifact_cache",
                "key": dict(self._artifact_key),
                "cost": cost,
            }
            self.search_trajectory.phase("strategy_cache_hit", _t_phase,
                                         devices=ndev,
                                         ops=len(self.graph.ops))
        elif search_enabled:
            mesh = self._run_strategy_search(ndev)
            self.strategy_provenance = {"source": "search",
                                        "cause": _research_cause}
            self.search_trajectory.phase("strategy_search", _t_phase,
                                         devices=ndev)
            # the artifact payload is written after the precision pass in
            # step 4 stamps compute/accum dtypes, so cache replays restore
            # the full typed strategy (_pending_artifact_put below)
            _pending_artifact_put = (
                store is not None and self._artifact_key is not None)
        else:
            tp = max(1, self.config.tensor_parallel_degree)
            sp = max(1, self.config.sequence_parallel_degree)
            ep = max(1, self.config.expert_parallel_degree)
            pp = max(1, self.config.pipeline_parallel_degree)
            dp = max(1, ndev // (tp * sp * ep * pp))
            # FSDP/ZeRO (config.fsdp_degree): the fsdp axis is carved out
            # of the data-parallel workers — weights shard over it, the
            # batch shards over ("data", "fsdp") jointly — so it must
            # divide the data degree (clamped down to the largest
            # power-of-two-ish divisor otherwise)
            fsdp = max(1, self.config.fsdp_degree)
            while fsdp > 1 and (fsdp > dp or dp % fsdp != 0):
                fsdp //= 2
            if fsdp != max(1, self.config.fsdp_degree):
                warnings.warn(
                    f"fsdp_degree {self.config.fsdp_degree} does not divide "
                    f"the data-parallel degree {dp}; clamped to {fsdp}"
                )
            axes = {"data": dp // fsdp if fsdp > 1 else dp, "model": tp,
                    "seq": sp, "expert": ep, "pipe": pp}
            if fsdp > 1:
                axes["fsdp"] = fsdp
            mesh = build_mesh(axes)
            strategies.apply_data_parallel(self.graph, dp, axis_idx=0)
            strategies.apply_tensor_parallel(self.graph, tp, axis_idx=1)
            strategies.apply_sequence_parallel(self.graph, sp, axis_idx=2)
            strategies.apply_expert_parallel(self.graph, ep, axis_idx=3)
            strategies.apply_pipeline_parallel(self.graph, pp, axis_idx=4)
            if fsdp > 1:
                strategies.apply_weight_sharding(self.graph, fsdp,
                                                 axis_idx=5)
            self.strategy_provenance = {"source": "manual"}
            self.search_trajectory.phase(
                "manual_lowering", _t_phase, devices=ndev,
                data=dp, model=tp, seq=sp, expert=ep, pipe=pp, fsdp=fsdp,
            )

        # 3. Label tensor matched to final op's sharding (model.cc:3054)
        logits_pt = self.graph.output_tensors()[-1]
        if self.loss_type in (
            LossType.LOSS_CATEGORICAL_CROSSENTROPY,
            LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        ):
            final_ops = [o for o in self.graph.ops
                         if any(t.guid == logits_pt.guid for t in o.outputs)]

            if final_ops:
                tail_type, tail_params = _resolve_value_tail(final_ops[0])
                if not _probability_like_tail(tail_type, tail_params):
                    warnings.warn(
                        "cross-entropy losses expect probability outputs "
                        "(the reference's loss kernels take them; "
                        "loss_functions.cc) but the model's final op is "
                        f"{tail_type.name} — raw logits get clipped to "
                        "[1e-12, 1] and gradients die. End the model with "
                        "model.softmax(...)."
                    )
        if self.label_tensor is None:
            label_dt = (
                DataType.DT_INT32
                if self.loss_type == LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
                else logits_pt.data_type
            )
            label_dims = (
                tuple(logits_pt.material_shape()[:-1]) + (1,)
                if label_dt == DataType.DT_INT32
                else logits_pt.material_shape()
            )
            self.label_tensor = Tensor(label_dims, label_dt, name="label")
            self.label_tensor._model = self

        # 4. Build executor + initialize weights (reference: optimizer->init,
        #    NCCL communicator setup — here: jit + shardings)
        plan_cost_model = self._build_cost_model()
        # Slice fault domains (runtime/fault_domains.py): on a multi-node
        # machine each node (slice) is a failure domain — recorded on the
        # model so the checkpoint sidecar, the health monitor and fit()'s
        # failure classification all share one map. None on single-node
        # machines (no slice boundary exists) or when the machine model
        # doesn't describe the actual mesh.
        _machine = plan_cost_model.machine
        if (_machine.num_nodes > 1
                and _machine.num_workers == int(mesh.devices.size)):
            from ..runtime.fault_domains import FaultDomainMap

            self.fault_domains = FaultDomainMap.from_machine(_machine)
        else:
            self.fault_domains = None
        compute_dtype = (
            jnp.bfloat16 if self.config.allow_mixed_precision else None
        )
        # bf16 grad storage rides mixed precision unless explicitly forced
        # off (config.bf16_grads; AMP-style half-width grads, f32 masters)
        use_bf16_grads = (
            self.config.allow_mixed_precision
            if self.config.bf16_grads is None else self.config.bf16_grads
        )
        grad_dtype = jnp.bfloat16 if use_bf16_grads else None
        # Precision as first-class PCG state (analysis/precision.py): stamp
        # compute_dtype/accum_dtype on the final graph's tensors from the
        # registry rules, then run the FFA7xx precision audit over the
        # winner — same warn-don't-block contract as the FFA5xx perf lint
        # above (fit(lint=...) re-checks and can hard-fail).
        from ..analysis.precision import (
            annotate_graph_precision,
            precision_diagnostics,
        )

        annotate_graph_precision(
            self.graph,
            compute_dtype=(DataType.DT_BF16
                           if self.config.allow_mixed_precision else None),
        )
        prec_rep = precision_diagnostics(
            self.graph, views=getattr(self, "searched_views", None),
            num_devices=ndev,
            drift_budget=self.config.precision_drift_budget,
            grad_dtype=(DataType.DT_BF16 if use_bf16_grads else None),
        )
        if prec_rep.errors:
            warnings.warn(
                "static precision analysis flagged the compiled strategy "
                "(fit(lint=...) re-checks; docs/analysis.md FFA7xx): "
                + "; ".join(d.format() for d in prec_rep.errors[:5])
            )
        self.search_trajectory.event(
            "precision_lint", errors=len(prec_rep.errors),
            warnings=len(prec_rep.warnings),
            codes=sorted({d.code for d in prec_rep}),
        )
        if _pending_artifact_put:
            self._artifact_store_put(store, mesh)
        # Map user input tensors (creation order) to their PCG tensors; only
        # those actually consumed by the graph become executor inputs.
        cur_inputs = self.graph.input_tensors()
        ordered_inputs = [cur_inputs[i] for i in self._input_positions]
        constants = {
            cur_inputs[i].guid: (cur_inputs[i], v)
            for i, v in self._constant_positions.items()
        }
        _t_phase = time.perf_counter()
        self.executor = PCGExecutor(
            self.graph,
            mesh,
            self.optimizer,
            self.loss_type,
            self.metrics_obj,
            compute_dtype=compute_dtype,
            grad_dtype=grad_dtype,
            seed=self.config.seed,
            input_order=ordered_inputs,
            remat=self.config.remat,
            constants=constants,
            plan_cost_model=plan_cost_model,
            overlap_grad_sync=self.config.overlap_backward_update,
        )
        self.search_trajectory.phase("executor_build", _t_phase)
        _t_phase = time.perf_counter()
        self.state = self.executor.init_state()
        self.search_trajectory.phase("init_state", _t_phase)
        self.perf_metrics = PerfMetrics()

    def compile_decode(self, *, strategy_path: Optional[str] = None,
                       export_path: Optional[str] = None):
        """Run the Unity search a SECOND time over the same layer graph
        with the DECODE cost objective (ROADMAP item 3; the Splitwise/
        DistServe disaggregation insight): single-token decode is
        HBM-bandwidth-bound where training is MXU-bound, so the cheapest
        parallelization differs — the decode oracle prices each op off
        the bytes one token streams (weights/shard + KV-cache reads +
        1-token activations) and prices collectives latency-bound
        (search/cost_model.py CostObjective.DECODE).

        The model then carries TWO searched strategies: `graph`/
        `searched_views` (training/prefill, compute-bound) and
        `decode_graph`/`decode_searched_views`, with a separate
        `decode_trajectory` recording this search's decisions. The
        ContinuousBatcher lowers its batch decode executables from
        `decode_executor` while prefill keeps the training strategy
        (runtime/serving.py).

        strategy_path: import the decode strategy from a strategy_io
        JSON file instead of searching (ServingConfig.
        decode_strategy_path feeds this). export_path: export the
        searched strategy for later import. Returns the decode
        executor."""
        assert self.executor is not None, (
            "compile() the model before compile_decode() — the decode "
            "strategy is searched over the same layer graph and serves "
            "alongside the training one"
        )
        cfg = self.config
        ndev = min(cfg.numWorkers, len(jax.devices()))
        self.decode_trajectory = obs.SearchTrajectory()
        _t_phase = time.perf_counter()
        # fresh lowering: the training search REWROTE self.graph with its
        # own substitutions; the decode search must start from the same
        # unrewritten layer graph, not the training winner
        graph, _ = layers_to_pcg(self.layers)
        if cfg.perform_fusion:
            from ..pcg.fusion import apply_fusion

            graph = apply_fusion(graph)
        self.decode_trajectory.phase("decode_lowering", _t_phase,
                                     ops=len(graph.ops))
        cost_model = self._build_cost_model(objective="decode")
        _t_phase = time.perf_counter()
        if strategy_path:
            from ..runtime.strategy_io import (
                apply_imported_strategy,
                import_strategy,
            )

            strategy = import_strategy(strategy_path)
            apply_imported_strategy(graph, strategy, num_devices=ndev)
            views = {
                op.guid: op.machine_view for op in graph.ops
                if getattr(op, "machine_view", None) is not None
            }
            cost = None
            self.decode_trajectory.phase(
                "decode_strategy_import", _t_phase,
                records=len(strategy), devices=ndev,
            )
        else:
            from ..pcg.machine_view import MachineResource
            from ..search import (
                GraphSearchHelper,
                SearchHelper,
                generate_all_pcg_xfers,
            )

            machine = cost_model.machine
            sh = SearchHelper(cost_model, trajectory=self.decode_trajectory)
            degrees = []
            d = 2
            while d <= machine.num_workers:
                degrees.append(d)
                d *= 2
            budget = cfg.search_budget if cfg.search_budget > 0 else 10
            # parallelization xfers ONLY — no operator-substitution rules.
            # A substitution rewrites compute ops and rebuilds their
            # weights fresh from initializers, but the decode strategy
            # must serve the weights TRAINED under the training graph
            # (the batcher feeds both lowerings the same param store,
            # keyed by op name); a rewritten op could never find its
            # weights and would force the serving fallback every time.
            xfers = generate_all_pcg_xfers(degrees or [1], cfg)
            res = MachineResource(
                num_nodes=machine.num_nodes,
                all_procs_per_node=machine.workers_per_node,
                available_procs_per_node=machine.workers_per_node,
            )
            gsh = GraphSearchHelper(
                sh, xfers, alpha=cfg.search_alpha, budget=budget,
                trajectory=self.decode_trajectory,
            )
            graph, result = gsh.graph_optimize(graph, res)
            views = result.views
            cost = result.cost
            self.decode_trajectory.phase("decode_strategy_search", _t_phase,
                                         devices=ndev)
        self.decode_graph = graph
        self.decode_searched_views = views
        self.decode_searched_cost = cost
        # same vetting the training strategy gets: structural validators +
        # the static perf pass — run under the decode objective so FFA509
        # (over-sharded KV heads, latency-bound per-token collectives on
        # the critical path) fires here, at compile time
        from ..search import run_strategy_validators

        problems = run_strategy_validators(graph, views, ndev)
        if problems:
            warnings.warn(
                "decode-searched strategy failed structural validation "
                "(falling through to lowering, which demotes infeasible "
                "degrees to replicated): " + "; ".join(problems[:5])
            )
        from ..analysis.perf import perf_diagnostics

        perf_rep = perf_diagnostics(
            graph, views=views, cost_model=cost_model, num_devices=ndev,
            expert_degree=getattr(cfg, "expert_parallel_degree", 1),
            objective="decode",
        )
        if perf_rep.errors:
            warnings.warn(
                "static perf analysis flagged the decode-searched strategy "
                "(docs/analysis.md FFA5xx): "
                + "; ".join(d.format() for d in perf_rep.errors[:5])
            )
        self.decode_trajectory.event(
            "perf_lint", errors=len(perf_rep.errors),
            warnings=len(perf_rep.warnings),
            codes=sorted({d.code for d in perf_rep}),
        )
        # FFA7xx precision audit of the decode strategy: annotate the
        # decode graph's precision flow (decode serves under the same AMP
        # dtype as training compute) and vet it like the train path does
        from ..analysis.precision import (
            annotate_graph_precision,
            precision_diagnostics,
        )

        annotate_graph_precision(
            graph,
            compute_dtype=(DataType.DT_BF16
                           if cfg.allow_mixed_precision else None),
        )
        prec_rep = precision_diagnostics(
            graph, views=views, num_devices=ndev,
            drift_budget=cfg.precision_drift_budget,
        )
        if prec_rep.errors:
            warnings.warn(
                "static precision analysis flagged the decode-searched "
                "strategy (docs/analysis.md FFA7xx): "
                + "; ".join(d.format() for d in prec_rep.errors[:5])
            )
        self.decode_trajectory.event(
            "precision_lint", errors=len(prec_rep.errors),
            warnings=len(prec_rep.warnings),
            codes=sorted({d.code for d in prec_rep}),
        )
        if export_path:
            from types import SimpleNamespace

            from ..runtime.strategy_io import export_strategy

            export_strategy(graph, SimpleNamespace(views=views, cost=cost),
                            export_path)
        # decode executor over the decode graph: params stay keyed by op
        # name, so a decode build whose op names survived the rewrite can
        # consume the TRAINING state's params directly; the batcher
        # checks compatibility before swapping it in (runtime/serving.py)
        cur_inputs = graph.input_tensors()
        ordered_inputs = [cur_inputs[i] for i in self._input_positions]
        constants = {
            cur_inputs[i].guid: (cur_inputs[i], v)
            for i, v in self._constant_positions.items()
        }
        axis_sizes = strategies.assign_mesh_axes(graph, ndev)
        mesh = build_mesh(axis_sizes)
        _t_phase = time.perf_counter()
        self.decode_executor = PCGExecutor(
            graph,
            mesh,
            self.optimizer,
            self.loss_type,
            self.metrics_obj,
            compute_dtype=(
                jnp.bfloat16 if cfg.allow_mixed_precision else None
            ),
            grad_dtype=None,  # decode never materializes gradients
            seed=cfg.seed,
            input_order=ordered_inputs,
            remat=False,
            constants=constants,
            plan_cost_model=cost_model,
        )
        self.decode_trajectory.phase("decode_executor_build", _t_phase)
        return self.decode_executor

    def _probe_artifact_store(self, store, ndev: int):
        """Look up + replay a stored strategy for the current lowering.

        Returns `((views, mesh_axes, cost), None)` on a usable hit, else
        `(None, cause)` where cause names why a search still runs
        ("cache_miss" / "cache_corrupt" — both feed
        ff_elastic_research_total). A replay that fails partway has
        already mutated tensor degrees, so the stale path re-lowers
        self.graph fresh before handing it to the search. Store failures
        of any kind degrade to a fresh search — a poisoned cache is
        never worse than no cache."""
        from ..runtime.artifact_store import (
            ArtifactCorruptionError,
            calibration_fingerprint,
            graph_fingerprint,
            make_key,
            replay_strategy,
            topology_digest,
        )
        from ..runtime.elastic import topology_fingerprint
        from ..runtime.strategy_io import StrategyImportError

        parts = {
            "graph": graph_fingerprint(self.graph),
            "topology": topology_digest(topology_fingerprint()),
            "calibration": calibration_fingerprint(
                getattr(self, "_profiled_op_costs", None),
                getattr(self, "_calibration_globals", None),
            ),
        }
        key = make_key(objective="train", num_devices=ndev, **parts)
        self._artifact_key_parts = parts
        self._artifact_key = key
        try:
            payload = store.get(key)
        except ArtifactCorruptionError:
            return None, "cache_corrupt"
        except Exception as e:
            warnings.warn(
                f"artifact store lookup failed ({e!r}); falling back to "
                "a fresh search"
            )
            return None, "cache_corrupt"
        if payload is None:
            return None, "cache_miss"
        try:
            # replay rebuilds the searched PCG around this lowering's
            # compute ops (search-inserted parallel ops reconstructed,
            # sharding state restored per dim) — the rebuilt graph
            # REPLACES the fresh lowering, exactly as a search would
            graph2, views, mesh_axes, cost = replay_strategy(
                self.graph, payload, num_devices=ndev)
            self.graph = graph2
            return (views, mesh_axes, cost), None
        except StrategyImportError as e:
            warnings.warn(
                f"artifact store entry could not be replayed ({e}); "
                "quarantining it and falling back to a fresh search"
            )
            try:
                store.note_stale(key, str(e))
            except Exception as qe:
                warnings.warn(
                    f"artifact store could not quarantine the stale "
                    f"entry ({qe!r}); the fresh search proceeds anyway"
                )
            # the failed replay mutated tensor degrees in place — the
            # search must start from an unmutated lowering
            self.graph, self._tensor_map = layers_to_pcg(self.layers)
            if self.config.perform_fusion:
                from ..pcg.fusion import apply_fusion

                self.graph = apply_fusion(self.graph)
            return None, "cache_miss"

    def _artifact_store_put(self, store, mesh) -> None:
        """Write the freshly searched winner through to the artifact
        store under the key _probe_artifact_store computed. Never fails
        the compile — the strategy is already in hand."""
        from ..runtime.artifact_store import strategy_payload

        try:
            mesh_axes = {
                str(name): int(size)
                for name, size in zip(mesh.axis_names, mesh.devices.shape)
            }
            store.put(self._artifact_key, strategy_payload(
                self.graph,
                getattr(self, "searched_views", None),
                cost=getattr(self, "searched_cost", None),
                mesh_axes=mesh_axes,
                provenance={"writer": "compile"},
            ))
        except Exception as e:
            warnings.warn(
                f"artifact store write failed ({e!r}); continuing "
                "without caching the strategy"
            )

    def _build_cost_model(self, objective: str = "train"):
        """The cost oracle for stage planning (and the search): the
        configured machine (file / search-dims / --machine-model-version)
        with the shipped calibration. `objective` selects what workload
        the oracle prices (search/cost_model.py CostObjective): "train"
        (default) or "decode" — the single-token HBM-roofline pricing
        compile_decode()'s second search runs under."""
        from ..search import CostModel, MachineModel, parse_machine_config

        cfg = self.config
        override = getattr(self, "_machine_override", None)
        if override is not None:
            # recompile_for_topology re-targeted a machine description at
            # the live device count (elastic resume); it wins over the
            # stale file/config topology
            machine = override
        elif cfg.machine_model_file:
            machine = parse_machine_config(cfg.machine_model_file)
        else:
            nodes = (cfg.search_num_nodes if cfg.search_num_nodes > 0
                     else cfg.numNodes)
            workers = (cfg.search_num_workers if cfg.search_num_workers > 0
                       else cfg.workersPerNode)
            machine = MachineModel(num_nodes=nodes, workers_per_node=workers)
        if cfg.machine_model_version >= 1 and not hasattr(machine, "topology"):
            from ..search.network import TopologyAwareMachineModel

            machine = TopologyAwareMachineModel(
                num_nodes=machine.num_nodes,
                workers_per_node=machine.workers_per_node,
                ici_bandwidth=machine.ici_bandwidth,
                dcn_bandwidth=machine.dcn_bandwidth,
                chip=machine.chip,
            )
        pen = cfg.search_survivability_penalty
        if pen < 0:
            # auto: bias toward slice-loss-survivable strategies only
            # where slices exist as failure domains (multi-node machine)
            pen = 0.25 if machine.num_nodes > 1 else 0.0
        cm = CostModel(
            machine, bf16=cfg.allow_mixed_precision,
            overlap_backward_update=cfg.search_overlap_backward_update,
            survivability_penalty=pen,
            objective=objective,
        )
        # In-situ measurements ride on the oracle through the shared
        # refresh seam (search/cost_model.py apply_calibration): per-op
        # timings from explain_strategy(...).apply(model) or a persisted
        # CalibrationStore override the analytic roofline for serial
        # views; the store's measured overlap efficiency and per-kind
        # collective bandwidths override the shipped calibration's. The
        # online re-search (runtime/tuner.py) rebuilds its oracle through
        # this same path, so drift-corrected searches are priced exactly
        # like compile-time ones. (--measured-search, if enabled above,
        # supersedes the per-op table with proper per-shard measurement.)
        from ..search import apply_calibration

        glb = getattr(self, "_calibration_globals", None) or {}
        return apply_calibration(
            cm,
            profiled=getattr(self, "_profiled_op_costs", None),
            overlap_efficiency=glb.get("overlap_efficiency"),
            collective_bandwidths=glb.get("collective_bytes_per_s"),
        )

    def _run_strategy_search(self, ndev: int):
        """Unity search over the lowered PCG (reference: compile's
        GRAPH_OPTIMIZE_TASK -> GraphSearchHelper::graph_optimize,
        substitution.cc:1898). Returns the execution mesh."""
        from ..pcg.machine_view import MachineResource
        from ..search import (
            CostModel,
            GraphSearchHelper,
            MachineModel,
            SearchHelper,
            generate_all_pcg_xfers,
            parse_machine_config,
        )

        cfg = self.config
        # (--machine-model-version 1 selects the EnhancedMachineModel
        # analog — per-link ICI hops, DCN hierarchy, congestion;
        # search/network.py)
        cost_model = self._build_cost_model()
        machine = cost_model.machine
        if cfg.measure_operator_costs:
            # --measured-search: per-op on-device timing feeds the search
            from ..search.measure import attach_measured_mode

            attach_measured_mode(
                cost_model,
                compute_dtype=(
                    jnp.bfloat16 if cfg.allow_mixed_precision else None
                ),
                cache_path=cfg.measured_cache_path or None,
            )
        sh = SearchHelper(cost_model, trajectory=self.search_trajectory)
        degrees = []
        d = 2
        while d <= machine.num_workers:
            degrees.append(d)
            d *= 2
        budget = cfg.search_budget if cfg.search_budget > 0 else 10
        xfers = generate_all_pcg_xfers(degrees or [1], cfg)
        # declarative rules: --substitution-json, or the shipped collection
        # (reference loads substitutions/graph_subst_3_v2.json by default;
        # ours is search/substitutions/graph_subst_tpu_v1.json — it adds
        # per-op partition sandwiches and column-parallel matmul, which
        # the programmatic xfers don't express)
        import os as _os

        from ..search.substitution_loader import (
            default_rules_path,
            load_rule_collection_from_path,
            rules_to_substitutions,
            zoo_rules_path,
        )

        if cfg.substitution_json_path:
            # explicit --substitution-json: a missing file must raise, not
            # silently fall back to the bundled defaults
            rules = load_rule_collection_from_path(cfg.substitution_json_path)
            xfers = xfers + rules_to_substitutions(rules)
        else:
            for rp in (default_rules_path(), zoo_rules_path()):
                if _os.path.exists(rp):
                    rules = load_rule_collection_from_path(rp)
                    xfers = xfers + rules_to_substitutions(rules)
        res = MachineResource(
            num_nodes=machine.num_nodes,
            all_procs_per_node=machine.workers_per_node,
            available_procs_per_node=machine.workers_per_node,
        )
        mem_budget = cfg.device_mem or machine.chip.hbm_capacity
        if cfg.perform_memory_search:
            # reference: --memory-search lambda loop (graph.cc:2060-2130)
            from ..search.memory_optimization import (
                graph_optimize_with_memory,
            )

            best_graph, result, _mem, _lam = graph_optimize_with_memory(
                self.graph, cost_model, res, xfers,
                device_mem_budget=mem_budget,
                alpha=cfg.search_alpha, budget=budget,
                train=self._is_training_compile(), optimizer=self.optimizer,
                grad_bytes_ratio=self._grad_bytes_ratio(),
                trajectory=self.search_trajectory,
            )
        else:
            gsh = GraphSearchHelper(
                sh,
                xfers,
                alpha=cfg.search_alpha,
                budget=budget,
                trajectory=self.search_trajectory,
            )
            best_graph, result = gsh.graph_optimize(self.graph, res)
        self.graph = best_graph
        self.searched_views = result.views
        self.searched_cost = result.cost
        # Pipeline as a SEARCHED dimension (beyond-parity: the reference's
        # OP_PIPELINE is enum-only, ffconst.h:158): when the best
        # unpipelined strategy's per-chip TRAINING memory (weights +
        # grads + optimizer slots + activations) exceeds the HBM budget,
        # weigh GPipe candidates (bubble fraction + cut-activation
        # transfers) against the best FITTING unpipelined strategy a
        # memory-pressured re-search finds, and adopt whichever is
        # cheaper. Runs before re-indexing/exports because it may replace
        # the strategy either way.
        pipe, alt = self._search_pipeline_degree(
            cost_model, result, ndev, mem_budget, res=res, xfers=xfers
        )
        if alt is not None:
            self.graph, result = alt
            self.searched_views = result.views
            self.searched_cost = result.cost
        self.search_trajectory.event(
            "pipeline_search", degree=pipe,
            replaced_by_researched=alt is not None, cost=result.cost,
        )
        # re-index pt lookup for the (possibly rewritten) graph
        self._pt_by_guid = {}
        for op in self.graph.ops:
            for t in list(op.outputs) + list(op.weights):
                self._pt_by_guid[t.guid] = t
        for t in self.graph.input_tensors():
            self._pt_by_guid[t.guid] = t
        # strategy-validator hook (search/__init__.py): structural vetting
        # of the final search result — machine views addressing only live
        # devices, degree products within the device count — so an insane
        # strategy is flagged here, not discovered as wrong numbers later
        from ..search import run_strategy_validators

        problems = run_strategy_validators(self.graph, self.searched_views,
                                           ndev)
        if problems:
            warnings.warn(
                "searched strategy failed structural validation "
                "(falling through to lowering, which demotes infeasible "
                "degrees to replicated): " + "; ".join(problems[:5])
            )
        # static perf audit of the WINNER (analysis/perf.py FFA5xx): the
        # search trusted a cost model that discounts overlappable
        # collectives — verify the discounts are schedulable and the
        # topology pricing holds before the strategy ever executes. The
        # cost model here is the SAME oracle the search scored with, so
        # an FFA501 finding is the search disagreeing with itself.
        from ..analysis.perf import perf_diagnostics

        perf_rep = perf_diagnostics(
            self.graph, views=self.searched_views, cost_model=cost_model,
            num_devices=ndev,
            expert_degree=getattr(cfg, "expert_parallel_degree", 1),
        )
        if perf_rep.errors:
            warnings.warn(
                "static perf analysis flagged the searched strategy "
                "(fit(lint=...) re-checks; docs/analysis.md FFA5xx): "
                + "; ".join(d.format() for d in perf_rep.errors[:5])
            )
        self.search_trajectory.event(
            "perf_lint", errors=len(perf_rep.errors),
            warnings=len(perf_rep.warnings),
            codes=sorted({d.code for d in perf_rep}),
        )
        if cfg.export_strategy_file:
            from ..runtime.strategy_io import export_strategy

            export_strategy(self.graph, result, cfg.export_strategy_file)
        if cfg.export_strategy_computation_graph_file:
            with open(cfg.export_strategy_computation_graph_file, "w") as f:
                f.write(self.graph.export_dot())
        axis_sizes = strategies.assign_mesh_axes(self.graph, ndev)
        if pipe > 1:
            # the pipeline candidate is a stage split + data parallelism
            # within each stage; it REPLACES the overflowing strategy's
            # axes (tensor degrees not matching the new axes demote to
            # replicated in lowering, as with any searched strategy)
            axis_sizes = {"data": max(1, ndev // pipe), "pipe": pipe}
            self.searched_pipeline_degree = pipe
        return build_mesh(axis_sizes)

    def _grad_bytes_ratio(self) -> float:
        """Gradient-buffer width relative to the master weight: 0.5 under
        the bf16-grad AMP recipe (executor grad_dtype), else 1.0 — the
        memory search charges `weights * (1 + this + optimizer slots)`."""
        cfg = self.config
        use_bf16 = (cfg.allow_mixed_precision if cfg.bf16_grads is None
                    else cfg.bf16_grads)
        return 0.5 if use_bf16 else 1.0

    def _is_training_compile(self) -> bool:
        """Inference compiles allocate no gradients or optimizer slots —
        charging them (2-4x weight bytes under Adam) would wrongly
        reject strategies that fit inference HBM comfortably."""
        return self.comp_mode == CompMode.COMP_MODE_TRAINING

    def recompile_for_topology(self, num_devices: Optional[int] = None) -> None:
        """Re-plan the compiled model for the CURRENT device topology
        (runtime/elastic.py): point the machine description at
        `num_devices` (default: every live device), then re-run compile()
        — which re-runs the strategy search / manual lowering for the new
        machine, rebuilds the mesh + executor and re-initializes state.
        Weights do NOT carry over; restore from a checkpoint afterwards
        (restore_elastic / fit(elastic=True))."""
        assert self.loss_type is not None, (
            "compile() the model once before recompile_for_topology"
        )
        from ..search import for_device_count, parse_machine_config

        n = num_devices if num_devices is not None else len(jax.devices())
        cfg = self.config
        # hypothetical-machine overrides would pin the search to the OLD
        # topology; the whole point here is planning for the live one
        cfg.search_num_nodes = -1
        cfg.search_num_workers = -1
        override = getattr(self, "_machine_override", None)
        if cfg.machine_model_file:
            # the file describes the machine we LOST; keep its per-chip and
            # link constants (the hardware kind didn't change) but re-point
            # the topology at the surviving device count
            base = parse_machine_config(cfg.machine_model_file)
            self._machine_override = for_device_count(n, like=base)
            cfg.machine_model_file = ""
        elif override is not None:
            # a previous elastic recompile already lifted the file into an
            # override; re-target it again for this topology change
            self._machine_override = for_device_count(n, like=override)
        else:
            from ..search import MachineModel

            m = for_device_count(n, like=MachineModel(
                num_nodes=cfg.numNodes, workers_per_node=cfg.workersPerNode,
            ))
            cfg.numNodes = m.num_nodes
            cfg.workersPerNode = m.workers_per_node
        self.compile(
            optimizer=self.optimizer,
            loss_type=self.loss_type,
            metrics=self.metrics_obj.measures if self.metrics_obj else (),
            comp_mode=self.comp_mode,
        )

    def _search_pipeline_degree(self, cost_model, result, ndev,
                                mem_budget, res=None, xfers=None):
        """Propose pipeline parallelism when the searched strategy cannot
        fit per-chip HBM under TRAINING memory accounting (weights +
        gradients + optimizer slots + activation residuals — reference:
        memory_optimization.h:45-100). Candidate cost for S stages over
        ndev devices (dp = ndev/S within each stage, M microbatches):

            T(S) ~ max_stage_time/dp * (M + S - 1)/M
                   + cut_bytes * 2 / ici_bw / dp

        i.e. the GPipe bubble fraction plus fwd+bwd boundary-activation
        transfers; per-chip memory ~ stage weights (replicated in the
        stage's dp group, carrying the grads+slots multiplier) + stage
        activation shards for ALL M microbatches (the scan-based GPipe
        backward stashes every microbatch's residuals).

        Returns (degree, alt): degree==1 when the unpipelined strategy
        already fits (a test pins that pipeline is NOT chosen then);
        alt!=None is a FITTING unpipelined (graph, result) found by a
        memory-pressured re-search that beats every pipeline candidate
        on cost — TP's per-layer collectives against GPipe's bubble is a
        cost question, not a memory one, so it is decided on cost."""
        from ..search.memory_optimization import (
            measure_memory,
            weight_bytes_multiplier,
        )
        from ..parallel.pipeline import balanced_linear_partition

        cfg = self.config
        if ndev < 2:
            return 1, None
        train = self._is_training_compile()
        gratio = self._grad_bytes_ratio()
        wmul = (weight_bytes_multiplier(
                    self.optimizer, gratio,
                    warn=any(op.weights for op in self.graph.ops))
                if train else 1.0)
        mem = measure_memory(
            self.graph, result.views, cost_model,
            train=train, optimizer=self.optimizer, grad_bytes_ratio=gratio,
        ).max_bytes
        if mem <= mem_budget:
            return 1, None
        from ..pcg.machine_view import MachineView

        machine = cost_model.machine
        ops = [o for o in self.graph.ops if not o.is_parallel_op]
        order = {o.guid: i for i, o in enumerate(self.graph.topo_order())}
        ops.sort(key=lambda o: order[o.guid])
        v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
        costs = [cost_model.measure_operator_cost(o, v1).total_time
                 for o in ops]
        w_bytes = [
            sum(t.get_volume() * t.data_type.size for t in o.weights)
            for o in ops
        ]
        a_bytes = [
            sum(t.get_volume() * t.data_type.size for t in o.outputs)
            for o in ops
        ]
        best_s, best_t = 1, float("inf")
        S = 2
        while S <= ndev and len(ops) >= S:
            if ndev % S == 0:
                dp = ndev // S
                M = max(cfg.num_microbatches, S)
                bounds = balanced_linear_partition(costs, S)
                stage_t = [sum(costs[bounds[i]:bounds[i + 1]])
                           for i in range(S)]
                stage_w = [sum(w_bytes[bounds[i]:bounds[i + 1]])
                           for i in range(S)]
                stage_a = [sum(a_bytes[bounds[i]:bounds[i + 1]])
                           for i in range(S)]
                cut_bytes = sum(a_bytes[bounds[i + 1] - 1]
                                for i in range(S - 1))
                t = (max(stage_t) / dp * (M + S - 1) / M
                     + cut_bytes * 2 / machine.ici_bandwidth / dp)
                # stage weights replicate within the stage's dp group and
                # carry grads + optimizer slots (wmul); the scan-based
                # GPipe schedule (backward = reversed scan under
                # jax.grad) stashes ALL M microbatches' residuals — per
                # chip that is the stage's full batch-shard of
                # activations, not just the in-flight window
                m_per_chip = max(
                    w * wmul + a / dp
                    for w, a in zip(stage_w, stage_a)
                )
                if m_per_chip <= mem_budget and t < best_t:
                    best_s, best_t = S, t
            S *= 2
        if res is not None and xfers is not None \
                and not cfg.perform_memory_search:
            # The overflowing strategy was the COST winner; whether or
            # not any pipeline stage count fit, let the lambda loop look
            # for a fitting unpipelined strategy (e.g. parameter-parallel
            # sharding that divides the weight+grad+slot bytes). Adopt it
            # when it fits and beats the pipeline estimate on simulated
            # runtime (or when no pipeline fit at all). (Under
            # --memory-search that loop already ran and failed to fit,
            # so it is not repeated here.)
            from ..search.memory_optimization import (
                graph_optimize_with_memory,
            )

            budget = cfg.search_budget if cfg.search_budget > 0 else 10
            g2, r2, mem2, _lam = graph_optimize_with_memory(
                self.graph, cost_model, res, xfers,
                device_mem_budget=mem_budget,
                alpha=cfg.search_alpha, budget=budget,
                train=train, optimizer=self.optimizer,
                grad_bytes_ratio=gratio,
                trajectory=self.search_trajectory,
            )
            if mem2.max_bytes <= mem_budget and r2.cost < best_t:
                return 1, (g2, r2)
        if best_s == 1:
            warnings.warn(
                f"per-chip training memory "
                f"{mem / 2**20:.0f} MB exceeds the "
                f"{mem_budget / 2**20:.0f} MB budget and no pipeline "
                f"stage count or re-searched strategy fits; keeping the "
                f"fastest (overflowing) strategy"
            )
        return best_s, None

    # ------------------------------------------------------------------
    # training loop (reference: flexflow_cffi.py:2058 fit)
    # ------------------------------------------------------------------
    def _assert_same_global_batch(self, xs, y, bs: int) -> None:
        """Multi-host contract (runtime/distributed.py): every process
        feeds the SAME global batch. A diverging feed silently corrupts
        training — each process contributes its local shard of what it
        BELIEVES is the global array and no error ever surfaces — and an
        uneven batch count desyncs the collectives into a hang. Verify a
        cheap signature (dataset size, batch size, first-batch checksums)
        across processes before training and fail loudly on mismatch."""
        from jax.experimental import multihost_utils

        first = next(self._batches(list(xs) + [y], bs))
        sig = [float(bs), float(xs[0].shape[0])]
        for a in first:
            arr = np.asarray(a)
            head = arr.reshape(-1)[: 4096]
            sig += [
                float(np.sum(arr.astype(np.float64))),
                float(np.sum(np.abs(head.astype(np.float64)))),
            ]
        multihost_utils.assert_equal(
            np.asarray(sig, np.float32),
            fail_message=(
                "multi-host contract violated: every process must feed the "
                "SAME global batch and dataset (runtime/distributed.py) — "
                "rank data/batch signatures differ"
            ),
        )

    def _batches(self, arrays: List[np.ndarray], batch_size: int):
        n = arrays[0].shape[0]
        nb = n // batch_size
        for i in range(nb):
            yield [a[i * batch_size : (i + 1) * batch_size] for a in arrays]

    def fit(
        self,
        x: Union[np.ndarray, List[np.ndarray], None] = None,
        y: Optional[np.ndarray] = None,
        batch_size: Optional[int] = None,
        epochs: Optional[int] = None,
        verbose: bool = True,
        *,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every_n_steps: Optional[int] = None,
        keep_last_n: int = 3,
        resume: bool = True,
        skip_nonfinite_steps: bool = False,
        step_guard=None,
        max_consecutive_skips: int = 10,
        fault_injector=None,
        preemption_signal=None,
        elastic: bool = False,
        health_monitor=None,
        verify_strategy=None,
        canary=None,
        lint: Optional[str] = None,
        telemetry=None,
        tuner=None,
    ):
        if self.executor is None:
            from ..runtime.verify import NotCompiledError

            raise NotCompiledError("fit: call compile() first")
        if lint not in (None, "off", "warn", "error"):
            raise ValueError(
                'fit(lint=...) accepts "error", "warn", or "off" '
                f"(got {lint!r})"
            )
        # -- telemetry session (obs/): fit(telemetry=TelemetryConfig(...))
        # runs one session end to end — compile/search trajectory replay,
        # per-step events, metrics — and flushes events.jsonl /
        # metrics.prom / trace.json on exit. A session the caller already
        # opened (obs.session(...)) is fed without being finished here.
        tel = None
        _own_session = False
        if telemetry is not None:
            if not isinstance(telemetry, obs.TelemetryConfig):
                raise ValueError(
                    "fit(telemetry=...) takes an obs.TelemetryConfig "
                    f"(got {telemetry!r})"
                )
            tel = obs.start(telemetry)
            _own_session = True
        else:
            tel = obs.active()
        if tel is not None:
            tel.attach_model(self)
        try:
            return self._fit_impl(
                x, y, batch_size, epochs, verbose,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every_n_steps=checkpoint_every_n_steps,
                keep_last_n=keep_last_n, resume=resume,
                skip_nonfinite_steps=skip_nonfinite_steps,
                step_guard=step_guard,
                max_consecutive_skips=max_consecutive_skips,
                fault_injector=fault_injector,
                preemption_signal=preemption_signal,
                elastic=elastic, health_monitor=health_monitor,
                verify_strategy=verify_strategy, canary=canary,
                lint=lint, tel=tel, tuner=tuner,
            )
        except Exception as e:
            # OOM forensics (obs/step_profile.py): a step that dies with
            # RESOURCE_EXHAUSTED leaves the static memory prediction,
            # the live allocator stats and the top allocations behind —
            # the post-mortem answers "what ate the HBM" offline
            if tel is not None and "RESOURCE_EXHAUSTED" in str(e):
                from ..obs.step_profile import dump_oom_forensics

                try:
                    path = dump_oom_forensics(self, tel.config.dir,
                                              error=str(e))
                    obs.event("oom_forensics", cat="obs", path=path)
                except Exception as dump_err:  # fflint: disable=FFL002 — forensics must not mask the OOM
                    warnings.warn(f"oom forensics dump failed: {dump_err}")
            # flight recorder (obs/flight_recorder.py): typed failures
            # (non-finite grads, strategy divergence, KV exhaustion,
            # slice loss, ...) dump the recent event/metric tail plus
            # live-state providers; no-op for untyped exceptions or
            # without an armed recorder
            obs.record_failure(e, where="fit")
            raise
        finally:
            if _own_session:
                obs.finish()

    def _fit_impl(
        self, x, y, batch_size, epochs, verbose, *,
        checkpoint_dir, checkpoint_every_n_steps, keep_last_n, resume,
        skip_nonfinite_steps, step_guard, max_consecutive_skips,
        fault_injector, preemption_signal, elastic, health_monitor,
        verify_strategy, canary, lint, tel, tuner=None,
    ):
        if lint in ("warn", "error"):
            # static preflight (analysis/): shape/sharding inference,
            # collective consistency, and HBM-fit over the compiled PCG —
            # rejects a broken strategy before ANY device time is spent
            # (the differential verify_strategy preflight below still
            # needs 2 real steps)
            from ..analysis import StaticAnalysisError, analyze_model

            report = analyze_model(self)
            if not report.ok:
                if lint == "error":
                    raise StaticAnalysisError(report)
                warnings.warn("static analysis found problems "
                              "(fit(lint='warn')):\n" + report.summary())
            elif verbose and len(report):
                obs.progress(f"[analysis] {report!r}", name="analysis",
                             cat="compile")
        x, y = _unwrap_loaders(x, y)
        xs = x if isinstance(x, (list, tuple)) else [x]
        bs = batch_size or self.config.batch_size
        ep = epochs or self.config.epochs
        n = xs[0].shape[0]
        if n < bs:
            raise ValueError(
                f"dataset has {n} samples < batch_size {bs}; nothing to train on"
            )
        if n % bs != 0:
            obs.progress(
                f"[flexflow_tpu] warning: dropping {n % bs} tail samples "
                f"(dataset {n} % batch {bs})",
                name="tail_samples_dropped", dropped=n % bs,
            )
        if verify_strategy:
            # differential preflight (runtime/verify.py): K steps of the
            # searched strategy vs a serial single-device reference from
            # identical params/RNG; divergence raises
            # StrategyDivergenceError naming the first diverging op
            # BEFORE any real training budget is spent on a broken plan
            from ..runtime import verify as _vfy

            if verify_strategy not in (True, "preflight"):
                raise ValueError(
                    "fit(verify_strategy=...) accepts 'preflight' "
                    f"(got {verify_strategy!r})"
                )
            verdict = _vfy.verify_strategy(
                self, (xs, y), steps=2, batch_size=bs,
                raise_on_divergence=True,
            )
            obs.progress(
                "[verify] preflight: " + verdict.summary().split("\n")[0],
                verbose=verbose, name="verify_preflight", cat="runtime",
                ok=verdict.ok,
            )
        if (checkpoint_dir is not None or skip_nonfinite_steps
                or step_guard is not None or fault_injector is not None
                or preemption_signal is not None or elastic
                or health_monitor is not None or canary is not None
                or tuner is not None):
            # resilient stepwise loop (runtime/resilience.py): periodic
            # atomic checkpoints + mid-epoch resume, NaN/Inf step guard,
            # preemption handling, deterministic fault injection; with
            # elastic/health_monitor, the elastic runtime's topology-
            # change resume and hang watchdog ride along
            # (runtime/elastic.py)
            from ..runtime import resilience as _rz
            from ..runtime.elastic import shrunk_devices as _shrunk_devices

            failover_stack = contextlib.ExitStack()
            failovers = 0
            try:
                while True:
                    try:
                        return self._fit_resilient(
                            xs, y, bs, ep, verbose,
                            checkpoint_dir=checkpoint_dir,
                            checkpoint_every_n_steps=checkpoint_every_n_steps,
                            keep_last_n=keep_last_n, resume=resume,
                            skip_nonfinite_steps=skip_nonfinite_steps,
                            step_guard=step_guard,
                            max_consecutive_skips=max_consecutive_skips,
                            fault_injector=fault_injector,
                            preemption_signal=preemption_signal,
                            elastic=elastic,
                            health_monitor=health_monitor,
                            canary=canary,
                            tel=tel,
                            tuner=tuner,
                        )
                    except (_rz.SliceLossError, _rz.SliceDrained) as e:
                        # slice-granular failover: a SIMULATED whole-slice
                        # loss / drained preemption carries the surviving
                        # device count, so an elastic fit can shrink the
                        # visible device set in-process, re-search for the
                        # survivors and resume from the checkpoint the
                        # handler just flushed. Real (non-simulated)
                        # losses re-raise for the orchestrator, whose
                        # restart lands in restore_elastic.
                        surv = getattr(e, "surviving_devices", None)
                        if (not elastic or checkpoint_dir is None
                                or surv is None
                                or not getattr(e, "simulated", False)
                                or failovers >= 3):
                            raise
                        failovers += 1
                        # even a HANDLED slice loss leaves a forensics
                        # bundle: the post-incident review wants the
                        # pre-failover event tail, not just the recovery
                        obs.record_failure(e, where="slice_failover",
                                           surviving_devices=surv,
                                           attempt=failovers)
                        obs.event(
                            "slice_failover", cat="runtime", step=e.step,
                            kind=type(e).__name__,
                            surviving_devices=surv, attempt=failovers,
                        )
                        obs.count(
                            "ff_slice_failovers_total",
                            help="in-process shrink-onto-survivors "
                                 "failovers (fit elastic=True)",
                        )
                        obs.progress(
                            f"[elastic] {type(e).__name__} at step "
                            f"{e.step}: shrinking onto {surv} device(s), "
                            "re-searching and resuming from "
                            f"{e.checkpoint_path or 'last checkpoint'}",
                            verbose=verbose, name="slice_failover",
                            cat="runtime", step=e.step,
                            surviving_devices=surv,
                        )
                        if surv < len(jax.devices()):
                            failover_stack.enter_context(
                                _shrunk_devices(surv)
                            )
                        if preemption_signal is not None:
                            preemption_signal.clear()
                        # the drain checkpoint is the resume point
                        resume = True
                        # loop: re-entry sees mesh_is_live() False ->
                        # recompile_for_topology + checkpoint restore
            finally:
                failover_stack.close()
        # guard residue from a previous resilient fit would change the
        # step signature; drop it for the fast unguarded paths
        if self.executor.step_guard is not None:
            self.executor.set_step_guard(None)
        if getattr(self.state, "guard", None) is not None:
            self.state = dataclasses.replace(self.state, guard=None)
        step_fn = self.executor.build_train_step()
        in_pts = self.executor.input_pts
        if self.config.profiling:
            # reference: per-op event timing prints under --profiling
            # (kernels/linear_kernels.cu:94-117)
            from ..runtime.profiler import profile_ops

            first = next(self._batches(list(xs) + [y], bs))
            cast = [
                np.asarray(a, pt.data_type.np_dtype)
                for pt, a in zip(in_pts, first[:-1])
            ]
            profs = profile_ops(self, cast, backward=True)
            for op_name, p in sorted(profs.items(),
                                     key=lambda kv: -kv[1].total_s):
                obs.progress(
                    f"[profiling] {op_name}: {p.forward_s * 1e3:.3f} ms fwd"
                    f" + {p.backward_s * 1e3:.3f} ms bwd",
                    name="op_profile", cat="runtime", op=op_name,
                    forward_s=p.forward_s, backward_s=p.backward_s,
                )
        label_dt = self.label_tensor.data_type.jnp_dtype
        spd = max(1, self.config.iterations_per_dispatch)
        scan_fn = self.executor.build_train_scan() if spd > 1 else None
        self.perf_metrics = PerfMetrics()
        if jax.process_count() > 1:
            self._assert_same_global_batch(xs, y, bs)
        n_chips = max(1, self.executor.mesh.devices.size)
        tstep = 0
        start = time.time()
        num_samples = 0
        for epoch in range(ep):
            # per-epoch accumulator like the reference (PerfMetrics is reset
            # each epoch, model.cc reset_metrics)
            self.perf_metrics = PerfMetrics()
            # Keep partials on device during the epoch so host dispatch stays
            # ahead of the chip (no per-batch sync); fold once at epoch end.
            device_partials = []
            chunk: List[list] = []

            def flush(chunk):
                # fuse the chunk's steps into ONE dispatch (lax.scan driver
                # — the Legion trace-replay analog); partials come back
                # stacked on a steps axis
                nonlocal tstep
                t0 = time.perf_counter() if tel is not None else 0.0
                bxs = [
                    self.executor.shard_batch_stack(
                        pt,
                        np.stack([np.asarray(b[i], pt.data_type.np_dtype)
                                  for b in chunk]),
                    )
                    for i, pt in enumerate(in_pts)
                ]
                bys = self.executor.put_replicated(
                    np.stack([b[-1] for b in chunk]).astype(label_dt)
                )
                # one key per step, split exactly like the stepwise path so
                # dropout masks are identical whatever the dispatch grouping
                subs = []
                for _ in chunk:
                    self._rng, sub = jax.random.split(self._rng)
                    subs.append(sub)
                self.state, partials = scan_fn(
                    self.state, bxs, bys,
                    self.executor.put_replicated(jnp.stack(subs)),
                )
                device_partials.append(partials)
                if tel is not None:
                    tel.record_chunk(
                        first_step=tstep, steps=len(chunk),
                        dur_s=time.perf_counter() - t0, batch_size=bs,
                        n_chips=n_chips, t0=t0,
                    )
                tstep += len(chunk)

            for batch in self._batches(list(xs) + [y], bs):
                if spd > 1:
                    chunk.append(batch)
                    if len(chunk) == spd:
                        flush(chunk)
                        chunk = []
                else:
                    t0 = time.perf_counter() if tel is not None else 0.0
                    bx = [
                        self.executor.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
                        for pt, a in zip(in_pts, batch[:-1])
                    ]
                    by = self.executor.put_replicated(
                        np.asarray(batch[-1]).astype(label_dt)
                    )
                    self._rng, sub = jax.random.split(self._rng)
                    self.state, partials = step_fn(
                        self.state, bx, by, self.executor.put_replicated(sub)
                    )
                    device_partials.append(partials)
                    if tel is not None:
                        loss_val = None
                        if tel.config.sync_per_step:
                            loss_val = float(
                                _fetch_global(partials["loss"]).ravel()[-1]
                            )
                        tel.record_step(
                            step=tstep, dur_s=time.perf_counter() - t0,
                            batch_size=bs, n_chips=n_chips, loss=loss_val,
                            t0=t0,
                        )
                    tstep += 1
                num_samples += bs
            if chunk:  # tail chunk shorter than spd (own compiled shape)
                flush(chunk)
            folded = jax.tree_util.tree_map(
                lambda *vs: sum(float(np.sum(_fetch_global(v))) for v in vs),
                *device_partials,
            )
            last_loss = float(
                _fetch_global(device_partials[-1]["loss"]).ravel()[-1]
            )
            folded.pop("loss", None)
            gnorm_sum = folded.pop("grad_norm", None)
            self.perf_metrics.update(folded)
            if tel is not None:
                tel.record_epoch(epoch=epoch, loss=last_loss,
                                 grad_norm_sum=gnorm_sum,
                                 steps=len(device_partials))
            obs.progress(
                f"epoch {epoch}: loss={last_loss:.4f} "
                + self.perf_metrics.report(),
                verbose=verbose, name="epoch", epoch=epoch, loss=last_loss,
            )
        jax.block_until_ready(self.state.params)
        elapsed = time.time() - start
        # reference: transformer.cc:208-211 throughput print
        obs.progress(
            f"ELAPSED TIME = {elapsed:.4f}s, "
            f"THROUGHPUT = {num_samples / elapsed:.2f} samples/s",
            name="fit_done", elapsed_s=elapsed, samples=num_samples,
        )
        if tel is not None and getattr(tel.config, "step_profile", False):
            # in-situ step observatory (obs/step_profile.py): the step is
            # warm, the batch shapes are live — capture the measured
            # timeline + overlap/HBM reconciliation into this session
            from ..obs.step_profile import capture_into_session

            try:
                capture_into_session(self, tel, xs, y, bs)
            except Exception as e:  # fflint: disable=FFL002 — observability must not fail training
                warnings.warn(f"step-profile capture failed: {e}")
        return self.perf_metrics

    # ------------------------------------------------------------------
    # resilient training loop (runtime/resilience.py)
    # ------------------------------------------------------------------
    def _rng_key_data(self) -> list:
        """self._rng as a JSON-serializable list (checkpoint cursor)."""
        try:
            data = jax.random.key_data(self._rng)
        except Exception:
            data = self._rng
        return np.asarray(data).tolist()

    def _set_rng_from_key_data(self, data) -> None:
        arr = jnp.asarray(np.asarray(data, np.uint32))
        try:
            if jnp.issubdtype(self._rng.dtype, jax.dtypes.prng_key):
                arr = jax.random.wrap_key_data(arr)
        except Exception:  # fflint: disable=FFL002 — old jax: raw uint32 key
            pass
        self._rng = arr

    def _save_resilient_ckpt(self, manager, step, epoch, batch_index,
                             done=False) -> str:
        """Checkpoint + the data-loader cursor: `batch_index` is the NEXT
        batch to run in `epoch`, and `rng` the key stream that batch will
        split from, so a resumed run replays the exact step sequence."""
        return manager.save(self, step, extra_meta={"train": {
            "epoch": epoch,
            "batch_index": batch_index,
            "rng": self._rng_key_data(),
            "done": done,
        }})

    def _canary_check(self, vfy, canary, prev_state, args, step_fn,
                      partials, fault_injector, manager, global_step,
                      epoch, bi, pnorm_fn, prev_pnorm, prev_loss):
        """SDC/determinism canary + per-step invariants
        (runtime/verify.py CanaryConfig). At the canary cadence the step
        is re-executed on the SAME cached inputs from the SAME pre-step
        state (args[0] still references it) and the two results compared;
        per-step invariants bound param-norm drift and loss deltas. Any
        violation reverts to the pre-step state, flushes it as a
        checkpoint (the state AFTER the step is untrusted) and raises —
        the same checkpoint-and-raise escalation the watchdog uses.
        Returns the updated (prev_pnorm, prev_loss) trackers."""
        def escalate(exc):
            obs.event("canary_violation", cat="runtime", step=global_step,
                      error=type(exc).__name__, detail=str(exc)[:500])
            obs.count("ff_canary_violations_total",
                      help="canary / invariant violations")
            self.state = prev_state
            if manager is not None:
                exc.checkpoint_path = self._save_resilient_ckpt(
                    manager, global_step, epoch, bi
                )
            raise exc

        if canary.every_n_steps > 0 \
                and global_step % canary.every_n_steps == 0:
            if fault_injector is not None:
                # SDC simulation: flip one bit in one weight of the FIRST
                # execution's result, as a faulty core would have
                # (target=None keeps disk-targeted plans for
                # CheckpointManager.save)
                plan = fault_injector.fire("bitflip", global_step,
                                           target=None)
                if plan is not None:
                    flipped, _name = vfy.bitflip_params(
                        self.state.params, op=plan.get("op"),
                        weight=plan.get("weight"),
                        bit=plan.get("bit", 6),
                        index=plan.get("index", 3),
                    )
                    self.state = dataclasses.replace(
                        self.state, params=flipped
                    )
            obs.count("ff_canary_checks_total",
                      help="canary step re-executions")
            state2, partials2 = step_fn(*args)
            bad = vfy.compare_step_results(
                {"params": self.state.params, "loss": partials["loss"]},
                {"params": state2.params, "loss": partials2["loss"]},
                mode=canary.mode, rtol=canary.rtol, atol=canary.atol,
            )
            if bad:
                escalate(vfy.CanaryMismatchError(
                    f"step {global_step}: canary re-execution disagrees "
                    f"({canary.mode} mode) — non-deterministic step or "
                    "silent data corruption: " + "; ".join(bad),
                    step=global_step, mismatches=bad,
                ))
        if pnorm_fn is not None:
            loss = float(_fetch_global(partials["loss"]).ravel()[-1])
            if not np.isfinite(loss) and self.executor.step_guard is None:
                escalate(vfy.InvariantViolationError(
                    f"step {global_step}: non-finite loss {loss} (enable "
                    "skip_nonfinite_steps for skip-and-rescale instead)",
                    step=global_step, invariant="finite_loss",
                ))
            if (canary.max_loss_delta is not None and prev_loss is not None
                    and abs(loss - prev_loss) > canary.max_loss_delta):
                escalate(vfy.InvariantViolationError(
                    f"step {global_step}: loss moved "
                    f"{abs(loss - prev_loss):.3g} in one step "
                    f"(bound {canary.max_loss_delta:g})",
                    step=global_step, invariant="loss_delta",
                ))
            pn = float(np.asarray(pnorm_fn(self.state.params)))
            if not np.isfinite(pn) or (
                prev_pnorm is not None and prev_pnorm > 0
                and pn > prev_pnorm * canary.max_param_norm_ratio
            ):
                escalate(vfy.InvariantViolationError(
                    f"step {global_step}: global param norm {pn:.3g} "
                    f"drifted past {canary.max_param_norm_ratio:g}x the "
                    f"previous step's ({prev_pnorm})",
                    step=global_step, invariant="param_norm_drift",
                ))
            return pn, loss
        return prev_pnorm, prev_loss

    def _fit_resilient(self, xs, y, bs, ep, verbose, *, checkpoint_dir,
                       checkpoint_every_n_steps, keep_last_n, resume,
                       skip_nonfinite_steps, step_guard,
                       max_consecutive_skips, fault_injector,
                       preemption_signal, elastic=False,
                       health_monitor=None, canary=None, tel=None,
                       tuner=None):
        from ..runtime import resilience as rz
        from ..runtime import verify as vfy

        if elastic and not self.executor.mesh_is_live():
            # a host (and its devices) disappeared since compile(): any
            # dispatch onto the stale mesh would wedge. Re-search the
            # strategy for the surviving machine and recompile; the
            # checkpoint restore below reshards the weights onto it.
            n = len(jax.devices())
            obs.progress(
                f"[elastic] device topology changed; re-searching "
                f"strategy for {n} device(s) and recompiling",
                verbose=verbose, name="elastic_recompile", cat="runtime",
                devices=n,
            )
            self.recompile_for_topology(n)
            if tel is not None:
                # the recompile minted a fresh trajectory/executor —
                # replay the re-search into the event log too
                tel._attached_models = [
                    m for m in tel._attached_models if m is not self
                ]
                tel.attach_model(self)

        guard_cfg = step_guard
        if guard_cfg is None and skip_nonfinite_steps:
            guard_cfg = rz.StepGuardConfig(
                max_consecutive_skips=max_consecutive_skips
            )
        self.executor.set_step_guard(guard_cfg)
        if guard_cfg is not None and getattr(self.state, "guard", None) is None:
            self.state = dataclasses.replace(
                self.state, guard=self.executor.init_guard_state()
            )
        elif guard_cfg is None and getattr(self.state, "guard", None) is not None:
            self.state = dataclasses.replace(self.state, guard=None)

        n = xs[0].shape[0]
        steps_per_epoch = n // bs
        manager = None
        if checkpoint_dir is not None:
            manager = rz.CheckpointManager(
                checkpoint_dir, keep_last_n=keep_last_n,
                fault_injector=fault_injector,
            )
        every = checkpoint_every_n_steps or steps_per_epoch
        preempt = preemption_signal or rz.PreemptionSignal()
        # drain-protocol state: how many steps ran inside a preemption
        # notice's grace window, whether the notice came from the fault
        # injector (simulated -> in-process failover may shrink devices
        # itself), and the last measured checkpoint-flush duration (feeds
        # the executor's drain-window estimate)
        drain_steps = 0
        drain_simulated = False
        drain_max_steps = None
        last_ckpt_dur_s = None
        mon = health_monitor
        if mon is not None:
            if getattr(mon, "fault_domains", None) is None:
                # share compile()'s fault-domain map so peer staleness
                # classifies per slice (host loss vs whole-slice loss)
                mon.fault_domains = getattr(self, "fault_domains", None)
            mon.start()

        # -- strategy tuner (runtime/tuner.py): fit(tuner=TunerConfig(...))
        # arms the self-healing re-search/hot-swap loop. It observes the
        # synced step durations below and acts between steps; when it
        # swaps (commit or rollback) the live executor changes and the
        # step function/input layout are rebuilt after the boundary hook.
        tuner_obj = None
        if tuner is not None:
            from ..runtime.tuner import StrategyTuner
            from ..runtime.tuner import TunerConfig as _TunerCfg

            if isinstance(tuner, StrategyTuner):
                tuner_obj = tuner
            else:
                tuner_obj = StrategyTuner(
                    self,
                    tuner if isinstance(tuner, _TunerCfg) else _TunerCfg(),
                    fault_injector=fault_injector,
                )
            # persisted quarantines (runtime/artifact_store.py): a
            # candidate rolled back by a previous process is never
            # re-proposed; committed winners write through for reuse
            tuner_obj.attach_artifact_store(
                getattr(self, "artifact_store", None))
            self._tuner = tuner_obj

        # the canary re-executes steps from the pre-step state, which
        # donation would have reclaimed on accelerators — use the
        # undonated step variant when it is armed
        step_fn = self.executor.build_train_step(donate=(canary is None))
        in_pts = self.executor.input_pts
        label_dt = self.label_tensor.data_type.jnp_dtype
        n_chips = max(1, self.executor.mesh.devices.size)
        if jax.process_count() > 1:
            self._assert_same_global_batch(xs, y, bs)
        pnorm_fn = None
        prev_pnorm = None
        prev_loss = None
        if canary is not None and canary.check_invariants:
            from ..parallel.executor import global_grad_norm

            pnorm_fn = jax.jit(global_grad_norm)

        start_epoch, start_batch, global_step = 0, 0, 0
        if manager is not None and resume:
            info = manager.restore_latest(self, elastic=elastic)
            if info is not None and elastic:
                from ..runtime.elastic import (
                    topology_fingerprint,
                    topology_matches,
                )

                saved_topo = (info.meta or {}).get("topology")
                live_topo = topology_fingerprint(self.executor.mesh)
                if not topology_matches(saved_topo, live_topo):
                    obs.progress(
                        f"[elastic] resumed step {info.step} across a "
                        f"topology change "
                        f"({(saved_topo or {}).get('num_devices', '?')} -> "
                        f"{live_topo['num_devices']} devices); strategy "
                        "re-searched and parameters resharded",
                        verbose=verbose, name="elastic_resume",
                        cat="runtime", step=info.step,
                        saved_devices=(saved_topo or {}).get("num_devices"),
                        live_devices=live_topo["num_devices"],
                    )
            if info is not None:
                tm = (info.meta or {}).get("train", {})
                start_epoch = int(tm.get("epoch", 0))
                start_batch = int(tm.get("batch_index", 0))
                if tm.get("rng") is not None:
                    self._set_rng_from_key_data(tm["rng"])
                global_step = info.step
                if start_batch >= steps_per_epoch:
                    start_epoch += 1
                    start_batch = 0
                obs.progress(
                    f"[resilience] resumed from step {info.step} "
                    f"(epoch {start_epoch}, batch {start_batch})",
                    verbose=verbose, name="checkpoint_resume",
                    cat="checkpoint", step=info.step, epoch=start_epoch,
                    batch=start_batch,
                )

        self.perf_metrics = PerfMetrics()
        start = time.time()
        num_samples = 0
        epoch, bi = start_epoch, start_batch
        try:
            for epoch in range(start_epoch, ep):
                self.perf_metrics = PerfMetrics()
                device_partials = []
                for bi, batch in enumerate(self._batches(list(xs) + [y], bs)):
                    if epoch == start_epoch and bi < start_batch:
                        continue
                    # -- preemption check BETWEEN steps (SIGTERM-style) --
                    if fault_injector is not None:
                        plan = fault_injector.fire("preempt", global_step)
                        if plan is not None:
                            preempt.trigger(
                                graceful=plan.get("graceful", True)
                            )
                    if fault_injector is not None:
                        plan = fault_injector.fire("preemption_notice",
                                                   global_step)
                        if plan is not None:
                            # deadline-bearing drain notice (simulated pod
                            # manager grace): arm the signal WITH its
                            # deadline; the drain protocol below uses the
                            # grace window instead of stopping immediately
                            preempt.trigger(
                                graceful=True,
                                deadline_s=plan.get("deadline_s", 30.0),
                                leaving_slice=plan.get("slice"),
                                surviving_devices=plan.get(
                                    "surviving_devices"
                                ),
                            )
                            drain_simulated = True
                            if plan.get("max_drain_steps") is not None:
                                drain_max_steps = int(
                                    plan["max_drain_steps"]
                                )
                    if preempt.triggered() and not preempt.draining:
                        raise rz.TrainingPreempted(
                            f"preempted before step {global_step}",
                            step=global_step, graceful=preempt.graceful,
                        )
                    if preempt.draining:
                        # -- drain protocol: the notice granted a grace
                        # deadline. Keep training while the remaining
                        # grace comfortably exceeds one more step + a
                        # checkpoint flush (executor drain window), then
                        # flush a final checkpoint and hand off to the
                        # slice failover (fit(elastic=True)) / the
                        # orchestrator BEFORE the deadline lands.
                        remaining = preempt.deadline_remaining()
                        window = self.executor.drain_window_s(
                            checkpoint_s=last_ckpt_dur_s
                        )
                        if drain_steps == 0:
                            obs.event(
                                "preemption_notice", cat="runtime",
                                step=global_step,
                                deadline_s=preempt.deadline_s,
                                leaving_slice=preempt.leaving_slice,
                                surviving_devices=preempt.surviving_devices,
                            )
                            obs.progress(
                                f"[resilience] preemption notice: "
                                f"{preempt.deadline_s:.1f}s grace"
                                + (f", slice {preempt.leaving_slice} "
                                   "leaving"
                                   if preempt.leaving_slice is not None
                                   else "")
                                + f"; draining (window {window:.2f}s)",
                                verbose=verbose, name="preemption_notice",
                                cat="runtime", step=global_step,
                            )
                        if remaining <= window or (
                            drain_max_steps is not None
                            and drain_steps >= drain_max_steps
                        ):
                            exc = rz.SliceDrained(
                                f"drained {drain_steps} step(s) under a "
                                f"{preempt.deadline_s:.1f}s preemption "
                                f"deadline before step {global_step}",
                                step=global_step,
                                deadline_s=preempt.deadline_s,
                                drained_steps=drain_steps,
                                leaving_slice=preempt.leaving_slice,
                                surviving_devices=preempt.surviving_devices,
                            )
                            exc.simulated = drain_simulated
                            if manager is not None:
                                exc.checkpoint_path = \
                                    self._save_resilient_ckpt(
                                        manager, global_step, epoch, bi
                                    )
                            left = preempt.deadline_remaining()
                            exc.met_deadline = (left is None or left >= 0.0)
                            self.search_trajectory.event(
                                "slice_drain", step=global_step,
                                deadline_s=preempt.deadline_s,
                                drained_steps=drain_steps,
                                met_deadline=exc.met_deadline,
                                leaving_slice=preempt.leaving_slice,
                            )
                            obs.event(
                                "slice_drain", cat="runtime",
                                step=global_step,
                                drained_steps=drain_steps,
                                met_deadline=exc.met_deadline,
                                checkpoint=exc.checkpoint_path,
                            )
                            raise exc
                        drain_steps += 1
                    if fault_injector is not None:
                        plan = fault_injector.fire("slice_loss", global_step)
                        if plan is not None:
                            # an entire fault domain vanished at once —
                            # the slice-granular analog of host_loss. The
                            # TrainingPreempted handler below flushes the
                            # final checkpoint; fit(elastic=True) then
                            # shrinks onto the survivors and resumes.
                            lost = plan.get("slice")
                            surv = plan.get("surviving_devices")
                            if surv is None and lost is not None and \
                                    getattr(self, "fault_domains", None):
                                surv = len(self.fault_domains
                                           .surviving_devices([lost]))
                            err = rz.SliceLossError(
                                f"slice {lost} lost before step "
                                f"{global_step}",
                                step=global_step,
                                graceful=plan.get("graceful", True),
                                lost_slice=lost,
                                surviving_devices=surv,
                            )
                            err.simulated = True
                            self.search_trajectory.event(
                                "slice_lost", step=global_step,
                                slice=lost, surviving_devices=surv,
                            )
                            obs.event("slice_lost", cat="runtime",
                                      step=global_step, slice=lost,
                                      surviving_devices=surv)
                            obs.count(
                                "ff_slice_losses_total",
                                help="whole-slice losses (real + injected)",
                            )
                            if lost is not None:
                                obs.gauge_set(
                                    "ff_slice_healthy", 0.0,
                                    help="1 while a fault domain's hosts "
                                         "all heartbeat, 0 once lost",
                                    slice=lost,
                                )
                            raise err
                    if fault_injector is not None:
                        plan = fault_injector.fire("host_loss", global_step)
                        if plan is not None:
                            # a host dropped out: flush-and-exit (the
                            # TrainingPreempted handler below writes the
                            # final checkpoint) so the orchestrator can
                            # restart elastically on the survivors
                            raise rz.HostLossError(
                                f"host lost before step {global_step}",
                                step=global_step,
                                graceful=plan.get("graceful", True),
                                surviving_devices=plan.get(
                                    "surviving_devices"
                                ),
                            )
                    if mon is not None:
                        if (fault_injector is not None
                                and fault_injector.fire("hung_step",
                                                        global_step)):
                            # simulated dead collective: blocks until the
                            # watchdog detects the stall and releases us
                            mon.simulate_hang()
                        if mon.hang_detected:
                            info = mon.hang_info
                            if info.get("kind") == "slice_loss":
                                # every host of a slice stopped
                                # heartbeating: whole-slice loss, not a
                                # straggler — flush-and-exit through the
                                # slice-granular error so recovery shrinks
                                # onto the survivors instead of waiting
                                # for the dead slice
                                lost = (info.get("lost_slices")
                                        or [None])[0]
                                err = rz.SliceLossError(
                                    "health watchdog: whole-slice loss "
                                    f"detected before step {global_step} "
                                    f"({info.get('classification', info)})",
                                    step=global_step, lost_slice=lost,
                                    surviving_devices=info.get(
                                        "surviving_devices"
                                    ),
                                )
                                self.search_trajectory.event(
                                    "slice_lost", step=global_step,
                                    slice=lost,
                                    surviving_devices=info.get(
                                        "surviving_devices"
                                    ),
                                )
                                raise err
                            raise rz.CollectiveTimeout(
                                "health watchdog: "
                                f"{info.get('kind', 'hang')} "
                                f"detected before step {global_step} "
                                f"({info})",
                                step=global_step, info=info,
                            )
                        mon.step_started(global_step)
                    t0 = time.perf_counter()
                    bx = [
                        self.executor.shard_batch(
                            pt, np.asarray(a, pt.data_type.np_dtype)
                        )
                        for pt, a in zip(in_pts, batch[:-1])
                    ]
                    by = self.executor.put_replicated(
                        np.asarray(batch[-1]).astype(label_dt)
                    )
                    self._rng, sub = jax.random.split(self._rng)
                    args = [self.state, bx, by,
                            self.executor.put_replicated(sub)]
                    if guard_cfg is not None:
                        poison = 1.0
                        if fault_injector is not None and \
                                fault_injector.fire("nan_grads", global_step):
                            poison = float("nan")
                        args.append(self.executor.put_replicated(
                            jnp.asarray(poison, jnp.float32)
                        ))
                    prev_state = self.state if canary is not None else None
                    self.state, partials = step_fn(*args)
                    if mon is not None:
                        # the watchdog can only observe completion if we
                        # wait for it — per-step sync is the price of
                        # hang detection (documented in docs/resilience.md)
                        jax.block_until_ready(partials["loss"])
                        mon.step_finished(global_step)
                    if (mon is not None or preempt.draining
                            or tuner_obj is not None):
                        # feed the executor's step-time EMA (drain-window
                        # estimate) and the tuner's drift watch — only
                        # from synced steps, where the wall time measures
                        # the step and not a dispatch
                        if mon is None:
                            jax.block_until_ready(partials["loss"])
                        _dur = time.perf_counter() - t0
                        self.executor.note_step_duration(_dur)
                        if tuner_obj is not None:
                            tuner_obj.observe_step(_dur)
                    if canary is not None:
                        prev_pnorm, prev_loss = self._canary_check(
                            vfy, canary, prev_state, args, step_fn,
                            partials, fault_injector, manager,
                            global_step, epoch, bi, pnorm_fn,
                            prev_pnorm, prev_loss,
                        )
                    if tel is not None:
                        loss_val = None
                        if tel.config.sync_per_step or mon is not None:
                            # the monitor already synced on the loss, so
                            # fetching it costs nothing extra
                            loss_val = float(
                                _fetch_global(partials["loss"]).ravel()[-1]
                            )
                        tel.record_step(
                            step=global_step,
                            dur_s=time.perf_counter() - t0,
                            batch_size=bs, n_chips=n_chips, loss=loss_val,
                            t0=t0,
                        )
                    device_partials.append(partials)
                    num_samples += bs
                    global_step += 1
                    if guard_cfg is not None:
                        # skip monitor: a run stuck on non-finite grads
                        # must fail loudly, not silently stop learning
                        skips = int(_fetch_global(
                            self.state.guard.consecutive_skips
                        ))
                        if tel is not None:
                            tel.metrics.gauge(
                                "ff_loss_scale",
                                "dynamic loss scale (step guard)",
                            ).set(float(_fetch_global(
                                self.state.guard.loss_scale
                            )))
                        if skips >= guard_cfg.max_consecutive_skips:
                            raise rz.NonFiniteGradientsError(
                                f"{skips} consecutive non-finite gradient "
                                f"steps (step {global_step}); loss_scale="
                                f"{float(_fetch_global(self.state.guard.loss_scale)):g}"
                            )
                    if tuner_obj is not None and not preempt.draining:
                        # step-boundary tuner hook: probe/trigger/collect
                        # the background search, execute a pending swap
                        # transactionally, police the guard window. A True
                        # return means the LIVE EXECUTOR changed (commit
                        # or rollback) — rebuild the step function and
                        # input layout for the new strategy. A swap during
                        # a preemption drain is suppressed: the grace
                        # window is for checkpointing, not re-planning.
                        if tuner_obj.on_step_boundary(
                            global_step, batch=(batch[:-1], batch[-1])
                        ):
                            step_fn = self.executor.build_train_step(
                                donate=(canary is None)
                            )
                            in_pts = self.executor.input_pts
                            n_chips = max(
                                1, self.executor.mesh.devices.size
                            )
                    if manager is not None and global_step % every == 0:
                        _ck0 = time.perf_counter()
                        self._save_resilient_ckpt(
                            manager, global_step, epoch, bi + 1
                        )
                        last_ckpt_dur_s = time.perf_counter() - _ck0
                if device_partials:
                    folded = jax.tree_util.tree_map(
                        lambda *vs: sum(
                            float(np.sum(_fetch_global(v))) for v in vs
                        ),
                        *device_partials,
                    )
                    last_loss = float(
                        _fetch_global(device_partials[-1]["loss"]).ravel()[-1]
                    )
                    folded.pop("loss", None)
                    skipped = folded.pop("skipped", 0.0)
                    gnorm_sum = folded.pop("grad_norm", None)
                    self.perf_metrics.update(folded)
                    if tel is not None:
                        tel.record_epoch(
                            epoch=epoch, loss=last_loss,
                            grad_norm_sum=gnorm_sum,
                            steps=len(device_partials), skipped=skipped,
                        )
                    extra = (f" skipped_steps={int(skipped)}"
                             if skipped else "")
                    obs.progress(
                        f"epoch {epoch}: loss={last_loss:.4f} "
                        + self.perf_metrics.report() + extra,
                        verbose=verbose, name="epoch", epoch=epoch,
                        loss=last_loss, skipped_steps=int(skipped),
                    )
        except rz.TrainingPreempted as e:
            if manager is not None and e.graceful \
                    and e.checkpoint_path is None:
                # SIGTERM grace period: flush a final checkpoint so the
                # resumed run continues exactly where this one stopped
                # (the drain protocol already wrote SliceDrained's —
                # don't save twice)
                e.checkpoint_path = self._save_resilient_ckpt(
                    manager, global_step, epoch, bi
                )
            raise
        except rz.CollectiveTimeout as e:
            # checkpoint-and-raise: flush the last good state, then exit
            # through the typed error so the orchestrator restarts
            # elastically instead of leaving a deadlocked psum spinning
            if manager is not None:
                e.checkpoint_path = self._save_resilient_ckpt(
                    manager, global_step, epoch, bi
                )
            raise
        jax.block_until_ready(self.state.params)
        if manager is not None:
            self._save_resilient_ckpt(manager, global_step, ep, 0, done=True)
        elapsed = time.time() - start
        if num_samples:
            obs.progress(
                f"ELAPSED TIME = {elapsed:.4f}s, "
                f"THROUGHPUT = {num_samples / elapsed:.2f} samples/s",
                name="fit_done", elapsed_s=elapsed, samples=num_samples,
            )
        if tel is not None and getattr(tel.config, "step_profile", False):
            # same in-situ capture epilogue as the plain loop: the
            # resilient route is the only one the tuner takes, and the
            # overlay it publishes is where the strategy-swap boundary
            # instants land (obs/step_profile.py publish_step_profile)
            from ..obs.step_profile import capture_into_session

            try:
                capture_into_session(self, tel, xs, y, bs)
            except Exception as e:  # fflint: disable=FFL002 — observability must not fail training
                warnings.warn(f"step-profile capture failed: {e}")
        return self.perf_metrics

    def eval(self, x=None, y=None, batch_size: Optional[int] = None):
        if self.executor is None:
            from ..runtime.verify import NotCompiledError

            raise NotCompiledError("eval: call compile() first")
        x, y = _unwrap_loaders(x, y)
        xs = x if isinstance(x, (list, tuple)) else [x]
        bs = batch_size or self.config.batch_size
        step_fn = self.executor.build_eval_step()
        in_pts = self.executor.input_pts
        pm = PerfMetrics()
        for batch in self._batches(list(xs) + [y], bs):
            bx = [
                self.executor.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
                for pt, a in zip(in_pts, batch[:-1])
            ]
            by = jnp.asarray(batch[-1], self.label_tensor.data_type.jnp_dtype)
            _, partials = step_fn(self.state.params, bx, by,
                                  self.state.net_state)
            pm.update({k: float(v) for k, v in partials.items()})
        obs.progress(pm.report(), name="eval_done")
        return pm

    def predict(self, x, batch_size: Optional[int] = None):
        assert self.executor is not None
        xs = x if isinstance(x, (list, tuple)) else [x]
        fwd = self.executor.build_forward()
        bs = batch_size or self.config.batch_size
        outs = []
        n = xs[0].shape[0]
        for i in range(0, n, bs):
            chunk = [a[i : i + bs] for a in xs]
            pad = bs - chunk[0].shape[0]
            if pad > 0:  # pad the tail batch to the compiled batch size
                chunk = [
                    np.concatenate([c, np.repeat(c[-1:], pad, axis=0)], axis=0)
                    for c in chunk
                ]
            bx = [jnp.asarray(c) for c in chunk]
            out = np.asarray(fwd(self.state.params, bx,
                                 self.state.net_state))
            outs.append(out[: bs - pad] if pad > 0 else out)
        return np.concatenate(outs, axis=0) if outs else np.empty((0,))

    # -- stepwise API for cffi parity (reference: model.cc forward/backward/
    #    update/zero_gradients driven from flexflow_cffi.fit) -------------
    def set_iteration_batch(self, inputs: List[np.ndarray], label: np.ndarray):
        self._current_batch = (inputs, label)

    def _bound_inputs(self) -> List:
        inputs, _ = self._current_batch
        for i, a in enumerate(inputs):
            assert a is not None, (
                f"input tensor '{self._fit_input_tensors[i].name or i}' was "
                "never attached — call set_tensor/attach_numpy_array first"
            )
        return inputs

    def forward(self, seq_length: int = -1):
        assert self.executor is not None and self._current_batch is not None
        fwd = self.executor.build_forward(seq_length)
        bx = [jnp.asarray(a) for a in self._bound_inputs()]
        self._last_logits = fwd(self.state.params, bx, self.state.net_state)
        # The stepwise loop is synchronous like the reference's per-phase
        # Legion tasks. Blocking also keeps two sharded programs with
        # collectives from running concurrently, which can wedge the
        # CPU-mesh in-process all-reduce rendezvous.
        jax.block_until_ready(self._last_logits)
        return self._last_logits

    def zero_gradients(self):
        self._pending_grads = None

    def backward(self, seq_length: int = -1):
        assert self.executor is not None and self._current_batch is not None
        _, label = self._current_batch
        assert label is not None, (
            "label tensor was never attached — call set_tensor/"
            "attach_numpy_array on ffmodel.label_tensor first"
        )
        bx = [jnp.asarray(a) for a in self._bound_inputs()]
        by = jnp.asarray(label, self.label_tensor.data_type.jnp_dtype)
        # one jitted program (not eager per-op sharded execution, which
        # loses fusion and can wedge the CPU-mesh in-process collectives);
        # cached + invalidated on the executor like the other step traces
        grad_fn = self.executor.build_grad_step(seq_length)
        self._pending_grads, self._pending_net_state = grad_fn(
            self.state.params, bx, by, self.state.net_state
        )
        jax.block_until_ready(self._pending_grads)  # see forward()

    def update(self):
        assert self._pending_grads is not None, "call backward() first"
        new_params, new_opt = self.optimizer.update(
            self.state.params, self._pending_grads, self.state.opt_state
        )
        net_state = dict(self.state.net_state)
        net_state.update(getattr(self, "_pending_net_state", None) or {})
        self.state = TrainState(
            params=new_params, opt_state=new_opt, step=self.state.step + 1,
            net_state=net_state, guard=self.state.guard,
        )
        self._pending_grads = None
        self._pending_net_state = None

    def output_probability_like(self, output_index: int = -1) -> Optional[bool]:
        """Whether the model's output carries PROBABILITIES (tail op is
        softmax/sigmoid or a fused sigmoid activation) rather than raw
        logits. None when undetermined (not compiled / output untraced).
        Serving's beam scorer uses this instead of sniffing values."""
        if self.graph is None:
            return None
        outs = self.graph.output_tensors()
        if not outs:
            return None
        pt = outs[output_index]
        ops = [o for o in self.graph.ops
               if any(t.guid == pt.guid for t in o.outputs)]
        if not ops:
            return None
        return _probability_like_tail(*_resolve_value_tail(ops[0]))

    def get_perf_metrics(self) -> PerfMetrics:
        return self.perf_metrics

    def reset_metrics(self):
        """reference: flexflow_cffi.py:1968 reset_metrics."""
        self.perf_metrics = PerfMetrics()

    def compute_metrics(self):
        """Fold the current batch's metrics into perf_metrics
        (reference: flexflow_cffi.py:2004 compute_metrics)."""
        assert self._last_logits is not None and self._current_batch is not None
        _, label = self._current_batch
        from ..parallel.executor import truncate_labels

        by = jnp.asarray(label, self.label_tensor.data_type.jnp_dtype)
        by = truncate_labels(by, self._last_logits)
        partials = self.metrics_obj.compute(self._last_logits, by)
        self.perf_metrics.update(
            {k: float(v) for k, v in partials.items() if k != "loss"}
        )
        return self.perf_metrics

    def init_layers(self):
        """Re-initialize all weights (reference: flexflow_cffi.py:1975;
        there a Legion task per weight — here a fresh executor state)."""
        assert self.executor is not None, "call compile() first"
        self.state = self.executor.init_state()

    def prefetch(self):
        """No-op: XLA prefetches HBM transfers itself; kept for script
        compatibility (reference: flexflow_cffi.py:1982)."""

    def map_tensor(self, tensor, parallel_op=None):
        """No-op: tensors materialize with their NamedSharding at first use
        (reference: flexflow_cffi.py:937 maps Legion regions)."""

    def create_constant(self, dims, value, data_type=DataType.DT_FLOAT):
        """Constant input tensor: materialized by the executor, never part
        of fit()'s batch inputs (reference: flexflow_cffi.py:941)."""
        t = self.create_tensor(dims, data_type, create_grad=False)
        self._constant_values[t.guid] = float(value)
        return t

    def create_constant_tensor(self, array, data_type=None):
        """Constant tensor with arbitrary (non-trainable) contents — used by
        the torch frontend to bake traced masks/indices into the graph."""
        arr = np.asarray(array)
        dt = to_data_type(arr.dtype) if data_type is None else data_type
        t = self.create_tensor(arr.shape, dt, create_grad=False)
        self._constant_values[t.guid] = arr.astype(dt.np_dtype)
        return t

    def get_layers(self) -> Dict[int, Layer]:
        return dict(enumerate(self.layers))

    def get_layer_by_id(self, idx: int) -> Layer:
        return self.layers[idx]

    def get_layer_by_name(self, name: str) -> Layer:
        for layer in self.layers:
            if layer.name == name:
                return layer
        raise KeyError(f"no layer named {name!r}")

    def get_last_layer(self) -> Layer:
        return self.layers[-1]

    def print_layers(self, id: int = -1):
        """reference: flexflow_cffi.py print_layers."""
        for i, layer in enumerate(self.layers):
            if id in (-1, i):
                shapes = [tuple(t.dims) for t in layer.outputs]
                # user-facing inspection API: printing IS the contract
                print(  # fflint: disable=FFL201
                    f"layer {i}: {layer.name} ({layer.op_type.name}) "
                    f"-> {shapes}")

    # ------------------------------------------------------------------
    # weight access (reference: parallel_tensor.cc set_tensor/get_tensor)
    # ------------------------------------------------------------------
    def _find_weight_slot(self, t: Tensor):
        layer = t.owner_layer
        if layer is None or self.state is None:
            return None
        for i, wt in enumerate(layer.weights):
            if wt.guid == t.guid:
                # weight name from the lowered op
                for op in self.graph.ops:
                    if op.layer_guid == layer.guid:
                        return op.name, op.weight_names[i]
        return None

    def _get_tensor_value(self, t: Tensor):
        slot = self._find_weight_slot(t)
        if slot is not None:
            return np.asarray(self.state.params[slot[0]][slot[1]])
        if self._current_batch is not None:
            ins, lab = self._current_batch
            if (self.label_tensor is not None
                    and t.guid == self.label_tensor.guid and lab is not None):
                return np.asarray(lab)
            for i, ft in enumerate(self._fit_input_tensors):
                if ft.guid == t.guid and ins[i] is not None:
                    return np.asarray(ins[i])
        raise KeyError(f"tensor {t} is not a weight; activations are not retained")

    def _set_tensor_value(self, t: Tensor, value: np.ndarray):
        slot = self._find_weight_slot(t)
        if slot is None:
            # input or label tensor: bind the batch for the stepwise loop
            # (reference: mnist_mlp_attach.py input.set_tensor per batch)
            return self._attach_array(t, value)
        op_name, w_name = slot
        old = self.state.params[op_name][w_name]
        assert tuple(value.shape) == tuple(old.shape), (
            f"shape mismatch {value.shape} vs {old.shape}"
        )
        self.state.params[op_name][w_name] = jax.device_put(
            value.astype(old.dtype), old.sharding
        )

    def _attach_array(self, t: Tensor, arr) -> None:
        """Bind a numpy array to an input/label tensor for the stepwise
        forward/backward/update loop (reference: attach_numpy_array,
        flexflow_cffi.py — zero-copy Legion attach; here the array feeds
        the next jitted call)."""
        assert self.executor is not None, "attach needs compile() first"
        arr = np.asarray(arr)
        n = len(self.executor.input_pts)
        ins, lab = self._current_batch or ([None] * n, None)
        ins = list(ins)
        if self.label_tensor is not None and t.guid == self.label_tensor.guid:
            self._current_batch = (ins, arr)
            return
        for i, ft in enumerate(self._fit_input_tensors):
            if ft.guid == t.guid:
                ins[i] = arr
                self._current_batch = (ins, lab)
                return
        raise KeyError(
            f"tensor {t} is neither a weight, a graph input, nor the label"
        )

    def create_data_loader(self, batch_tensor: Tensor, full_array: np.ndarray):
        from .dataloader import SingleDataLoader

        dl = SingleDataLoader(self, batch_tensor, full_array)
        self._dataloaders.append(dl)
        return dl


def _unwrap_loaders(x, y):
    """fit/eval accept SingleDataLoader objects for x/y like the reference
    (flexflow_cffi.py fit(x=dataloader_input, y=dataloader_label)); unwrap
    them to their backing arrays."""
    from .dataloader import SingleDataLoader

    def unwrap(v):
        if isinstance(v, SingleDataLoader):
            return v.full_array[: v.num_samples]
        return v

    if isinstance(x, (list, tuple)):
        x = [unwrap(v) for v in x]
    else:
        x = unwrap(x)
    return x, unwrap(y)


def _to_regularizer(reg):
    """Normalize a kernel_regularizer spec to (RegularizerMode, lambda).

    Accepts keras-style objects with `.type`/`._lambda` (frontends/keras/
    regularizers.py), ("l1"|"l2", lam) tuples, or a bare float (treated as L2
    like the reference's kernel_reg_lambda, linear.cc:41)."""
    if reg is None:
        return RegularizerMode.REG_MODE_NONE, 0.0
    if isinstance(reg, (int, float)):
        return RegularizerMode.REG_MODE_L2, float(reg)
    if isinstance(reg, tuple):
        kind, lam = reg
        mode = {
            "l1": RegularizerMode.REG_MODE_L1,
            "l2": RegularizerMode.REG_MODE_L2,
        }[str(kind).lower()]
        return mode, float(lam)
    return RegularizerMode(reg.type), float(reg._lambda)


def _to_dt(dt) -> DataType:
    if isinstance(dt, DataType):
        return dt
    from ..ff_types import to_data_type

    return to_data_type(dt)


def _to_acti(a) -> ActiMode:
    if isinstance(a, ActiMode):
        return a
    if a in (None, "none"):
        return ActiMode.AC_MODE_NONE
    return {
        "relu": ActiMode.AC_MODE_RELU,
        "sigmoid": ActiMode.AC_MODE_SIGMOID,
        "tanh": ActiMode.AC_MODE_TANH,
        "gelu": ActiMode.AC_MODE_GELU,
    }[a]
