"""Paged KV-cache allocation for the serving runtime.

Continuous batching (runtime/serving.py) admits requests into a running
decode batch at token granularity, so the scarce resource is no longer
"a batch slot" but KV-cache memory: each admitted sequence holds
`2 * layers * heads * head_dim * position` cache entries that grow one
token per step. This module is the accounting layer that turns that
growth into an admission signal — the vLLM lesson (PagedAttention,
SOSP'23) applied at the allocator level:

  * memory is carved into fixed-size **pages** of `page_size` token
    positions each;
  * a sequence **reserves** its worst case (prompt + max_new_tokens,
    rounded up to pages) at admission — reservations are the hard
    budget, so an admitted request can never deadlock mid-decode
    waiting for a page held by another admitted request;
  * pages **materialize** lazily as the sequence actually grows
    (`touch`), so `ff_kv_pages_in_use` reports real occupancy while
    `reserved` drives backpressure;
  * when a reservation cannot be satisfied the allocator raises a typed
    `KVCacheExhaustedError` — the admission controller turns that into
    queue backpressure or a shed, never a silent drop.

The physical decode caches today are dense per-slot arrays managed by
`executor.build_decode` (one `max_len`-wide strip per slot); the pool's
page tables map logical (sequence, position) ranges onto page ids so the
accounting is exact at token granularity and the layout can move to
physically paged storage without touching the admission logic.

CPU-testable: `FaultInjector` site ``kv_exhaustion`` makes any
reservation fail as if the pool were full (tests/test_serving.py,
scripts/load_check.py chaos legs).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from .resilience import ResilienceError


class KVCacheExhaustedError(ResilienceError):
    """A KV-page reservation could not be satisfied: the pool is out of
    pages (or the ``kv_exhaustion`` fault site simulated it). Carries
    enough context for the admission controller to decide between
    backpressure (wait for running sequences to retire) and a shed
    (the request can NEVER fit)."""

    def __init__(self, msg: str, *, pages_needed: int = 0,
                 pages_free: int = 0, never_fits: bool = False):
        super().__init__(msg)
        self.pages_needed = pages_needed
        self.pages_free = pages_free
        self.never_fits = never_fits


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Sizing knobs for the page pool (docs/serving.md "KV-cache
    sizing"). `num_pages * page_size` is the total token-position budget
    across all in-flight sequences; `watermark` holds back a fraction of
    pages from admission so in-flight growth plus a small burst never
    hits the hard edge."""

    num_pages: int
    page_size: int = 16
    watermark: float = 0.0

    def __post_init__(self):
        if self.num_pages <= 0:
            raise ValueError(f"num_pages must be positive: {self.num_pages}")
        if self.page_size <= 0:
            raise ValueError(f"page_size must be positive: {self.page_size}")
        if not 0.0 <= self.watermark < 1.0:
            raise ValueError(f"watermark must be in [0, 1): {self.watermark}")

    def pages_for(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_size))


class PagePool:
    """Thread-safe page allocator with per-sequence page tables.

    Lifecycle per sequence: ``reserve(seq_id, max_tokens)`` at admission
    (the hard budget check), ``touch(seq_id, tokens)`` as the sequence
    grows (materializes pages out of the reservation), ``release(seq_id)``
    at retirement/shed/failover. All three are O(pages) and safe to call
    from the batcher, admission and failover threads concurrently."""

    def __init__(self, config: KVCacheConfig, *, fault_injector=None):
        self.config = config
        self.fault_injector = fault_injector
        self._lock = threading.Lock()
        self._free: List[int] = list(range(config.num_pages))[::-1]
        self._tables: Dict[str, List[int]] = {}
        self._reserved: Dict[str, int] = {}
        self.stats = {"reservations": 0, "exhaustions": 0, "released": 0}

    # -- introspection ---------------------------------------------------
    @property
    def num_pages(self) -> int:
        return self.config.num_pages

    @property
    def pages_free(self) -> int:
        """Pages not covered by any reservation (NOT merely untouched)."""
        with self._lock:
            return self.config.num_pages - sum(self._reserved.values())

    @property
    def pages_reserved(self) -> int:
        with self._lock:
            return sum(self._reserved.values())

    @property
    def pages_in_use(self) -> int:
        """Materialized (touched) pages — what `ff_kv_pages_in_use`
        reports; always <= pages_reserved."""
        with self._lock:
            return sum(len(t) for t in self._tables.values())

    def snapshot(self) -> Dict[str, int]:
        """Consistent one-lock view of the pool's occupancy — the
        request flight recorder attaches this to kv_reserve/kv_release
        trace events, where three separately-locked property reads could
        tear against a concurrent admission."""
        with self._lock:
            used = sum(len(t) for t in self._tables.values())
            reserved = sum(self._reserved.values())
        return {"pages_in_use": used, "pages_reserved": reserved,
                "pages_free": self.config.num_pages - reserved}

    def page_table(self, seq_id: str) -> tuple:
        with self._lock:
            return tuple(self._tables.get(seq_id, ()))

    def holds(self, seq_id: str) -> bool:
        with self._lock:
            return seq_id in self._reserved

    def _admittable_pages(self) -> int:
        # held-back watermark pages never count toward admission
        held_back = int(self.config.num_pages * self.config.watermark)
        return (self.config.num_pages - held_back
                - sum(self._reserved.values()))

    def can_reserve(self, max_tokens: int) -> bool:
        need = self.config.pages_for(max_tokens)
        with self._lock:
            return need <= self._admittable_pages()

    def never_fits(self, max_tokens: int) -> bool:
        """True when the demand exceeds the WHOLE pool — waiting for
        retirements can't help, so the request must be shed."""
        held_back = int(self.config.num_pages * self.config.watermark)
        return self.config.pages_for(max_tokens) > (
            self.config.num_pages - held_back
        )

    # -- lifecycle -------------------------------------------------------
    def reserve(self, seq_id: str, max_tokens: int) -> int:
        """Commit `ceil(max_tokens / page_size)` pages to `seq_id`.
        Raises KVCacheExhaustedError (never silently over-commits) when
        the admittable budget can't cover it; `never_fits` on the error
        distinguishes "wait" from "shed"."""
        need = self.config.pages_for(max_tokens)
        if self.fault_injector is not None:
            plan = self.fault_injector.fire("kv_exhaustion")
            if plan is not None:
                self.stats["exhaustions"] += 1
                raise KVCacheExhaustedError(
                    f"kv page pool exhausted (fault injection): "
                    f"{need} page(s) for {seq_id}",
                    pages_needed=need, pages_free=0,
                    never_fits=bool(plan.get("never_fits", False)),
                )
        with self._lock:
            if seq_id in self._reserved:
                raise ValueError(f"sequence {seq_id!r} already reserved")
            avail = self._admittable_pages()
            if need > avail:
                self.stats["exhaustions"] += 1
                raise KVCacheExhaustedError(
                    f"kv page pool exhausted: {need} page(s) needed for "
                    f"{seq_id}, {avail} admittable of {self.config.num_pages}",
                    pages_needed=need, pages_free=max(0, avail),
                    never_fits=self.never_fits(max_tokens),
                )
            self._reserved[seq_id] = need
            self._tables[seq_id] = []
            self.stats["reservations"] += 1
        self._export()
        return need

    def touch(self, seq_id: str, tokens: int) -> List[int]:
        """Materialize pages so positions [0, tokens) are backed; returns
        the newly allocated page ids (empty when already covered).
        Growth beyond the reservation is a caller bug and raises — the
        admission-time worst case is the contract that makes mid-decode
        deadlock impossible."""
        with self._lock:
            if seq_id not in self._reserved:
                raise KeyError(f"sequence {seq_id!r} holds no reservation")
            table = self._tables[seq_id]
            need = self.config.pages_for(tokens)
            if need > self._reserved[seq_id]:
                raise ValueError(
                    f"sequence {seq_id!r} grew to {need} page(s), beyond "
                    f"its reservation of {self._reserved[seq_id]}"
                )
            new = []
            while len(table) < need:
                # free list can't underrun: every materialization is
                # covered by a reservation counted out of num_pages
                new.append(self._free.pop())
                table.append(new[-1])
        if new:
            self._export()
        return new

    def release(self, seq_id: str) -> int:
        """Return `seq_id`'s pages and reservation to the pool (idempotent
        — failover and retirement may race). Returns pages freed."""
        with self._lock:
            if seq_id not in self._reserved:
                return 0
            pages = self._tables.pop(seq_id)
            self._free.extend(reversed(pages))
            del self._reserved[seq_id]
            self.stats["released"] += 1
            freed = len(pages)
        self._export()
        return freed

    def _export(self) -> None:
        from .. import obs

        obs.gauge_set("ff_kv_pages_in_use", self.pages_in_use,
                      help="materialized KV-cache pages across sequences")
        obs.gauge_set("ff_kv_pages_reserved", self.pages_reserved,
                      help="KV-cache pages committed to admitted sequences")


def kv_page_bytes(model, page_size: int) -> Optional[int]:
    """Bytes one page costs across the model's self-attention layers
    (2 * page_size * heads * head_dim * itemsize per layer) — the
    docs/serving.md sizing formula, computed from the compiled graph.
    Returns None when the graph has no fused-MHA self-attention (e.g.
    primitive-op imports, where the cache cost lives in prefix tensors)."""
    import numpy as np

    from ..ff_types import OperatorType

    ex = getattr(model, "executor", None)
    if ex is None:
        return None
    total = 0
    itemsize = np.dtype(np.float32).itemsize
    cdt = getattr(ex, "compute_dtype", None)
    if cdt is not None:
        itemsize = np.dtype(cdt).itemsize
    for op in ex.topo:
        if getattr(op, "op_type", None) != OperatorType.OP_MULTIHEAD_ATTENTION:
            continue
        p = op.params
        total += page_size * p.num_heads * (p.qk_head_dim + p.v_head_dim) \
            * itemsize
    return total or None
