"""Dropout operator.

TPU-native equivalent of reference src/ops/dropout.cc (cuDNN dropout with
persistent states): jax.random.bernoulli with a PRNGKey threaded through
FwdCtx. The reference's per-device dropout state ≈ our per-step folded key.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..ff_types import OperatorType
from .registry import register_op


@dataclasses.dataclass(frozen=True)
class DropoutParams:
    """reference: include/flexflow/ops/dropout_params.h"""

    rate: float = 0.5
    seed: int = 0


def _infer(params, in_shapes, in_dtypes):
    return [in_shapes[0]], [in_dtypes[0]]


def _forward(params: DropoutParams, weights, inputs, ctx):
    (x,) = inputs
    if not ctx.training or params.rate <= 0.0 or ctx.rng is None:
        return [x]
    keep = 1.0 - params.rate
    # per-op seed param folds into the step key (reference: dropout.cc
    # seeds the cuDNN dropout state per layer)
    rng = jax.random.fold_in(ctx.rng, params.seed)
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return [jnp.where(mask, x / keep, 0).astype(x.dtype)]


register_op(OperatorType.OP_DROPOUT, "Dropout", infer=_infer, forward=_forward,
            seq_pointwise=True)
