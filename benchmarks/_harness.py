"""Shared throughput-measurement harness for the benchmark scripts.

One discipline for every bench (bench.py documents the reasoning): batches
pre-staged on device, steps fused through the scan driver (the Legion
trace-replay analog) so per-step host dispatch is amortized, and a scalar
probe reduced on device forces completion — `block_until_ready` returns
early through the remote-TPU tunnel.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))


def run_throughput(build, *, metric: str, batch: int, label_classes: int,
                   spd: int = 10, chunks: int = 4, mixed: bool = True,
                   label_shape=None) -> float:
    """build(model, batch) adds layers to a fresh FFModel. Prints the
    one-line JSON record and returns samples/s/chip."""
    import jax

    from flexflow_tpu import (
        FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.allow_mixed_precision = mixed
    model = FFModel(cfg)
    build(model, batch)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    ex = model.executor
    rng = np.random.RandomState(0)
    xs = []
    for pt in ex.input_pts:
        shape = pt.material_shape()
        if pt.data_type.name.startswith("DT_INT"):
            arr = rng.randint(0, 1000, shape).astype(np.int32)
        else:
            arr = rng.rand(*shape).astype(np.float32)
        xs.append(ex.shard_batch(pt, arr))
    y = jax.numpy.asarray(
        rng.randint(0, label_classes,
                    label_shape or (batch, 1)).astype(np.int32)
    )
    state = model.state
    probe = jax.jit(
        lambda params: sum(
            leaf.reshape(-1)[0].astype(jax.numpy.float32)
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )

    def sync(st):
        return float(np.asarray(probe(st.params)))

    scan = ex.build_train_scan()
    stacked = [jax.numpy.broadcast_to(x, (spd,) + x.shape) for x in xs]
    ys = jax.numpy.broadcast_to(y, (spd,) + y.shape)
    keys = jax.random.split(jax.random.PRNGKey(0), spd)
    # two warmups: the second absorbs the donated-layout recompile
    for _ in range(2):
        state, _ = scan(state, stacked, ys, keys)
    sync(state)
    t0 = time.perf_counter()
    for _ in range(chunks):
        state, _ = scan(state, stacked, ys, keys)
    sync(state)
    dt = time.perf_counter() - t0
    iters = spd * chunks
    n_chips = max(1, len(jax.devices()))
    sps = batch * iters / dt / n_chips
    print(json.dumps({
        "metric": metric,
        "value": round(sps, 2),
        "unit": "samples/s/chip",
        "vs_baseline": None,
    }))
    return sps
