"""Shim: reference python/flexflow/torch/ (PyTorch-FX frontend)."""
from . import model  # noqa: F401
from flexflow_tpu.frontends.torch.model import PyTorchModel  # noqa: F401
