"""Accuracy thresholds for the keras example suite (reference:
examples/python/keras import `from accuracy import ModelAccuracy`, defined in
examples/python/native/accuracy.py)."""
from enum import Enum


class ModelAccuracy(Enum):
    MNIST_MLP = 90
    MNIST_CNN = 90
    REUTERS_MLP = 90
    CIFAR10_CNN = 90
    CIFAR10_ALEXNET = 90
