#!/usr/bin/env bash
# Static-analysis sweep (ISSUE 4 + the FFA5xx perf passes of ISSUE 9),
# mirroring verify_check.sh: the project AST linter, the
# substitution-rule lint over the shipped collection, the analyzer CLI
# over the bench Transformer (flat and 2-slice machines, --fail-on
# error), and the analyzer test suites on CPU meshes of varying size —
# seeded-defect PCGs (wrong reduction axis, degree-vs-devices mismatch,
# cross-shard collective order, over-HBM views, unsound overlap
# discount, overlap-schedule donation race, padding-bound shard,
# slice-crossing ring, mis-degreed all-to-all) must each produce their
# diagnostic code STATICALLY, and the clean searched zoo strategies
# must produce zero errors. Use before touching pcg/, search/, parallel
# strategies, or the analyzer itself:
#
#   scripts/analyze_check.sh                 # full sweep (8, 4-device)
#   FF_ANALYZE_DEVICES=8 scripts/analyze_check.sh -k collective
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== fflint: project AST rules over flexflow_tpu/ ==="
python tools/fflint.py flexflow_tpu/

echo "=== substitution-rule lint: shipped collection ==="
env JAX_PLATFORMS=cpu python -m flexflow_tpu.analysis --fail-on error

echo "=== analyzer CLI: bench Transformer (CPU-sized), full pass stack ==="
env JAX_PLATFORMS=cpu \
    JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m flexflow_tpu.analysis model --budget 2 --fail-on error

echo "=== analyzer CLI: bench Transformer on the 2-slice machine ==="
env JAX_PLATFORMS=cpu \
    JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m flexflow_tpu.analysis model --budget 2 \
        --machine-model-file machine_config_multislice \
        --fail-on error --json > /dev/null

devices="${FF_ANALYZE_DEVICES:-8 4}"
for n in $devices; do
    echo "=== analysis sweep: ${n}-device CPU mesh ==="
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_analysis.py tests/test_perf_analysis.py \
        -v -p no:cacheprovider "$@"
done
