"""Device mesh construction and ParallelTensor → sharding lowering.

This is where the reference's MachineView/ParallelTensor machinery meets
TPU hardware: a jax.sharding.Mesh plays the role of the reference's machine
(all GPUs across nodes), NamedSharding plays the role of a ParallelTensor's
Legion partition, and the XLA SPMD partitioner plays the role of FFMapper +
Realm data movement (reference: src/mapper/mapper.cc slice_task routing each
index point to its MachineView device).

Axis convention: a mesh is built with an ordered dict of named axes. A
ParallelDim with degree>1 carries `parallel_idx` = index into that axis list.
Replica dims (is_replica_dim) mean the *other* dims' shards are replicated
over that axis — for weights under DP this is exactly "replicated over the
data axis".
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


# Canonical axis names in priority order. data = sample dim, model = tensor
# parallel, seq = sequence/context parallel, expert = MoE experts,
# pipe = pipeline stages, fsdp = FSDP/ZeRO weight sharding (weights shard
# over it; the batch shards over data AND fsdp jointly, so the fsdp group
# is a subdivision of the data-parallel workers — SpecLayout's convention).
AXIS_NAMES = ("data", "model", "seq", "expert", "pipe", "fsdp")


def build_mesh(
    axis_sizes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Build a Mesh from {axis_name: size}. Total size must divide the
    device count; leftover devices are left out (like a MachineView that
    doesn't cover the whole machine)."""
    if devices is None:
        devices = jax.devices()
    # keep size-1 axes so axis indices are stable across strategies
    axes = list(axis_sizes.items()) or [("data", 1)]
    n = int(np.prod([v for _, v in axes]))
    assert n <= len(devices), f"mesh {axis_sizes} needs {n} devices, have {len(devices)}"
    dev_array = np.asarray(devices[:n]).reshape([v for _, v in axes])
    return Mesh(dev_array, tuple(k for k, _ in axes))


def build_hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axes: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Multi-slice mesh: `dcn_axes` span slices (data-center network),
    `ici_axes` stay within a slice (inter-chip interconnect).

    TPU-native equivalent of the reference's two-level comm hierarchy
    (NCCL within a node + GASNet across nodes, SURVEY §5): lay out the
    device array so collectives over ici axes ride ICI and only the dcn
    axes (put data parallelism there) cross slices. Uses
    mesh_utils.create_hybrid_device_mesh when devices carry slice
    topology; single-slice (or CPU-simulated) device sets fall back to
    build_mesh with dcn axes leading.
    """
    if devices is None:
        devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    merged = dict(dcn_axes)
    merged.update(ici_axes)
    if len(slice_ids) > 1:
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh wants mesh_shape and dcn_mesh_shape of
        # EQUAL length (per-axis ici and dcn factors). Order axes dcn-first,
        # give dcn axes ici-factor 1 and ici axes dcn-factor 1 — the result
        # then has shape (dcn sizes..., ici sizes...) with dcn axes actually
        # spanning slices; no reshape (which would scramble device order).
        names = tuple(dcn_axes) + tuple(ici_axes)
        per_slice = tuple([1] * len(dcn_axes)) + tuple(ici_axes.values())
        across = tuple(dcn_axes.values()) + tuple([1] * len(ici_axes))
        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=per_slice,
            dcn_mesh_shape=across,
            devices=devices,
        )
        return Mesh(dev_array, names)
    return build_mesh(
        {n: merged[n] for n in tuple(dcn_axes) + tuple(ici_axes)}, devices
    )


def default_data_parallel_mesh(num_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = num_devices or len(devices)
    return build_mesh({"data": n}, devices)


def pspec_for_parallel_tensor(pt, mesh: Mesh) -> PartitionSpec:
    """Lower ParallelTensor dims to a PartitionSpec over `mesh`.

    Partitioned material dims map to their axis; replica dims are dropped
    (replication is PartitionSpec's default for unmentioned axes).

    ZeRO/FSDP batch rule: under weight sharding the batch spans the data
    AND fsdp axes jointly (the fsdp group IS a subdivision of the
    data-parallel workers), so a "data"-assigned dim whose degree equals
    data_size x fsdp_size lowers to the tuple ("data", "fsdp") — the
    SpecLayout convention (parallel/weight_sharding.py). The same rule
    covers the "expert" axis, which is the data axis renamed by the
    expert merge (parallel/strategies.py assign_mesh_axes)."""
    names = mesh.axis_names
    sizes = dict(zip(names, mesh.devices.shape))
    spec = []
    used = set()
    for d in pt.dims:
        if d.is_replica_dim:
            continue
        if d.degree > 1 and 0 <= d.parallel_idx < len(names) \
                and names[d.parallel_idx] not in used:
            # a mesh axis may appear at most once per spec: when the search
            # composes two shards that both land on the same axis (e.g.
            # row- AND column-parallel on one Linear), the first dim keeps
            # the axis and later dims stay replicated — a valid (weaker)
            # lowering of the strategy
            name = names[d.parallel_idx]
            entry = name
            if (name in ("data", "expert") and "fsdp" in names
                    and "fsdp" not in used
                    and d.degree != sizes[name]
                    and d.degree == sizes[name] * sizes.get("fsdp", 1)):
                entry = (name, "fsdp")
                used.add("fsdp")
            used.add(name)
            spec.append(entry)
        else:
            spec.append(None)
    # trim trailing Nones
    while spec and spec[-1] is None:
        spec.pop()
    return PartitionSpec(*spec)


def sharding_for_parallel_tensor(pt, mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, pspec_for_parallel_tensor(pt, mesh))


def machine_view_to_axes(view, mesh: Mesh) -> Tuple[str, ...]:
    """Map a MachineView's dims onto mesh axis names by size. Round-1
    restriction: views must align with mesh axis sizes (the search's
    enumerate_machine_views generates views that do)."""
    out = []
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for d in view.dim:
        for name, sz in sizes.items():
            if sz == d and name not in out:
                out.append(name)
                break
    return tuple(out)
