"""Checkpoint / resume.

The reference has NO full checkpoint subsystem (SURVEY §5: only per-tensor
get/set_tensor and strategy export). This module is the capability upgrade
SURVEY §5 calls for: full training-state checkpointing (params + optimizer
state + step + data-loader cursor) via Orbax, restoring onto the same or a
different mesh (orbax re-shards on load).
"""
from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("flexflow_tpu.runtime.checkpoint")


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _to_host(tree):
    """Host-gather every fully-addressable device array to numpy before
    the write, so the on-disk checkpoint carries no device-sharding
    dependency — a checkpoint written on an 8-device mesh must stay
    readable by a 4-device survivor (runtime/elastic.py), and orbax
    refuses to restore a sharded array whose saved devices are gone.
    Non-fully-addressable arrays (true multi-host shards) are left to
    orbax's distributed save path.

    copy=True is load-bearing: on CPU, np.asarray(jax_array) can be a
    ZERO-COPY view of the device buffer, and the train step's
    donate_argnums reuses that exact memory on the next step — a
    checkpoint serialized from the view after training resumes would
    contain the NEXT step's bytes (observed: mid-run saves corrupted
    once the jit cache was warm enough for the race to land)."""
    def conv(x):
        if isinstance(x, jax.Array) and x.is_fully_addressable:
            return np.array(x, copy=True)
        return x

    return jax.tree_util.tree_map(conv, tree)


def _restore_to_host(path: str):
    """Read a checkpoint into host numpy arrays regardless of what
    sharding it was saved with. Plain restore handles host-gathered
    (v3+) checkpoints; older sharded ones need explicit numpy
    restore_args or orbax re-resolves the saved (possibly dead) device
    set."""
    import orbax.checkpoint as ocp

    ckptr = _checkpointer()
    try:
        return ckptr.restore(path)
    except (ValueError, TypeError, KeyError, OSError, RuntimeError) as e:
        # the expected failure: a pre-v3 checkpoint whose saved sharding
        # names dead devices; anything else (corrupt store) fails the
        # numpy retry below too, and louder
        logger.warning(
            "checkpoint %s: plain restore failed (%r); retrying with "
            "explicit host-numpy restore_args", path, e,
        )
        meta = ckptr.metadata(path)
        restore_args = jax.tree_util.tree_map(
            lambda _: ocp.RestoreArgs(restore_type=np.ndarray), meta
        )
        return ckptr.restore(path, restore_args=restore_args)


def save_checkpoint(model, path: str, *, step: Optional[int] = None,
                    extra_meta: Optional[dict] = None,
                    _pre_rename_hook=None) -> str:
    """Save a model's full training state. `model` is a compiled FFModel.

    Atomic: the state tree and its meta sidecar are written under tmp
    names and renamed into place last, so a crash (or an injected IOError
    — `_pre_rename_hook` is the resilience test seam, called after the
    tmp write and before the rename) never leaves a partial checkpoint at
    `path`; the half-written tmp is cleaned up on the way out.
    `extra_meta` (e.g. fit's data-loader cursor) rides in the sidecar."""
    from .verify import NotCompiledError, tensor_checksums

    if model.state is None:
        raise NotCompiledError(
            "save_checkpoint: model has no training state — call "
            "compile() (and restore/fit) before saving"
        )
    path = os.path.abspath(path)
    state = {
        "params": model.state.params,
        "opt_state": _strip_none(model.state.opt_state),
        "step": np.asarray(step if step is not None else model.state.step),
    }
    if model.state.net_state:
        # cross-batch buffers (BN running stats, Cache) are part of the
        # trained state — dropping them silently reverts eval behavior
        state["net_state"] = model.state.net_state
    guard = getattr(model.state, "guard", None)
    if guard is not None:
        # loss-scale / skip counters survive restarts, or a resumed run
        # would re-probe the scale it already backed off
        state["guard"] = {
            "loss_scale": np.asarray(guard.loss_scale),
            "good_steps": np.asarray(guard.good_steps),
            "consecutive_skips": np.asarray(guard.consecutive_skips),
            "total_skips": np.asarray(guard.total_skips),
        }
    # sidecar metadata for topology validation on restore: the live
    # device topology, plus each op's searched MachineView/degrees so an
    # elastic restore (runtime/elastic.py) can tell the checkpoint was
    # planned for a different machine and re-search for the live one
    from .strategy_io import op_strategy_record

    views = getattr(model, "searched_views", None) or {}
    meta = {
        "version": 3,
        "ops": [
            op_strategy_record(op, views.get(op.guid))
            for op in model.graph.topo_order()
        ],
    }
    if getattr(model, "executor", None) is not None:
        from .elastic import topology_fingerprint

        meta["topology"] = topology_fingerprint(
            model.executor.mesh,
            fault_domains=getattr(model, "fault_domains", None),
        )
    if extra_meta:
        meta.update(extra_meta)
    host_state = _to_host(state)
    # per-tensor content checksums (runtime/verify.py): restore and the
    # offline audit re-hash the bytes, so on-disk corruption — bitrot, a
    # truncated object, a flipped bit — is caught by name instead of
    # silently training on garbage weights
    from .verify import CHECKSUM_ALGO

    meta["integrity"] = {
        "algo": CHECKSUM_ALGO,
        "tensors": tensor_checksums(host_state),
    }
    tmp = f"{path}.tmp-{os.getpid()}"
    tmp_meta = tmp + ".meta.json"
    try:
        _checkpointer().save(tmp, host_state, force=True)
        with open(tmp_meta, "w") as f:
            json.dump(meta, f)
        if _pre_rename_hook is not None:
            _pre_rename_hook()
        # swap in: unique-per-step manager paths never pre-exist; direct
        # overwrites move the old version aside so readers never see a
        # mix of the two
        old = None
        if os.path.isdir(path):
            old = f"{path}.tmp-old-{os.getpid()}"
            os.rename(path, old)
        os.rename(tmp, path)
        os.replace(tmp_meta, path + ".meta.json")
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        from .. import obs

        obs.gauge_set(
            "ff_checkpoint_bytes",
            sum(int(np.asarray(v).nbytes)
                for v in jax.tree_util.tree_leaves(host_state)),
            help="serialized size of the last checkpoint's state tree",
        )
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        if os.path.exists(tmp_meta):
            try:
                os.remove(tmp_meta)
            except OSError:
                pass
        raise
    return path


def load_checkpoint_meta(path: str) -> Optional[dict]:
    """The checkpoint's sidecar metadata (topology + any extra_meta the
    writer attached, e.g. fit's resume cursor), or None when absent."""
    meta_path = os.path.abspath(path) + ".meta.json"
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        return json.load(f)


def _put_resharded(arr: np.ndarray, like) -> "jax.Array":
    """device_put onto `like`'s sharding, falling back to replicated when
    the array's shape no longer divides the live mesh axes (an elastic
    restore can legally land a degree on a mesh it doesn't divide — the
    data is still correct, just not distributed)."""
    try:
        return jax.device_put(arr.astype(like.dtype), like.sharding)
    except (ValueError, TypeError) as e:
        # jax raises ValueError when the shape doesn't divide the mesh
        # axes (TypeError on some older sharding paths); anything else is
        # a real bug and must propagate
        from jax.sharding import NamedSharding, PartitionSpec

        sh = like.sharding
        repl = (NamedSharding(sh.mesh, PartitionSpec())
                if isinstance(sh, NamedSharding) else None)
        logger.warning(
            "restore: array of shape %s does not divide the live mesh "
            "(%s); replicating instead", tuple(arr.shape), e,
        )
        return jax.device_put(arr.astype(like.dtype), repl)


def restore_checkpoint(model, path: str, *,
                       strict_topology: bool = True) -> int:
    """Restore params/opt_state into a compiled FFModel. Returns the step.
    Arrays are device_put with the model's current shardings (so a
    checkpoint taken on one mesh restores onto another).

    `strict_topology=False` (elastic restore, runtime/elastic.py) drops
    the exact op-list equality check — a strategy re-searched for a
    different device count inserts different parallel ops — and matches
    weights by (op name, weight name) instead, keeping the fresh
    initialization for anything unmatched. The per-weight outcome lands
    in ``model._restore_report`` ({"unmatched_model", "unmatched_checkpoint",
    "replicated"})."""
    from ..parallel.executor import GuardState, TrainState
    from .verify import NotCompiledError, verify_checksums

    if model.state is None:
        raise NotCompiledError(
            "restore_checkpoint: compile() the model before restoring"
        )
    path = os.path.abspath(path)
    meta_path = path + ".meta.json"
    meta = None
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        ours = [op.name for op in model.graph.topo_order()]
        theirs = [o["name"] for o in meta["ops"]]
        if ours != theirs:
            if strict_topology:
                raise ValueError(
                    "checkpoint topology mismatch: "
                    f"checkpoint has {len(theirs)} ops, model has "
                    f"{len(ours)}; pass elastic=True (or use "
                    "runtime.elastic.restore_elastic) to restore across a "
                    "re-searched strategy"
                )
            logger.info(
                "elastic restore: checkpoint graph (%d ops) differs from "
                "the live graph (%d ops); matching weights by name",
                len(theirs), len(ours),
            )
    report = {"unmatched_model": [], "unmatched_checkpoint": [],
              "replicated": []}
    restored = _restore_to_host(path)
    if meta is not None and meta.get("integrity"):
        # bytes-level integrity gate (runtime/verify.py): a corrupt
        # tensor raises CheckpointCorruptionError naming it, which
        # CheckpointManager.restore_latest treats like any other
        # unloadable checkpoint — fall back to the previous intact one
        verify_checksums(restored, meta["integrity"], path=path)
    params = restored["params"]
    # re-shard onto the live mesh
    new_params = {}
    for op_name, wd in model.state.params.items():
        new_params[op_name] = {}
        for w_name, old in wd.items():
            src = params.get(op_name, {}).get(w_name) \
                if not strict_topology else params[op_name][w_name]
            if src is None:
                report["unmatched_model"].append(f"{op_name}/{w_name}")
                new_params[op_name][w_name] = old
                continue
            arr = np.asarray(src)
            if tuple(arr.shape) != tuple(old.shape):
                if strict_topology:
                    raise ValueError(
                        f"checkpoint weight {op_name}/{w_name} has shape "
                        f"{tuple(arr.shape)}, model expects "
                        f"{tuple(old.shape)}"
                    )
                report["unmatched_model"].append(f"{op_name}/{w_name}")
                new_params[op_name][w_name] = old
                continue
            put = _put_resharded(arr, old)
            if put.sharding != old.sharding:
                report["replicated"].append(f"{op_name}/{w_name}")
            new_params[op_name][w_name] = put
    for op_name in params if isinstance(params, dict) else ():
        for w_name in params[op_name]:
            if op_name not in new_params or w_name not in new_params[op_name]:
                report["unmatched_checkpoint"].append(f"{op_name}/{w_name}")
    if report["unmatched_model"]:
        logger.warning(
            "elastic restore: %d weight(s) missing from the checkpoint "
            "keep their fresh initialization: %s",
            len(report["unmatched_model"]),
            ", ".join(report["unmatched_model"]),
        )
    opt_state = _merge_restore(model.state.opt_state, restored.get("opt_state"))
    step = int(np.asarray(restored.get("step", 0)))
    saved_net = restored.get("net_state")
    net_state = model.state.net_state
    if saved_net:
        net_state = {}
        for op_name, bufs in model.state.net_state.items():
            net_state[op_name] = {
                name: jax.device_put(
                    np.asarray(saved_net[op_name][name]).astype(old.dtype),
                    old.sharding,
                )
                if op_name in saved_net and name in saved_net[op_name]
                else old
                for name, old in bufs.items()
            }
    saved_guard = restored.get("guard")
    guard = getattr(model.state, "guard", None)
    if saved_guard is not None:
        guard = GuardState(
            loss_scale=jnp.asarray(
                np.asarray(saved_guard["loss_scale"]), jnp.float32
            ),
            good_steps=jnp.asarray(
                np.asarray(saved_guard["good_steps"]), jnp.int32
            ),
            consecutive_skips=jnp.asarray(
                np.asarray(saved_guard["consecutive_skips"]), jnp.int32
            ),
            total_skips=jnp.asarray(
                np.asarray(saved_guard["total_skips"]), jnp.int32
            ),
        )
    model.state = TrainState(params=new_params, opt_state=opt_state,
                             step=step, net_state=net_state, guard=guard)
    model._restore_report = report
    return step


def _strip_none(tree):
    """Orbax rejects raw None leaves in some layouts; encode as sentinel."""
    return jax.tree_util.tree_map(
        lambda x: x, tree, is_leaf=lambda x: x is None
    ) if tree is not None else {}


def _merge_restore(live, saved):
    if saved is None:
        return live
    flat_live, treedef = jax.tree_util.tree_flatten(
        live, is_leaf=lambda x: x is None
    )
    try:
        flat_saved = treedef.flatten_up_to(saved)
    except (ValueError, TypeError, KeyError) as e:
        # structure changed (different optimizer) — keep the fresh state,
        # but say so: a silently-reset momentum surprises a resumed run
        logger.warning(
            "restore: optimizer state structure does not match the "
            "checkpoint's (%r); keeping freshly-initialized optimizer "
            "state", e,
        )
        return live
    from jax.sharding import NamedSharding

    out = []
    for lv, sv in zip(flat_live, flat_saved):
        if lv is None or sv is None:
            out.append(lv)
        else:
            arr = np.asarray(sv)
            if hasattr(lv, "sharding") and isinstance(lv.sharding,
                                                      NamedSharding):
                out.append(_put_resharded(arr, lv))
            elif hasattr(lv, "sharding"):
                # single-device leaves (Adam's beta_t scalars, built by
                # plain jnp.asarray) must come back UNCOMMITTED: a
                # device_put onto their SingleDeviceSharding pins them to
                # device 0, and the next jitted step then sees state
                # leaves committed to conflicting device sets and refuses
                # to run ("incompatible devices") on any multi-device mesh
                out.append(jnp.asarray(arr.astype(lv.dtype)))
            else:
                out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)
