"""Shim: reference python/flexflow/torch/model.py (PyTorchModel et al.)."""
from flexflow_tpu.frontends.torch.model import *  # noqa: F401,F403
