"""Overload-robust inference serving over compiled models.

TPU-native counterpart to the reference's Triton prototype (triton/src/,
~8k LoC "incomplete prototype" serving ONNX models on Legion — SURVEY
§2.6), grown into a production front end whose adversary is the offered
load, not the strategy (the Orca OSDI'22 lesson: schedule at iteration
granularity, shed at admission, never hang):

  * **generation APIs** — greedy/beam/KV-cache decode over the compiled
    graph (`greedy_generate`, `incremental_generate`, ...);
  * **continuous batching** — `ContinuousBatcher` keeps a running decode
    batch whose slots each advance through their OWN sequence
    (per-slot positions, executor.build_decode), admitting new requests
    and retiring finished ones every iteration, with KV memory governed
    by the paged allocator (runtime/kvcache.py);
  * **admission control** — bounded queue with end-to-end deadlines
    (checked at enqueue, dequeue and every decode iteration), a token
    bucket whose refill adapts to the p95 of `ff_serving_latency_seconds`,
    and KV-page backpressure; every rejection is a typed
    `RequestShedError` subclass counted in `ff_serving_shed_total` —
    zero silent drops;
  * **replica failover** — `ReplicaSet` runs N batcher replicas off one
    shared queue, health-checked by the elastic runtime's
    `HealthMonitor` (runtime/elastic.py); a dead/hung replica's
    in-flight requests are requeued onto its siblings while it restarts
    (via `restore_elastic` resharding when a checkpoint dir is given),
    and replica count scales with queue depth;
  * **dynamic batching** — the original `BatchScheduler` (pads/packs
    single-shot forward requests to the compiled batch) stays for
    non-generative and encoder-decoder models.

  * **prefix sharing** — admission consults the page pool's
    content-addressed index (`reserve(..., tokens=prompt)`): published
    prompt pages are attached refcounted and discounted from the KV
    charge, prompts seen verbatim before skip their prefill compute
    entirely (a bounded host-side strip cache — exact because identical
    prompt + identical params reproduce the identical cache strip), and
    failover stranding/requeue transfers page ownership exactly once
    (typed `KVCacheAccountingError` on double release, never silent).

Chaos-testable on CPU: FaultInjector sites ``replica_death``,
``slow_worker``, ``kv_exhaustion``, ``serving_worker``,
``shared_page_corruption``, ``release_race`` and ``cow_fault``
(tests/test_serving.py, tests/test_kvshare.py, scripts/load_check.py).
"""
from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.request_trace import (
    NULL_REQUEST_TRACE,
    SLOMonitor,
    mint_request_trace,
    record_request_stages,
)
from .kvcache import KVCacheConfig, KVCacheExhaustedError, PagePool
from .resilience import ResilienceError
from .verify import NotCompiledError, ServingConfigError

logger = logging.getLogger("flexflow_tpu.runtime.serving")


def greedy_generate(
    model,
    encoder_ids: np.ndarray,
    *,
    max_new_tokens: Optional[int] = None,
    start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
) -> np.ndarray:
    """Greedy autoregressive seq2seq decode over a compiled encoder-decoder
    FFModel (e.g. an imported MT5ForConditionalGeneration) whose two graph
    inputs are (encoder_ids, decoder_ids) and whose output is per-position
    vocab logits.

    The compiled graph is static-shape, so each step re-runs the SAME
    jitted forward with the decoder prefix grown by one token — the causal
    mask guarantees position t sees only tokens <= t, so the padded tail
    cannot leak. No KV cache: one full forward per token (O(L) calls of
    one cached executable). The reference has no generation API at all —
    its serving story is the Triton prototype's single forward — so this
    is a capability upgrade on the serving side.
    """
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    fwd = model.executor.build_forward()
    enc_t, dec_t = model._fit_input_tensors[:2]
    bs, dec_len = dec_t.dims[0], dec_t.dims[1]
    if tuple(encoder_ids.shape) != tuple(enc_t.dims):
        raise ServingConfigError(
            f"encoder_ids shape {tuple(encoder_ids.shape)} != compiled input "
            f"shape {tuple(enc_t.dims)}"
        )
    want = dec_len - 1 if max_new_tokens is None else max_new_tokens
    steps = min(want, dec_len - 1)
    enc = np.asarray(encoder_ids, enc_t.data_type.np_dtype)

    def next_logits(t, dec):
        return np.asarray(fwd(model.state.params, [enc, dec],
                              model.state.net_state))[:, t]

    return _greedy_decode_loop(
        bs, dec_len, steps, next_logits, dec_t.data_type.np_dtype,
        start_token_id=start_token_id, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id,
    )


def _greedy_decode_loop(bs, dec_len, steps, next_logits, dec_dt, *,
                        start_token_id, eos_token_id, pad_token_id):
    """The shared greedy seq2seq loop: greedy_generate (full forward per
    token) and incremental_seq2seq_generate (KV-cache step per token)
    differ ONLY in how position t's logits are produced — sharing the
    scaffold keeps their documented token-exact equivalence structural.
    next_logits(t, dec) -> (bs, vocab) values for position t given the
    decoder buffer so far."""
    dec = np.full((bs, dec_len), pad_token_id, dec_dt)
    dec[:, 0] = start_token_id
    if steps <= 0:
        return dec[:, :1]
    finished = np.zeros(bs, bool)
    for t in range(steps):
        nxt = next_logits(t, dec).argmax(-1)
        if eos_token_id is not None:
            nxt = np.where(finished, pad_token_id, nxt)
            finished |= nxt == eos_token_id
        dec[:, t + 1] = nxt
        if eos_token_id is not None and finished.all():
            break
    return dec[:, : t + 2]


def incremental_seq2seq_generate(
    model,
    encoder_ids: np.ndarray,
    *,
    max_new_tokens: Optional[int] = None,
    start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    assume_causal: bool = False,
) -> np.ndarray:
    """KV-cache greedy decode for a compiled encoder-decoder FFModel —
    same signature and token-exact output as greedy_generate, but
    O(1)/token: the encoder runs ONCE (executor.build_decode computes the
    static subgraph and the cross-attention K/V at init), each step feeds
    one decoder position through the liveness-analyzed decoder subgraph
    (parallel/decode.py). Works on imported HF graphs (mt5) where
    attention is primitive batch_matmul/softmax ops."""
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    if len(model._fit_input_tensors) < 2:
        raise ServingConfigError(
            "incremental_seq2seq_generate needs an encoder-decoder model "
            "(two graph inputs); use incremental_generate for decoder-only"
        )
    ex = model.executor
    enc_t, dec_t = model._fit_input_tensors[:2]
    bs, dec_len = dec_t.dims[0], dec_t.dims[1]
    if tuple(encoder_ids.shape) != tuple(enc_t.dims):
        raise ServingConfigError(
            f"encoder_ids shape {tuple(encoder_ids.shape)} != compiled input "
            f"shape {tuple(enc_t.dims)}"
        )
    want = dec_len - 1 if max_new_tokens is None else max_new_tokens
    steps = min(want, dec_len - 1)
    if steps <= 0:
        out = np.full((bs, 1), start_token_id, dec_t.data_type.np_dtype)
        return out
    init_caches, step = ex.build_decode(bs, dec_len,
                                        assume_causal=assume_causal)
    caches = init_caches(
        model.state.params,
        [np.asarray(encoder_ids, enc_t.data_type.np_dtype)],
    )

    def next_logits(t, dec):
        nonlocal caches
        logits, caches = step(
            model.state.params, caches, jnp.int32(t),
            [jnp.asarray(dec[:, t : t + 1])],
        )
        return np.asarray(logits)[:, -1]

    return _greedy_decode_loop(
        bs, dec_len, steps, next_logits, dec_t.data_type.np_dtype,
        start_token_id=start_token_id, eos_token_id=eos_token_id,
        pad_token_id=pad_token_id,
    )


def incremental_generate(
    model,
    prompt_ids: np.ndarray,
    *,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    static_inputs=(),
    decode_input: Optional[int] = None,
    assume_causal: bool = False,
) -> np.ndarray:
    """KV-cache autoregressive decoding for a causal decoder-only FFModel
    (token ids in, per-position vocab logits out): each step feeds ONE
    position through executor.build_decode, appending that position's K/V
    to per-layer caches — one O(max_len)-wide attention row per token
    instead of greedy_generate's full O(L²) forward per token. Capability the reference
    lacks entirely (its Triton prototype serves single forwards).

    prompt_ids: (batch, prompt_len) int array. Returns (batch, total_len)
    including the prompt.

    static_inputs: arrays for any non-decode graph inputs (e.g. an
    explicit attention-mask input), passed through to init_caches;
    decode_input selects which graph input the prompt drives (default:
    build_decode's convention, the last); assume_causal vouches for
    primitive-op attention whose causality can't be proven from baked
    constants (parallel/decode.py)."""
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    prompt_ids = np.asarray(prompt_ids)
    bs, plen = prompt_ids.shape
    if max_new_tokens <= 0:
        return prompt_ids.copy()
    total = plen + max_new_tokens
    cap = max_len or total
    if cap < total:
        raise ServingConfigError(f"max_len {cap} < prompt+new {total}")
    init_caches, step = model.executor.build_decode(
        bs, cap, decode_input=decode_input, assume_causal=assume_causal
    )
    caches = init_caches(model.state.params, list(static_inputs))
    dec_idx = (decode_input if decode_input is not None
               else len(model._fit_input_tensors) - 1)
    in_t = model._fit_input_tensors[dec_idx]
    id_dt = in_t.data_type.np_dtype

    out = np.full((bs, total), pad_token_id, id_dt)
    out[:, :plen] = prompt_ids
    finished = np.zeros(bs, bool)
    # one-shot prefill: the whole prompt goes through a single step (the
    # decode kernels handle any block width with intra-block causal
    # masking), populating every prompt position's K/V at once
    logits, caches = step(
        model.state.params, caches, jnp.int32(0),
        [jnp.asarray(prompt_ids.astype(id_dt))],
    )
    nxt = np.asarray(logits)[:, -1].argmax(-1)
    if eos_token_id is not None:
        finished |= nxt == eos_token_id
    out[:, plen] = nxt
    for t in range(plen, total - 1):
        if eos_token_id is not None and finished.all():
            break  # out is already pad-filled to the documented full width
        tok = out[:, t : t + 1].astype(id_dt)
        logits, caches = step(
            model.state.params, caches, jnp.int32(t), [jnp.asarray(tok)]
        )
        nxt = np.asarray(logits)[:, 0].argmax(-1)
        if eos_token_id is not None:
            nxt = np.where(finished, pad_token_id, nxt)
            finished |= nxt == eos_token_id
        out[:, t + 1] = nxt
    return out


def incremental_beam_generate(
    model,
    prompt_ids: np.ndarray,
    *,
    num_beams: int = 4,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    encoder_ids: Optional[np.ndarray] = None,
    static_inputs=(),
    assume_causal: bool = False,
) -> np.ndarray:
    """Beam search over the KV-cache decoder: the decode step is built at
    batch=num_beams (build_decode jits for any batch, so no
    compiled-batch packing), each step feeds ONE position per beam, and on
    a beam reorder the per-layer caches are gathered along the batch axis
    on-device. Scores are sums of log-probs (probability and logit output
    heads both handled — _as_log_probs), no length penalty; samples decode
    sequentially.

    prompt_ids: (n, prompt_len). Returns (n, prompt_len + max_new_tokens)
    top beams. For encoder-decoder models pass encoder_ids (n, enc_len)
    and a prompt of start tokens — each sample's encoder statics and
    cross-attention K/V are computed once at its init."""
    import jax

    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    prompt_ids = np.asarray(prompt_ids)
    plen = prompt_ids.shape[1]
    if max_new_tokens <= 0:
        return prompt_ids.copy()
    in_t = model._fit_input_tensors[-1]
    total = plen + max_new_tokens
    cap = max_len or total
    if cap < total:
        raise ServingConfigError(f"max_len {cap} < prompt+new {total}")
    init_caches, step = model.executor.build_decode(
        num_beams, cap, assume_causal=assume_causal
    )
    id_dt = in_t.data_type.np_dtype
    prob_hint = model.output_probability_like()
    if encoder_ids is not None:
        enc_t = model._fit_input_tensors[0]
        enc_rows = np.asarray(encoder_ids, enc_t.data_type.np_dtype)
        if enc_rows.shape[0] != prompt_ids.shape[0]:
            raise ServingConfigError(
                f"encoder_ids rows {enc_rows.shape[0]} != prompt rows "
                f"{prompt_ids.shape[0]}"
            )

    outs = []
    for i, row in enumerate(prompt_ids.astype(id_dt)):
        if encoder_ids is None:
            # static_inputs (if any) must be shaped for batch=num_beams
            caches = init_caches(model.state.params, list(static_inputs))
        else:
            enc_block = np.broadcast_to(
                enc_rows[i], (num_beams,) + enc_rows[i].shape
            ).copy()
            # static_inputs are the non-decode inputs AFTER the encoder
            # ids (input order), shaped for batch=num_beams
            caches = init_caches(model.state.params,
                                 [enc_block] + list(static_inputs))
        beams = np.full((num_beams, total), pad_token_id, id_dt)
        beams[:, :plen] = row
        scores = np.full(num_beams, -np.inf)
        scores[0] = 0.0  # beams identical until the first branch
        done = np.zeros(num_beams, bool)
        # prefill: same prompt in every beam slot, one block step
        block = np.broadcast_to(row, (num_beams, plen)).copy()
        logits, caches = step(model.state.params, caches, jnp.int32(0),
                              [jnp.asarray(block)])
        logp = _as_log_probs(np.asarray(logits)[:, -1], prob_hint)
        for t in range(plen, total):
            src_beams, toks, scores = _beam_topk(
                scores, logp, done, pad_token_id, num_beams
            )
            beams = beams[src_beams]
            beams[:, t] = np.where(done[src_beams], pad_token_id, toks)
            if eos_token_id is not None:
                done = done[src_beams] | (beams[:, t] == eos_token_id)
            # per-beam caches follow their beams (identity gathers are
            # common early on; jnp.take keeps the shuffle on-device).
            # "static" and "mha_static" (cross-attention encoder K/V) stay
            # untouched: they are beam-invariant, and constant-derived
            # static entries have leading axis 1 — a batch gather would
            # fill out-of-bounds rows with NaN.
            idx = jnp.asarray(src_beams.astype(np.int32))
            gathered = jax.tree_util.tree_map(
                lambda c: jnp.take(c, idx, axis=0),
                {"prefix": caches["prefix"], "mha": caches["mha"]},
            )
            caches = {"static": caches["static"],
                      "mha_static": caches["mha_static"], **gathered}
            if (eos_token_id is not None and done.all()) or t == total - 1:
                break
            logits, caches = step(
                model.state.params, caches, jnp.int32(t),
                [jnp.asarray(beams[:, t : t + 1])],
            )
            logp = _as_log_probs(np.asarray(logits)[:, 0], prob_hint)
        outs.append(beams[0])
    return np.stack(outs)


def _log_softmax(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=-1, keepdims=True)
    e = np.exp(x - m)
    return (x - m) - np.log(e.sum(axis=-1, keepdims=True))


def _as_log_probs(x: np.ndarray,
                  probability: Optional[bool] = None) -> np.ndarray:
    """Model outputs may be PROBABILITIES (the framework convention: CE
    models end in softmax/sigmoid) or raw logits (imported heads).
    log-softmax of probabilities is NOT log(p) — it flattens every gap to
    <1 nat and corrupts beam accumulation. The caller passes the answer
    from the graph's tail op (model.output_probability_like()); the
    numeric sniff (non-negative rows summing to ~1) is only the fallback
    for the undetermined case — bf16 softmax heads over large vocabs can
    drift past its tolerance, so the structural answer wins."""
    if probability is None:
        probability = bool(
            (x >= 0).all() and np.allclose(x.sum(axis=-1), 1.0, atol=1e-3)
        )
    if probability:
        return np.log(np.clip(x, 1e-30, None))
    return _log_softmax(x)


def _beam_topk(scores, logp, done, pad_token_id, num_beams):
    """One beam-search selection step, shared by beam_generate and
    incremental_beam_generate: finished beams propagate unchanged via a
    single pad candidate; top-k via argpartition (O(n), no full sort)."""
    vocab = logp.shape[-1]
    cand = scores[:, None] + np.where(done[:, None], -np.inf, logp)
    for b in np.nonzero(done)[0]:
        cand[b, pad_token_id] = scores[b]
    flat = np.argpartition(cand.ravel(), -num_beams)[-num_beams:]
    flat = flat[np.argsort(cand.ravel()[flat])[::-1]]
    return flat // vocab, flat % vocab, cand.ravel()[flat]


def beam_generate(
    model,
    encoder_ids: np.ndarray,
    *,
    num_beams: int = 4,
    max_new_tokens: Optional[int] = None,
    start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
) -> np.ndarray:
    """Beam-search decode over the same compiled forward as greedy_generate
    (scores are sum of per-token log-probs; no length penalty). Each step
    runs the beams of ONE sample as a batch-shaped forward, so the
    compiled batch size must be >= num_beams; samples decode sequentially.
    num_beams=1 degenerates to greedy."""
    if model.executor is None:
        raise NotCompiledError("compile() the model first")
    fwd = model.executor.build_forward()
    enc_t, dec_t = model._fit_input_tensors[:2]
    bs, dec_len = dec_t.dims[0], dec_t.dims[1]
    if num_beams > bs:
        raise ServingConfigError(
            f"num_beams {num_beams} > compiled batch {bs}; recompile with a "
            "larger batch"
        )
    if tuple(encoder_ids.shape[1:]) != tuple(enc_t.dims[1:]):
        raise ServingConfigError(
            f"encoder_ids row shape {tuple(encoder_ids.shape[1:])} != "
            f"compiled {tuple(enc_t.dims[1:])}"
        )
    want = dec_len - 1 if max_new_tokens is None else max_new_tokens
    steps = min(want, dec_len - 1)
    n_rows = encoder_ids.shape[0]
    if steps <= 0:
        return np.full((n_rows, 1), start_token_id, dec_t.data_type.np_dtype)
    prob_hint = model.output_probability_like()

    outs = []
    for row in np.asarray(encoder_ids, enc_t.data_type.np_dtype):
        # beams packed into the compiled batch; unused slots repeat beam 0
        enc = np.broadcast_to(row, (bs,) + row.shape).copy()
        beams = np.full((num_beams, dec_len), pad_token_id,
                        dec_t.data_type.np_dtype)
        beams[:, 0] = start_token_id
        scores = np.full(num_beams, -np.inf)
        scores[0] = 0.0  # all beams identical at t=0: keep one alive
        done = np.zeros(num_beams, bool)
        for t in range(steps):
            dec = np.full((bs, dec_len), pad_token_id, beams.dtype)
            dec[:num_beams] = beams
            logp = _as_log_probs(
                np.asarray(fwd(model.state.params, [enc, dec],
                               model.state.net_state))[:num_beams, t],
                prob_hint,
            )
            src, tok, scores = _beam_topk(scores, logp, done, pad_token_id,
                                          num_beams)
            beams = beams[src]
            beams[:, t + 1] = tok
            done = done[src]
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
                if done.all():
                    break
        # fixed width for every sample (early-stopped rows carry pad after
        # EOS) so the batch stacks even when samples finish at different t
        outs.append(beams[int(np.argmax(scores)), : steps + 1])
    return np.stack(outs, axis=0)


# ----------------------------------------------------------------------
# typed admission failures — every non-admitted request gets one of these
# (and a ff_serving_shed_total increment); silence is a bug
# ----------------------------------------------------------------------
class RequestShedError(ResilienceError):
    """The serving runtime refused (or abandoned) a request on purpose —
    load shedding, not a fault. NOT a TimeoutError subclass: the default
    RetryPolicy must not hammer an overloaded service with retries."""

    reason = "shed"

    def __init__(self, msg: str, *, reason: Optional[str] = None):
        super().__init__(msg)
        if reason is not None:
            self.reason = reason


class DeadlineExceededError(RequestShedError):
    """The request's deadline passed (or provably cannot be met) before
    a result was produced — whether it was still queued, being admitted,
    or mid-decode. `stage` says where along the pipeline it died."""

    reason = "deadline"

    def __init__(self, msg: str, *, stage: str = "queue"):
        super().__init__(msg)
        self.stage = stage


class QueueFullError(RequestShedError):
    """The bounded admission queue is at capacity — the canonical
    overload signal. Clients should back off; the server stays live."""

    reason = "queue_full"


class RateLimitedError(RequestShedError):
    """The token-bucket rate limiter is empty: offered load exceeds the
    (possibly p95-adapted) sustainable rate."""

    reason = "rate_limited"


class ReplicaDeathError(ResilienceError):
    """A serving replica crashed (or the ``replica_death`` fault site
    simulated it). Raised inside the replica's serve loop; the
    ReplicaSet requeues its in-flight work and restarts it."""


def _shed(reason: str, n: float = 1.0) -> None:
    from .. import obs

    obs.count("ff_serving_shed_total", n,
              help="requests shed by admission control/deadlines",
              reason=reason)


# ----------------------------------------------------------------------
# serving configuration
# ----------------------------------------------------------------------
@dataclasses.dataclass
class ServingConfig:
    """Knobs for the continuous-batching runtime (docs/serving.md).

    `max_len` caps prompt+generated tokens per sequence (the decode
    cache width); `slots` is the in-flight sequence count per replica
    (the decode batch). KV paging defaults to exactly covering
    `slots` full-length sequences — set `num_pages` smaller to exercise
    admission backpressure, larger for headroom. `rate_limit` (req/s)
    enables the token bucket; with `adaptive_rate` its refill follows
    the p95 of `ff_serving_latency_seconds` via AIMD toward
    `target_p95_s`."""

    max_len: int
    slots: int = 4
    page_size: int = 16
    num_pages: Optional[int] = None
    watermark: float = 0.0
    # content-addressed prefix sharing (docs/serving.md "Prefix
    # sharing"): admission attaches already-published prompt pages
    # refcounted (discounting them from the KV charge) and publishes
    # this prompt's full blocks for later arrivals. Exactness is
    # unconditional — shared pages are immutable by construction
    # (copy-on-write in the pool is the enforced safety valve).
    share_prefixes: bool = True
    # prompts memoized for exact prefill-FLOP skipping (LRU entries of
    # (bucket, prompt) -> prefilled cache strip); 0 disables the skip
    # while keeping page-level dedup
    prefix_cache_entries: int = 8
    max_queue_depth: int = 64
    default_deadline_s: float = 30.0
    default_max_new_tokens: int = 16
    rate_limit: Optional[float] = None
    rate_burst: int = 8
    adaptive_rate: bool = False
    target_p95_s: float = 1.0
    # SLO targets (obs/request_trace.SLOMonitor): completed requests are
    # judged against these; violations count in ff_slo_violations_total
    # and a sustained violation fraction scales the ReplicaSet up. None
    # disables the corresponding check.
    slo_ttft_s: Optional[float] = None
    slo_p99_s: Optional[float] = None
    eos_token_id: Optional[int] = None
    assume_causal: bool = False
    # disaggregated prefill/decode: when set (and the model has no
    # decode-searched strategy yet), compile_decode() imports this
    # strategy file so the batched decode step lowers from the
    # decode-objective strategy while prefill keeps the train-searched
    # (compute-bound) one. Ignored when model.decode_executor exists.
    decode_strategy_path: Optional[str] = None
    # online decode re-search (the StrategyTuner's serving leg,
    # docs/adaptation.md): when the admitted prompt-length distribution
    # drifts more than decode_retune_threshold (relative to the
    # distribution observed around the last decode build) across at
    # least decode_retune_min_admissions requests, the batcher re-runs
    # compile_decode() between batches (active_slots == 0 only — the
    # running batch's caches belong to the old lowering) and hot-swaps
    # the batched decode step. The existing _decode_executor_mismatch
    # probe vets the candidate; any incompatibility falls back to the
    # current decode step (the rollback path), and either way the
    # attempt lands in ff_strategy_swaps_total{leg="serving"}.
    decode_retune: bool = False
    decode_retune_threshold: float = 0.5
    decode_retune_min_admissions: int = 8
    decode_retune_cooldown_iters: int = 50
    idle_wait_s: float = 0.005
    # compile every decode executable (all prefill buckets + the batched
    # step) when the replica boots, BEFORE it takes traffic: a mid-run
    # jit compile stalls the whole running batch (and on a shared-core
    # CPU harness can starve sibling replicas into watchdog failovers)
    precompile: bool = True

    def __post_init__(self):
        if self.max_len <= 1:
            raise ServingConfigError(f"max_len must be > 1: {self.max_len}")
        if self.slots <= 0:
            raise ServingConfigError(f"slots must be positive: {self.slots}")
        if self.max_queue_depth <= 0:
            raise ServingConfigError(
                f"max_queue_depth must be positive: {self.max_queue_depth}"
            )

    def kv_config(self) -> KVCacheConfig:
        cfg = KVCacheConfig(num_pages=1, page_size=self.page_size)
        pages = self.num_pages
        if pages is None:
            pages = self.slots * cfg.pages_for(self.max_len)
        return KVCacheConfig(num_pages=pages, page_size=self.page_size,
                             watermark=self.watermark)


class GenerationRequest:
    """One decode request: prompt ids in, prompt+generated ids out.

    Completion is exactly-once and owner-checked: a failover requeue
    bumps `generation`, so a stalled replica that later wakes up cannot
    publish a result for work that was handed to a sibling. Callers
    block on `result()`, which raises the request's TYPED error (shed /
    deadline / abort) instead of returning garbage or hanging."""

    def __init__(self, prompt: np.ndarray, max_new_tokens: int, *,
                 deadline_s: float = 30.0):
        self.id = uuid.uuid4().hex[:12]
        self.prompt = np.asarray(prompt)
        if self.prompt.ndim != 1:
            raise ServingConfigError(
                f"prompt must be a 1-D token array, got shape "
                f"{self.prompt.shape}"
            )
        self.max_new_tokens = int(max_new_tokens)
        self.submitted_t = time.monotonic()
        self.deadline = self.submitted_t + float(deadline_s)
        self.admitted_t: Optional[float] = None  # last slot admission
        self.first_token_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.generation = 0  # bumped on failover requeue
        # flight recorder (obs/request_trace.py): ReplicaSet.submit /
        # AdmissionQueue.offer mint a sampled context; the shared null
        # object keeps the unsampled path allocation-free
        self.trace = NULL_REQUEST_TRACE
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.tokens: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None

    # -- completion (exactly once, owner-checked) ------------------------
    def _finish(self, *, tokens: Optional[np.ndarray] = None,
                error: Optional[BaseException] = None,
                generation: Optional[int] = None) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            if generation is not None and generation != self.generation:
                return False  # requeued to another replica meanwhile
            self.tokens = tokens
            self.error = error
            self.finished_t = time.monotonic()
            self._event.set()
            return True

    def _requeue_bump(self) -> Optional[int]:
        """Take ownership away from a dead replica; returns the new
        generation, or None when the request already finished."""
        with self._lock:
            if self._event.is_set():
                return None
            self.generation += 1
            return self.generation

    # -- client API ------------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        from .resilience import InferenceTimeout

        if not self._event.wait(timeout):
            raise InferenceTimeout(
                f"request {self.id} unanswered after {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return self.tokens


class TokenBucket:
    """Classic token bucket with an AIMD-adaptable refill rate: the
    additive-increase/multiplicative-decrease loop (`adapt`) follows the
    serving p95 toward a latency target, so sustained overload tightens
    admission instead of growing the queue without bound."""

    def __init__(self, rate: float, burst: int, *,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(1, int(burst))
        self._clock = clock
        self._tokens = float(self.burst)
        self._last = clock()
        self._lock = threading.Lock()
        self.min_rate = max(0.1, self.rate / 64.0)
        self.max_rate = self.rate * 16.0

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def adapt(self, p95_s: float, target_p95_s: float) -> float:
        """One AIMD step: p95 over target multiplicatively cuts the
        refill; under target additively grows it back. Returns the new
        rate (also exported as ff_serving_admission_rate)."""
        from .. import obs

        with self._lock:
            if p95_s == p95_s:  # NaN = no samples yet: leave the rate be
                if p95_s > target_p95_s:
                    self.rate = max(self.min_rate, self.rate * 0.7)
                else:
                    self.rate = min(self.max_rate, self.rate + 1.0)
            rate = self.rate
        obs.gauge_set("ff_serving_admission_rate", rate,
                      help="token-bucket refill rate (requests/s)")
        return rate


class AdmissionQueue:
    """Bounded FIFO shared by every replica's batcher. `offer` sheds at
    enqueue (queue full / dead-on-arrival deadline); `poll` sheds
    expired requests at dequeue so a blown deadline is never executed
    on-device; `requeue` (failover) pushes to the FRONT and is exempt
    from the bound — admitted work is never dropped by its own rescue."""

    def __init__(self, max_depth: int):
        self.max_depth = max_depth
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def _export_depth(self) -> None:
        from .. import obs

        obs.gauge_set("ff_serving_queue_depth", len(self),
                      help="requests waiting for a decode slot")

    def offer(self, req: GenerationRequest) -> None:
        if req.trace is NULL_REQUEST_TRACE:
            # direct-queue callers (no ReplicaSet) still get a flight
            # recorder; the mint is deterministic per id, so a request
            # already judged unsampled stays unsampled
            req.trace = mint_request_trace(req.id)
        now = time.monotonic()
        if now >= req.deadline:
            err = DeadlineExceededError(
                f"request {req.id} dead on arrival "
                f"({now - req.deadline:.3f}s past deadline)", stage="enqueue",
            )
            _shed("deadline")
            req.trace.shed("deadline", stage="enqueue")
            req._finish(error=err)
            raise err
        with self._lock:
            if len(self._q) >= self.max_depth:
                full = QueueFullError(
                    f"admission queue at capacity ({self.max_depth})"
                )
                _shed("queue_full")
                req.trace.shed("queue_full", stage="enqueue")
                req._finish(error=full)
                raise full
            req.trace.queue_begin(depth=len(self._q))
            self._q.append(req)
            self._nonempty.notify()
        self._export_depth()

    def requeue(self, req: GenerationRequest) -> None:
        with self._lock:
            self._q.appendleft(req)
            self._nonempty.notify()
        self._export_depth()

    def poll(self, timeout: float = 0.0) -> Optional[GenerationRequest]:
        """Next live request, shedding expired ones at dequeue (typed
        error + counter — the satellite-fix semantics: a request that
        blew its deadline while queued must not reach the device)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                while self._q:
                    req = self._q.popleft()
                    if req.done():
                        continue  # aborted/shed elsewhere
                    now = time.monotonic()
                    if now >= req.deadline:
                        _shed("deadline")
                        req.trace.shed("deadline", stage="dequeue")
                        req._finish(error=DeadlineExceededError(
                            f"request {req.id} expired in queue "
                            f"({now - req.deadline:.3f}s past deadline)",
                            stage="dequeue",
                        ))
                        continue
                    self._export_depth_locked()
                    return req
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._nonempty.wait(remaining)

    def _export_depth_locked(self) -> None:
        from .. import obs

        obs.gauge_set("ff_serving_queue_depth", len(self._q),
                      help="requests waiting for a decode slot")

    def drain(self, error_factory) -> int:
        """Fail every queued request with a typed error (shutdown path —
        zero silent drops). Returns the number drained."""
        with self._lock:
            pending = list(self._q)
            self._q.clear()
        n = 0
        for req in pending:
            if req._finish(error=error_factory(req)):
                _shed("aborted")
                n += 1
        self._export_depth()
        return n


# ----------------------------------------------------------------------
# continuous (in-flight) batching
# ----------------------------------------------------------------------
@dataclasses.dataclass
class _Slot:
    req: GenerationRequest
    generation: int
    seq_key: str
    tokens: List[int]
    prompt_len: int
    pos: int  # cache positions written == len(tokens) - 1


class ContinuousBatcher:
    """Iteration-level decode scheduler for ONE replica (Orca-style): a
    running batch of `config.slots` sequences, each at its own position
    (the per-slot `t` vector of executor.build_decode). Every iteration:

      1. retire finished slots (EOS / max_new_tokens / blown deadline)
         and release their KV pages;
      2. admit queued requests into free slots — deadline re-checked at
         dequeue, KV pages reserved worst-case (backpressure when the
         pool can't cover it; typed shed when it never could), prompt
         prefilled through a batch-1 decode step bucketed to powers of
         two (bounds recompilation), and the prefilled cache strip
         inserted into the running batch;
      3. run ONE batched decode step for every active slot.

    Decoder-only models only (one graph input): encoder-decoder graphs
    compute per-request encoder statics that a shared running batch
    cannot represent — those go through BatchScheduler.

    Faults: ``replica_death`` raises out of the loop (the ReplicaSet
    requeues + restarts), ``slow_worker`` stalls an iteration inside the
    health-monitored step window so the watchdog sees a hung step,
    ``kv_exhaustion`` fires in the page pool."""

    def __init__(self, model, config: ServingConfig,
                 queue_: AdmissionQueue, *,
                 name: str = "replica0",
                 pool: Optional[PagePool] = None,
                 fault_injector=None,
                 monitor=None,
                 on_dead: Optional[Callable] = None,
                 device_lock: Optional[threading.RLock] = None,
                 slo: Optional[SLOMonitor] = None):
        if model.executor is None:
            raise NotCompiledError("compile() the model first")
        if len(model._fit_input_tensors) != 1:
            raise ServingConfigError(
                "continuous batching serves decoder-only models (one graph "
                "input); use BatchScheduler/incremental_seq2seq_generate "
                "for encoder-decoder graphs"
            )
        self.model = model
        self.config = config
        self.queue = queue_
        self.name = name
        self.fault_injector = fault_injector
        self.monitor = monitor
        self.on_dead = on_dead
        self.slo = slo  # shared SLOMonitor (ReplicaSet-owned), or None
        self.pool = pool or PagePool(config.kv_config(),
                                     fault_injector=fault_injector)
        # ALL in-process replicas must funnel device work through one
        # lock: concurrent jitted executions + compiles from sibling
        # threads can wedge the single-process CPU backend (and on a
        # shared core buy nothing anyway) — production replicas live in
        # separate processes and never contend here
        self._device_lock = device_lock or threading.RLock()
        ex = model.executor
        # prefill ALWAYS lowers from the train-searched (compute-bound)
        # strategy: a prompt is a full-sequence forward, exactly the
        # shape the training objective priced
        self._init1, self._step1 = ex.build_decode(
            1, config.max_len, assume_causal=config.assume_causal
        )
        # batched decode prefers the decode-searched strategy (HBM
        # roofline objective) when one exists / is configured AND its
        # cache pytree is splice-compatible with the prefill lowering —
        # _insert_slot_locked copies prefill caches leaf-by-leaf into
        # the running batch, so the two lowerings must agree on cache
        # structure. Anything else falls back to the training executor
        # (counted, warned once).
        self.decode_strategy_active = False
        dex = getattr(model, "decode_executor", None)
        if dex is None and config.decode_strategy_path:
            model.compile_decode(strategy_path=config.decode_strategy_path)
            dex = model.decode_executor
        initB, stepB = ex.build_decode(
            config.slots, config.max_len, assume_causal=config.assume_causal
        )
        if dex is not None:
            from ..parallel.decode import (DecodeExactnessError,
                                           decode_fallback)
            try:
                initB_d, stepB_d = dex.build_decode(
                    config.slots, config.max_len,
                    assume_causal=config.assume_causal,
                )
                problem = self._decode_executor_mismatch(dex, initB_d)
                if problem is not None:
                    decode_fallback(self.name, "decode_strategy_incompatible",
                                    problem)
                else:
                    initB, stepB = initB_d, stepB_d
                    self.decode_strategy_active = True
            except DecodeExactnessError as e:
                decode_fallback(self.name, "decode_strategy_unbuildable",
                                str(e))
        self._initB, self._stepB = initB, stepB
        in_t = model._fit_input_tensors[-1]
        self._id_dt = in_t.data_type.np_dtype
        self._caches = None
        self.slots: List[Optional[_Slot]] = [None] * config.slots
        self._stop = threading.Event()
        self.dead = False
        self.death_cause: Optional[BaseException] = None
        self.draining = False
        self._thread: Optional[threading.Thread] = None
        self._iteration = 0
        self._admit_seq = 0  # per-admission nonce: pool keys stay unique
        # even if a request is ever double-admitted across a failover race
        # slot teardown mutex: _release and _strand_slots TAKE the slot
        # under this lock before touching the pool, so a wedged serve
        # thread waking up mid-steal and the watchdog can never both
        # release the same seq_key (pool double-release is typed now)
        self._teardown_lock = threading.Lock()
        # exact prefill-skip memo: (bucket, prompt bytes) -> (first
        # token, batch-1 cache strip). Identical prompt + identical
        # params reproduce the identical strip, so replaying it is
        # bit-exact; bounded LRU, invalidated on decode retune.
        self._prefix_cache: "OrderedDict" = OrderedDict()
        # per-token service-time EWMA drives the "cannot meet deadline"
        # early shed; warms up after the first measured iterations
        self._token_ewma_s: Optional[float] = None
        # decode-retune drift watch: admitted prompt-length EWMA vs the
        # distribution frozen at the last decode build (tuner serving leg)
        self._plen_ewma: Optional[float] = None
        self._plen_at_build: Optional[float] = None
        self._plen_admissions = 0
        self._retune_cooldown_until = 0
        self.stats = {"admitted": 0, "finished": 0, "iterations": 0,
                      "prefills": 0, "retired_eos": 0, "shed_decode": 0,
                      "stranded_requeued": 0, "decode_retunes": 0,
                      "prefix_hits": 0, "prefill_skips": 0}

    def _decode_executor_mismatch(self, dex, initB_d) -> Optional[str]:
        """None if the decode-searched lowering can serve the batched
        step, else a human-readable reason. Two lowerings are
        splice-compatible when (a) every weight-bearing op in the decode
        graph finds its weights in the (training) param store by op
        name, and (b) the decode-build's cache pytree matches the
        prefill build's section-by-section: guid-keyed 'static'/'prefix'
        sections must agree (guids differ across lowerings, so in
        practice both must be empty — true for decoder-only fused-MHA
        graphs), 'mha' sections must cover the same op names with the
        same per-slot leaf shapes. Probed with jax.eval_shape — no cache
        allocation happens here."""
        params = (self.model.state.params
                  if getattr(self.model, "state", None) is not None else None)
        if params is not None:
            missing = [op.name for op in dex.topo
                       if op.weights and not op.is_parallel_op
                       and op.name not in params]
            if missing:
                return (f"decode graph ops {missing} have no weights in the "
                        f"model's param store")
        try:
            dec = jax.eval_shape(initB_d, params, ())
            pre = jax.eval_shape(self._init1, params, ())
        except Exception as e:
            return f"cache shape probe failed: {e}"
        for section in ("static", "prefix", "mha_static"):
            d_keys = set(dec.get(section, {}))
            p_keys = set(pre.get(section, {}))
            if d_keys != p_keys:
                return (f"{section!r} cache keys differ between the decode- "
                        f"and train-searched lowerings "
                        f"({len(d_keys)} vs {len(p_keys)} entries)")
        if set(dec["mha"]) != set(pre["mha"]):
            return ("attention cache op names differ between the decode- "
                    "and train-searched lowerings")
        for name, dleaves in dec["mha"].items():
            dflat, dtree = jax.tree_util.tree_flatten(dleaves)
            pflat, ptree = jax.tree_util.tree_flatten(pre["mha"][name])
            if dtree != ptree:
                return f"attention cache structure differs for {name!r}"
            for a, b in zip(dflat, pflat):
                if a.shape[1:] != b.shape[1:] or a.dtype != b.dtype:
                    return (f"attention cache leaf mismatch for {name!r}: "
                            f"{a.shape}/{a.dtype} vs {b.shape}/{b.dtype}")
        return None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve_loop, daemon=True,
                name=f"ff-serve-{self.name}",
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def thread_alive(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self.dead)

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def in_flight(self) -> List[_Slot]:
        return [s for s in self.slots if s is not None]

    # -- admission -------------------------------------------------------
    def _bucket(self, plen: int) -> int:
        b = 1
        while b < plen:
            b *= 2
        return min(b, self.config.max_len)

    def _reserve_tokens(self, plen: int, max_new: int) -> int:
        # prefill touches the whole padded bucket; decode grows to
        # plen + max_new - 1 written positions (the last sampled token's
        # K/V is never appended). Reserve the max so growth can't stall.
        return min(self.config.max_len,
                   max(self._bucket(plen), plen + max_new))

    def _try_admit_one(self) -> bool:
        req = self.queue.poll(timeout=0.0)
        if req is None:
            return False
        from .. import obs

        now = time.monotonic()
        plen = len(req.prompt)
        total = plen + req.max_new_tokens
        if plen < 1 or total > self.config.max_len:
            err = RequestShedError(
                f"request {req.id}: prompt {plen} + max_new "
                f"{req.max_new_tokens} exceeds max_len "
                f"{self.config.max_len}", reason="too_long",
            )
            _shed("too_long")
            req.trace.shed("too_long", stage="admit", replica=self.name)
            req._finish(error=err)
            return True
        # early shed: with a warmed service-time estimate, a request
        # whose decode provably outlives its deadline never runs
        if self._token_ewma_s is not None:
            eta = now + req.max_new_tokens * self._token_ewma_s
            if eta > req.deadline:
                _shed("deadline")
                req.trace.shed("deadline", stage="admit",
                               replica=self.name)
                req._finish(error=DeadlineExceededError(
                    f"request {req.id} cannot meet its deadline: needs "
                    f"~{req.max_new_tokens * self._token_ewma_s:.3f}s, has "
                    f"{max(0.0, req.deadline - now):.3f}s", stage="admit",
                ))
                return True
        generation = req.generation
        self._admit_seq += 1
        seq_key = f"{req.id}:{generation}:{self.name}:{self._admit_seq}"
        share = self.config.share_prefixes
        prompt_tokens = req.prompt.tolist() if share else None
        try:
            # with the prompt given, reserve() attaches published prefix
            # pages refcounted and only charges the unshared remainder —
            # the dedup that lets N same-prefix sessions share one pool
            rr = self.pool.reserve(
                seq_key, self._reserve_tokens(plen, req.max_new_tokens),
                tokens=prompt_tokens)
        except KVCacheExhaustedError as e:
            if e.never_fits:
                _shed("kv_exhausted")
                req.trace.shed("kv_exhausted", stage="admit",
                               replica=self.name,
                               pages_needed=e.pages_needed)
                # a can-NEVER-fit request is a sizing bug, not
                # backpressure — worth a forensics bundle (deduped per
                # exception; backpressure requeues below stay silent)
                obs.record_failure(e, replica=self.name,
                                   request=req.id,
                                   kv_snapshot=self.pool.snapshot())
                req._finish(error=RequestShedError(
                    f"request {req.id} can never fit the KV page pool: "
                    f"{e}", reason="kv_exhausted",
                ))
                return True
            # backpressure: put it back and wait for retirements
            self.queue.requeue(req)
            req.trace.event("kv_backpressure", replica=self.name,
                            pages_needed=e.pages_needed,
                            pages_free=e.pages_free)
            obs.event("serving_kv_backpressure", cat="serving",
                      replica=self.name, request=req.id,
                      pages_needed=e.pages_needed, pages_free=e.pages_free)
            return False
        slot_idx = self.slots.index(None)
        bucket = self._bucket(plen)
        req.admitted_t = time.monotonic()
        req.trace.admitted(self.name, generation=generation,
                           slot=slot_idx, prompt_len=plen)
        if rr.shared_pages:
            self.stats["prefix_hits"] += 1
        if req.trace.sampled:
            req.trace.event("kv_reserve", replica=self.name,
                            pages=rr.pages, shared=rr.shared_pages,
                            **self.pool.snapshot())
        cache_key = ((bucket, req.prompt.astype(self._id_dt).tobytes())
                     if share and self.config.prefix_cache_entries > 0
                     else None)
        cached = (self._prefix_cache.get(cache_key)
                  if cache_key is not None else None)
        prefill_span = req.trace.span("prefill", replica=self.name,
                                      bucket=bucket, prompt_len=plen,
                                      skipped=cached is not None)
        try:
            if cached is not None:
                # exact FLOP skip: this verbatim prompt was prefilled
                # before under the same params, so its strip (and first
                # token) are bit-identical — replay instead of compute
                first, caches1 = cached
                self._prefix_cache.move_to_end(cache_key)
                self.stats["prefill_skips"] += 1
            else:
                first, caches1 = self._prefill(req, plen)
                if cache_key is not None:
                    self._prefix_cache[cache_key] = (first, caches1)
                    while (len(self._prefix_cache)
                           > self.config.prefix_cache_entries):
                        self._prefix_cache.popitem(last=False)
        except BaseException:
            self.pool.release(seq_key)
            raise
        self._insert_slot(slot_idx, caches1)
        prefill_span.done()
        req.first_token_t = time.monotonic()
        obs.observe("ff_serving_ttft_seconds",
                    req.first_token_t - req.submitted_t,
                    help="time from submit to first generated token")
        slot = _Slot(req=req, generation=generation, seq_key=seq_key,
                     tokens=list(req.prompt.tolist()) + [first],
                     prompt_len=plen, pos=plen)
        self.pool.touch(seq_key, bucket)
        if share:
            # make this prompt's full pages content-addressable so
            # later same-prefix admissions attach instead of allocating
            self.pool.publish(seq_key, prompt_tokens)
        self.slots[slot_idx] = slot
        self.stats["admitted"] += 1
        self.stats["prefills"] += 1
        self._note_admitted_plen(plen)
        self._maybe_retire(slot_idx)
        return True

    def _prefill(self, req: GenerationRequest, plen: int):
        """Run the prompt through the batch-1 decode step, padded to a
        power-of-two bucket (bounds distinct jit shapes to log2(max_len)).
        The padded tail's garbage K/V sits at positions >= plen, which
        decode overwrites position-by-position before the causal mask
        ever exposes them."""
        bucket = self._bucket(plen)
        padded = np.zeros((1, bucket), self._id_dt)
        padded[0, :plen] = req.prompt.astype(self._id_dt)
        with self._device_lock:
            caches1 = self._init1(self.model.state.params, ())
            logits, caches1 = self._step1(
                self.model.state.params, caches1, jnp.int32(0),
                [jnp.asarray(padded)],
            )
            first = int(np.asarray(logits)[0, plen - 1].argmax(-1))
        return first, caches1

    def _insert_slot(self, slot_idx: int, caches1) -> None:
        """Swap a prefilled batch-1 cache strip into the running batch:
        every per-slot cache leaf is written wholesale at `slot_idx`, so
        whatever a previous occupant left there is fully replaced."""
        import jax

        with self._device_lock:
            self._insert_slot_locked(jax, slot_idx, caches1)

    def _insert_slot_locked(self, jax, slot_idx: int, caches1) -> None:
        if self._caches is None:
            self._caches = self._initB(self.model.state.params, ())
        caches = self._caches
        out = {"static": caches["static"], "mha_static": caches["mha_static"],
               "prefix": {}, "mha": {}}
        for g, c in caches["prefix"].items():
            row = caches1["prefix"][g]
            if tuple(c.shape) != (self.config.slots,) + tuple(row.shape[1:]):
                raise ServingConfigError(
                    f"prefix cache guid {g} has no per-slot leading axis "
                    f"(batch shape {tuple(c.shape)} vs row "
                    f"{tuple(row.shape)}) — this graph folds batch with "
                    "another axis and cannot be continuously batched"
                )
            out["prefix"][g] = jax.lax.dynamic_update_slice_in_dim(
                c, row.astype(c.dtype), slot_idx, axis=0
            )
        for opname, kv in caches["mha"].items():
            k1, v1 = caches1["mha"][opname]
            kB, vB = kv
            out["mha"][opname] = (
                jax.lax.dynamic_update_slice_in_dim(
                    kB, k1.astype(kB.dtype), slot_idx, axis=0),
                jax.lax.dynamic_update_slice_in_dim(
                    vB, v1.astype(vB.dtype), slot_idx, axis=0),
            )
        self._caches = out

    # -- retirement ------------------------------------------------------
    def _release(self, slot_idx: int) -> None:
        # take-then-release: whoever swaps the slot out owns the ONE
        # pool release for its seq_key (double release is typed now)
        with self._teardown_lock:
            slot = self.slots[slot_idx]
            self.slots[slot_idx] = None
        if slot is not None:
            freed = self.pool.release(slot.seq_key)
            if slot.req.trace.sampled:
                slot.req.trace.event("kv_release", replica=self.name,
                                     pages=freed, **self.pool.snapshot())

    def _finish_slot(self, slot_idx: int) -> None:
        from .. import obs

        slot = self.slots[slot_idx]
        generated = len(slot.tokens) - slot.prompt_len
        ok = slot.req._finish(tokens=np.asarray(slot.tokens, self._id_dt),
                              generation=slot.generation)
        if ok:
            latency = time.monotonic() - slot.req.submitted_t
            obs.observe("ff_serving_latency_seconds", latency,
                        help="end-to-end serving request latency")
            obs.count("ff_serving_requests_total",
                      help="serving requests answered")
            obs.count("ff_serving_tokens_total", generated,
                      help="tokens generated by the serving runtime")
            self.stats["finished"] += 1
            stages = record_request_stages(slot.req, generated=generated,
                                           slo=self.slo, replica=self.name)
            slot.req.trace.completed(
                self.name, generation=slot.generation, tokens=generated,
                **{f"{k}_s": round(v, 6) for k, v in stages.items()},
            )
        self._release(slot_idx)

    def _maybe_retire(self, slot_idx: int) -> None:
        slot = self.slots[slot_idx]
        if slot is None:
            return
        if slot.req.done():  # aborted / requeued elsewhere
            self._release(slot_idx)
            return
        now = time.monotonic()
        if now > slot.req.deadline:
            _shed("deadline")
            self.stats["shed_decode"] += 1
            slot.req.trace.shed("deadline", stage="decode",
                                replica=self.name,
                                tokens=len(slot.tokens) - slot.prompt_len)
            slot.req._finish(error=DeadlineExceededError(
                f"request {slot.req.id} blew its deadline mid-decode "
                f"after {len(slot.tokens) - slot.prompt_len} token(s)",
                stage="decode",
            ), generation=slot.generation)
            self._release(slot_idx)
            return
        generated = len(slot.tokens) - slot.prompt_len
        eos = self.config.eos_token_id
        if generated >= slot.req.max_new_tokens or (
            eos is not None and slot.tokens[-1] == eos
        ):
            if eos is not None and slot.tokens[-1] == eos:
                self.stats["retired_eos"] += 1
            self._finish_slot(slot_idx)

    # -- the iteration loop ---------------------------------------------
    def _decode_iteration(self) -> None:
        t_vec = np.zeros(self.config.slots, np.int32)
        toks = np.zeros((self.config.slots, 1), self._id_dt)
        active = []
        sampled_any = False
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            active.append(i)
            sampled_any = sampled_any or slot.req.trace.sampled
            t_vec[i] = slot.pos
            toks[i, 0] = slot.tokens[slot.pos]
            if self.config.share_prefixes:
                # protocol guard: this step writes K/V at slot.pos. Only
                # full PROMPT blocks are ever published, and decode
                # positions sit strictly past them, so this is a no-op in
                # steady state — but if a shared page were ever in the
                # write path, the pool copies it private here (COW)
                # instead of letting the write leak into siblings
                self.pool.note_write(slot.seq_key, slot.pos)
        span_t0 = time.perf_counter() if sampled_any else 0.0
        with self._device_lock:
            logits, self._caches = self._stepB(
                self.model.state.params, self._caches, jnp.asarray(t_vec),
                [jnp.asarray(toks)],
            )
            logits = np.asarray(logits)
        span_dur = (time.perf_counter() - span_t0) if sampled_any else 0.0
        occupancy = len(active)
        for i in active:
            slot = self.slots[i]
            if slot is None:
                continue  # taken by a concurrent teardown sweep mid-step
            slot.tokens.append(int(logits[i, 0].argmax(-1)))
            slot.pos += 1
            new_pages = self.pool.touch(
                slot.seq_key, max(self._bucket(slot.prompt_len), slot.pos))
            if slot.req.trace.sampled:
                # one completed span per sampled slot per iteration:
                # slot occupancy + position make decode stalls and
                # batch-sharing visible per request in the Perfetto lane
                slot.req.trace.iteration(
                    self.name, t0=span_t0, dur_s=span_dur,
                    iteration=self._iteration, slot=i, pos=slot.pos,
                    occupancy=occupancy,
                )
                if new_pages:
                    slot.req.trace.event("kv_touch", replica=self.name,
                                         pages=len(new_pages),
                                         pos=slot.pos)
            self._maybe_retire(i)

    def _warmup_compiles(self) -> None:
        """Compile the batched decode step and every prefill bucket on
        throwaway caches before taking traffic. Runs on the serve thread
        under the HealthMonitor's compile grace window; the running batch
        then never waits on XLA mid-request."""
        params = self.model.state.params
        with self._device_lock:
            caches = self._initB(params, ())
            t_vec = jnp.zeros((self.config.slots,), jnp.int32)
            toks = jnp.zeros((self.config.slots, 1), self._id_dt)
            self._stepB(params, caches, t_vec, [toks])
            b = 1
            while True:
                caches1 = self._init1(params, ())
                self._step1(params, caches1,
                            jnp.int32(0), [jnp.zeros((1, b), self._id_dt)])
                if b >= self.config.max_len:
                    break
                b = min(2 * b, self.config.max_len)

    def _strand_slots(self) -> int:
        """Hand every occupied slot back to the shared queue (or shed it
        typed when its deadline is gone) — the dying replica's half of
        failover. The serve thread calls this on ANY dead-exit, so a
        request admitted in the very race window where the ReplicaSet
        declared the replica dead still gets rescued; pool keys carry a
        per-admission nonce, so even a double-handled request can never
        collide in a page pool. Safe to call from the ReplicaSet too
        (stuck-thread steal): slots are taken under the teardown mutex
        so page refs transfer exactly once, and completion stays
        exactly-once via the generation check."""
        from .. import obs

        requeued = 0
        for i in range(len(self.slots)):
            # take-then-release under the teardown mutex: the dying
            # serve thread and a watchdog steal can both sweep, but only
            # the taker decrefs — page ownership transfers exactly once
            with self._teardown_lock:
                slot = self.slots[i]
                self.slots[i] = None
            if slot is None:
                continue
            self.pool.release(slot.seq_key)
            gen = slot.req._requeue_bump()
            if gen is None:
                continue  # finished meanwhile
            if time.monotonic() >= slot.req.deadline:
                _shed("deadline")
                slot.req.trace.shed("deadline", stage="failover",
                                    replica=self.name)
                slot.req._finish(error=DeadlineExceededError(
                    f"request {slot.req.id} expired during replica "
                    "failover", stage="failover",
                ))
                continue
            slot.req.trace.requeued(self.name, generation=gen,
                                    tokens_done=len(slot.tokens)
                                    - slot.prompt_len)
            self.queue.requeue(slot.req)
            requeued += 1
        if requeued:
            self.stats["stranded_requeued"] += requeued
            obs.count("ff_serving_requeues_total", requeued,
                      help="in-flight requests requeued by failover")
        return requeued

    # -- online decode re-search (the StrategyTuner's serving leg) -------
    def _note_admitted_plen(self, plen: int) -> None:
        """Feed one admission's prompt length into the drift watch. The
        first decode_retune_min_admissions requests freeze the baseline
        the later distribution is compared against."""
        if not self.config.decode_retune:
            return
        self._plen_admissions += 1
        self._plen_ewma = (float(plen) if self._plen_ewma is None
                           else 0.8 * self._plen_ewma + 0.2 * float(plen))
        if (self._plen_at_build is None and self._plen_admissions
                >= self.config.decode_retune_min_admissions):
            self._plen_at_build = self._plen_ewma

    def _retune_wanted(self) -> bool:
        cfg = self.config
        if (not cfg.decode_retune
                or self._iteration < self._retune_cooldown_until
                or self._plen_at_build is None
                or self._plen_ewma is None
                or self._plen_admissions < cfg.decode_retune_min_admissions):
            return False
        base = max(1.0, self._plen_at_build)
        return abs(self._plen_ewma - base) / base > cfg.decode_retune_threshold

    def _retune_decode(self) -> None:
        """Re-run the decode-objective strategy search and hot-swap the
        batched decode step. Only called with an empty batch: the live
        caches belong to the outgoing lowering, so they are dropped and
        rebuilt by the next admission's _initB. Any failure keeps the
        current decode step serving (the rollback path is the same
        decode_fallback the boot-time selection uses); every attempt
        lands in ff_strategy_swaps_total{leg="serving"}."""
        from .. import obs
        from ..parallel.decode import DecodeExactnessError, decode_fallback
        from .tuner import SWAP_METRIC, SWAP_METRIC_HELP

        cfg = self.config
        self._retune_cooldown_until = (self._iteration
                                       + cfg.decode_retune_cooldown_iters)
        obs.event("decode_retune_started", cat="serving", replica=self.name,
                  plen_ewma=round(self._plen_ewma or 0.0, 2),
                  plen_at_build=round(self._plen_at_build or 0.0, 2))
        outcome = "rolled_back"
        detail = None
        try:
            with self._device_lock:
                dex = self.model.compile_decode()
                initB_d, stepB_d = dex.build_decode(
                    cfg.slots, cfg.max_len, assume_causal=cfg.assume_causal,
                )
                problem = self._decode_executor_mismatch(dex, initB_d)
                if problem is not None:
                    detail = problem
                    decode_fallback(self.name, "decode_retune_incompatible",
                                    problem)
                else:
                    self._initB, self._stepB = initB_d, stepB_d
                    self._caches = None  # rebuilt by the next admission
                    # memoized strips came from the old serving epoch;
                    # drop them rather than reason about compatibility
                    self._prefix_cache.clear()
                    self.decode_strategy_active = True
                    outcome = "committed"
        except DecodeExactnessError as e:
            detail = str(e)
            decode_fallback(self.name, "decode_retune_unbuildable", str(e))
        except Exception as e:  # fflint: disable=FFL002 — a failed retune must not kill the replica
            detail = str(e)
            logger.warning("decode retune failed on %s; keeping the "
                           "current decode strategy: %s", self.name, e)
        # either way the drift baseline resets to the distribution the
        # retune decision saw — no immediate re-trigger
        self._plen_at_build = self._plen_ewma
        self.stats["decode_retunes"] += 1
        obs.count(SWAP_METRIC, help=SWAP_METRIC_HELP, outcome=outcome,
                  leg="serving")
        obs.event("decode_retune_finished", cat="serving",
                  replica=self.name, outcome=outcome,
                  **({"detail": detail[:200]} if detail else {}))

    def _serve_loop(self) -> None:
        from .. import obs

        try:
            if self.config.precompile:
                with obs.span("serving_warmup", cat="serving",
                              replica=self.name):
                    self._warmup_compiles()
            while not self._stop.is_set() and not self.dead:
                while (not self.draining and None in self.slots
                       and self._try_admit_one()):
                    pass
                if self.fault_injector is not None:
                    if self.fault_injector.fire(
                        "replica_death", self._iteration, replica=self.name
                    ) is not None:
                        raise ReplicaDeathError(
                            f"replica {self.name} death injected at "
                            f"iteration {self._iteration}"
                        )
                if self.active_slots == 0:
                    if self.draining:
                        return
                    if self._retune_wanted():
                        self._retune_decode()
                        continue
                    time.sleep(self.config.idle_wait_s)
                    continue
                it = self._iteration
                if self.monitor is not None:
                    self.monitor.step_started(it)
                t0 = time.monotonic()
                if self.fault_injector is not None:
                    plan = self.fault_injector.fire("slow_worker", it,
                                                    replica=self.name)
                    if plan is not None:
                        # a wedged device/interconnect: the iteration
                        # stalls INSIDE the monitored step window so the
                        # HealthMonitor watchdog sees a hung step
                        time.sleep(float(plan.get("delay_s", 1.0)))
                self._decode_iteration()
                dt = time.monotonic() - t0
                if self.monitor is not None:
                    self.monitor.step_finished(it)
                # each active sequence gains one token per iteration, so
                # the iteration wall time IS the per-token service time
                self._token_ewma_s = (
                    dt if self._token_ewma_s is None
                    else 0.8 * self._token_ewma_s + 0.2 * dt
                )
                self._iteration += 1
                self.stats["iterations"] += 1
                obs.gauge_set("ff_serving_batch_occupancy",
                              self.active_slots,
                              help="occupied decode slots", replica=self.name)
        except BaseException as e:  # replica died: hand off and stop
            self.dead = True
            self.death_cause = e
            logger.exception("serving replica %s died", self.name)
            obs.event("replica_died", cat="serving", replica=self.name,
                      error=type(e).__name__, detail=str(e)[:300])
            self._strand_slots()
            if self.on_dead is not None:
                self.on_dead(self, e)
        else:
            # marked dead externally (watchdog/heartbeat failover) while
            # we were mid-iteration: whatever we still hold goes back to
            # the queue — the ReplicaSet's snapshot may have raced an
            # admission and seen these slots empty
            if self.dead and not self._stop.is_set():
                self._strand_slots()


# ----------------------------------------------------------------------
# multi-replica failover + autoscaling
# ----------------------------------------------------------------------
@dataclasses.dataclass
class _Replica:
    name: str
    model: object
    batcher: ContinuousBatcher
    monitor: object  # runtime.elastic.HealthMonitor


class ReplicaSet:
    """N continuous-batching replicas off ONE shared admission queue.

    * **admission** happens once, at `submit`: rate limiting (token
      bucket, optionally p95-adaptive), then the bounded queue — every
      rejection is typed and counted.
    * **health**: each replica gets a HealthMonitor (runtime/elastic.py)
      watching per-iteration step progress plus a heartbeat probing the
      serve thread; a hung or dead replica triggers failover.
    * **failover**: the dead replica's in-flight requests are requeued
      at the queue FRONT (generation-bumped so the corpse can't publish
      stale results; blown deadlines are shed typed), siblings keep
      draining the queue meanwhile, and a restart thread brings a
      replacement up — from the **warm-spare pool** when one is
      available (`warm_spares`: models built AND decode-precompiled at
      startup, so activation is just a checkpoint restore — an
      in-process rebuild's strategy search would steal the CPU from
      live replicas mid-overload), else a full rebuild through
      ``restore_elastic`` resharding when `ckpt_dir` is given — with
      exponential backoff and a bounded budget.
    * **autoscaling** (optional): queue depth above
      `scale_up_queue_depth` adds replicas up to `max_replicas`; a
      sustained-idle queue retires them down to `min_replicas`."""

    def __init__(self, model_fn: Callable[[], object],
                 config: ServingConfig, *,
                 replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 ckpt_dir: Optional[str] = None,
                 fault_injector=None,
                 health_timeout_s: float = 30.0,
                 compile_grace_s: Optional[float] = None,
                 max_replica_restarts: int = 3,
                 restart_backoff_s: float = 0.2,
                 warm_spares: int = 0,
                 scale_up_queue_depth: Optional[int] = None,
                 scale_down_idle_s: float = 10.0,
                 autoscale_interval_s: float = 0.25,
                 artifact_store=None,
                 fleet_spool_dir: Optional[str] = None):
        self.model_fn = model_fn
        self.config = config
        # strategy/artifact store (runtime/artifact_store.py): every
        # replica/spare build runs under store.ambient(), so the opaque
        # model_fn's compile() reuses searched strategies — warm spares
        # and autoscaler scale-ups boot from the store instead of
        # re-searching
        self.artifact_store = artifact_store
        self.min_replicas = max(1, replicas)
        self.max_replicas = max(self.min_replicas, max_replicas or replicas)
        self.ckpt_dir = ckpt_dir
        self.fault_injector = fault_injector
        self.health_timeout_s = health_timeout_s
        self.compile_grace_s = compile_grace_s
        self.max_replica_restarts = max(0, max_replica_restarts)
        self.restart_backoff_s = restart_backoff_s
        self.warm_spares = max(0, warm_spares)
        self._spares: List[ContinuousBatcher] = []
        # one device lock across every replica (and restart/restore work)
        # in this process — see ContinuousBatcher.__init__
        self._device_lock = threading.RLock()
        self.scale_up_queue_depth = (scale_up_queue_depth
                                     or 2 * config.slots)
        self.scale_down_idle_s = scale_down_idle_s
        self.autoscale_interval_s = autoscale_interval_s
        self.queue = AdmissionQueue(config.max_queue_depth)
        self.bucket: Optional[TokenBucket] = None
        if config.rate_limit is not None:
            self.bucket = TokenBucket(config.rate_limit, config.rate_burst)
        # one SLO monitor across every replica: completion verdicts come
        # from the batchers' _finish_slot, the autoscaler and adaptive
        # admission read it back (obs/request_trace.SLOMonitor)
        self.slo = SLOMonitor(ttft_target_s=config.slo_ttft_s,
                              latency_p99_target_s=config.slo_p99_s)
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}
        self._counter = 0
        self._restarts = 0
        self._pending_restarts = 0
        self._closed = False
        self._started = False
        self._scaler: Optional[threading.Thread] = None
        self._scaler_stop = threading.Event()
        self._idle_since: Optional[float] = None
        self._rate_check = 0
        # local latency reservoir: the adaptive bucket and the load
        # harness read p95 without needing a telemetry session
        from ..obs.metrics import Histogram

        self.latency = Histogram(threading.Lock())
        self.stats = {"submitted": 0, "requeued": 0, "restarts": 0,
                      "spares_used": 0, "scale_ups": 0, "scale_downs": 0,
                      "cold_start_s": []}
        # fleet observatory (obs/fleet.py, obs/anomaly.py): the sentinel
        # watches latency/ttft p95, queue depth, shed rate, KV occupancy
        # and per-replica heartbeat gaps each autoscale tick; scale-ups
        # name the anomaly that preceded them. With fleet_spool_dir set,
        # every replica's counters are spooled per tick — and once more
        # with a terminal status at death/drain — so the cross-process
        # rollup conserves request counts through kills and scale-downs.
        from ..obs.anomaly import AnomalySentinel

        self.sentinel = AnomalySentinel()
        self.fleet_spool_dir = fleet_spool_dir
        self._spools: Dict[str, object] = {}
        # replica name -> (iterations seen, monotonic time it changed)
        self._progress: Dict[str, Tuple[int, float]] = {}
        self._shed_seen = 0.0

    # -- fleet observatory ----------------------------------------------
    @staticmethod
    def _series_rec(name: str, kind: str, value) -> dict:
        if kind == "histogram":
            return {"name": name, "kind": kind, "labels": {},
                    "state": value}
        return {"name": name, "kind": kind, "labels": {},
                "value": float(value)}

    def _replica_series(self, batcher: ContinuousBatcher) -> List[dict]:
        st = batcher.stats
        snap = batcher.pool.snapshot()
        c, g = self._series_rec, self._series_rec
        return [
            c("ff_serving_requests_total", "counter", st["finished"]),
            c("ff_serving_admitted_total", "counter", st["admitted"]),
            c("ff_serving_prefills_total", "counter", st["prefills"]),
            c("ff_serving_shed_decode_total", "counter",
              st["shed_decode"]),
            c("ff_serving_stranded_requeued_total", "counter",
              st["stranded_requeued"]),
            g("ff_serving_active_slots", "gauge", batcher.active_slots),
            g("ff_kv_pages_in_use", "gauge", snap["pages_in_use"]),
            g("ff_kv_pages_shared", "gauge", snap["pages_shared"]),
        ]

    def _write_replica_spool(self, batcher: ContinuousBatcher,
                             status: str = "live") -> None:
        if self.fleet_spool_dir is None:
            return
        from ..obs.fleet import MetricSpool

        sp = self._spools.get(batcher.name)
        if sp is None:
            sp = MetricSpool(self.fleet_spool_dir, batcher.name,
                             replica=batcher.name)
            self._spools[batcher.name] = sp
        try:
            sp.write(series=self._replica_series(batcher), status=status)
        except OSError as e:
            logger.warning("fleet spool write for %s failed (%s)",
                           batcher.name, e)

    def _write_set_spool(self, status: str = "live") -> None:
        if self.fleet_spool_dir is None:
            return
        from ..obs.fleet import MetricSpool

        sp = self._spools.get("replicaset")
        if sp is None:
            sp = MetricSpool(self.fleet_spool_dir, "replicaset")
            self._spools["replicaset"] = sp
        st = self.stats
        rec = self._series_rec
        series = [
            rec("ff_serving_submitted_total", "counter", st["submitted"]),
            rec("ff_serving_requeued_total", "counter", st["requeued"]),
            rec("ff_replica_restarts_total", "counter", st["restarts"]),
            rec("ff_replica_scale_ups_total", "counter", st["scale_ups"]),
            rec("ff_serving_queue_depth", "gauge", len(self.queue)),
            rec("ff_serving_replicas", "gauge", self.replica_count()),
            rec("ff_serving_latency_seconds", "histogram",
                self.latency.state()),
        ]
        try:
            sp.write(series=series, status=status)
        except OSError as e:
            logger.warning("fleet replicaset spool write failed (%s)", e)

    def _observe_fleet(self, depth: int) -> None:
        """One autoscale tick of sentinel feeding + spool refresh. Knob
        choices: hysteresis 1 (the tick itself already integrates over
        the interval, and the scale-up decision wants the anomaly tag
        available the same tick the pressure appears); min_delta floors
        absolute — a queue depth of 1 against an all-zero warm baseline
        is not an incident, a slots-sized jump is; direction "high"
        because a draining queue or falling latency is recovery, and a
        recovery-tagged detector in cooldown would mask the NEXT real
        spike from the scale-up blame window."""
        now = time.monotonic()
        s = self.sentinel
        s.observe("queue_depth", float(depth),
                  min_delta=float(self.config.slots), hysteresis=1,
                  direction="high")
        if self.latency.count >= 8:
            s.observe("serving_latency_p95", self.latency.quantile(0.95),
                      min_delta=0.1, hysteresis=1, direction="high")
        if self.slo.ttft.count >= 8:
            s.observe("ttft_p95", self.slo.ttft.quantile(0.95),
                      min_delta=0.05, hysteresis=1, direction="high")
        with self._lock:
            reps = list(self._replicas.values())
        shed = 0.0
        occupancy = 0.0
        for r in reps:
            b = r.batcher
            shed += b.stats["shed_decode"]
            snap = b.pool.snapshot()
            occupancy = max(occupancy, snap["pages_in_use"]
                            / max(1, b.pool.config.num_pages))
            it = b.stats["iterations"]
            last = self._progress.get(b.name)
            if last is None or last[0] != it:
                self._progress[b.name] = (it, now)
            elif b.thread_alive():
                s.observe_gap(f"replica_heartbeat:{b.name}",
                              now - last[1],
                              limit_s=self.health_timeout_s)
            self._write_replica_spool(b)
        if reps:
            s.observe("kv_occupancy", occupancy, min_delta=0.2,
                      hysteresis=1, direction="high")
        delta = max(0.0, shed - self._shed_seen)
        self._shed_seen = shed
        s.observe("shed_rate",
                  delta / max(self.autoscale_interval_s, 1e-6),
                  min_delta=1.0, hysteresis=1, direction="high")
        self._write_set_spool()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaSet":
        if self._started:
            return self
        self._started = True
        # spares FIRST: built and decode-precompiled while nothing is
        # serving, so a failover — even one in the very first serving
        # iteration — finds them ready and activation costs only a
        # checkpoint restore
        for i in range(self.warm_spares):
            t0 = time.perf_counter()
            with self._store_scope():
                model = self.model_fn()
            self.stats["cold_start_s"].append(time.perf_counter() - t0)
            batcher = self._new_batcher(model, name=f"spare{i}")
            batcher._warmup_compiles()
            with self._lock:
                self._spares.append(batcher)
        for _ in range(self.min_replicas):
            self._add_replica()
        if self.ckpt_dir is not None:
            self._ensure_checkpoint()
        self._scaler = threading.Thread(target=self._autoscale_loop,
                                        daemon=True,
                                        name="ff-serve-autoscaler")
        self._scaler.start()
        return self

    def stop(self, timeout: float = 15.0, abort_pending: bool = True) -> None:
        self._closed = True
        self._scaler_stop.set()
        if self._scaler is not None:
            self._scaler.join(timeout=2.0)
        deadline = time.monotonic() + timeout
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            rep.batcher.draining = True
        while time.monotonic() < deadline:
            if len(self.queue) == 0 and all(
                r.batcher.active_slots == 0 for r in reps
            ):
                break
            time.sleep(0.02)
        if abort_pending:
            self.queue.drain(lambda req: RequestShedError(
                f"request {req.id} aborted: serving shut down",
                reason="aborted",
            ))
        for rep in reps:
            rep.batcher.stop(timeout=5.0)
            for slot_idx, slot in enumerate(rep.batcher.slots):
                if slot is not None and abort_pending:
                    if slot.req._finish(error=RequestShedError(
                        f"request {slot.req.id} aborted: serving shut "
                        "down", reason="aborted",
                    ), generation=slot.generation):
                        _shed("aborted")
                    rep.batcher._release(slot_idx)
            rep.monitor.stop()
            # final spool AFTER the serve thread stopped: the tallies
            # are final, so the fleet rollup conserves counters exactly
            self._write_replica_spool(rep.batcher, status="exited")
        self._write_set_spool(status="exited")

    # -- replica management ---------------------------------------------
    def _store_scope(self):
        """The ambient-store context every replica/spare build runs
        under — a no-op without a store."""
        if self.artifact_store is not None:
            return self.artifact_store.ambient()
        import contextlib

        return contextlib.nullcontext()

    def _build_model(self, *, elastic: bool):
        t0 = time.perf_counter()
        try:
            with self._device_lock, self._store_scope():
                if elastic and self.ckpt_dir is not None:
                    from .elastic import ElasticRestoreError, restore_elastic

                    try:
                        model, _info = restore_elastic(self.model_fn,
                                                       self.ckpt_dir,
                                                       verbose=False)
                        return model
                    except ElasticRestoreError:
                        pass  # no restorable checkpoint: fresh build below
                return self.model_fn()
        finally:
            # replica cold-start latency (build + compile + restore):
            # scripts/load_check.py reads the p95 to show the artifact
            # store shortening kill-mid-ramp recovery
            self.stats["cold_start_s"].append(time.perf_counter() - t0)

    def _new_batcher(self, model,
                     name: Optional[str] = None) -> ContinuousBatcher:
        if name is None:
            with self._lock:
                name = f"replica{self._counter}"
                self._counter += 1
        return ContinuousBatcher(
            model, self.config, self.queue, name=name,
            fault_injector=self.fault_injector,
            on_dead=self._on_batcher_dead,
            device_lock=self._device_lock,
            slo=self.slo,
        )

    def _activate(self, batcher: ContinuousBatcher) -> _Replica:
        from . import elastic as el
        from .. import obs

        monitor = el.HealthMonitor(
            timeout_s=self.health_timeout_s,
            compile_grace_s=self.compile_grace_s,
            heartbeat_fn=self._thread_heartbeat(batcher),
            heartbeat_interval_s=max(0.05, self.health_timeout_s / 4.0),
            on_hang=lambda info, b=batcher: self._on_hang(b, info),
        )
        batcher.monitor = monitor
        rep = _Replica(name=batcher.name, model=batcher.model,
                       batcher=batcher, monitor=monitor)
        with self._lock:
            self._replicas[rep.name] = rep
        monitor.start()
        batcher.start()
        obs.event("replica_started", cat="serving", replica=rep.name)
        obs.gauge_set("ff_serving_replicas", self.replica_count(),
                      help="live serving replicas")
        return rep

    def _take_spare(self) -> Optional[ContinuousBatcher]:
        """A warm spare whose mesh still matches the live topology —
        activation only needs the latest checkpoint restored onto it.
        A stale spare (topology changed underneath it) is discarded."""
        while True:
            with self._lock:
                if not self._spares:
                    return None
                batcher = self._spares.pop()
            if batcher.model.executor.mesh_is_live():
                if self.ckpt_dir is not None:
                    from .resilience import CheckpointManager

                    with self._device_lock:
                        CheckpointManager(self.ckpt_dir).restore_latest(
                            batcher.model, elastic=True
                        )
                return batcher

    def _add_replica(self, *, elastic: bool = False,
                     allow_spare: bool = False) -> _Replica:
        if allow_spare:
            spare = self._take_spare()
            if spare is not None:
                self.stats["spares_used"] += 1
                return self._activate(spare)
        return self._activate(self._new_batcher(
            self._build_model(elastic=elastic)))

    def _thread_heartbeat(self, batcher: ContinuousBatcher):
        """PR-2 heartbeat transport probing the serve thread: a beat
        that finds the thread dead (crashed outside the step window)
        names it as a straggler, which escalates through on_hang."""

        def beat() -> Optional[list]:
            if batcher.dead or (
                batcher._thread is not None
                and not batcher._thread.is_alive()
                and not batcher._stop.is_set()
            ):
                return [batcher.name]
            return None

        return beat

    def _ensure_checkpoint(self) -> None:
        from .resilience import CheckpointManager

        mgr = CheckpointManager(self.ckpt_dir)
        if mgr.latest_step() is None:
            with self._lock:
                rep = next(iter(self._replicas.values()), None)
            if rep is not None:
                mgr.save(rep.model, step=0,
                         extra_meta={"serving": {"replica": rep.name}})

    def replica_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.batcher.thread_alive())

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    # -- failover --------------------------------------------------------
    def _on_hang(self, batcher: ContinuousBatcher, info: dict) -> None:
        from .. import obs

        obs.event("replica_hang", cat="serving", replica=batcher.name,
                  **{k: v for k, v in info.items() if k != "step"})
        self._fail_replica(batcher, ReplicaDeathError(
            f"replica {batcher.name} hung: {info.get('kind', 'unknown')}"
        ))

    def _on_batcher_dead(self, batcher: ContinuousBatcher,
                         exc: BaseException) -> None:
        self._fail_replica(batcher, exc)

    def _fail_replica(self, batcher: ContinuousBatcher,
                      exc: BaseException) -> None:
        """Take a replica out of rotation and restart it in the
        background. Idempotent — the watchdog and the serve loop may
        both report the same death.

        Slot rescue is the SERVE THREAD's job (_strand_slots on its
        dead-exit): snapshotting its slots from here would race its
        admission loop — the snapshot can miss a request admitted in
        that instant, which would then hang forever. Only when the
        thread is genuinely wedged (a real hung collective — it will
        never reach its exit path) does this thread steal the slots
        after a grace join."""
        from .. import obs

        with self._lock:
            rep = self._replicas.pop(batcher.name, None)
        if rep is None:
            return  # already handled
        batcher.dead = True
        rep.monitor.stop()
        if batcher._thread is not None and (
            batcher._thread is not threading.current_thread()
        ):
            batcher._thread.join(timeout=5.0)
            if batcher._thread.is_alive():
                # truly wedged: it cannot run its own exit stranding
                logger.warning("replica %s thread is wedged; stealing its "
                               "in-flight slots", batcher.name)
                batcher._strand_slots()
        requeued = batcher.stats["stranded_requeued"]
        self.stats["requeued"] += requeued
        logger.warning("replica %s failed (%s: %s); requeued %d in-flight "
                       "request(s)", batcher.name, type(exc).__name__, exc,
                       requeued)
        obs.event("replica_failover", cat="serving", replica=batcher.name,
                  requeued=requeued, error=type(exc).__name__,
                  detail=str(exc)[:300])
        obs.gauge_set("ff_serving_replicas", self.replica_count(),
                      help="live serving replicas")
        # forensics: the dying replica's KV pool audit + final counters,
        # while its state still exists (obs/flight_recorder.py)
        try:
            kv_pool: dict = {"snapshot": batcher.pool.snapshot()}
            kv_pool["audit"] = batcher.pool.audit().to_dict()
        except Exception as e:  # fflint: disable=FFL002 — forensics only
            kv_pool = {"error": f"{type(e).__name__}: {e}"}
        obs.forensics_dump("replica_death", error=exc,
                           replica=batcher.name, requeued=requeued,
                           stats=dict(batcher.stats), kv_pool=kv_pool)
        # terminal spool: the fleet rollup keeps this replica's final
        # tallies (counter conservation through the kill) and reads the
        # explicit "dead" status without waiting out the age window
        self._write_replica_spool(batcher, status="dead")
        self._spools.pop(batcher.name, None)
        if self._closed:
            return
        with self._lock:
            if self._restarts >= self.max_replica_restarts:
                obs.event("replica_restart_budget_exhausted", cat="serving",
                          replica=batcher.name,
                          restarts=self._restarts)
                return
            self._restarts += 1
            self._pending_restarts += 1
            restarts = self._restarts
        threading.Thread(
            target=self._restart_replica, args=(batcher.name, restarts),
            daemon=True, name=f"ff-serve-restart-{batcher.name}",
        ).start()

    @staticmethod
    def pool_release_quiet(batcher: ContinuousBatcher, slot: _Slot) -> None:
        # sweeps that legitimately race the serve loop's own release
        # (retirement / dead-exit stranding may have freed the slot
        # already) pass missing_ok so the typed double-release guard
        # stays armed for real failover bugs
        try:
            batcher.pool.release(slot.seq_key, missing_ok=True)
        except Exception:  # fflint: disable=FFL002 — best-effort cleanup
            pass

    def _restart_replica(self, dead_name: str, attempt: int) -> None:
        from .. import obs

        time.sleep(self.restart_backoff_s * (2.0 ** (attempt - 1)))
        try:
            rep = self._add_replica(elastic=True, allow_spare=True)
        except BaseException as e:
            logger.exception("restart of dead replica %s failed", dead_name)
            obs.event("replica_restart_failed", cat="serving",
                      replica=dead_name, error=type(e).__name__,
                      detail=str(e)[:300])
            return
        finally:
            with self._lock:
                self._pending_restarts -= 1
        self.stats["restarts"] += 1
        obs.count("ff_replica_restarts_total",
                  help="serving replicas restarted after death/hang")
        obs.event("replica_restarted", cat="serving", dead=dead_name,
                  replacement=rep.name, attempt=attempt,
                  elastic=self.ckpt_dir is not None)

    # -- autoscaling -----------------------------------------------------
    def _autoscale_loop(self) -> None:
        from .. import obs

        while not self._scaler_stop.wait(self.autoscale_interval_s):
            depth = len(self.queue)
            self._observe_fleet(depth)
            with self._lock:
                pending = self._pending_restarts
            # replicas mid-restart count toward capacity: scaling up to
            # "replace" one that failover is already replacing would
            # over-provision, and the later idle scale-down would drain
            # a replica that real traffic still needs
            n = self.replica_count() + pending
            slo_pressure = self.slo.should_scale_up()
            if ((depth >= self.scale_up_queue_depth or slo_pressure)
                    and n < self.max_replicas):
                try:
                    rep = self._add_replica(allow_spare=True)
                except BaseException as e:
                    obs.event("replica_scale_up_failed", cat="serving",
                              error=type(e).__name__, detail=str(e)[:300])
                    continue
                self.stats["scale_ups"] += 1
                # the sentinel saw this tick's observations already
                # (_observe_fleet runs first), so the pressure that
                # motivated this scale-up is in its blame window
                blame = self.sentinel.blame(
                    max_age_s=max(5.0, 20 * self.autoscale_interval_s))
                obs.event("replica_scale_up", cat="serving",
                          replica=rep.name, queue_depth=depth,
                          cause=("slo" if slo_pressure
                                 and depth < self.scale_up_queue_depth
                                 else "queue_depth"),
                          anomaly=blame or "",
                          slo_violation_rate=round(
                              self.slo.violation_rate(), 4))
                self._idle_since = None
                continue
            busy = depth > 0 or any(
                r.batcher.active_slots for r in self._replicas.values()
            )
            if busy:
                self._idle_since = None
                continue
            if n <= self.min_replicas:
                continue
            now = time.monotonic()
            if self._idle_since is None:
                self._idle_since = now
                continue
            if now - self._idle_since >= self.scale_down_idle_s:
                self._scale_down_one()
                self._idle_since = None

    def _scale_down_one(self) -> None:
        from .. import obs

        with self._lock:
            victims = [r for r in self._replicas.values()
                       if r.batcher.thread_alive()]
            if len(victims) <= self.min_replicas:
                return
            rep = victims[-1]
            del self._replicas[rep.name]
        # drain, don't kill: draining stops admissions and the loop exits
        # on its own once the last slot retires; a hard stop here would
        # orphan in-flight requests (a silent drop). Stragglers past the
        # grace window are requeued exactly like failover.
        rep.batcher.draining = True
        grace = time.monotonic() + 30.0
        while rep.batcher.active_slots and time.monotonic() < grace:
            time.sleep(0.02)
        for i in range(len(rep.batcher.slots)):
            # take the straggler slot under the batcher's teardown mutex
            # so this sweep and the (still-running) serve loop can't
            # both decref its pages
            with rep.batcher._teardown_lock:
                slot = rep.batcher.slots[i]
                rep.batcher.slots[i] = None
            if slot is None:
                continue
            gen = slot.req._requeue_bump()
            self.pool_release_quiet(rep.batcher, slot)
            if gen is not None:
                slot.req.trace.requeued(rep.name, generation=gen,
                                        scale_down=True)
                self.queue.requeue(slot.req)
                self.stats["requeued"] += 1
        rep.batcher.stop(timeout=5.0)
        rep.monitor.stop()
        self.stats["scale_downs"] += 1
        self._write_replica_spool(rep.batcher, status="exited")
        self._spools.pop(rep.name, None)
        obs.event("replica_scale_down", cat="serving", replica=rep.name)
        obs.gauge_set("ff_serving_replicas", self.replica_count(),
                      help="live serving replicas")

    # -- client API ------------------------------------------------------
    def _latency_p95(self) -> float:
        from .. import obs

        # the SLO monitor's window is fed by EVERY completed request
        # (record_request_stages), not just blocking generate() callers,
        # so it is the preferred signal when populated
        if self.slo.sample_count > 0:
            q = self.slo.latency_quantile(0.95)
            if q == q:  # not NaN
                return q
        tel = obs.active()
        if tel is not None:
            h = tel.metrics.find("ff_serving_latency_seconds")
            if h is not None and getattr(h, "count", 0):
                return h.quantile(0.95)
        return self.latency.quantile(0.95)

    def submit(self, prompt: np.ndarray, *,
               max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None) -> GenerationRequest:
        """Admission-controlled enqueue. Raises (typed, counted):
        RateLimitedError / QueueFullError / DeadlineExceededError. A
        returned request is ADMITTED: it will end in a result or a typed
        error — never silence."""
        if self._closed or not self._started:
            raise ServingConfigError(
                "ReplicaSet is not accepting requests (call start(); "
                "not after stop())"
            )
        req = GenerationRequest(
            prompt,
            max_new_tokens if max_new_tokens is not None
            else self.config.default_max_new_tokens,
            deadline_s=(deadline_s if deadline_s is not None
                        else self.config.default_deadline_s),
        )
        req.trace = mint_request_trace(req.id)
        if self.bucket is not None:
            if self.config.adaptive_rate:
                self._rate_check += 1
                if self._rate_check % 16 == 0:
                    self.bucket.adapt(self._latency_p95(),
                                      self.config.target_p95_s)
            if not self.bucket.try_acquire():
                err = RateLimitedError(
                    f"request {req.id} rate-limited "
                    f"({self.bucket.rate:.1f} req/s)"
                )
                _shed("rate_limited")
                req.trace.shed("rate_limited", stage="submit")
                req._finish(error=err)
                raise err
        self.queue.offer(req)  # sheds typed on full/dead-on-arrival
        self.stats["submitted"] += 1
        return req

    def generate(self, prompt: np.ndarray, *,
                 max_new_tokens: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking submit+result; observes the local latency reservoir
        the adaptive rate limiter reads."""
        req = self.submit(prompt, max_new_tokens=max_new_tokens,
                          deadline_s=deadline_s)
        out = req.result(timeout)
        self.latency.observe(time.monotonic() - req.submitted_t)
        return out

    def queue_depth(self) -> int:
        return len(self.queue)

    def aggregate_stats(self) -> dict:
        with self._lock:
            reps = list(self._replicas.values())
        agg = dict(self.stats)
        agg["replicas"] = {r.name: dict(r.batcher.stats) for r in reps}
        agg["queue_depth"] = len(self.queue)
        return agg


class InferenceRequest:
    def __init__(self, inputs: List[np.ndarray],
                 deadline: Optional[float] = None):
        self.id = uuid.uuid4().hex
        self.inputs = inputs
        self.deadline = deadline  # absolute monotonic; None = no deadline
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class BatchScheduler:
    """Dynamic batcher (reference: triton/src/instance.cc lifecycle +
    per-request execution, re-thought as a batch queue).

    `max_delay_s`: how long to wait to fill a batch before running partial.

    Fault tolerance (runtime/resilience.py): `infer` raises a typed
    InferenceTimeout (retried under `retry_policy`) instead of asserting,
    and when the worker thread has died — crashed on a batch, or never
    started — falls back to DEGRADED mode, running the request unbatched
    on the caller's thread so the service keeps answering (slower, but
    up). A crashed worker is auto-restarted up to `max_worker_restarts`
    times with exponential backoff (`restart_backoff_s` base); once the
    budget is spent the scheduler stays degraded until the operator
    intervenes. Restart counts surface in `stats["worker_restarts"]`.
    `fault_injector` site ``serving_worker`` kills the worker
    deterministically in tests.

    Deadlines propagate INTO the queue: `infer(timeout=...)` stamps the
    request, and the worker sheds expired requests at dequeue with a
    typed DeadlineExceededError (counted in ff_serving_shed_total)
    instead of burning device time on an answer nobody is waiting for.
    `max_queue_depth` bounds the queue; beyond it `submit` sheds with
    QueueFullError."""

    def __init__(self, model, *, max_delay_s: float = 0.005,
                 retry_policy=None, fault_injector=None,
                 max_worker_restarts: int = 3,
                 restart_backoff_s: float = 0.25,
                 max_queue_depth: Optional[int] = None):
        if model.executor is None:
            raise NotCompiledError("compile() the model first")
        from .resilience import RetryPolicy

        self.model = model
        self.batch_size = model.executor.input_pts[0].material_shape()[0]
        self.max_delay_s = max_delay_s
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=2, base_delay_s=0.01, max_delay_s=0.5
        )
        self.fault_injector = fault_injector
        self.max_worker_restarts = max(0, max_worker_restarts)
        self.restart_backoff_s = restart_backoff_s
        self.max_queue_depth = max_queue_depth
        self._q: "queue.Queue[InferenceRequest]" = queue.Queue()
        self._fwd = model.executor.build_forward()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._started = False
        self._worker_error: Optional[BaseException] = None
        # guards ALL restart/backoff state: _worker_error, _next_restart_t
        # and the worker_restarts stat — the worker thread and any number
        # of infer() callers race on these
        self._restart_lock = threading.Lock()
        self._next_restart_t = 0.0
        self.stats = {"requests": 0, "batches": 0, "padded_slots": 0,
                      "degraded": 0, "timeouts": 0, "worker_restarts": 0,
                      "shed": 0, "degraded_retries": 0}

    # -- client API ------------------------------------------------------
    def start(self):
        if not self._started:
            self._worker.start()
            self._started = True
        return self

    def stop(self):
        self._stop.set()
        if self._started:
            self._worker.join(timeout=5)

    def worker_alive(self) -> bool:
        return (self._started and self._worker.is_alive()
                and self._worker_error is None)

    def _maybe_restart_worker(self) -> bool:
        """Bounded auto-restart after a worker crash: spawn a fresh worker
        thread once the backoff window has elapsed, at most
        `max_worker_restarts` times. Returns True when a live worker is
        available (already alive, or just restarted); False keeps the
        caller on the degraded path."""
        if self.worker_alive():
            return True
        if not self._started or self._stop.is_set():
            return False
        with self._restart_lock:
            if self.worker_alive():  # another caller beat us to it
                return True
            if self.stats["worker_restarts"] >= self.max_worker_restarts:
                return False  # budget spent: stay degraded
            if time.monotonic() < self._next_restart_t:
                return False  # still backing off: degraded for now
            self.stats["worker_restarts"] += 1
            from .. import obs

            obs.count("ff_serving_worker_restarts_total",
                      help="serving worker threads restarted after crash")
            obs.event("serving_worker_restart", cat="serving",
                      restarts=self.stats["worker_restarts"])
            self._worker_error = None
            self._worker = threading.Thread(target=self._loop, daemon=True)
            self._worker.start()
            return True

    def submit(self, inputs: List[np.ndarray],
               deadline: Optional[float] = None) -> InferenceRequest:
        """Each request carries ONE sample per model input (no batch dim).
        `deadline` is absolute time.monotonic(); the worker sheds the
        request (typed) if it is still queued past it."""
        if (self.max_queue_depth is not None
                and self._q.qsize() >= self.max_queue_depth):
            self.stats["shed"] += 1
            _shed("queue_full")
            raise QueueFullError(
                f"BatchScheduler queue at capacity ({self.max_queue_depth})"
            )
        req = InferenceRequest([np.asarray(a) for a in inputs],
                               deadline=deadline)
        self._q.put(req)
        return req

    def infer(self, inputs: List[np.ndarray], timeout: float = 30.0) -> np.ndarray:
        """Blocking single-sample inference. Timeouts raise
        InferenceTimeout and are retried per `self.retry_policy`; a dead
        worker degrades to direct unbatched execution instead of hanging
        every caller until restart. A request whose deadline passes
        while still queued is shed with DeadlineExceededError (not
        retried, not executed)."""
        from .. import obs
        from .resilience import InferenceTimeout, retry

        t_start = time.perf_counter()
        deadline = time.monotonic() + timeout

        def attempt():
            if not self._maybe_restart_worker():
                return self._infer_direct(inputs)
            req = self.submit(inputs, deadline=deadline)
            if not req.event.wait(timeout):
                self.stats["timeouts"] += 1
                if not self.worker_alive():
                    # died while we waited — the request will never be
                    # answered from the queue
                    return self._degraded_retry(req, inputs)
                raise InferenceTimeout(
                    f"request {req.id} unanswered after {timeout}s "
                    f"(queue depth {self._q.qsize()})"
                )
            if req.error is not None:
                if isinstance(req.error, RequestShedError):
                    raise req.error  # shed on purpose: never re-executed
                # the worker failed ON this batch; answer from the
                # degraded path rather than bubbling its crash to callers
                return self._degraded_retry(req, inputs)
            return req.result

        try:
            out = retry(attempt, self.retry_policy)
        except BaseException:
            obs.count("ff_serving_errors_total",
                      help="serving requests that failed after retries")
            raise
        # latency percentiles ride the histogram's reservoir
        # (metrics.prom buckets + p50/p95/p99 in metrics.jsonl)
        obs.observe("ff_serving_latency_seconds",
                    time.perf_counter() - t_start,
                    help="end-to-end serving request latency")
        obs.count("ff_serving_requests_total",
                  help="serving requests answered")
        return out

    def _degraded_retry(self, req: InferenceRequest,
                        inputs: List[np.ndarray]) -> np.ndarray:
        """An in-flight request was orphaned by a worker death and is
        being re-run on the degraded path — surfaced as a structured
        event (the satellite fix: this used to happen silently)."""
        from .. import obs

        self.stats["degraded_retries"] += 1
        obs.count("ff_serving_degraded_retries_total",
                  help="in-flight requests re-run unbatched after a "
                       "worker death")
        obs.event("serving_degraded_retry", cat="serving",
                  request=req.id,
                  error=type(req.error).__name__ if req.error else "orphaned")
        return self._infer_direct(inputs)

    def _infer_direct(self, inputs: List[np.ndarray]) -> np.ndarray:
        """DEGRADED mode: run one request on the caller's thread, padded
        to the compiled batch (same jitted executable, no queue)."""
        self.stats["degraded"] += 1
        arrays = [
            jnp.asarray(np.broadcast_to(
                np.asarray(a)[None], (self.batch_size,) + np.asarray(a).shape
            ))
            for a in inputs
        ]
        out = np.asarray(self._fwd(self.model.state.params, arrays,
                                   self.model.state.net_state))
        return out[0]

    # -- batching loop ---------------------------------------------------
    def _shed_if_expired(self, req: InferenceRequest) -> bool:
        """Dequeue-time deadline check (satellite fix): a request whose
        caller already gave up must not reach the device — shed it with
        a typed error the caller sees instead of a silent late answer."""
        if req.deadline is None or time.monotonic() < req.deadline:
            return False
        self.stats["shed"] += 1
        _shed("deadline")
        req.error = DeadlineExceededError(
            f"request {req.id} expired while queued", stage="dequeue",
        )
        req.event.set()
        return True

    def _loop(self):
        import jax.numpy as jnp

        n_inputs = len(self.model.executor.input_pts)
        while not self._stop.is_set():
            batch: List[InferenceRequest] = []
            try:
                got = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            if not self._shed_if_expired(got):
                batch.append(got)
            fill_by = time.monotonic() + self.max_delay_s
            while len(batch) < self.batch_size:
                remaining = fill_by - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    got = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if not self._shed_if_expired(got):
                    batch.append(got)
            if not batch:
                continue
            try:
                if self.fault_injector is not None:
                    self.fault_injector.fire("serving_worker",
                                             self.stats["batches"])
                pad = self.batch_size - len(batch)
                arrays = []
                for i in range(n_inputs):
                    rows = [r.inputs[i] for r in batch]
                    stacked = np.stack(rows + [rows[-1]] * pad, axis=0)
                    arrays.append(jnp.asarray(stacked))
                out = np.asarray(self._fwd(self.model.state.params, arrays,
                                           self.model.state.net_state))
            except BaseException as e:
                # worker is no longer trustworthy: fail the in-flight
                # requests (their callers re-run degraded) and exit so
                # worker_alive() routes future traffic around the queue
                # until _maybe_restart_worker's backoff window opens.
                # Backoff state is written under the restart lock
                # (satellite fix): infer() callers racing through
                # _maybe_restart_worker read these fields, and an
                # unlocked write could let a restart slip in before the
                # backoff window was published.
                with self._restart_lock:
                    self._worker_error = e
                    self._next_restart_t = time.monotonic() + (
                        self.restart_backoff_s
                        * (2.0 ** self.stats["worker_restarts"])
                    )
                for r in batch:
                    r.error = e
                    r.event.set()
                return
            for j, r in enumerate(batch):
                r.result = out[j]
                r.event.set()
            self.stats["requests"] += len(batch)
            self.stats["batches"] += 1
            self.stats["padded_slots"] += pad
