"""ONNX frontend (reference: python/flexflow/onnx/)."""
from .model import HAS_ONNX, ONNXModel  # noqa: F401
