"""Per-operator numerical alignment vs CPU PyTorch — forward AND backward.

The reference's correctness oracle (tests/align/: align_create_tensor_ff.py
runs each op in FlexFlow and torch, align_test.py asserts closeness for ~20
operators fwd+bwd). Here each case runs the registered op forward under
jax (CPU), and gradients via jax.grad, against the torch equivalent.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import jax
import jax.numpy as jnp

from flexflow_tpu.ff_types import ActiMode, AggrMode, DataType, OperatorType, PoolType
from flexflow_tpu.ops import FwdCtx, get_op_def
from flexflow_tpu.ops.attention import MultiHeadAttentionParams
from flexflow_tpu.ops.batch_matmul import BatchMatmulParams
from flexflow_tpu.ops.conv2d import Conv2DParams
from flexflow_tpu.ops.elementwise import ElementBinaryParams, ElementUnaryParams
from flexflow_tpu.ops.embedding import EmbeddingParams
from flexflow_tpu.ops.linear import LinearParams
from flexflow_tpu.ops.normalization import LayerNormParams
from flexflow_tpu.ops.pool2d import Pool2DParams
from flexflow_tpu.ops.reduce import ReduceParams, TopKParams
from flexflow_tpu.ops.softmax import SoftmaxParams
from flexflow_tpu.ops.tensor_ops import (
    ConcatParams,
    GatherParams,
    ReshapeParams,
    TransposeParams,
)

RNG = np.random.RandomState(0)
CTX = FwdCtx(training=False, rng=None)


def run_op(op_type, params, weights, inputs):
    d = get_op_def(op_type)
    outs = d.forward(params, weights, [jnp.asarray(x) for x in inputs], CTX)
    return [np.asarray(o) for o in outs]


def grads_of(op_type, params, weights, inputs, cotangent):
    """d(sum(out * cotangent))/d(inputs[0])"""
    d = get_op_def(op_type)

    def f(x0):
        out = d.forward(params, weights, [x0] + [jnp.asarray(x) for x in inputs[1:]], CTX)[0]
        return jnp.sum(out * jnp.asarray(cotangent))

    return np.asarray(jax.grad(f)(jnp.asarray(inputs[0])))


def torch_grad(fn, x, cotangent):
    t = torch.from_numpy(x).requires_grad_(True)
    out = fn(t)
    out.backward(torch.from_numpy(cotangent))
    return t.grad.numpy()


def assert_close(a, b, atol=1e-4):
    np.testing.assert_allclose(a, b, atol=atol, rtol=1e-4)


def test_linear_fwd_bwd():
    x = RNG.randn(4, 8).astype(np.float32)
    w = RNG.randn(8, 6).astype(np.float32)
    b = RNG.randn(6).astype(np.float32)
    p = LinearParams(out_channels=6)
    (ours,) = run_op(OperatorType.OP_LINEAR, p, {"kernel": jnp.asarray(w), "bias": jnp.asarray(b)}, [x])
    theirs = x @ w + b
    assert_close(ours, theirs)
    ct = RNG.randn(4, 6).astype(np.float32)
    g = grads_of(OperatorType.OP_LINEAR, p, {"kernel": jnp.asarray(w), "bias": jnp.asarray(b)}, [x], ct)
    tg = torch_grad(lambda t: t @ torch.from_numpy(w) + torch.from_numpy(b), x, ct)
    assert_close(g, tg)


def test_conv2d_fwd_bwd():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    w = RNG.randn(5, 3, 3, 3).astype(np.float32)
    p = Conv2DParams(out_channels=5, kernel_h=3, kernel_w=3, padding_h=1, padding_w=1,
                     use_bias=False)
    (ours,) = run_op(OperatorType.OP_CONV2D, p, {"kernel": jnp.asarray(w)}, [x])
    theirs = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), padding=1
    ).numpy()
    assert_close(ours, theirs)
    ct = RNG.randn(*ours.shape).astype(np.float32)
    g = grads_of(OperatorType.OP_CONV2D, p, {"kernel": jnp.asarray(w)}, [x], ct)
    tg = torch_grad(
        lambda t: torch.nn.functional.conv2d(t, torch.from_numpy(w), padding=1), x, ct
    )
    assert_close(g, tg)


def test_pool2d_max_avg():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    for ptype, tfn in [
        (PoolType.POOL_MAX, torch.nn.functional.max_pool2d),
        (PoolType.POOL_AVG, torch.nn.functional.avg_pool2d),
    ]:
        p = Pool2DParams(kernel_h=2, kernel_w=2, stride_h=2, stride_w=2,
                         pool_type=ptype)
        (ours,) = run_op(OperatorType.OP_POOL2D, p, {}, [x])
        theirs = tfn(torch.from_numpy(x), 2, 2).numpy()
        assert_close(ours, theirs)


def test_layernorm_fwd_bwd():
    x = RNG.randn(4, 6, 16).astype(np.float32)
    scale = RNG.randn(16).astype(np.float32)
    bias = RNG.randn(16).astype(np.float32)
    p = LayerNormParams(axes=(-1,))
    w = {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)}
    (ours,) = run_op(OperatorType.OP_LAYERNORM, p, w, [x])
    theirs = torch.nn.functional.layer_norm(
        torch.from_numpy(x), (16,), torch.from_numpy(scale), torch.from_numpy(bias)
    ).numpy()
    assert_close(ours, theirs)
    ct = RNG.randn(*x.shape).astype(np.float32)
    g = grads_of(OperatorType.OP_LAYERNORM, p, w, [x], ct)
    tg = torch_grad(
        lambda t: torch.nn.functional.layer_norm(
            t, (16,), torch.from_numpy(scale), torch.from_numpy(bias)
        ), x, ct,
    )
    assert_close(g, tg, atol=1e-3)


def test_softmax_fwd_bwd():
    x = RNG.randn(4, 10).astype(np.float32)
    p = SoftmaxParams(dim=-1)
    (ours,) = run_op(OperatorType.OP_SOFTMAX, p, {}, [x])
    assert_close(ours, torch.softmax(torch.from_numpy(x), -1).numpy())
    ct = RNG.randn(4, 10).astype(np.float32)
    g = grads_of(OperatorType.OP_SOFTMAX, p, {}, [x], ct)
    tg = torch_grad(lambda t: torch.softmax(t, -1), x, ct)
    assert_close(g, tg)


def test_batch_matmul_fwd_bwd():
    a = RNG.randn(3, 4, 5).astype(np.float32)
    b = RNG.randn(3, 5, 6).astype(np.float32)
    p = BatchMatmulParams()
    (ours,) = run_op(OperatorType.OP_BATCHMATMUL, p, {}, [a, b])
    assert_close(ours, np.matmul(a, b))
    ct = RNG.randn(3, 4, 6).astype(np.float32)
    g = grads_of(OperatorType.OP_BATCHMATMUL, p, {}, [a, b], ct)
    tg = torch_grad(lambda t: torch.bmm(t, torch.from_numpy(b)), a, ct)
    assert_close(g, tg)


def test_embedding_fwd():
    ids = RNG.randint(0, 20, (4, 3)).astype(np.int32)
    table = RNG.randn(20, 8).astype(np.float32)
    p = EmbeddingParams(num_entries=20, out_channels=8, aggr=AggrMode.AGGR_MODE_SUM)
    (ours,) = run_op(OperatorType.OP_EMBEDDING, p, {"weight": jnp.asarray(table)}, [ids])
    theirs = torch.nn.functional.embedding_bag(
        torch.from_numpy(ids.astype(np.int64)), torch.from_numpy(table), mode="sum"
    ).numpy()
    assert_close(ours, theirs)


@pytest.mark.parametrize("op_type,tfn", [
    (OperatorType.OP_RELU, torch.relu),
    (OperatorType.OP_SIGMOID, torch.sigmoid),
    (OperatorType.OP_TANH, torch.tanh),
    (OperatorType.OP_EXP, torch.exp),
    (OperatorType.OP_GELU, lambda t: torch.nn.functional.gelu(t)),
    (OperatorType.OP_RSQRT, torch.rsqrt),
])
def test_unary_ops(op_type, tfn):
    x = (RNG.rand(4, 8).astype(np.float32) + 0.5)
    p = ElementUnaryParams(op_type=op_type)
    (ours,) = run_op(op_type, p, {}, [x])
    assert_close(ours, tfn(torch.from_numpy(x)).numpy(), atol=2e-3)


@pytest.mark.parametrize("op_type,tfn", [
    (OperatorType.OP_EW_ADD, torch.add),
    (OperatorType.OP_EW_SUB, torch.sub),
    (OperatorType.OP_EW_MUL, torch.mul),
    (OperatorType.OP_EW_DIV, torch.div),
    (OperatorType.OP_EW_MAX, torch.maximum),
    (OperatorType.OP_EW_MIN, torch.minimum),
])
def test_binary_ops(op_type, tfn):
    a = RNG.randn(4, 8).astype(np.float32)
    b = RNG.randn(4, 8).astype(np.float32) + 2.0
    p = ElementBinaryParams(op_type=op_type)
    (ours,) = run_op(op_type, p, {}, [a, b])
    assert_close(ours, tfn(torch.from_numpy(a), torch.from_numpy(b)).numpy())


def test_shape_ops():
    x = RNG.randn(4, 6, 8).astype(np.float32)
    (r,) = run_op(OperatorType.OP_RESHAPE, ReshapeParams((4, 48)), {}, [x])
    assert r.shape == (4, 48)
    (t,) = run_op(OperatorType.OP_TRANSPOSE, TransposeParams((0, 2, 1)), {}, [x])
    assert_close(t, np.transpose(x, (0, 2, 1)))
    (c,) = run_op(OperatorType.OP_CONCAT, ConcatParams(axis=1), {}, [x, x])
    assert c.shape == (4, 12, 8)


def test_gather_topk():
    x = RNG.randn(4, 10).astype(np.float32)
    idx = RNG.randint(0, 10, (4, 3)).astype(np.int32)
    (g,) = run_op(OperatorType.OP_GATHER, GatherParams(dim=1), {}, [x, idx])
    tg = torch.gather(torch.from_numpy(x), 1, torch.from_numpy(idx.astype(np.int64)))
    assert_close(g, tg.numpy())
    vals, inds = run_op(OperatorType.OP_TOPK, TopKParams(k=3), {}, [x])
    tv, ti = torch.topk(torch.from_numpy(x), 3)
    assert_close(vals, tv.numpy())


def test_reduce_ops():
    x = RNG.randn(4, 6, 8).astype(np.float32)
    (s,) = run_op(OperatorType.OP_REDUCE_SUM, ReduceParams(axes=(1,)), {}, [x])
    assert_close(s, x.sum(1), atol=1e-4)
    (mn,) = run_op(OperatorType.OP_REDUCE_MEAN, ReduceParams(axes=(2,), keepdims=True), {}, [x])
    assert_close(mn, x.mean(2, keepdims=True))


def test_mha_shapes_and_grad():
    """Attention: check shape + finite grads (torch's cuDNN-style packed MHA
    differs in weight layout, so exact alignment is covered by the
    end-to-end torch-frontend test instead)."""
    b, s, e, h = 2, 6, 16, 4
    q = RNG.randn(b, s, e).astype(np.float32)
    p = MultiHeadAttentionParams(embed_dim=e, num_heads=h)
    d = get_op_def(OperatorType.OP_MULTIHEAD_ATTENTION)
    wq = RNG.randn(e, h, 4).astype(np.float32)
    wo = RNG.randn(h, 4, e).astype(np.float32)
    weights = {
        "wq": jnp.asarray(wq), "wk": jnp.asarray(wq), "wv": jnp.asarray(wq),
        "wo": jnp.asarray(wo), "bias_o": jnp.zeros(e),
    }
    (out,) = d.forward(p, weights, [jnp.asarray(q)] * 3, CTX)
    assert out.shape == (b, s, e)
    g = jax.grad(
        lambda x: jnp.sum(d.forward(p, weights, [x, x, x], CTX)[0])
    )(jnp.asarray(q))
    assert np.isfinite(np.asarray(g)).all()


def test_batchnorm_fwd_bwd():
    """reference: tests/align batch-norm case (src/ops/batch_norm.cc is
    training-mode batch stats + optional fused relu)."""
    from flexflow_tpu.ops.normalization import BatchNormParams

    x = RNG.randn(4, 3, 8, 8).astype(np.float32)
    scale = RNG.rand(3).astype(np.float32) + 0.5
    bias = RNG.randn(3).astype(np.float32)
    params = BatchNormParams(relu=False)
    out, = run_op(OperatorType.OP_BATCHNORM, params,
                  {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)}, [x])

    tbn = torch.nn.functional.batch_norm(
        torch.from_numpy(x), None, None,
        weight=torch.from_numpy(scale), bias=torch.from_numpy(bias),
        training=True, eps=params.eps,
    )
    assert_close(out, tbn.detach().numpy(), atol=1e-3)

    cot = RNG.randn(*out.shape).astype(np.float32)
    g = grads_of(OperatorType.OP_BATCHNORM, params,
                 {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)},
                 [x], cot)
    tg = torch_grad(
        lambda t: torch.nn.functional.batch_norm(
            t, None, None, weight=torch.from_numpy(scale),
            bias=torch.from_numpy(bias), training=True, eps=params.eps),
        x, cot,
    )
    assert_close(g, tg, atol=1e-3)

    # fused relu variant
    out_r, = run_op(OperatorType.OP_BATCHNORM, BatchNormParams(relu=True),
                    {"scale": jnp.asarray(scale), "bias": jnp.asarray(bias)}, [x])
    assert_close(out_r, np.maximum(tbn.detach().numpy(), 0), atol=1e-3)


def test_split_fwd_bwd():
    from flexflow_tpu.ops.tensor_ops import SplitParams

    x = RNG.randn(4, 10).astype(np.float32)
    params = SplitParams(sizes=(3, 7), axis=1)
    a, b = run_op(OperatorType.OP_SPLIT, params, {}, [x])
    ta, tb = torch.split(torch.from_numpy(x), [3, 7], dim=1)
    assert_close(a, ta.numpy())
    assert_close(b, tb.numpy())

    # grad flows through both outputs
    d = get_op_def(OperatorType.OP_SPLIT)

    def f(x0):
        o1, o2 = d.forward(params, {}, [x0], CTX)
        return jnp.sum(o1) + 2 * jnp.sum(o2)

    g = np.asarray(jax.grad(f)(jnp.asarray(x)))
    expect = np.concatenate([np.ones((4, 3)), 2 * np.ones((4, 7))], axis=1)
    assert_close(g, expect.astype(np.float32))


def test_cast_and_scalar_ops():
    from flexflow_tpu.ops.tensor_ops import CastParams

    x = RNG.randn(3, 5).astype(np.float32) * 3
    out, = run_op(OperatorType.OP_CAST, CastParams(dtype=DataType.DT_INT32),
                  {}, [x])
    assert out.dtype == np.int32
    np.testing.assert_array_equal(
        out, torch.from_numpy(x).to(torch.int32).numpy()
    )

    for op_type, scalar, tfn in [
        (OperatorType.OP_SCALAR_MULTIPLY, 2.5, lambda t: t * 2.5),
        (OperatorType.OP_SCALAR_ADD, -1.25, lambda t: t - 1.25),
        (OperatorType.OP_SCALAR_SUB, 0.5, lambda t: t - 0.5),
        (OperatorType.OP_SCALAR_TRUE_DIV, 4.0, lambda t: t / 4.0),
        (OperatorType.OP_POW, 2.0, lambda t: t ** 2.0),
    ]:
        params = ElementUnaryParams(op_type=op_type, scalar=scalar)
        out, = run_op(op_type, params, {}, [np.abs(x)])
        assert_close(out, tfn(torch.from_numpy(np.abs(x))).numpy())
        cot = RNG.randn(3, 5).astype(np.float32)
        g = grads_of(op_type, params, {}, [np.abs(x)], cot)
        tg = torch_grad(lambda t, _f=tfn: _f(t), np.abs(x), cot)
        assert_close(g, tg)


def test_flat_and_reverse():
    from flexflow_tpu.ops.tensor_ops import FlatParams, ReverseParams

    x = RNG.randn(2, 3, 4, 5).astype(np.float32)
    out, = run_op(OperatorType.OP_FLAT, FlatParams(), {}, [x])
    assert_close(out, torch.from_numpy(x).flatten(1).numpy())

    out, = run_op(OperatorType.OP_REVERSE, ReverseParams(axis=2), {}, [x])
    assert_close(out, torch.flip(torch.from_numpy(x), dims=[2]).numpy())


def test_losses_align_torch():
    """Loss gradients vs torch (reference: src/loss_functions/ —
    LOSS_BWD_TASK writes logit grads)."""
    from flexflow_tpu.core.losses import get_loss_fn
    from flexflow_tpu.ff_types import LossType

    logits = RNG.randn(8, 10).astype(np.float32)
    labels_int = RNG.randint(0, 10, (8, 1)).astype(np.int32)

    # sparse categorical CE (applied on softmax output, like the reference's
    # softmax + sparse-cce pairing)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    fn = get_loss_fn(LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    ours = float(fn(jnp.asarray(probs), jnp.asarray(labels_int)))
    tref = torch.nn.functional.cross_entropy(
        torch.from_numpy(logits), torch.from_numpy(labels_int[:, 0]).long()
    )
    assert abs(ours - float(tref)) < 1e-4

    g = np.asarray(jax.grad(
        lambda p: fn(p, jnp.asarray(labels_int))
    )(jnp.asarray(probs)))
    assert g.shape == probs.shape

    # MSE avg-reduce: reference semantics = sum over features, mean over
    # batch (src/loss_functions/ MSE "avg" divides by batch only)
    y = RNG.randn(8, 10).astype(np.float32)
    fn = get_loss_fn(LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    ours = float(fn(jnp.asarray(logits), jnp.asarray(y)))
    tref = torch.nn.functional.mse_loss(
        torch.from_numpy(logits), torch.from_numpy(y), reduction="sum") / 8
    assert abs(ours - float(tref)) < 1e-3
