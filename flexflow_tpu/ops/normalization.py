"""BatchNorm and LayerNorm operators.

TPU-native equivalents of reference src/ops/batch_norm.cc (cuDNN BN with
running stats) and src/ops/layer_norm.cc (custom CUDA kernels, 446 LoC .cu).
Both are expressed in jnp; XLA fuses the mean/var reductions with the
normalize+scale epilogue, which is what the hand-written CUDA kernels do.

BatchNorm running stats: the reference mutates running_mean/var inside the
fwd task. In our functional design, running stats live in the model's
non-trainable state and the op returns updated stats through ctx.state_out.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp

from ..ff_types import DataType, OperatorType
from .registry import WeightSpec, register_op


@dataclasses.dataclass(frozen=True)
class BatchNormParams:
    """reference: src/ops/batch_norm.cc ctor"""

    relu: bool = True
    momentum: float = 0.9
    eps: float = 1e-5


def _bn_infer(params, in_shapes, in_dtypes):
    return [in_shapes[0]], [in_dtypes[0]]


def _bn_weights(params, in_shapes, in_dtypes):
    c = in_shapes[0][1]  # NCHW
    return [
        WeightSpec("scale", (c,), in_dtypes[0], "one"),
        WeightSpec("bias", (c,), in_dtypes[0], "zero"),
    ]


def _bn_normalize(params, weights, x, mean, var):
    bshape = [1, -1] + [1] * (x.ndim - 2)
    xf = x.astype(jnp.float32)
    y = (xf - mean.reshape(bshape)) / jnp.sqrt(var.reshape(bshape) + params.eps)
    y = y * weights["scale"].astype(jnp.float32).reshape(bshape) + \
        weights["bias"].astype(jnp.float32).reshape(bshape)
    y = y.astype(x.dtype)
    if params.relu:
        y = jnp.maximum(y, 0)
    return y


def _bn_batch_stats(x):
    # Normalize over (N, H, W) per channel — NCHW axes (0, 2, 3)
    axes = (0, 2, 3) if x.ndim == 4 else tuple(i for i in range(x.ndim) if i != 1)
    xf = x.astype(jnp.float32)
    return jnp.mean(xf, axis=axes), jnp.var(xf, axis=axes)


def _bn_forward(params: BatchNormParams, weights, inputs, ctx):
    (x,) = inputs
    mean, var = _bn_batch_stats(x)
    return [_bn_normalize(params, weights, x, mean, var)]


def _bn_state(params, in_shapes, in_dtypes):
    c = in_shapes[0][1]  # NCHW
    return [
        WeightSpec("running_mean", (c,), DataType.DT_FLOAT, "zero"),
        WeightSpec("running_var", (c,), DataType.DT_FLOAT, "one"),
    ]


def _bn_forward_stateful(params: BatchNormParams, weights, state, inputs, ctx):
    """Training: batch stats normalize, running stats update with
    `momentum` (reference: cuDNN BN's exponentialAverageFactor,
    batch_norm.cu). Inference: the RUNNING stats normalize — the piece the
    stateless forward can't do."""
    (x,) = inputs
    if not state:  # stateless caller (cost measurement, decode) — batch stats
        return _bn_forward(params, weights, inputs, ctx), {}
    if ctx.training:
        mean, var = _bn_batch_stats(x)
        m = params.momentum
        new_state = {
            "running_mean": m * state["running_mean"] + (1 - m) * mean,
            "running_var": m * state["running_var"] + (1 - m) * var,
        }
        return [_bn_normalize(params, weights, x, mean, var)], new_state
    return [
        _bn_normalize(params, weights, x, state["running_mean"],
                      state["running_var"])
    ], state


register_op(
    OperatorType.OP_BATCHNORM,
    "BatchNorm",
    infer=_bn_infer,
    weights=_bn_weights,
    forward=_bn_forward,
    state_spec=_bn_state,
    forward_stateful=_bn_forward_stateful,
)


@dataclasses.dataclass(frozen=True)
class LayerNormParams:
    """reference: include/flexflow/ops/layer_norm_params.h"""

    axes: Tuple[int, ...] = (-1,)
    elementwise_affine: bool = True
    eps: float = 1e-5


def _ln_infer(params, in_shapes, in_dtypes):
    return [in_shapes[0]], [in_dtypes[0]]


def _ln_weights(params: LayerNormParams, in_shapes, in_dtypes):
    if not params.elementwise_affine:
        return []
    s = in_shapes[0]
    norm_shape = tuple(s[a % len(s)] for a in params.axes)
    return [
        WeightSpec("scale", norm_shape, in_dtypes[0], "one"),
        WeightSpec("bias", norm_shape, in_dtypes[0], "zero"),
    ]


def _ln_forward(params: LayerNormParams, weights, inputs, ctx):
    (x,) = inputs
    axes = tuple(a % x.ndim for a in params.axes)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + params.eps)
    if params.elementwise_affine:
        bshape = [x.shape[a] if a in axes else 1 for a in range(x.ndim)]
        y = y * weights["scale"].astype(jnp.float32).reshape(bshape)
        y = y + weights["bias"].astype(jnp.float32).reshape(bshape)
    return [y.astype(x.dtype)]


def _ln_seq_pointwise(params, op):
    """Safe on a single decoded token only while the normalized axes
    exclude the sequence axis (axis 1 of a rank>=3 tensor)."""
    nd = len(op.inputs[0].material_shape())
    return nd < 3 or all(a % nd != 1 for a in params.axes)


register_op(
    OperatorType.OP_LAYERNORM,
    "LayerNorm",
    infer=_ln_infer,
    weights=_ln_weights,
    forward=_ln_forward,
    seq_pointwise=_ln_seq_pointwise,
)
