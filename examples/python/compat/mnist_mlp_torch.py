"""PyTorch-FX MNIST MLP through the `flexflow` compat package (reference:
examples/python/pytorch/mnist_mlp.py + mnist_mlp_torch.py — export the torch
module to the flexflow file format, then rebuild with
PyTorchModel.file_to_ff and train)."""
import os
import tempfile

import numpy as np
import torch.nn as nn

from flexflow.core import *  # noqa: F401,F403
from flexflow.torch.model import PyTorchModel, torch_to_flexflow
from flexflow.keras.datasets import mnist


class MLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.linear1 = nn.Linear(784, 512)
        self.linear2 = nn.Linear(512, 512)
        self.linear3 = nn.Linear(512, 10)
        self.relu = nn.ReLU()
        self.softmax = nn.Softmax(dim=-1)

    def forward(self, x):
        y = self.relu(self.linear1(x))
        y = self.relu(self.linear2(y))
        return self.softmax(self.linear3(y))


def top_level_task(epochs=1, n_samples=4096):
    # reference mnist_mlp_torch.py: torch_to_flexflow(model, "mlp.ff")
    path = os.path.join(tempfile.gettempdir(), "mlp.ff")
    torch_to_flexflow(MLP(), path)

    ffconfig = FFConfig()
    ffmodel = FFModel(ffconfig)
    input_tensor = ffmodel.create_tensor(
        [ffconfig.batch_size, 784], DataType.DT_FLOAT)

    # reference mnist_mlp.py: PyTorchModel.file_to_ff("mlp.ff", ...)
    output_tensors = PyTorchModel.file_to_ff(path, ffmodel, [input_tensor])

    ffoptimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.optimizer = ffoptimizer
    ffmodel.compile(
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY,
                 MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])
    label_tensor = ffmodel.label_tensor

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train[:n_samples].reshape(n_samples, 784).astype('float32') / 255
    y_train = y_train[:n_samples].astype('int32').reshape(-1, 1)

    dataloader_input = ffmodel.create_data_loader(input_tensor, x_train)
    dataloader_label = ffmodel.create_data_loader(label_tensor, y_train)
    ffmodel.init_layers()
    ffmodel.fit(x=dataloader_input, y=dataloader_label, epochs=epochs)
    return ffmodel.get_perf_metrics().get_accuracy()


if __name__ == "__main__":
    print("mnist mlp torch (compat)")
    top_level_task()
