"""Elastic runtime tests (runtime/elastic.py): topology fingerprinting,
checkpoint resharding onto a shrunk mesh with strategy re-search, the
health watchdog, and host-loss fault injection.

Everything runs on the CPU mesh (8 virtual devices, conftest.py);
`shrunk_devices` simulates host loss by shrinking what jax.devices()
reports. The multi-topology chaos sweep is @pytest.mark.slow and runs
standalone via scripts/elastic_check.sh."""
import os
import time

import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.runtime.elastic import (
    ElasticRestoreError,
    FileHeartbeat,
    HealthMonitor,
    restore_elastic,
    shrunk_devices,
    topology_fingerprint,
    topology_matches,
    validate_machine_views,
)
from flexflow_tpu.runtime.resilience import (
    CheckpointManager,
    CollectiveTimeout,
    FaultInjector,
    HostLossError,
)

# scripts/elastic_check.sh re-runs this suite on 8/4/2-device process
# meshes (JAX_NUM_CPU_DEVICES, conftest.py); cases that encode the
# 8-device tier-1 topology (or shrink to 4 inside the process) skip on
# smaller meshes instead of asserting a device count that isn't there
import jax  # noqa: E402  (conftest configured the platform already)

NDEV = len(jax.devices())
needs8 = pytest.mark.skipif(
    NDEV != 8, reason="encodes the 8-device tier-1 mesh"
)
needs4 = pytest.mark.skipif(NDEV < 4, reason="needs >= 4 devices")


def small_model(hidden=16, batch=32, machine_file=None, search_budget=None):
    cfg = FFConfig()
    cfg.batch_size = batch
    if machine_file is not None:
        cfg.machine_model_file = machine_file
    if search_budget is not None:
        cfg.search_budget = search_budget
    m = FFModel(cfg)
    x = m.create_tensor((batch, 4), DataType.DT_FLOAT)
    t = m.dense(x, hidden, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1, momentum=0.9),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = rng.randint(0, 3, (n, 1)).astype(np.int32)
    return x, y


def params_of(m):
    # copy=True: np.asarray(jax_array) can be a zero-copy view on CPU,
    # which the donated train step overwrites on the next fit (see
    # tests/test_resilience.py params_of)
    return {
        name: {k: np.array(v, copy=True) for k, v in wd.items()}
        for name, wd in m.state.params.items()
    }


def assert_params_close(a, b, atol=1e-6):
    for name, wd in a.items():
        for k, v in wd.items():
            np.testing.assert_allclose(b[name][k], v, atol=atol,
                                       err_msg=f"{name}/{k}")


def slow_chip_machine(tmp_path, workers=8):
    """A machine file whose chips are slow and links fast, so the
    strategy search actually spreads work (the TPU-spec defaults make a
    toy model's compute free relative to any collective, and the search
    rightly picks a single device)."""
    p = str(tmp_path / "slow_machine.cfg")
    with open(p, "w") as f:
        f.write(f"num_nodes = 1\nworkers_per_node = {workers}\n"
                "peak_flops_bf16 = 1e9\nhbm_bandwidth = 1e9\n"
                "ici_bandwidth = 1e12\nici_latency = 1e-9\n")
    return p


# ----------------------------------------------------------------------
# topology fingerprinting
# ----------------------------------------------------------------------
def test_topology_fingerprint_shape_and_match():
    m = small_model()
    fp = topology_fingerprint(m.executor.mesh)
    assert fp["num_devices"] == int(m.executor.mesh.devices.size)
    assert fp["platform"] == "cpu"
    assert fp["mesh_axes"]  # named axis -> size
    assert topology_matches(fp, dict(fp))
    changed = dict(fp, num_devices=fp["num_devices"] + 1)
    assert not topology_matches(fp, changed)
    # pre-v3 sidecars carry no fingerprint: treated as unchanged
    assert topology_matches(None, fp)
    assert topology_matches(fp, None)


@needs4
def test_fingerprint_without_mesh_uses_process_devices():
    import jax

    fp = topology_fingerprint()
    assert fp["num_devices"] == len(jax.devices())
    with shrunk_devices(4):
        assert topology_fingerprint()["num_devices"] == 4
    assert topology_fingerprint()["num_devices"] == fp["num_devices"]


def test_validate_machine_views_flags_dead_devices():
    from flexflow_tpu.pcg.machine_view import MachineView

    ok = MachineView(start_device_id=0, dim=(4,), stride=(1,))
    bad = MachineView(start_device_id=4, dim=(4,), stride=(1,))
    assert validate_machine_views({1: ok, 2: None}, 4) == []
    violations = validate_machine_views({1: ok, 2: bad}, 4)
    assert len(violations) == 1 and "op 2" in violations[0]


def test_checkpoint_sidecar_records_topology_and_views(tmp_path):
    from flexflow_tpu.runtime.checkpoint import load_checkpoint_meta

    m = small_model()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(m, step=1)
    meta = load_checkpoint_meta(mgr.step_path(1))
    assert meta["version"] >= 3
    topo = meta["topology"]
    assert topo["num_devices"] == int(m.executor.mesh.devices.size)
    # every op record carries the strategy fields an elastic restore reads
    for rec in meta["ops"]:
        assert {"name", "op_type", "machine_view", "output_degrees",
                "weight_degrees"} <= set(rec)


# ----------------------------------------------------------------------
# elastic resume across a topology change (the acceptance demo)
# ----------------------------------------------------------------------
@needs8
def test_restore_elastic_8_to_4_params_identical(tmp_path):
    """Checkpoint written on the 8-device mesh restores onto a 4-device
    survivor: strategy re-planned, params bit-identical after gather."""
    x, y = dataset(64)
    m8 = small_model()
    assert int(m8.executor.mesh.devices.size) == 8
    m8.fit(x, y, epochs=1, verbose=False, checkpoint_dir=str(tmp_path),
           checkpoint_every_n_steps=1)
    ref = params_of(m8)

    with shrunk_devices(4):
        m4, info = restore_elastic(small_model, str(tmp_path))
        assert int(m4.executor.mesh.devices.size) == 4
        assert info.step == m8.state.step
        saved_topo = info.meta["topology"]
        live_topo = topology_fingerprint(m4.executor.mesh)
        assert saved_topo["num_devices"] == 8
        assert live_topo["num_devices"] == 4
        assert not topology_matches(saved_topo, live_topo)
        assert_params_close(ref, params_of(m4), atol=0)  # bit-identical


@needs8
def test_elastic_resume_matches_uninterrupted_4dev_run(tmp_path):
    """8-device run killed after epoch 1 resumes on 4 devices and lands
    on the same params as a 4-device run that was never interrupted."""
    x, y = dataset(64)
    # reference: uninterrupted 2-epoch run entirely on 4 devices
    with shrunk_devices(4):
        mref = small_model()
        mref.fit(x, y, epochs=2, verbose=False)
        ref = params_of(mref)

    # elastic run: epoch 1 on 8 devices (same init: same seed), then the
    # pod shrinks and the run resumes on 4
    m8 = small_model()
    m8.fit(x, y, epochs=1, verbose=False, checkpoint_dir=str(tmp_path))
    with shrunk_devices(4):
        m4, info = restore_elastic(small_model, str(tmp_path))
        m4.fit(x, y, epochs=2, verbose=False, checkpoint_dir=str(tmp_path),
               elastic=True)
        # deterministic data order + SGD: only collective reduction order
        # differs between the 8- and 4-way epoch-1 sums
        assert_params_close(ref, params_of(m4), atol=1e-5)


@needs8
def test_fit_elastic_true_recompiles_after_shrink(tmp_path):
    """fit(elastic=True) itself notices the stale mesh (mesh_is_live
    False after a shrink) and re-plans before resuming."""
    x, y = dataset(64)
    m = small_model()
    m.fit(x, y, epochs=1, verbose=False, checkpoint_dir=str(tmp_path))
    ref = params_of(m)
    with shrunk_devices(4):
        assert not m.executor.mesh_is_live()
        m.fit(x, y, epochs=2, verbose=False, checkpoint_dir=str(tmp_path),
              elastic=True)
        assert int(m.executor.mesh.devices.size) == 4
        assert m.executor.mesh_is_live()
    # epoch 1 state was restored (not re-initialized) before epoch 2 ran
    assert m.state.step > 0


@needs8
def test_searched_strategy_researched_for_shrunk_machine(tmp_path):
    """With a machine file that makes the search spread (slow chips), the
    8-device searched strategy is re-searched for 4 survivors: new
    MachineViews are valid for (and the mesh spans exactly) the live
    device set."""
    mf = slow_chip_machine(tmp_path)
    x, y = dataset(64)

    def model_fn():
        return small_model(machine_file=mf, search_budget=4)

    m8 = model_fn()
    assert int(m8.executor.mesh.devices.size) == 8
    assert validate_machine_views(m8.searched_views, 8) == []
    # the 8-wide plan is NOT valid for a 4-device survivor
    assert validate_machine_views(m8.searched_views, 4) != []
    m8.fit(x, y, epochs=1, verbose=False, checkpoint_dir=str(tmp_path))
    ref = params_of(m8)

    with shrunk_devices(4):
        m4, info = restore_elastic(model_fn, str(tmp_path))
        assert int(m4.executor.mesh.devices.size) == 4
        assert validate_machine_views(m4.searched_views, 4) == []
        assert_params_close(ref, params_of(m4), atol=0)
        # the sidecar still remembers the 8-device plan it was saved under
        assert info.meta["topology"]["num_devices"] == 8


def test_restore_elastic_no_checkpoint_raises(tmp_path):
    with pytest.raises(ElasticRestoreError, match="no restorable"):
        restore_elastic(small_model, str(tmp_path / "empty"))


@needs4
def test_research_views_and_for_device_count(tmp_path):
    """The search-layer elastic entries: for_device_count re-targets a
    machine at the survivor count keeping chip constants; research_views
    reassigns valid views for it without a full substitution search."""
    from flexflow_tpu.search import (
        CostModel,
        MachineModel,
        for_device_count,
        research_views,
    )

    base = MachineModel(num_nodes=2, workers_per_node=4)
    m4 = for_device_count(4, like=base)
    assert m4.num_workers == 4 and m4.workers_per_node == 4
    assert m4.chip is base.chip or m4.chip == base.chip
    m6 = for_device_count(6, like=base)
    assert m6.num_workers == 6  # 4 doesn't divide 6: falls back to 3x2
    assert for_device_count(1, like=base).num_workers == 1

    # a graph searched for 4 devices (degree-4 structure) re-views onto a
    # GROWN 8-device machine without a full substitution search...
    with shrunk_devices(4):
        model = small_model(machine_file=slow_chip_machine(tmp_path, 4),
                            search_budget=4)
        assert int(model.executor.mesh.devices.size) == 4
    machine8 = for_device_count(8, like=model._build_cost_model().machine)
    result = research_views(model.graph, CostModel(machine8))
    assert result.cost != float("inf")
    assert validate_machine_views(result.views, 8) == []
    # ...but its degree-4 STRUCTURE cannot be re-viewed onto 2 devices:
    # infinity tells the elastic layer a full re-compile must re-search
    machine2 = for_device_count(2, like=model._build_cost_model().machine)
    assert research_views(model.graph, CostModel(machine2)).cost \
        == float("inf")


# ----------------------------------------------------------------------
# health watchdog
# ----------------------------------------------------------------------
def test_watchdog_detects_hung_step_and_flushes_checkpoint(tmp_path):
    """Acceptance: an injected hung step is detected within the timeout
    and escalates through checkpoint-and-raise (CollectiveTimeout)."""
    x, y = dataset(64)
    m = small_model()
    fi = FaultInjector().inject("hung_step", at_step=3)
    mon = HealthMonitor(timeout_s=0.5)
    t0 = time.monotonic()
    try:
        with pytest.raises(CollectiveTimeout) as ei:
            m.fit(x, y, epochs=2, verbose=False,
                  checkpoint_dir=str(tmp_path), checkpoint_every_n_steps=2,
                  fault_injector=fi, health_monitor=mon)
    finally:
        mon.stop()
    elapsed = time.monotonic() - t0
    assert ei.value.info["kind"] == "hung_step"
    assert ei.value.step == 3
    # detection bounded by the timeout (+ slack for the poll interval,
    # jit compile of the steps before the hang, and a slow CI host)
    assert elapsed < 30.0
    assert fi.fired["hung_step"] == 1
    # the last good state was flushed on the way out...
    assert ei.value.checkpoint_path is not None
    assert os.path.isdir(ei.value.checkpoint_path)
    # ...and a fresh process resumes from it
    m2 = small_model()
    m2.fit(x, y, epochs=2, verbose=False, checkpoint_dir=str(tmp_path))
    assert m2.state.step == 4  # 2 epochs x (64/32) steps, resumed


def test_watchdog_quiet_on_healthy_run(tmp_path):
    x, y = dataset(64)
    m = small_model()
    mon = HealthMonitor(timeout_s=30.0)
    try:
        m.fit(x, y, epochs=1, verbose=False, health_monitor=mon)
        assert not mon.hang_detected
    finally:
        mon.stop()


def test_file_heartbeat_detects_straggler(tmp_path):
    hb_dir = str(tmp_path / "hb")
    me = FileHeartbeat(hb_dir, "host0", stale_after_s=0.2)
    peer = FileHeartbeat(hb_dir, "host1", stale_after_s=0.2)
    peer.beat()
    assert me() == []  # fresh peer: healthy
    mon = HealthMonitor(timeout_s=5.0, heartbeat_fn=me,
                        heartbeat_interval_s=0.05).start()
    try:
        deadline = time.monotonic() + 5.0
        while not mon.hang_detected and time.monotonic() < deadline:
            time.sleep(0.05)  # host1 never beats again -> goes stale
        assert mon.hang_detected
        assert mon.hang_info["kind"] == "straggler"
        assert mon.hang_info["peers"] == ["host1"]
    finally:
        mon.stop()


def test_file_heartbeat_missing_expected_peer():
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        me = FileHeartbeat(d, "host0", stale_after_s=30.0,
                           expected_peers=["host0", "host1"])
        assert me() == ["host1"]  # expected but never appeared


def test_heartbeat_error_escalates():
    def broken():
        raise RuntimeError("transport down")

    mon = HealthMonitor(timeout_s=5.0, heartbeat_fn=broken,
                        heartbeat_interval_s=0.05).start()
    try:
        deadline = time.monotonic() + 5.0
        while not mon.hang_detected and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.hang_detected
        assert mon.hang_info["kind"] == "heartbeat_error"
    finally:
        mon.stop()


def test_on_hang_callback_fires_once():
    calls = []
    mon = HealthMonitor(timeout_s=0.1, poll_interval_s=0.02,
                        on_hang=calls.append, compile_grace_s=0.0)
    mon.start()
    try:
        mon.step_started(7)
        deadline = time.monotonic() + 5.0
        while not mon.hang_detected and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.hang_detected
        assert len(calls) == 1 and calls[0]["step"] == 7
    finally:
        mon.stop()


def test_first_step_gets_compile_grace():
    """The first step of a run is usually inside XLA compilation — which
    takes minutes at scale, not timeout_s — so the hung-step check gives
    it compile_grace_s of extra slack; steady-state steps get the tight
    timeout (flaked as a spurious step-0 'hang' on cold-cache CI before
    the grace window existed)."""
    mon = HealthMonitor(timeout_s=0.1, poll_interval_s=0.02,
                        compile_grace_s=30.0)
    mon.start()
    try:
        mon.step_started(0)        # "compiling": outlives timeout_s...
        time.sleep(0.5)
        assert not mon.hang_detected   # ...but sits inside the grace
        mon.step_finished(0)
        mon.step_started(1)        # steady state: tight timeout applies
        deadline = time.monotonic() + 5.0
        while not mon.hang_detected and time.monotonic() < deadline:
            time.sleep(0.02)
        assert mon.hang_detected
        assert mon.hang_info["kind"] == "hung_step"
        assert mon.hang_info["step"] == 1
    finally:
        mon.stop()


# ----------------------------------------------------------------------
# host-loss fault injection -> elastic restart
# ----------------------------------------------------------------------
@needs4
def test_host_loss_flushes_then_elastic_restart(tmp_path):
    """The orchestrator-eye view: HostLossError carries the survivor
    count, the final checkpoint is flushed, and the restarted run picks
    up on the shrunk machine."""
    x, y = dataset(64)
    m = small_model()
    fi = FaultInjector().inject("host_loss", at_step=1, surviving_devices=4)
    with pytest.raises(HostLossError) as ei:
        m.fit(x, y, epochs=2, verbose=False, checkpoint_dir=str(tmp_path),
              fault_injector=fi)
    assert ei.value.surviving_devices == 4
    assert ei.value.checkpoint_path is not None  # graceful: state flushed

    with shrunk_devices(ei.value.surviving_devices):
        m2, info = restore_elastic(small_model, str(tmp_path))
        assert info.step == 1
        m2.fit(x, y, epochs=2, verbose=False, checkpoint_dir=str(tmp_path),
               elastic=True)
        assert m2.state.step == 4  # 2 epochs x 2 steps, resumed mid-run


# ----------------------------------------------------------------------
# slow chaos sweep (scripts/elastic_check.sh)
# ----------------------------------------------------------------------
@pytest.mark.slow
@needs8
def test_elastic_shrink_sweep_8_4_2(tmp_path):
    """8 -> 4 -> 2 device shrink chain: each resume restores the previous
    topology's checkpoint bit-identically and keeps training."""
    x, y = dataset(64)
    m = small_model()
    m.fit(x, y, epochs=1, verbose=False, checkpoint_dir=str(tmp_path))
    prev = params_of(m)
    expected_step = m.state.step
    for n, epochs in ((4, 2), (2, 3)):
        with shrunk_devices(n):
            mn, info = restore_elastic(small_model, str(tmp_path))
            assert int(mn.executor.mesh.devices.size) == n
            assert info.step == expected_step
            assert_params_close(prev, params_of(mn), atol=0)
            mn.fit(x, y, epochs=epochs, verbose=False,
                   checkpoint_dir=str(tmp_path), elastic=True)
            prev = params_of(mn)
            expected_step = mn.state.step
    assert expected_step == 3 * 2  # 3 epochs total, 2 steps each


@pytest.mark.slow
@needs4
def test_hung_step_then_elastic_restart_on_survivors(tmp_path):
    """The full production story in one test: a collective hangs (host
    died mid-psum), the watchdog checkpoints-and-raises, the orchestrator
    restarts on the survivors, training continues elastically."""
    x, y = dataset(64)
    m = small_model()
    fi = FaultInjector().inject("hung_step", at_step=2)
    mon = HealthMonitor(timeout_s=0.5)
    try:
        with pytest.raises(CollectiveTimeout) as ei:
            m.fit(x, y, epochs=3, verbose=False,
                  checkpoint_dir=str(tmp_path), checkpoint_every_n_steps=1,
                  fault_injector=fi, health_monitor=mon)
    finally:
        mon.stop()
    assert ei.value.checkpoint_path is not None
    with shrunk_devices(4):
        m2, info = restore_elastic(small_model, str(tmp_path))
        assert info.step == 2
        mon2 = HealthMonitor(timeout_s=30.0)
        try:
            m2.fit(x, y, epochs=3, verbose=False,
                   checkpoint_dir=str(tmp_path), elastic=True,
                   health_monitor=mon2)
            assert not mon2.hang_detected
        finally:
            mon2.stop()
        assert m2.state.step == 3 * 2
