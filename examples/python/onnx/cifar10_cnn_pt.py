"""Export the CIFAR-10 CNN to ONNX, torch layout (reference:
examples/python/onnx/cifar10_cnn_pt.py)."""
import numpy as np

from flexflow.onnx.model import proto


def _conv(rng, name, cin, cout, nodes, inits, prev, out):
    w = (rng.randn(cout, cin, 3, 3) / np.sqrt(cin * 9)).astype(np.float32)
    b = np.zeros(cout, np.float32)
    inits += [proto.from_array(w, f"{name}.weight"),
              proto.from_array(b, f"{name}.bias")]
    nodes.append(proto.make_node(
        "Conv", [prev, f"{name}.weight", f"{name}.bias"], [out], name=name,
        kernel_shape=[3, 3], strides=[1, 1], pads=[1, 1, 1, 1]))
    nodes.append(proto.make_node("Relu", [out], [out + "_r"], name=name + "_relu"))
    return out + "_r"


def export(path="cifar10_cnn_pt.onnx", seed=0):
    rng = np.random.RandomState(seed)
    nodes, inits = [], []
    prev = "input.1"
    prev = _conv(rng, "conv1", 3, 32, nodes, inits, prev, "c1")
    prev = _conv(rng, "conv2", 32, 32, nodes, inits, prev, "c2")
    nodes.append(proto.make_node("MaxPool", [prev], ["p1"], name="pool1",
                                 kernel_shape=[2, 2], strides=[2, 2]))
    prev = _conv(rng, "conv3", 32, 64, nodes, inits, "p1", "c3")
    prev = _conv(rng, "conv4", 64, 64, nodes, inits, prev, "c4")
    nodes.append(proto.make_node("MaxPool", [prev], ["p2"], name="pool2",
                                 kernel_shape=[2, 2], strides=[2, 2]))
    nodes.append(proto.make_node("Flatten", ["p2"], ["flat"], name="flatten", axis=1))
    w = (rng.randn(512, 64 * 8 * 8) / 64).astype(np.float32)
    b = np.zeros(512, np.float32)
    w2 = (rng.randn(10, 512) / 16).astype(np.float32)
    b2 = np.zeros(10, np.float32)
    inits += [proto.from_array(w, "fc1.weight"), proto.from_array(b, "fc1.bias"),
              proto.from_array(w2, "fc2.weight"), proto.from_array(b2, "fc2.bias")]
    nodes.append(proto.make_node("Gemm", ["flat", "fc1.weight", "fc1.bias"],
                                 ["g1"], name="fc1", transB=1))
    nodes.append(proto.make_node("Relu", ["g1"], ["g1r"], name="fc1_relu"))
    nodes.append(proto.make_node("Gemm", ["g1r", "fc2.weight", "fc2.bias"],
                                 ["g2"], name="fc2", transB=1))
    nodes.append(proto.make_node("Softmax", ["g2"], ["output"], name="softmax",
                                 axis=-1))
    graph = proto.make_graph(
        nodes, "torch_jit",
        [proto.make_tensor_value_info("input.1", proto.TensorProto.FLOAT,
                                      ["N", 3, 32, 32])],
        [proto.make_tensor_value_info("output", proto.TensorProto.FLOAT,
                                      ["N", 10])],
        initializer=inits)
    proto.save_model(proto.make_model(graph), path)
    return path


if __name__ == "__main__":
    print("exported", export())
