"""ResNet-50 through the native-python core API (reference:
examples/python/native/resnet.py; network from models/resnet)."""
from flexflow.core import *  # noqa: F401,F403
import numpy as np

from flexflow_tpu.models.resnet import build_resnet


def top_level_task(num_samples=256, epochs=None, height=64, width=64):
    ffconfig = FFConfig()
    ffmodel = FFModel(ffconfig)

    input_tensor, _ = build_resnet(
        ffmodel, batch_size=ffconfig.batch_size, num_classes=10,
        height=height, width=width)

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY,
                 MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])
    label_tensor = ffmodel.label_tensor

    rng = np.random.RandomState(0)
    x_train = rng.rand(num_samples, 3, height, width).astype("float32")
    y_train = rng.randint(0, 10, (num_samples, 1)).astype("int32")

    dl_x = ffmodel.create_data_loader(input_tensor, x_train)
    dl_y = ffmodel.create_data_loader(label_tensor, y_train)

    ffmodel.init_layers()
    epochs = epochs or ffconfig.epochs
    ts_start = ffconfig.get_current_time()
    ffmodel.fit(x=dl_x, y=dl_y, epochs=epochs)
    ts_end = ffconfig.get_current_time()
    run_time = 1e-6 * (ts_end - ts_start)
    print("epochs %d, ELAPSED TIME = %.4fs, THROUGHPUT = %.2f samples/s\n" % (
        epochs, run_time, num_samples * epochs / run_time))
    return ffmodel.get_perf_metrics()


if __name__ == "__main__":
    print("resnet")
    top_level_task()
