#!/usr/bin/env bash
# Fleet cold-start sweep (docs/artifact_cache.md): the persistent
# strategy/artifact store makes a replica boot a cache lookup instead of
# a from-scratch Unity search.
#
#   leg 1  tests/test_artifact_store.py full suite (including the
#          @pytest.mark.slow 8->4->8 zero-redundant-search story tier-1
#          skips) on 8- and 4-device CPU meshes
#   leg 2  populate -> kill -> cold-boot: one process compiles with the
#          store and exits; a SECOND process (true cold start) must
#          replay the cached strategy with zero searches. Then the
#          corrupt-entry chaos leg: a bit-flipped entry must degrade to
#          a fresh search (typed + quarantined + counted), never crash.
#   leg 3  load_check kill-mid-ramp cold-start p95 WITHOUT the store vs
#          WITH it — both printed; the with-store p95 must be lower.
#
#   scripts/coldstart_check.sh                 # full sweep
#   FF_COLDSTART_DEVICES=8 scripts/coldstart_check.sh
set -euo pipefail
cd "$(dirname "$0")/.."

devices="${FF_COLDSTART_DEVICES:-8 4}"
for n in $devices; do
    echo "=== artifact store suite: ${n}-device CPU mesh ==="
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_artifact_store.py -v \
        -p no:cacheprovider "$@"
done

OUT="$(mktemp -d)"
trap 'rm -rf "$OUT"' EXIT

coldboot() {  # $1 = mode: populate | coldboot | corrupt
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES=8 \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        FF_COLDSTART_DIR="$OUT/store" \
        FF_COLDSTART_MODE="$1" \
        python - <<'EOF'
import os
import sys

import numpy as np

from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer)
from flexflow_tpu.core.model import FFModel as _FF
from flexflow_tpu.runtime.artifact_store import ArtifactStore

mode = os.environ["FF_COLDSTART_MODE"]
store = ArtifactStore(os.environ["FF_COLDSTART_DIR"])

searches = []
orig = _FF._run_strategy_search
_FF._run_strategy_search = lambda self, n: (searches.append(n),
                                            orig(self, n))[1]

if mode == "corrupt":
    # bit-flip every entry: the cold boot below must degrade to a fresh
    # search with the poison quarantined and counted — never crash,
    # never a wrong strategy
    for name in store.entries():
        path = os.path.join(store.entries_dir, name)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x20
        open(path, "wb").write(bytes(raw))

cfg = FFConfig()
cfg.batch_size = 32
cfg.search_budget = 20
m = FFModel(cfg)
x = m.create_tensor((32, 4), DataType.DT_FLOAT)
t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
t = m.dense(t, 3)
t = m.softmax(t)
m.compile(SGDOptimizer(lr=0.1),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY], artifact_store=store)
rng = np.random.RandomState(0)
m.fit(x=[rng.randn(64, 4).astype(np.float32)],
      y=rng.randint(0, 3, (64, 1)).astype(np.int32),
      epochs=1, verbose=False)

prov = m.strategy_provenance
print(f"[coldstart_check] {mode}: provenance={prov} "
      f"searches={len(searches)} counts={store.counts}", file=sys.stderr)
if mode == "populate":
    assert prov["cause"] == "cache_miss" and len(searches) == 1, prov
    assert store.entries(), "populate wrote no entry"
elif mode == "coldboot":
    assert prov["source"] == "artifact_cache", \
        f"cold boot re-searched: {prov}"
    assert searches == [], f"cold boot ran {len(searches)} search(es)"
    assert store.counts.get("hit") == 1, store.counts
elif mode == "corrupt":
    assert prov == {"source": "search", "cause": "cache_corrupt"}, prov
    assert len(searches) == 1
    assert store.counts.get("corrupt", 0) >= 1, store.counts
    import glob
    q = glob.glob(os.path.join(store.quarantine_dir, "*.corrupt-*"))
    assert q, "corrupt entry was not quarantined"
EOF
}

echo "=== cold start: populate -> kill -> cold boot ==="
coldboot populate
coldboot coldboot
echo "=== cold start: corrupt-entry chaos leg ==="
coldboot corrupt

echo "=== load_check cold-start p95: without vs with store ==="
# a real search budget so replica builds are search-dominated — the
# thing the store exists to skip; short phases keep CI wall clock sane.
# p99 is relaxed: search-dominated rebuilds intentionally steal CPU from
# the batcher here (this leg asserts the cold-start p95 criterion; the
# tail-latency contract is serving_check.sh's, under its own args)
LOAD_ARGS="--search-budget 20 --warm-s 2 --ramp-s 3 --post-s 2 \
    --base-rate 4 --ramp 4 --p99-factor 10"
env JAX_PLATFORMS=cpu JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/load_check.py $LOAD_ARGS --json "$OUT/without.json"
env JAX_PLATFORMS=cpu JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/load_check.py $LOAD_ARGS --json "$OUT/with.json" \
    --artifact-store "$OUT/load_store"
python - "$OUT/without.json" "$OUT/with.json" <<'EOF'
import json
import sys

without = json.load(open(sys.argv[1]))["cold_start"]
with_ = json.load(open(sys.argv[2]))["cold_start"]
print(f"[coldstart_check] replica cold-start p95: "
      f"without store {without['p95_s']}s "
      f"({without['builds']} builds) vs "
      f"with store {with_['p95_s']}s "
      f"({with_['builds']} builds, cache {with_['cache_counts']})")
assert with_["cache_counts"]["hit"] >= 1, with_
assert with_["p95_s"] < without["p95_s"], (
    f"store did not lower cold-start p95: {with_['p95_s']}s vs "
    f"{without['p95_s']}s"
)
EOF

echo "coldstart_check: OK"
