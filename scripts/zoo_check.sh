#!/usr/bin/env bash
# Workload-zoo gate (ISSUE 14): the searched MoE + 32k long-context
# flagships as first-class CI citizens (docs/models.md), hardware-free.
#
# Leg 1 runs tests/test_workload_zoo.py on the tier-1-shaped 8-device
# CPU mesh — including the slow cases: search beats pure data parallel,
# verify_strategy matches the serial lowering, the expert dispatch
# exports nonzero ff_pcg_collective_bytes{kind="all_to_all"}. Leg 2
# re-runs the FULL static pass stack (analysis.analyze_graph) over both
# searched strategies and fails on any ERROR diagnostic. Leg 3 repeats
# search + verify + analyzer on a 4-device mesh (the degree ladder must
# adapt, not break). Leg 4 lints the shipped expert-routing rule
# collections with the FFA4xx substitution lint. Use before touching
# models/zoo.py, search/substitution.py's expert/seq generators,
# parallel/strategies.py's expert lowering, or the zoo JSON rules:
#
#   scripts/zoo_check.sh             # all legs
#   scripts/zoo_check.sh -k moe      # filter leg 1's pytest
set -euo pipefail
cd "$(dirname "$0")/.."

run_on() {
    local devs="$1"
    shift
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$devs" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$devs" \
        "$@"
}

echo "=== zoo leg 1: workload suite incl. search+verify (8 devices) ==="
run_on 8 python -m pytest tests/test_workload_zoo.py -v \
    -p no:cacheprovider "$@"

sweep() {
    # search + static pass stack (+ optional verify) over both flagships
    # on the live mesh; ZOO_VERIFY=1 adds the differential replay
    run_on "$1" python - <<'PY'
import os

import jax
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.analysis import analyze_graph
from flexflow_tpu.models import (
    build_long_context_transformer,
    build_moe_transformer,
)
from flexflow_tpu.runtime.verify import verify_strategy

ndev = len(jax.devices())
verify = os.environ.get("ZOO_VERIFY") == "1"
rng = np.random.RandomState(0)

def check(name, build, batch, data):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = 24
    m = FFModel(cfg)
    build(m)
    m.compile(SGDOptimizer(lr=0.05),
              loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              metrics=[])
    cm = m._build_cost_model()
    rep = analyze_graph(
        m.graph, views=getattr(m, "searched_views", None),
        num_devices=ndev, hbm_bytes=cm.machine.chip.hbm_capacity,
        optimizer=m.optimizer, train=m._is_training_compile(),
        grad_bytes_ratio=m._grad_bytes_ratio(), cost_model=cm,
        executor=m.executor,
    )
    assert not rep.errors, (name, [str(d) for d in rep.errors])
    print(f"{name}: analyzer clean on {ndev} devices "
          f"({len(rep.warnings)} warning(s)), "
          f"searched cost {m.searched_cost:.4f}s")
    if verify:
        v = verify_strategy(m, data, steps=3)
        assert v.ok and not v.validator_problems, (name, v)
        print(f"{name}: verify_strategy ok on {ndev} devices")

check(
    "moe_transformer",
    lambda m: build_moe_transformer(
        m, batch_size=16, seq_length=64, hidden_size=768, num_heads=4,
        num_layers=2, num_experts=4, top_k=2, capacity_factor=1.2,
        lambda_bal=0.04),
    16,
    (rng.randn(16, 64, 768).astype(np.float32),
     rng.randint(0, 10, (16, 64, 1)).astype(np.int32)),
)
check(
    "long_context_transformer",
    lambda m: build_long_context_transformer(
        m, batch_size=4, seq_length=512, hidden_size=64, num_heads=8,
        num_layers=2),
    4,
    (rng.randn(4, 512, 64).astype(np.float32),
     rng.randint(0, 10, (4, 512, 1)).astype(np.int32)),
)
PY
}

echo "=== zoo leg 2: static pass stack over searched strategies (8 devices) ==="
sweep 8

echo "=== zoo leg 3: search + verify + analyzer on the 4-device mesh ==="
ZOO_VERIFY=1 sweep 4

echo "=== zoo leg 4: FFA4xx lint of the shipped expert-routing rules ==="
python -m flexflow_tpu.analysis rules \
    flexflow_tpu/search/substitutions/graph_subst_zoo_v1.json \
    flexflow_tpu/search/substitutions/moe_capacity_v1.json \
    --fail-on error

echo "zoo_check: all legs passed"
