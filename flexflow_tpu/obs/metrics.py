"""Metrics registry: counters, gauges, histograms.

The reference surfaces runtime health as scattered prints; here every
runtime subsystem feeds named series in one registry, exported as a
Prometheus text file (node-exporter textfile-collector compatible) and as
JSONL snapshots. Series support optional labels (`registry.counter(name,
kind="all-reduce")`) and are thread-safe: family/label-map creation is
guarded by the registry lock, and every series carries its OWN lock for
value updates (reservoir appends included) — updates come from the
training loop, every replica's serve thread, the batcher, watchdog and
health-monitor threads concurrently, so hot-path observes must not
serialize against each other on one global lock.

Naming follows Prometheus conventions: `ff_<noun>_<unit>` gauges /
histograms, `ff_<noun>_total` counters, base units (seconds, bytes).
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

# default histogram buckets: 100us .. ~2min, log-spaced — wide enough for
# both per-step wall times and serving latencies
DEFAULT_BUCKETS = tuple(
    1e-4 * (2.5 ** i) for i in range(12)
) + (float("inf"),)

_RESERVOIR = 4096  # raw samples kept per histogram for exact quantiles


class Counter:
    __slots__ = ("value", "_lock")

    kind = "counter"

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("value", "_lock")

    kind = "gauge"

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Cumulative-bucket histogram + a bounded reservoir of raw samples
    (newest `_RESERVOIR`) so `quantile()` reports exact percentiles of
    recent traffic instead of bucket-edge approximations."""

    __slots__ = ("buckets", "counts", "sum", "count", "_samples", "_lock")

    kind = "histogram"

    def __init__(self, lock, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        if not self.buckets or self.buckets[-1] != float("inf"):
            self.buckets = self.buckets + (float("inf"),)
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0
        self._samples: List[float] = []
        self._lock = lock

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    break
            self._samples.append(v)
            if len(self._samples) > _RESERVOIR:
                del self._samples[: len(self._samples) - _RESERVOIR]

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return float("nan")
            s = sorted(self._samples)
        i = min(len(s) - 1, max(0, int(math.ceil(q * len(s))) - 1))
        return s[i]

    # -- mergeable state (fleet aggregation) ----------------------------
    def state(self, max_samples: int = _RESERVOIR) -> dict:
        """JSON-serializable mergeable state: bucket edges/counts, sum,
        count, and (a bounded stride-subsample of) the reservoir, so a
        fleet aggregator can reconstruct cross-process percentiles."""
        with self._lock:
            samples = list(self._samples)
            counts = list(self.counts)
            total, n = self.sum, self.count
        if len(samples) > max_samples:
            stride = len(samples) / max_samples
            samples = [samples[int(i * stride)] for i in range(max_samples)]
        return {"buckets": list(self.buckets), "counts": counts,
                "sum": total, "count": n, "samples": samples}

    def merge_state(self, state: dict) -> None:
        """Fold another histogram's `state()` into this one. Bucket edges
        must match (or this histogram must still be empty, in which case
        it adopts the incoming edges); the reservoirs are concatenated
        and stride-subsampled back under the cap so merged quantiles
        reflect both populations."""
        edges = tuple(float(b) for b in state["buckets"])
        with self._lock:
            if self.count == 0 and not self._samples:
                self.buckets = edges
                self.counts = [0] * len(edges)
            elif edges != self.buckets:
                raise ValueError(
                    f"histogram bucket edges differ: {edges!r} vs "
                    f"{self.buckets!r}"
                )
            for i, c in enumerate(state["counts"]):
                self.counts[i] += int(c)
            self.sum += float(state["sum"])
            self.count += int(state["count"])
            self._samples.extend(float(v) for v in state["samples"])
            if len(self._samples) > _RESERVOIR:
                stride = len(self._samples) / _RESERVOIR
                self._samples = [self._samples[int(i * stride)]
                                 for i in range(_RESERVOIR)]


def _fmt_labels(labels: Optional[Tuple[Tuple[str, str], ...]],
                extra: Optional[Dict[str, str]] = None) -> str:
    items = list(labels or ())
    if extra:
        items += list(extra.items())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v != v:
        return "NaN"
    return repr(float(v))


class MetricsRegistry:
    """Get-or-create registry of named (and optionally labeled) series."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, help, {label-tuple: series})
        self._families: Dict[str, Tuple[str, str, Dict]] = {}

    def _series(self, cls, name: str, help_: str, labels: dict, **kw):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (cls.kind, help_, {})
                self._families[name] = fam
            elif fam[0] != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"requested {cls.kind}"
                )
            series = fam[2].get(key)
            if series is None:
                series = cls(threading.Lock(), **kw)
                fam[2][key] = series
            return series

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._series(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._series(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS, **labels) -> Histogram:
        return self._series(Histogram, name, help, labels, buckets=buckets)

    def find(self, name: str, **labels) -> Optional[object]:
        """The existing series, or None — WITHOUT creating one. Readers
        that merely inspect (the serving runtime's adaptive rate limiter
        polls the latency p95) must not pollute the export with empty
        series the way the get-or-create accessors would."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam[2].get(key)

    # -- export ----------------------------------------------------------
    def to_prometheus(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        lines: List[str] = []
        with self._lock:
            fams = {
                name: (kind, help_, dict(series))
                for name, (kind, help_, series) in sorted(
                    self._families.items()
                )
            }
        for name, (kind, help_, series) in fams.items():
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            for key, s in series.items():
                if kind == "histogram":
                    cum = 0
                    for b, c in zip(s.buckets, s.counts):
                        cum += c
                        lines.append(
                            f"{name}_bucket"
                            + _fmt_labels(key, {"le": _fmt_value(b)})
                            + f" {cum}"
                        )
                    lines.append(f"{name}_sum{_fmt_labels(key)} "
                                 f"{_fmt_value(s.sum)}")
                    lines.append(f"{name}_count{_fmt_labels(key)} {s.count}")
                else:
                    lines.append(
                        f"{name}{_fmt_labels(key)} {_fmt_value(s.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> List[dict]:
        """One JSON-serializable record per series (the metrics.jsonl
        lines): histograms carry sum/count plus p50/p95/p99 of the recent
        reservoir."""
        out: List[dict] = []
        now = time.time()
        with self._lock:
            fams = {
                name: (kind, dict(series))
                for name, (kind, _h, series) in sorted(self._families.items())
            }
        for name, (kind, series) in fams.items():
            for key, s in series.items():
                rec = {"time": now, "name": name, "kind": kind,
                       "labels": dict(key)}
                if kind == "histogram":
                    rec.update(sum=s.sum, count=s.count,
                               p50=s.quantile(0.50), p95=s.quantile(0.95),
                               p99=s.quantile(0.99))
                else:
                    rec["value"] = s.value
                out.append(rec)
        return out

    def to_jsonl(self) -> str:
        return "".join(json.dumps(r) + "\n" for r in self.snapshot())

    def export_state(self) -> List[dict]:
        """One mergeable record per series — unlike `snapshot()` (which
        reduces histograms to fixed percentiles), histogram records carry
        the full `Histogram.state()` so a `FleetAggregator` can merge
        reservoirs across processes without precision loss."""
        out: List[dict] = []
        with self._lock:
            fams = {
                name: (kind, dict(series))
                for name, (kind, _h, series) in sorted(self._families.items())
            }
        for name, (kind, series) in fams.items():
            for key, s in series.items():
                rec = {"name": name, "kind": kind, "labels": dict(key)}
                if kind == "histogram":
                    rec["state"] = s.state()
                else:
                    rec["value"] = s.value
                out.append(rec)
        return out


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal parser for the text exposition format (tests + the CLI's
    `prom` round-trip check): returns {series-with-labels: value},
    raising ValueError on malformed sample lines."""
    out: Dict[str, float] = {}
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            out[series] = (float("inf") if value == "+Inf"
                           else float(value))
        except ValueError as e:
            raise ValueError(f"line {i}: bad sample {line!r} ({e})") from e
    return out


def merge_histogram_states(states) -> dict:
    """Merge an iterable of `Histogram.state()` dicts into one. Raises
    ValueError on mismatched bucket edges (series exported with custom
    buckets cannot be silently blended into default-bucket series)."""
    acc = Histogram(threading.Lock())
    for st in states:
        acc.merge_state(st)
    return acc.state()


def parse_series_key(series: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Split a `name{k="v",...}` series key into (name, sorted label
    tuple) — the inverse of `_fmt_labels`, so `parse_prometheus` output
    round-trips into the structured form the aggregator merges on."""
    if "{" not in series:
        return series, ()
    name, _, rest = series.partition("{")
    body = rest.rstrip()
    if not body.endswith("}"):
        raise ValueError(f"bad series key {series!r}: unterminated labels")
    body = body[:-1]
    labels: List[Tuple[str, str]] = []
    # values are always double-quoted by _fmt_labels and never contain
    # quotes themselves in this codebase's label vocabulary
    for part in filter(None, body.split(",")):
        k, _, v = part.partition("=")
        if not _ or not v.startswith('"') or not v.endswith('"'):
            raise ValueError(f"bad label {part!r} in series {series!r}")
        labels.append((k.strip(), v[1:-1]))
    return name, tuple(sorted(labels))


def parse_prometheus_labeled(
    text: str,
) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Structured variant of `parse_prometheus`: keys are (name, sorted
    label tuple) so callers can filter/merge by label without re-parsing
    the flat series strings."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for series, value in parse_prometheus(text).items():
        out[parse_series_key(series)] = value
    return out
