#!/usr/bin/env bash
# FFA7xx precision-flow check (docs/analysis.md `precision` pass). Legs:
#   1. 8-device mesh: the full precision test suite — seeded defects
#      fire each of FFA701-705 (+ FFA407 in the rule lint), the mixed
#      zoo sweep is FFA7xx-error-free, strategy_io/artifact-store
#      round-trips preserve dtype annotations, and tightening
#      precision_drift_budget flips a borderline strategy to a typed
#      StrategyDivergenceError (tolerances derive from the budget);
#   2. 4-device mesh: analyzer CLI under --fail-on error over the bench
#      Transformer compiled --mixed-precision (default budget, then an
#      explicitly loose --drift-budget) — the searched bf16 strategy
#      must be statically clean;
#   3. both shipped rule collections re-linted (FFA407 rides the same
#      rules command CI already gates on).
# CI wires this into the lint workflow alongside the other *_check.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "=== precision_check leg 1: 8-device precision test suite ==="
JAX_NUM_CPU_DEVICES=8 python -m pytest tests/test_precision.py -q \
    -p no:cacheprovider

echo "=== precision_check leg 2: 4-device analyzer CLI, mixed precision ==="
JAX_NUM_CPU_DEVICES=4 \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m flexflow_tpu.analysis model \
    --budget 2 --mixed-precision --fail-on error
JAX_NUM_CPU_DEVICES=4 \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m flexflow_tpu.analysis model \
    --budget 2 --mixed-precision --drift-budget 0.5 --fail-on error

echo "=== precision_check leg 3: shipped rule collections (FFA407) ==="
python -m flexflow_tpu.analysis --fail-on error

echo "precision_check: OK"
