"""Weight initializers.

TPU-native equivalents of reference src/runtime/initializer.cc (349 LoC) +
initializer_kernel.cu (curand kernels): each initializer is a pure function of
a PRNGKey, applied per weight at compile time (the reference launches a Legion
task per weight partition).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Initializer:
    """Base (reference: include/flexflow/initializer.h:21)."""

    def __call__(self, key, shape, dtype):
        raise NotImplementedError


@dataclasses.dataclass
class GlorotUniformInitializer(Initializer):
    """reference: initializer.h GlorotUniform; matches Keras glorot_uniform."""

    seed: int = 0

    def __call__(self, key, shape, dtype):
        if len(shape) >= 2:
            # fan layout conventions: Linear (in, out); Conv OIHW
            if len(shape) == 4:  # OIHW conv kernel
                receptive = shape[2] * shape[3]
                fan_in, fan_out = shape[1] * receptive, shape[0] * receptive
            else:
                fan_in = int(np.prod(shape[:-1]))
                fan_out = shape[-1]
        else:
            fan_in = fan_out = shape[0] if shape else 1
        limit = float(np.sqrt(6.0 / max(1, fan_in + fan_out)))
        return jax.random.uniform(key, shape, jnp.float32, -limit, limit).astype(dtype)


@dataclasses.dataclass
class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype):
        return jnp.zeros(shape, dtype)


@dataclasses.dataclass
class OneInitializer(Initializer):
    def __call__(self, key, shape, dtype):
        return jnp.ones(shape, dtype)


@dataclasses.dataclass
class ConstantInitializer(Initializer):
    value: float = 0.0

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@dataclasses.dataclass
class UniformInitializer(Initializer):
    seed: int = 0
    min_value: float = 0.0
    max_value: float = 1.0

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(
            key, shape, jnp.float32, self.min_value, self.max_value
        ).astype(dtype)


@dataclasses.dataclass
class NormInitializer(Initializer):
    seed: int = 0
    mean: float = 0.0
    stddev: float = 1.0

    def __call__(self, key, shape, dtype):
        return (
            self.mean + self.stddev * jax.random.normal(key, shape, jnp.float32)
        ).astype(dtype)


_BY_NAME = {
    "glorot_uniform": GlorotUniformInitializer(),
    "zero": ZeroInitializer(),
    "zeros": ZeroInitializer(),
    "one": OneInitializer(),
    "ones": OneInitializer(),
    "uniform": UniformInitializer(),
    "normal": NormInitializer(),
    "norm": NormInitializer(),
}


def get_initializer(spec) -> Initializer:
    if isinstance(spec, Initializer):
        return spec
    if isinstance(spec, str):
        if spec.startswith("constant:"):
            return ConstantInitializer(float(spec.split(":", 1)[1]))
        return _BY_NAME[spec]
    raise TypeError(f"bad initializer spec {spec!r}")
