"""Strategy-search tests (pure host logic — no devices needed).

Mirrors the reference's unit-test scope (tests/unit/: dominators,
machine_view, parallel_config, substitution logic) plus SURVEY §7's
"property-test against brute force on tiny graphs" requirement for the DP.
"""
import itertools

import numpy as np
import pytest

from flexflow_tpu import ActiMode, AggrMode, DataType, FFConfig, FFModel
from flexflow_tpu.ff_types import OperatorType
from flexflow_tpu.pcg.lowering import layers_to_pcg
from flexflow_tpu.pcg.machine_view import (
    MachineResource,
    MachineView,
    enumerate_machine_views,
)
from flexflow_tpu.search import (
    CostModel,
    GraphSearchHelper,
    MCMCSearch,
    MachineModel,
    SearchHelper,
    generate_all_pcg_xfers,
    simulate_runtime,
)


def mlp_graph(batch=64, din=512, dh=1024, dout=256):
    model = FFModel(FFConfig())
    x = model.create_tensor((batch, din), DataType.DT_FLOAT)
    t = model.dense(x, dh, ActiMode.AC_MODE_RELU)
    t = model.dense(t, dout)
    graph, _ = layers_to_pcg(model.layers)
    return graph


def transformer_graph(batch=8, seq=64, hidden=128, heads=8):
    model = FFModel(FFConfig())
    x = model.create_tensor((batch, seq, hidden), DataType.DT_FLOAT)
    t = model.multihead_attention(x, x, x, hidden, heads)
    t = model.dense(t, hidden, ActiMode.AC_MODE_RELU)
    t = model.dense(t, hidden)
    graph, _ = layers_to_pcg(model.layers)
    return graph


@pytest.fixture
def machine():
    return MachineModel(num_nodes=1, workers_per_node=4)


# -- machine views (reference: tests/unit/test_machine_view.cc) -------------

def test_machine_view_device_ids():
    v = MachineView(start_device_id=2, dim=(3,), stride=(1,))
    assert v.device_ids() == [2, 3, 4]
    assert v.num_parts() == 3
    v2 = MachineView(start_device_id=0, dim=(2,), stride=(4,))
    assert v2.device_ids() == [0, 4]


def test_enumerate_views_cover_degrees():
    views = enumerate_machine_views(2, 4)
    degrees = {v.num_parts() for v in views}
    assert {1, 2, 3, 4}.issubset(degrees)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    assert all(
        res.is_valid_machine_view(v)
        for v in enumerate_machine_views(1, 4)
    )


def test_machine_resource_rejects_outside_views():
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=2)
    ok = MachineView(start_device_id=0, dim=(2,), stride=(1,))
    bad = MachineView(start_device_id=2, dim=(2,), stride=(1,))
    assert res.is_valid_machine_view(ok)
    assert not res.is_valid_machine_view(bad)


# -- cost model -------------------------------------------------------------

def test_cost_scales_with_size(machine):
    cm = CostModel(machine)
    g_small = mlp_graph(batch=32, dh=256)
    g_big = mlp_graph(batch=32, dh=4096)
    v = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    small = cm.measure_operator_cost(g_small.ops[0], v)
    big = cm.measure_operator_cost(g_big.ops[0], v)
    assert big.forward_time > small.forward_time
    assert big.total_memory > small.total_memory


def test_sharded_op_cheaper_but_sync_appears(machine):
    cm = CostModel(machine)
    g = mlp_graph()
    op = g.ops[0]
    v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    c1 = cm.measure_operator_cost(op, v1)
    # partition batch by 4 -> per-device compute shrinks, weight sync appears
    for t in op.outputs:
        t.dims[0].degree = 4
    v4 = MachineView(start_device_id=0, dim=(4,), stride=(1,))
    c4 = cm.measure_operator_cost(op, v4)
    assert c4.forward_time < c1.forward_time
    assert c4.sync_time > 0.0


def test_calibration_values_validated_on_load(machine):
    """ADVICE r2: a hand-edited calibration with an efficiency of 0.0 (or
    any falsy/out-of-range value) must be rejected at load, not silently
    treated as absent by an `or` fallback."""
    import pytest as _pytest

    for bad in (
        {"mxu_efficiency": 0.0},
        {"hbm_efficiency": -0.5},
        {"op_class": {"OP_LINEAR": {"mxu_efficiency": 1.5}}},
        {"op_class": {"OP_LINEAR": {"bwd_over_fwd": 0.0}}},
    ):
        with _pytest.raises(ValueError):
            CostModel(machine, calibration=bad)
    # in-range values load fine
    CostModel(machine, calibration={
        "mxu_efficiency": 0.6,
        "op_class": {"OP_LINEAR": {"mxu_efficiency": 0.5,
                                   "bwd_over_fwd": 2.0}},
    })


def test_allreduce_and_xfer_costs(machine):
    assert machine.allreduce_cost(1 << 20, [0, 1, 2, 3]) > 0
    assert machine.xfer_cost(1 << 20, 0, 0) == 0.0
    intra = machine.xfer_cost(1 << 20, 0, 1)
    assert intra > 0
    m2 = MachineModel(num_nodes=2, workers_per_node=4)
    inter = m2.xfer_cost(1 << 20, 0, 4)
    assert inter > intra


# -- DP search --------------------------------------------------------------

def test_dp_search_chain_matches_bruteforce(machine):
    """Property test (SURVEY §7 hard part (a)): on a pure chain the DP must
    find the same optimum as exhaustive enumeration over view tuples."""
    cm = CostModel(machine)
    sh = SearchHelper(cm)
    g = mlp_graph(batch=32, din=64, dh=128, dout=32)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    result = sh.graph_cost(g, res)

    ops = g.topo_order()
    prod = g.producers()
    all_views = [sh.valid_views(op, res) for op in ops]
    best = float("inf")
    for combo in itertools.product(*all_views):
        assign = {op.guid: v for op, v in zip(ops, combo)}
        total = 0.0
        for op, v in zip(ops, combo):
            total += cm.measure_operator_cost(op, v).total_time
            for t in op.inputs:
                p = prod.get(t.guid)
                if p is not None:
                    total += cm.estimate_xfer_cost(t, assign[p[0].guid], v)
        best = min(best, total)
    assert result.cost == pytest.approx(best, rel=1e-9)
    assert set(result.views) == {op.guid for op in ops}


def inception_block_graph(batch=32, din=64, dh=48):
    """Connected, bottleneck-FREE diamond (Inception-style towers
    reconverging through adds): x -> {d1, d2, d3} -> add -> add. No topo
    position has all prefix edges landing on it, so the DP must take the
    no-bottleneck fallback path."""
    cfg = FFConfig()
    m = FFModel(cfg)
    x = m.create_tensor((batch, din), DataType.DT_FLOAT)
    d1 = m.dense(x, dh)
    d2 = m.dense(x, dh)
    d3 = m.dense(x, dh)
    s1 = m.add(d1, d2)
    m.add(s1, d3)
    g, _ = layers_to_pcg(m.layers)
    from flexflow_tpu.search.substitution import partition_batch

    (g2,) = list(partition_batch(2).apply(g))
    return g2


def test_diamond_fallback_matches_bruteforce(machine):
    """The no-bottleneck fallback must return the TRUE optimum within its
    exact budget (round 1 picked views greedily here — VERDICT r1 weak #6:
    diamond PCGs could get silently suboptimal placements)."""
    cm = CostModel(machine)
    sh = SearchHelper(cm)
    g = inception_block_graph()
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    ops = g.topo_order()
    # precondition: this graph actually exercises the fallback — connected
    # with no bottleneck (one component, no index where prefix edges stop)
    assert len(sh._components(tuple(ops), g)) == 1
    result = sh.graph_cost(g, res)

    prod = g.producers()
    all_views = [sh.valid_views(op, res) for op in ops]
    best = float("inf")
    for combo in itertools.product(*all_views):
        assign = {op.guid: v for op, v in zip(ops, combo)}
        total = 0.0
        for op, v in zip(ops, combo):
            total += cm.measure_operator_cost(op, v).total_time
            if op.is_parallel_op:
                total += cm.parallel_op_cost(op)
            for t in op.inputs:
                p = prod.get(t.guid)
                if p is not None:
                    total += cm.estimate_xfer_cost(t, assign[p[0].guid], v)
        best = min(best, total)
    assert result.cost == pytest.approx(best, rel=1e-9)
    assert set(result.views) == {op.guid for op in ops}


def test_diamond_beam_no_worse_than_greedy(machine):
    """Past the exact budget the beam (width 16) must never be worse than
    the old greedy (width 1)."""
    cm = CostModel(machine)
    g = inception_block_graph(batch=64, din=128, dh=96)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    ops = tuple(g.topo_order())

    class Beamy(SearchHelper):
        DIAMOND_EXACT_BUDGET = 0  # force the beam path

    class Greedy(Beamy):
        DIAMOND_BEAM_WIDTH = 1

    beam = Beamy(cm)._diamond_assign(ops, {}, {}, res)
    greedy = Greedy(cm)._diamond_assign(ops, {}, {}, res)
    assert beam.cost <= greedy.cost + 1e-12


def test_dp_search_memoizes(machine):
    cm = CostModel(machine)
    sh = SearchHelper(cm)
    g = transformer_graph()
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    r1 = sh.graph_cost(g, res)
    n_memo = len(sh._memo)
    r2 = sh.graph_cost(g, res)
    assert r1.cost == r2.cost
    assert len(sh._memo) == n_memo  # second call fully memoized


# -- substitutions ----------------------------------------------------------

def test_partition_linear_combine_generates_candidate():
    from flexflow_tpu.search.substitution import partition_linear_combine

    g = mlp_graph()
    xfer = partition_linear_combine(4)
    cands = list(xfer.apply(g))
    assert len(cands) == 2  # one per dense layer
    c = cands[0]
    combines = [o for o in c.ops if o.op_type == OperatorType.OP_COMBINE]
    assert len(combines) == 1
    # a linear weight is now sharded
    shard = [
        w.dims
        for o in c.ops
        if o.op_type == OperatorType.OP_LINEAR
        for w in o.weights
        if any(d.degree == 4 for d in w.dims)
    ]
    assert shard


def test_partition_batch_generates_dp_candidate():
    from flexflow_tpu.search.substitution import partition_batch

    g = mlp_graph()
    cands = list(partition_batch(4).apply(g))
    assert len(cands) == 1
    c = cands[0]
    for op in c.ops:
        assert op.outputs[0].dims[0].degree == 4


def test_search_prefers_parallelism(machine):
    """On a 4-chip machine the searched strategy must beat the serial
    (degree-1) assignment — the Unity headline property."""
    cm = CostModel(machine)
    sh = SearchHelper(cm)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    g = mlp_graph(batch=4096, din=1024, dh=4096, dout=1024)
    serial = sh.graph_cost(g, res)
    gsh = GraphSearchHelper(sh, generate_all_pcg_xfers([2, 4]), budget=8)
    best_graph, best = gsh.graph_optimize(g, res)
    assert best.cost < serial.cost
    # the winning graph must actually be parallelized
    assert any(
        d.degree > 1 for op in best_graph.ops for t in op.outputs for d in t.dims
    )


# -- MCMC + simulator -------------------------------------------------------

def test_simulate_runtime_positive(machine):
    cm = CostModel(machine)
    g = mlp_graph()
    mc = MCMCSearch(cm)
    views = mc.data_parallel_start(g)
    t = simulate_runtime(g, views, cm)
    assert t > 0


def test_mcmc_improves_or_holds(machine):
    cm = CostModel(machine)
    g = mlp_graph(batch=256, dh=4096)
    mc = MCMCSearch(cm, seed=1)
    start = mc.data_parallel_start(g)
    t0 = simulate_runtime(g, start, cm)
    views, t1 = mc.optimize(g, budget=60, start=start)
    assert t1 <= t0 + 1e-12


# -- compile() integration --------------------------------------------------

def test_compile_with_search_budget_trains():
    """compile(search_budget>=0) must run the Unity search and still train
    (reference: GRAPH_OPTIMIZE path in FFModel::compile)."""
    import jax.numpy as jnp
    from flexflow_tpu import LossType, MetricsType, SGDOptimizer

    cfg = FFConfig()
    cfg.batch_size = 1024
    cfg.search_budget = 4
    model = FFModel(cfg)
    x = model.create_tensor((1024, 512), DataType.DT_FLOAT)
    t = model.dense(x, 2048, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 10)
    t = model.softmax(t)
    model.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    assert model.searched_cost > 0
    rng = np.random.RandomState(0)
    xs = rng.randn(1024, 512).astype(np.float32)
    ys = rng.randint(0, 10, (1024, 1)).astype(np.int32)
    pm = model.fit(xs, ys, batch_size=1024, epochs=1, verbose=False)
    assert pm.train_all == 1024


def test_strategy_export_import_roundtrip(tmp_path, machine):
    from flexflow_tpu.runtime.strategy_io import (
        apply_imported_strategy,
        export_strategy,
        import_strategy,
    )

    cm = CostModel(machine)
    sh = SearchHelper(cm)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    g = mlp_graph(batch=4096, din=1024, dh=4096, dout=1024)
    gsh = GraphSearchHelper(sh, generate_all_pcg_xfers([2, 4]), budget=8)
    best_graph, best = gsh.graph_optimize(g, res)
    path = str(tmp_path / "strategy.json")
    export_strategy(best_graph, best, path)
    strat = import_strategy(path)
    assert len(strat) == len(best_graph.ops)
    # re-apply onto a fresh lowering of the same layers
    g2 = mlp_graph(batch=4096, din=1024, dh=4096, dout=1024)
    # names differ across fresh graphs (guid-based); match by op order
    by_order = list(strat.values())
    for op, rec in zip(g2.topo_order(), by_order[: len(g2.ops)]):
        rec2 = dict(rec)
        rec2["name"] = op.name
        apply_imported_strategy(g2, {op.name: rec2})
    assert any(
        d.degree > 1 for op in g2.ops for t in op.outputs for d in t.dims
    )


def test_import_strategy_validates_schema(tmp_path):
    import json

    from flexflow_tpu.runtime.strategy_io import (
        SCHEMA_VERSION,
        StrategyImportError,
        import_strategy,
    )

    def write(name, blob, raw=None):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            f.write(raw if raw is not None else json.dumps(blob))
        return p

    with pytest.raises(StrategyImportError, match="not valid JSON"):
        import_strategy(write("garbage.json", None, raw="{not json"))
    with pytest.raises(StrategyImportError, match="missing top-level"):
        import_strategy(write("noops.json", {"version": 1}))
    with pytest.raises(StrategyImportError, match="missing integer"):
        import_strategy(write("nover.json", {"ops": []}))
    with pytest.raises(StrategyImportError, match="newer than the supported"):
        import_strategy(write("future.json",
                              {"version": SCHEMA_VERSION + 1, "ops": []}))
    with pytest.raises(StrategyImportError, match="has no 'name'"):
        import_strategy(write("noname.json",
                              {"version": 1, "ops": [{"op_type": "OP_LINEAR"}]}))
    with pytest.raises(StrategyImportError, match="positive ints"):
        import_strategy(write("baddeg.json", {
            "version": 1,
            "ops": [{"name": "a", "output_degrees": [["two"]]}],
        }))
    with pytest.raises(StrategyImportError, match="dim/stride length"):
        import_strategy(write("badmv.json", {
            "version": 1,
            "ops": [{"name": "a", "machine_view":
                     {"start_device_id": 0, "dim": [2], "stride": [1, 1]}}],
        }))
    # a well-formed older-or-current file loads
    ok = import_strategy(write("ok.json", {
        "version": 1,
        "ops": [{"name": "a", "output_degrees": [[2, 1]],
                 "machine_view": {"start_device_id": 0, "dim": [2],
                                  "stride": [1]}}],
    }))
    assert set(ok) == {"a"}


def test_apply_imported_strategy_reports_unmatched_and_checks_devices():
    from flexflow_tpu.runtime.strategy_io import (
        StrategyImportError,
        apply_imported_strategy,
    )

    g = mlp_graph(batch=64, din=16, dh=32, dout=8)
    names = [op.name for op in g.topo_order()]
    rec = {"name": names[0], "output_degrees": [], "weight_degrees": []}
    ghost = {"name": "op_that_never_existed", "output_degrees": [],
             "weight_degrees": []}
    unmatched = apply_imported_strategy(
        g, {rec["name"]: rec, ghost["name"]: ghost}
    )
    assert unmatched == ["op_that_never_existed"]

    # a degree product that does not divide the live device count is
    # rejected BEFORE any op is mutated
    bad = {"name": names[0], "output_degrees": [[8, 1]],
           "weight_degrees": []}
    with pytest.raises(StrategyImportError, match="does not divide"):
        apply_imported_strategy(g, {bad["name"]: bad}, num_devices=4)
    # ...as is a machine view addressing devices beyond the machine
    bad_mv = {"name": names[0], "output_degrees": [], "weight_degrees": [],
              "machine_view": {"start_device_id": 2, "dim": [4],
                               "stride": [1]}}
    with pytest.raises(StrategyImportError, match="addresses device"):
        apply_imported_strategy(g, {bad_mv["name"]: bad_mv}, num_devices=4)
    # degrees that DO fit apply cleanly under the same validation
    good = {"name": names[0], "output_degrees": [[4, 1]],
            "weight_degrees": []}
    assert apply_imported_strategy(g, {good["name"]: good},
                                   num_devices=4) == []


# -- topology-aware network model (reference: src/runtime/network.cc) -------

def test_torus_topology_routing():
    from flexflow_tpu.search.network import TorusTopology

    t = TorusTopology(dims=(4, 8))
    assert t.num_chips == 32
    assert t.coords(0) == (0, 0) and t.coords(9) == (1, 1)
    assert t.chip((1, 1)) == 9
    # wraparound: 0 and 24 (coords (0,0),(3,0)) are neighbors on a 4-torus
    assert t.hop_distance(0, 24) == 1
    path = t.shortest_path(0, 18)  # (0,0) -> (2,2)
    assert len(path) - 1 == t.hop_distance(0, 18) == 4


def test_topology_model_costs():
    from flexflow_tpu.search.network import TopologyAwareMachineModel, TorusTopology

    m = TopologyAwareMachineModel(
        num_nodes=1, workers_per_node=8, topology=TorusTopology(dims=(2, 4))
    )
    near = m.xfer_cost(1 << 20, 0, 1)
    far = m.xfer_cost(1 << 20, 0, 5)  # multi-hop
    assert far > near
    # point-to-point cost is STATELESS (search costs must not depend on
    # query order); contention is priced for concurrent flow sets
    assert m.xfer_cost(1 << 20, 0, 1) == near
    solo = m.concurrent_flows_cost([(1 << 20, 0, 1)])
    shared = m.concurrent_flows_cost(
        [(1 << 20, 0, 1), (1 << 20, 0, 1)]  # same link, two flows
    )
    assert shared > solo
    assert m.allreduce_cost(1 << 20, range(8)) > 0


def test_multislice_hierarchical_allreduce_and_dcn():
    """Groups spanning slices decompose into intra-slice + DCN phases
    (EnhancedMachineModel's hierarchy); cross-slice point-to-point rides
    DCN, not a fictitious ICI link."""
    from flexflow_tpu.search.network import (TopologyAwareMachineModel,
                                             TorusTopology)

    m = TopologyAwareMachineModel(
        num_nodes=2, workers_per_node=8, topology=TorusTopology(dims=(2, 4))
    )
    intra = m.allreduce_cost(1 << 20, range(8))          # one slice
    cross = m.allreduce_cost(1 << 20, range(16))         # both slices
    assert cross > intra  # pays the DCN ring on top
    m.reset_congestion()
    assert m.xfer_cost(1 << 20, 0, 9) > m.xfer_cost(1 << 20, 0, 1)


def test_topology_changes_search_decision():
    """The load-bearing EnhancedMachineModel property (VERDICT r1 #6): the
    flat and topology models must PICK DIFFERENT strategies for the same
    graph, and the topology model's pick must be strictly cheaper when
    both are evaluated on the topology. Construction: a (4, 2) torus makes
    every ring wider than 2 devices pay 2-hop neighbor links, so wide
    data-parallel weight syncs cost more than the flat model believes."""
    from flexflow_tpu import DataType, FFConfig, FFModel
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.network import (TopologyAwareMachineModel,
                                             TorusTopology)
    from flexflow_tpu.search.substitution import partition_batch

    def build_graph():
        cfg = FFConfig()
        m = FFModel(cfg)
        x = m.create_tensor((16384, 256), DataType.DT_FLOAT)
        m.dense(x, 256, use_bias=False)
        g, _ = layers_to_pcg(m.layers)
        return g

    flat = MachineModel(num_nodes=1, workers_per_node=8, ici_bandwidth=30e9)
    topo = TopologyAwareMachineModel(
        num_nodes=1, workers_per_node=8, ici_bandwidth=30e9,
        topology=TorusTopology(dims=(4, 2)),
    )
    res = MachineResource(num_nodes=1, all_procs_per_node=8,
                          available_procs_per_node=8)
    xfers = [partition_batch(d) for d in (2, 4, 8)]

    def search(machine):
        from flexflow_tpu.search.substitution import GraphSearchHelper

        sh = SearchHelper(CostModel(machine, calibration=False))
        gsh = GraphSearchHelper(sh, xfers, budget=8)
        return gsh.graph_optimize(build_graph(), res)

    g_flat, r_flat = search(flat)
    g_topo, r_topo = search(topo)

    def degree_of(g):
        lin = next(o for o in g.topo_order()
                   if o.op_type == OperatorType.OP_LINEAR)
        return lin.outputs[0].get_total_degree()

    d_flat, d_topo = degree_of(g_flat), degree_of(g_topo)
    assert d_flat != d_topo, (d_flat, d_topo)
    assert d_topo < d_flat  # topology shies away from wide 2-hop rings

    def cost_on_topology(g, views):
        sh = SearchHelper(CostModel(topo, calibration=False))
        ops = tuple(g.topo_order())
        fixed = {o.guid: views[o.guid] for o in ops}
        return sh._cost_of(ops, {}, fixed, res, g).cost

    c_flat_pick = cost_on_topology(g_flat, r_flat.views)
    c_topo_pick = cost_on_topology(g_topo, r_topo.views)
    assert c_topo_pick < c_flat_pick * 0.999, (c_topo_pick, c_flat_pick)


def test_view_canonicalization():
    """Round-3 scalability invariants: degree-1 ops get ONE canonical
    singleton per node (co-location with the producer's node stays
    expressible, intra-node duplicates collapse), and contiguous
    degree-d views keep only tile-ALIGNED starts (an unaligned start
    straddles tiles and never beats its aligned sibling)."""
    m8 = MachineModel(num_nodes=1, workers_per_node=8)
    sh = SearchHelper(CostModel(m8))
    res = MachineResource(num_nodes=1, all_procs_per_node=8,
                          available_procs_per_node=8)
    g = mlp_graph()
    op = g.ops[0]
    assert len(sh.valid_views(op, res)) == 1  # degree 1, one node

    for t in op.outputs:
        t.dims[0].degree = 2
    views = sh.valid_views(op, res)
    starts = sorted(v.start_device_id for v in views
                    if v.stride == (1,))
    assert all(s % 2 == 0 for s in starts), starts

    # two nodes: degree-1 gets one canonical start PER node
    m2 = MachineModel(num_nodes=2, workers_per_node=4)
    sh2 = SearchHelper(CostModel(m2))
    res2 = MachineResource(num_nodes=2, all_procs_per_node=4,
                           available_procs_per_node=4)
    g2 = mlp_graph()
    vs = sh2.valid_views(g2.ops[0], res2)
    assert sorted(v.start_device_id for v in vs) == [0, 4]

    # quarter anchoring: on a 32-worker node a LOW-degree view keeps only
    # node-quarter starts (without it a degree-2 op gets 16 views and one
    # Inception DP evaluation takes minutes — profiled dp4 97s -> ~3s;
    # finer concurrent-tower offsets come from nonsequence machine
    # splits, whose sub-resources re-anchor). 8-worker sets (above) are
    # unchanged: there the quarter never exceeds the tile size.
    m32 = MachineModel(num_nodes=1, workers_per_node=32)
    sh32 = SearchHelper(CostModel(m32))
    res32 = MachineResource(num_nodes=1, all_procs_per_node=32,
                            available_procs_per_node=32)
    g3 = mlp_graph()
    op3 = g3.ops[0]
    for t in op3.outputs:
        t.dims[0].degree = 2
    starts32 = sorted(v.start_device_id for v in sh32.valid_views(op3, res32)
                      if v.stride == (1,))
    assert starts32 == [0, 8, 16, 24], starts32


def test_machine_config_file_topology_end_to_end():
    """VERDICT r2 weak-7: the shipped machine files must drive the
    topology model's knobs end-to-end from a file — torus dims, DCN
    hierarchy, and (through FFModel.compile) a search on a machine bigger
    than the one running the test (the reference's
    --search-num-nodes/--search-num-workers story, config.h:154-155)."""
    import os

    from flexflow_tpu import LossType, MetricsType, SGDOptimizer
    from flexflow_tpu.search import parse_machine_config
    from flexflow_tpu.search.network import TopologyAwareMachineModel

    root = os.path.join(os.path.dirname(__file__), "..")

    m = parse_machine_config(os.path.join(root, "machine_config_v5e32"))
    assert isinstance(m, TopologyAwareMachineModel)
    assert m.topology.dims == (4, 8)
    assert m.num_workers == 32
    # hop-aware: a 4-hop transfer costs more than a neighbor hop
    assert m.xfer_cost(1 << 20, 0, 12) > m.xfer_cost(1 << 20, 0, 8)

    m2 = parse_machine_config(os.path.join(root, "machine_config_multislice"))
    assert isinstance(m2, TopologyAwareMachineModel)
    assert m2.num_nodes == 2 and m2.workers_per_node == 16
    # DCN hierarchy: a 32-chip group spanning both slices pays the DCN
    # ring on top of the intra-slice phases
    intra = m2.allreduce_cost(1 << 20, range(16))
    cross = m2.allreduce_cost(1 << 20, range(32))
    assert cross > intra

    # end-to-end: compile() with --machine-model-file searches ON the
    # 32-chip machine — a DLRM-style model whose fat embedding table the
    # search shards 16/32-way (parameter parallelism syncs nothing; pure
    # DP would allreduce the full table) — degrees the ambient 8-device
    # test machine could never offer
    from flexflow_tpu.models.dlrm import build_dlrm

    cfg = FFConfig()
    cfg.batch_size = 2048
    cfg.machine_model_file = os.path.join(root, "machine_config_v5e32")
    cfg.search_budget = 4
    model = FFModel(cfg)
    build_dlrm(model, 2048)
    model.compile(SGDOptimizer(lr=0.01),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
    # the SEARCH ran on the 32-chip file machine: its strategy carries
    # 16/32-part views the ambient 8-device machine could never offer
    # (execution lowering then demotes what the real 8 devices can't
    # shard — searching for a machine you don't have, config.h:154-155)
    assert any(v.num_parts() >= 16 for v in model.searched_views.values()), (
        sorted({v.num_parts() for v in model.searched_views.values()})
    )


def test_congestion_separates_colliding_placements():
    """VERDICT r2 #4 pin (simulate path): two placements with IDENTICAL
    hop counts — X routes both of an adder's input transfers over the
    same torus links, Y routes them disjointly. A congestion-blind model
    ties them (so a blind search can pick the colliding one); pricing
    link sharing through concurrent_flows_cost separates them."""
    from flexflow_tpu.search.network import (TopologyAwareMachineModel,
                                             TorusTopology)

    model = FFModel(FFConfig())
    x1 = model.create_tensor((256, 4096), DataType.DT_FLOAT)
    x2 = model.create_tensor((256, 4096), DataType.DT_FLOAT)
    a = model.relu(x1)
    b = model.tanh(x2)
    model.add(a, b)
    graph, _ = layers_to_pcg(model.layers)
    relu_op = next(o for o in graph.ops if o.op_type == OperatorType.OP_RELU)
    tanh_op = next(o for o in graph.ops if o.op_type == OperatorType.OP_TANH)
    add_op = next(o for o in graph.ops if o.op_type == OperatorType.OP_EW_ADD)

    def views_at(p1, p2):
        sv = {relu_op.guid: MachineView(start_device_id=p1, dim=(1,),
                                        stride=(1,)),
              tanh_op.guid: MachineView(start_device_id=p2, dim=(1,),
                                        stride=(1,)),
              add_op.guid: MachineView(start_device_id=0, dim=(1,),
                                       stride=(1,))}
        return sv

    # ring of 8: 2->0 is 2 hops; 3->0 (3 hops) vs 5->0 (3 hops via wrap).
    # X = producers at 2 and 3 (paths share links 2-1, 1-0); Y = 2 and 5
    # (opposite directions, disjoint links). Hop counts match pairwise.
    colliding, disjoint = views_at(2, 3), views_at(2, 5)

    aware = TopologyAwareMachineModel(
        num_nodes=1, workers_per_node=8, topology=TorusTopology(dims=(8,)),
        congestion_factor=1.0,
    )
    cm = CostModel(aware, calibration=False)
    t_x = simulate_runtime(graph, colliding, cm)
    t_y = simulate_runtime(graph, disjoint, cm)
    assert t_x > t_y, (t_x, t_y)

    # blind: same topology, congestion surcharge suppressed — ties
    cm_blind = CostModel(aware, calibration=False)
    cm_blind.concurrent_xfer_penalty = lambda flows: 0.0
    assert simulate_runtime(graph, colliding, cm_blind) == pytest.approx(
        simulate_runtime(graph, disjoint, cm_blind))


def test_congestion_flips_concurrent_split_decision():
    """VERDICT r2 #4 pin (DP path): two parallel towers off one producer,
    sized so the vertical machine split (concurrent halves) wins when
    boundary-flow congestion is ignored but LOSES once the far half's
    colliding input transfers are priced — the blind search's placement,
    re-evaluated under the congestion model, is strictly worse than the
    aware search's choice."""
    from flexflow_tpu.search.network import (TopologyAwareMachineModel,
                                             TorusTopology)

    def build():
        model = FFModel(FFConfig())
        x = model.create_tensor((64, 256), DataType.DT_FLOAT)
        t = model.dense(x, 256)
        # towers sized so (tower compute) sits between the boundary xfer
        # cost and the congested boundary cost: concurrent halves win
        # blind, lose once the far half's two colliding 65 KB input
        # transfers are priced at congestion_factor 8
        a1 = model.dense(t, 640)
        a2 = model.dense(t, 640)
        model.add(a1, a2)
        b1 = model.dense(t, 640)
        b2 = model.dense(t, 640)
        model.add(b1, b2)
        g, _ = layers_to_pcg(model.layers)
        return g

    machine = TopologyAwareMachineModel(
        num_nodes=1, workers_per_node=8, topology=TorusTopology(dims=(8,)),
        congestion_factor=8.0,
    )
    res = MachineResource(num_nodes=1, all_procs_per_node=8,
                          available_procs_per_node=8)

    g = build()
    aware = SearchHelper(CostModel(machine, calibration=False))
    r_aware = aware.graph_cost(g, res)

    blind = SearchHelper(CostModel(machine, calibration=False))
    blind.cost_model.concurrent_xfer_penalty = lambda flows: 0.0
    r_blind = blind.graph_cost(g, res)

    # the blind search spreads the towers over both halves (its towers'
    # device sets differ); re-pricing its placement with congestion on
    # must be strictly worse than the aware search's own choice
    eval_of_blind = aware._cost_of(
        tuple(g.topo_order()), {}, dict(r_blind.views), res, g
    )
    assert r_blind.cost < eval_of_blind.cost  # blind underestimates
    assert eval_of_blind.cost > r_aware.cost * 1.0001, (
        eval_of_blind.cost, r_aware.cost
    )


def test_recursive_logger_indents_search(caplog):
    """reference: src/runtime/recursive_logger.cc — depth-indented debug
    records around the DP search's recursive splits."""
    import logging

    from flexflow_tpu.utils.recursive_logger import RecursiveLogger, logger

    rl = RecursiveLogger()
    with caplog.at_level(logging.DEBUG, logger="flexflow_tpu.search"):
        with rl.enter("outer %d", 1):
            rl.info("inside")
            with rl.enter("inner"):
                rl.info("deep")
    msgs = [r.getMessage() for r in caplog.records]
    assert msgs == ["outer 1", "  inside", "  inner", "    deep"]
    assert rl.depth == 0  # balanced on exit

    # and the DP search emits nested records on a searchable graph
    caplog.clear()
    machine = MachineModel(num_nodes=1, workers_per_node=4)
    sh = SearchHelper(CostModel(machine))
    g = transformer_graph()  # 3-op chain: splits at index 1
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    with caplog.at_level(logging.DEBUG, logger="flexflow_tpu.search"):
        sh.graph_cost(g, res)
    assert any("sequence split" in r.getMessage() for r in caplog.records)


def test_disconnected_towers_take_nonsequence_split(caplog):
    """Two independent towers must route through the nonsequence
    (machine-splitting) path — running them concurrently on half machines
    can beat pricing them sequentially on the full machine (reference:
    find_optimal_nonsequence_graph_time)."""
    import logging

    model = FFModel(FFConfig())
    x1 = model.create_tensor((64, 256), DataType.DT_FLOAT)
    x2 = model.create_tensor((64, 256), DataType.DT_FLOAT)
    t1 = model.dense(x1, 256, ActiMode.AC_MODE_RELU)
    model.dense(t1, 128)
    t2 = model.dense(x2, 256, ActiMode.AC_MODE_RELU)
    model.dense(t2, 128)
    g, _ = layers_to_pcg(model.layers)

    machine = MachineModel(num_nodes=1, workers_per_node=4)
    sh = SearchHelper(CostModel(machine))
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    with caplog.at_level(logging.DEBUG, logger="flexflow_tpu.search"):
        r = sh.graph_cost(g, res)
    assert any("horizontal split" in rec.getMessage()
               for rec in caplog.records)
    assert r.cost < float("inf") and len(r.views) == 4

    # concurrent half-machine option is at least as good as pricing the
    # towers sequentially on the full machine
    sh2 = SearchHelper(CostModel(machine))
    ops = g.topo_order()  # DFS order keeps each tower contiguous
    ra = sh2._cost_of(tuple(ops[:2]), {}, {}, res, g)
    rb = sh2._cost_of(tuple(ops[2:]), {}, {}, res, g)
    assert r.cost <= ra.cost + rb.cost + 1e-12


def test_partition_embedding_generates_parameter_parallel_candidate():
    """partition_embedding_combine shards the table's channel dim and
    inserts a Combine (reference: embedding.cc:132-200 replica dims —
    DLRM parameter parallelism)."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.substitution import partition_embedding_combine

    cfg = FFConfig()
    m = FFModel(cfg)
    ids = m.create_tensor((8, 1), DataType.DT_INT32)
    t = m.embedding(ids, 1000, 64, AggrMode.AGGR_MODE_SUM)
    m.dense(t, 16)
    g, _ = layers_to_pcg(m.layers)
    cands = list(partition_embedding_combine(4).apply(g))
    assert len(cands) == 1
    emb = next(o for o in cands[0].ops
               if o.op_type == OperatorType.OP_EMBEDDING)
    assert any(d.degree == 4 for w in emb.weights for d in w.dims)
    assert any(o.op_type == OperatorType.OP_COMBINE for o in cands[0].ops)


def test_sharded_weight_sync_cheaper_than_replicated(machine):
    """Cost-model: a weight sharded across the view's devices must not pay
    the full-table allreduce that replicated (DP) weights pay — this is
    what makes parameter parallelism winnable for DLRM."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.pcg.lowering import layers_to_pcg

    cfg = FFConfig()
    m = FFModel(cfg)
    ids = m.create_tensor((256, 1), DataType.DT_INT32)
    t = m.embedding(ids, 100000, 64, AggrMode.AGGR_MODE_SUM)
    m.dense(t, 16)
    g, _ = layers_to_pcg(m.layers)
    emb = next(o for o in g.ops if o.op_type == OperatorType.OP_EMBEDDING)
    cm = CostModel(machine)
    view = MachineView(start_device_id=0, dim=(4,), stride=(1,))
    dp = cm.measure_operator_cost(emb, view)
    # shard the table over the channel dim (degree 4 == view parts)
    for w in emb.weights:
        w.dims[-1].degree = 4
    sharded = cm.measure_operator_cost(emb, view)
    assert dp.sync_time > 0
    assert sharded.sync_time == 0
    assert sharded.total_time < dp.total_time


def test_unity_beats_dp_on_dlrm(machine):
    """The searched strategy must beat pure DP on DLRM (the north-star
     'Unity-search speedup vs DP'): parameter-parallel embedding tables
    avoid the full-table gradient allreduce."""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models.dlrm import build_dlrm
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.substitution import partition_batch

    cfg = FFConfig()
    m = FFModel(cfg)
    build_dlrm(m, 2048)
    g, _ = layers_to_pcg(m.layers)
    cm = CostModel(machine)
    sh = SearchHelper(cm)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    dp_best = GraphSearchHelper(
        sh, [partition_batch(d) for d in (2, 4)], budget=3
    ).graph_optimize(g, res)[1].cost
    g2, _ = layers_to_pcg(m.layers)
    unity_best = GraphSearchHelper(
        SearchHelper(CostModel(machine)), generate_all_pcg_xfers([2, 4]),
        budget=20,
    ).graph_optimize(g2, res)[1].cost
    assert unity_best < dp_best


def test_searched_dlrm_trains_on_mesh():
    """compile(search) on DLRM must EXECUTE the searched strategy (sharded
    embedding tables) on the virtual mesh, not just cost it."""
    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer)
    from flexflow_tpu.models.dlrm import build_dlrm

    cfg = FFConfig()
    cfg.batch_size = 64
    cfg.search_budget = 10
    m = FFModel(cfg)
    build_dlrm(m, 64, embedding_sizes=(1000,) * 2, mlp_bot=(16, 32),
               mlp_top=(32, 2))
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    rng = np.random.RandomState(0)
    n = 128
    xs = [rng.randint(0, 1000, (n, 1)).astype(np.int32) for _ in range(2)]
    xs.append(rng.rand(n, 16).astype(np.float32))
    ys = rng.randint(0, 2, (n, 1)).astype(np.int32)
    pm = m.fit(xs, ys, batch_size=64, epochs=1, verbose=False)
    assert pm.train_all == n


def test_measured_mode_feeds_search():
    """--measured-search: the cost model microbenchmarks ops on the
    device (search/measure.py, reference Simulator::measure_operator_cost)
    and the measured times flow into strategy costs. (No fwd-time ordering
    assert: at unit-test sizes on CPU, dispatch overhead swamps the
    compute delta — the discriminating power is for real-chip shapes.)"""
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.pcg.machine_view import MachineView
    from flexflow_tpu.search.cost_model import CostModel as CM
    from flexflow_tpu.search.measure import OperatorMeasurer, attach_measured_mode

    cfg = FFConfig()
    m = FFModel(cfg)
    x = m.create_tensor((32, 64), DataType.DT_FLOAT)
    t = m.dense(x, 64)
    m.dense(t, 2048)
    g, _ = layers_to_pcg(m.layers)
    small, big = [o for o in g.topo_order()
                  if o.op_type == OperatorType.OP_LINEAR]
    meas = OperatorMeasurer(repeats=5)
    view = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    fs, bs = meas(small, view)
    fb, bb = meas(big, view)
    assert fs > 0 and bs > 0 and fb > 0 and bb > 0
    # cache hit returns identical values
    assert meas(small, view) == (fs, bs)
    # wired into a CostModel, the measured time IS the strategy cost input
    cm = CM(MachineModel(num_nodes=1, workers_per_node=4))
    attach_measured_mode(cm, repeats=5)
    got = cm.measure_operator_cost(small, view)
    assert got.forward_time == pytest.approx(
        cm.measure_fn(small, view)[0]
    )


def test_measured_search_compile_trains():
    from flexflow_tpu import (FFConfig, FFModel, LossType, MetricsType,
                              SGDOptimizer)

    cfg = FFConfig()
    cfg.batch_size = 32
    cfg.search_budget = 4
    cfg.measure_operator_costs = True
    m = FFModel(cfg)
    x = m.create_tensor((32, 64), DataType.DT_FLOAT)
    t = m.dense(x, 128, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 10)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    assert m.searched_cost > 0
    rng = np.random.RandomState(0)
    m.fit(rng.rand(64, 64).astype(np.float32),
          rng.randint(0, 10, (64, 1)).astype(np.int32),
          batch_size=32, epochs=1, verbose=False)
