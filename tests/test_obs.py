"""Unified telemetry tests (flexflow_tpu/obs/): event tracing, metrics
export, search trajectory, strategy explainability, CLI, and the
disabled-path guarantees."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
    TelemetryConfig,
)
import flexflow_tpu.obs as obs
from flexflow_tpu.obs.metrics import MetricsRegistry, parse_prometheus
from flexflow_tpu.obs.tracer import (
    Tracer,
    read_events_jsonl,
    to_chrome_trace,
    validate_event,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_session():
    """Every test starts and ends without an active global session."""
    obs.finish()
    yield
    obs.finish()


def small_model(search_budget=-1, hidden=16):
    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.search_budget = search_budget
    m = FFModel(cfg)
    x = m.create_tensor((8, 4), DataType.DT_FLOAT)
    t = m.dense(x, hidden, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 4).astype(np.float32),
            rng.randint(0, 3, (n, 1)).astype(np.int32))


# ----------------------------------------------------------------------
# end-to-end: fit(telemetry=...) artifacts
# ----------------------------------------------------------------------
def test_fit_telemetry_artifacts(tmp_path):
    """The acceptance path: a short searched fit with checkpointing
    produces events.jsonl (schema-valid, covering search + steps + a
    checkpoint event), a parsing metrics.prom, and a Perfetto-loadable
    trace.json."""
    m = small_model(search_budget=3)
    x, y = data()
    tdir = str(tmp_path / "tel")
    m.fit(x, y, batch_size=8, epochs=2, verbose=False,
          checkpoint_dir=str(tmp_path / "ckpt"),
          telemetry=TelemetryConfig(dir=tdir, sync_per_step=True))
    # session closed by fit
    assert obs.active() is None

    events, problems = read_events_jsonl(os.path.join(tdir, "events.jsonl"))
    assert problems == []
    cats = {e["cat"] for e in events}
    names = {e["name"] for e in events}
    assert "search" in cats          # search trajectory replayed
    assert "xfer_candidate" in names
    steps = [e for e in events if e["name"] == "step" and e["ph"] == "X"]
    assert len(steps) == 8           # 2 epochs x 4 steps
    assert all(e["dur"] > 0 for e in steps)
    assert all(e["args"]["batch_size"] == 8 for e in steps)
    # sync_per_step: loss recorded per step
    assert all(isinstance(e["args"].get("loss"), float) for e in steps)
    assert "checkpoint_save" in names

    prom = open(os.path.join(tdir, "metrics.prom")).read()
    series = parse_prometheus(prom)
    assert series["ff_steps_total"] == 8.0
    assert series["ff_samples_total"] == 64.0
    assert series["ff_checkpoint_saves_total"] >= 1.0
    assert "ff_step_wall_seconds_count" in series
    # PCG-derived static gauges
    assert "ff_static_hbm_peak_bytes" in series

    trace = json.load(open(os.path.join(tdir, "trace.json")))
    assert "traceEvents" in trace and len(trace["traceEvents"]) > 10
    # Perfetto requirements: metadata process names + non-negative ts
    assert any(e.get("ph") == "M" for e in trace["traceEvents"])
    assert all(e["ts"] >= 0 for e in trace["traceEvents"]
               if e.get("ph") != "M")

    lines = open(os.path.join(tdir, "metrics.jsonl")).read().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert any(r["name"] == "ff_step_wall_seconds" and r["count"] == 8
               for r in recs)


def test_fit_fast_path_telemetry(tmp_path):
    """Telemetry on the non-resilient fast loop (no checkpoint dir):
    per-step dispatch spans + epoch events, no per-step sync."""
    m = small_model()
    x, y = data()
    tdir = str(tmp_path / "tel")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False,
          telemetry=TelemetryConfig(dir=tdir, grad_norm=True))
    events, problems = read_events_jsonl(os.path.join(tdir, "events.jsonl"))
    assert problems == []
    steps = [e for e in events if e["name"] == "step"]
    assert len(steps) == 4
    assert any(e["name"] == "epoch_end" for e in events)
    series = parse_prometheus(
        open(os.path.join(tdir, "metrics.prom")).read()
    )
    # grad_norm=True armed the executor's extra step output
    assert series["ff_global_grad_norm"] > 0.0


def test_disabled_telemetry_emits_nothing(tmp_path, capsys):
    """With telemetry off: no session, no files, no event emission, and
    the obs helpers are no-ops (shared null span, no allocation)."""
    m = small_model()
    x, y = data()
    m.fit(x, y, batch_size=8, epochs=1, verbose=False)
    assert obs.active() is None
    assert obs.tracer() is obs.NULL_TRACER
    s1 = obs.span("anything", cat="x", k=1)
    s2 = obs.span("other")
    assert s1 is s2  # the preallocated null context manager
    with s1:
        pass
    obs.event("dropped")
    obs.count("ff_nothing_total")
    obs.gauge_set("ff_nothing", 1.0)
    obs.observe("ff_nothing_seconds", 0.1)
    assert obs.active() is None
    assert not any(f.endswith((".jsonl", ".prom"))
                   for f in os.listdir(str(tmp_path)))


def test_progress_preserves_output_and_verbosity(tmp_path, capsys):
    """The structured logger prints the same human-readable line at
    default verbosity, nothing when verbose=False, and feeds the event
    log when a session is active."""
    obs.progress("hello world", name="t")
    assert capsys.readouterr().out == "hello world\n"
    obs.progress("quiet", verbose=False)
    assert capsys.readouterr().out == ""
    with obs.session(TelemetryConfig(dir=str(tmp_path))) as tel:
        obs.progress("in session", name="greeting", extra=7)
        assert capsys.readouterr().out == "in session\n"
        assert any(e["name"] == "greeting"
                   and e["args"]["message"] == "in session"
                   and e["args"]["extra"] == 7
                   for e in tel.tracer.events)


def test_fit_epoch_line_format_unchanged(capsys):
    """Default-verbosity fit output keeps the pre-telemetry format."""
    m = small_model()
    x, y = data()
    m.fit(x, y, batch_size=8, epochs=1)
    out = capsys.readouterr().out
    assert "epoch 0: loss=" in out
    assert "ELAPSED TIME = " in out and "THROUGHPUT = " in out


# ----------------------------------------------------------------------
# tracer + metrics units
# ----------------------------------------------------------------------
def test_tracer_schema_and_chrome_trace(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    tr = Tracer(path, flush_every=2)
    with tr.span("phase_a", cat="compile", detail=1):
        tr.instant("inside", cat="compile")
    tr.instant("solo", cat="train", tid=3)
    tr.close()
    events, problems = read_events_jsonl(path)
    assert problems == []
    assert {e["name"] for e in events} == {"phase_a", "inside", "solo"}
    span = next(e for e in events if e["name"] == "phase_a")
    assert span["ph"] == "X" and span["dur"] >= 0
    assert validate_event({"ts": 0, "ph": "X", "name": "n", "cat": "c"})
    assert validate_event({"ts": 0, "ph": "i", "name": "n",
                           "cat": "c"}) == []
    ct = to_chrome_trace(events)
    # one pid per category, named via metadata
    md = {e["args"]["name"]: e["pid"] for e in ct["traceEvents"]
          if e.get("ph") == "M"}
    assert set(md) == {"compile", "train"}
    solo = next(e for e in ct["traceEvents"] if e["name"] == "solo")
    assert solo["tid"] == 3 and solo["pid"] == md["train"]


def test_tracer_max_events_drop_counter(tmp_path):
    tr = Tracer(str(tmp_path / "e.jsonl"), max_events=5)
    for i in range(10):
        tr.instant(f"e{i}")
    tr.close()
    events, _ = read_events_jsonl(str(tmp_path / "e.jsonl"))
    dropped = [e for e in events if e["name"] == "events_dropped"]
    assert len(events) == 6 and dropped[0]["args"]["dropped"] == 5


def test_metrics_registry_prometheus_roundtrip():
    reg = MetricsRegistry()
    reg.counter("ff_x_total", "things").inc(3)
    reg.gauge("ff_y", "level").set(2.5)
    reg.gauge("ff_pcg_collective_bytes", kind="all-reduce").set(128)
    h = reg.histogram("ff_lat_seconds", "latency")
    for v in (0.01, 0.02, 0.03, 0.5):
        h.observe(v)
    text = reg.to_prometheus()
    series = parse_prometheus(text)
    assert series["ff_x_total"] == 3.0
    assert series["ff_y"] == 2.5
    assert series['ff_pcg_collective_bytes{kind="all-reduce"}'] == 128.0
    assert series["ff_lat_seconds_count"] == 4.0
    assert abs(series["ff_lat_seconds_sum"] - 0.56) < 1e-9
    assert series['ff_lat_seconds_bucket{le="+Inf"}'] == 4.0
    assert h.quantile(0.5) == 0.02
    # kind collision is a loud error
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("ff_x_total")
    snap = reg.snapshot()
    assert any(r["name"] == "ff_lat_seconds" and r["p50"] == 0.02
               for r in snap)


def test_metrics_registry_concurrent_hammer():
    """Regression: concurrent observes/incs across threads — series
    creation races and reservoir appends must never lose updates or
    corrupt the sample list (each series carries its own lock; the
    registry lock guards family/label-map creation only)."""
    import threading

    reg = MetricsRegistry()
    threads, per_thread = 8, 500
    errors = []

    def hammer(tid):
        try:
            for i in range(per_thread):
                reg.counter("ff_hammer_total").inc()
                reg.gauge("ff_hammer_gauge", worker=str(tid)).set(i)
                reg.histogram("ff_hammer_seconds").observe(i * 1e-4)
                reg.histogram("ff_hammer_seconds",
                              worker=str(tid)).observe(i * 1e-4)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    ts = [threading.Thread(target=hammer, args=(t,)) for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    assert reg.counter("ff_hammer_total").value == threads * per_thread
    h = reg.histogram("ff_hammer_seconds")
    assert h.count == threads * per_thread
    assert sum(h.counts) == h.count  # bucket counts consistent
    for t in range(threads):
        assert reg.histogram("ff_hammer_seconds",
                             worker=str(t)).count == per_thread
    # export is parseable mid-flight state included
    parse_prometheus(reg.to_prometheus())


# ----------------------------------------------------------------------
# search trajectory
# ----------------------------------------------------------------------
def test_mcmc_trajectory_accept_reject_costs():
    m = small_model()
    from flexflow_tpu.search.mcmc import MCMCSearch

    traj = obs.SearchTrajectory()
    ms = MCMCSearch(m._build_cost_model(), trajectory=traj, seed=3)
    views, cost = ms.optimize(m.graph, budget=12, use_native=False)
    its = traj.mcmc_iterations()
    assert len(its) == 12
    for e in its:
        assert isinstance(e["accept"], bool)
        assert e["cost"] > 0 and e["best"] > 0
        assert e["op"] and e["view"]
    # the recorded best matches the returned cost
    ends = traj.of_kind("search_end")
    assert ends and ends[-1]["cost"] == pytest.approx(cost)
    assert traj.summary()["mcmc"]["iterations"] == 12


def test_compile_records_search_trajectory():
    m = small_model(search_budget=3)
    traj = m.search_trajectory
    kinds = {e["kind"] for e in traj.events}
    assert "phase" in kinds and "xfer_candidate" in kinds
    assert "dp_split" in kinds
    phases = {e["name"] for e in traj.of_kind("phase")}
    assert {"lowering", "strategy_search"} <= phases
    cands = traj.of_kind("xfer_candidate")
    assert cands and all(c["cost"] > 0 for c in cands)
    assert traj.summary()["final_cost"] is not None


def test_trajectory_bounded():
    traj = obs.SearchTrajectory(limit=10)
    for i in range(25):
        traj.event("mcmc_iter", iter=i)
    assert len(traj.events) == 10
    assert traj.dropped == {"mcmc_iter": 15}


# ----------------------------------------------------------------------
# explain_strategy
# ----------------------------------------------------------------------
def test_explain_strategy_names_miscalibrated_op():
    """A deliberately mispriced op class must surface at the top of the
    |simulated − measured| ranking."""
    from flexflow_tpu.search import CostModel, MachineModel

    m = small_model()
    # poison the oracle: softmax priced as if the MXU ran at 1e-9
    # efficiency -> absurdly huge simulated time for OP_SOFTMAX only
    bad = CostModel(
        MachineModel(num_nodes=1, workers_per_node=8),
        calibration={"op_class": {
            "OP_SOFTMAX": {"mxu_efficiency": 1e-9, "hbm_efficiency": 1e-9},
        }},
    )
    ex = obs.explain_strategy(m, repeats=1, warmup=1, cost_model=bad)
    worst = ex.most_miscalibrated()
    assert worst is not None and worst["op_type"] == "OP_SOFTMAX"
    assert worst["abs_err_s"] > 0
    ratios = ex.calibration_ratios()
    assert ratios["OP_SOFTMAX"] < 1.0  # measured far below simulated
    assert "OP_SOFTMAX" in ex.summary()


def test_explain_strategy_feedback_into_search_loop():
    """apply() feeds measured op costs back: the next compile's cost
    model resolves serial views to the measurement."""
    from flexflow_tpu.pcg.machine_view import MachineView

    m = small_model()
    ex = obs.explain_strategy(m, repeats=1, warmup=1)
    assert len(ex.rows) >= 3  # dense x2 + softmax
    for r in ex.rows:
        assert r["meas_fwd_s"] > 0 and r["meas_bwd_s"] >= 0
    n = ex.apply(m)
    assert n == len(ex.rows)
    cm = m._build_cost_model()
    v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    op = next(o for o in m.graph.ops if not o.is_parallel_op)
    row = next(r for r in ex.rows if r["name"] == op.name)
    got = cm.measure_operator_cost(op, v1)
    assert got.forward_time == pytest.approx(row["meas_fwd_s"])
    assert got.backward_time == pytest.approx(row["meas_bwd_s"])


# ----------------------------------------------------------------------
# profiler: warmup/backward + timeline schema parity
# ----------------------------------------------------------------------
def test_profile_ops_backward_and_backcompat():
    from flexflow_tpu.runtime.profiler import OpProfile, profile_ops

    m = small_model()
    x, _ = data(8)
    legacy = profile_ops(m, [x], repeats=1)
    assert all(isinstance(v, float) and v >= 0 for v in legacy.values())
    full = profile_ops(m, [x], repeats=1, warmup=2, backward=True)
    assert set(full) == set(legacy)
    dense = next(v for k, v in full.items() if "linear" in k)
    assert isinstance(dense, OpProfile)
    assert dense.backward_s > 0  # dense has a VJP
    assert dense.total_s == dense.forward_s + dense.backward_s


def test_simulated_timeline_shares_tracer_schema(tmp_path):
    """export_simulated_timeline and the runtime tracer emit the same
    Chrome-trace schema (categories as named processes), so both load
    into one Perfetto session and overlay."""
    from flexflow_tpu.runtime.profiler import (
        export_simulated_timeline,
        simulated_timeline_events,
    )

    m = small_model(search_budget=2)
    cm = m._build_cost_model()
    events = simulated_timeline_events(m.graph, m.searched_views, cm)
    assert events and all(validate_event(e) == [] for e in events)
    assert all(e["cat"] == "simulated" for e in events)
    assert all(e["args"]["forward_s"] >= 0 for e in events)
    path = str(tmp_path / "sim.json")
    export_simulated_timeline(m.graph, m.searched_views, cm, path)
    trace = json.load(open(path))
    md = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
    assert any(e["args"]["name"] == "simulated" for e in md)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert xs and all("dur" in e and e["ts"] >= 0 for e in xs)


def test_collective_bytes_estimate():
    from flexflow_tpu.analysis.collectives import estimate_collective_bytes

    m = small_model(search_budget=3)
    recs = estimate_collective_bytes(m.graph, m.searched_views)
    for r in recs:
        assert r["kind"] in ("scatter", "all-gather", "broadcast",
                             "all-reduce", "all-to-all")
        assert r["bytes"] >= 0 and r["parts"] >= 1


# ----------------------------------------------------------------------
# runtime feeds: guard/retry/serving under a session
# ----------------------------------------------------------------------
def test_guard_and_retry_metrics(tmp_path):
    from flexflow_tpu import FaultInjector

    m = small_model()
    x, y = data()
    fi = FaultInjector()
    fi.inject("nan_grads", at_step=1)
    tdir = str(tmp_path / "tel")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False,
          skip_nonfinite_steps=True, fault_injector=fi,
          telemetry=TelemetryConfig(dir=tdir))
    series = parse_prometheus(
        open(os.path.join(tdir, "metrics.prom")).read()
    )
    assert series["ff_nonfinite_skips_total"] == 1.0
    assert series["ff_loss_scale"] > 0.0


def test_serving_latency_metrics(tmp_path):
    from flexflow_tpu import BatchScheduler

    m = small_model()
    x, _ = data(8)
    with obs.session(TelemetryConfig(dir=str(tmp_path))) as tel:
        sched = BatchScheduler(m).start()
        try:
            for i in range(3):
                out = sched.infer([x[i]])
                assert out.shape == (3,)
        finally:
            sched.stop()
        series = parse_prometheus(tel.metrics.to_prometheus())
        assert series["ff_serving_requests_total"] == 3.0
        assert series["ff_serving_latency_seconds_count"] == 3.0
        h = tel.metrics.histogram("ff_serving_latency_seconds")
        assert h.quantile(0.95) > 0


def test_checkpoint_restore_events(tmp_path):
    m = small_model()
    x, y = data()
    ck = str(tmp_path / "ck")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False, checkpoint_dir=ck)
    m2 = small_model()
    with obs.session(TelemetryConfig(dir=str(tmp_path / "tel"))) as tel:
        from flexflow_tpu import restore_latest

        info = restore_latest(m2, ck)
        assert info is not None
        names = {e["name"] for e in tel.tracer.events}
        assert "checkpoint_restore" in names
        series = parse_prometheus(tel.metrics.to_prometheus())
        assert series["ff_checkpoint_restores_total"] == 1.0


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------
def test_obs_cli(tmp_path):
    m = small_model(search_budget=2)
    x, y = data()
    tdir = str(tmp_path / "tel")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False,
          telemetry=TelemetryConfig(dir=tdir))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ev = os.path.join(tdir, "events.jsonl")
    out = str(tmp_path / "cli_trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", "trace", ev, "-o", out],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr
    assert "traceEvents" in json.load(open(out))
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", "summary", ev],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr
    assert "steps: 4" in r.stdout
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", "prom",
         os.path.join(tdir, "metrics.jsonl")],
        capture_output=True, text=True, cwd=REPO, env=env,
    )
    assert r.returncode == 0, r.stderr
    assert parse_prometheus(r.stdout)["ff_steps_total"] == 4.0


# ----------------------------------------------------------------------
# fflint FFL201
# ----------------------------------------------------------------------
def test_fflint_bare_print_rule():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from fflint import lint_source
    finally:
        sys.path.pop(0)
    lib = os.path.join(REPO, "flexflow_tpu", "fake_mod.py")
    hits = lint_source("print('hi')\n", lib)
    assert [f.code for f in hits] == ["FFL201"]
    # pragma on the line suppresses
    assert lint_source("print('x')  # fflint: disable=FFL201\n", lib) == []
    # file-level pragma suppresses everywhere
    assert lint_source(
        "# fflint: disable-file=FFL201\nprint('a')\nprint('b')\n", lib
    ) == []
    # __main__ modules are CLI entry points: exempt
    main_mod = os.path.join(REPO, "flexflow_tpu", "obs", "__main__.py")
    assert lint_source("print('usage')\n", main_mod) == []
    # outside flexflow_tpu/: not a library-print concern
    assert lint_source("print('tool')\n",
                       os.path.join(REPO, "tools", "x.py")) == []
