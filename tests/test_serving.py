"""Overload-robust serving: continuous batching, admission control and
replica failover (runtime/serving.py, runtime/kvcache.py).

The contract under test is the Orca/vLLM-shaped one ROADMAP Open item 3
asks for: iteration-level scheduling with per-slot decode positions that
is EXACT vs the reference generator, admission decisions that are always
typed and counted (zero silent drops), KV-page accounting that
backpressures instead of over-committing, and a ReplicaSet that requeues
a dead replica's in-flight work and restores the replica elastically.
scripts/load_check.py drives the same stack under a sustained 10x ramp;
here every edge gets a deterministic unit."""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    AggrMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.runtime.kvcache import (
    KVCacheAccountingError,
    KVCacheConfig,
    KVCacheExhaustedError,
    PagePool,
)
from flexflow_tpu.runtime.resilience import FaultInjector
from flexflow_tpu.runtime.serving import (
    AdmissionQueue,
    BatchScheduler,
    ContinuousBatcher,
    DeadlineExceededError,
    GenerationRequest,
    QueueFullError,
    RateLimitedError,
    ReplicaDeathError,
    ReplicaSet,
    RequestShedError,
    ServingConfig,
    TokenBucket,
    incremental_generate,
)

VOCAB, SEQ, HIDDEN, HEADS = 29, 16, 16, 2


def build_lm(batch=2, seq=SEQ, layers=1):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = 1
    m = FFModel(cfg)
    ids = m.create_tensor((batch, seq), DataType.DT_INT32)
    t = m.embedding(ids, VOCAB, HIDDEN, AggrMode.AGGR_MODE_NONE)
    for _ in range(layers):
        t = m.multihead_attention(t, t, t, HIDDEN, HEADS, causal=True)
        t = m.dense(t, HIDDEN, ActiMode.AC_MODE_RELU)
    t = m.softmax(m.dense(t, VOCAB))
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


@pytest.fixture(scope="module")
def lm():
    return build_lm()


# ---------------------------------------------------------------------------
# paged KV-cache allocator
# ---------------------------------------------------------------------------

def test_page_pool_reserve_touch_release_accounting():
    pool = PagePool(KVCacheConfig(num_pages=8, page_size=4))
    assert pool.pages_free == 8
    rr = pool.reserve("a", 10)  # ceil(10/4) = 3 pages
    assert rr.pages == 3 and rr.shared_pages == 0
    assert pool.pages_free == 5 and pool.pages_reserved == 3
    assert pool.pages_in_use == 0  # nothing materialized yet
    assert pool.touch("a", 4) and pool.pages_in_use == 1
    assert pool.touch("a", 5) and pool.pages_in_use == 2
    assert pool.touch("a", 5) == []  # already covered
    assert len(pool.page_table("a")) == 2
    # growth beyond the reservation is a caller bug, not an over-commit
    with pytest.raises(ValueError):
        pool.touch("a", 16)
    assert pool.release("a") == 2
    # double release is a TYPED accounting error now, not a silent no-op
    # (a failover requeue bug must surface instead of corrupting refs)
    with pytest.raises(KVCacheAccountingError):
        pool.release("a")
    assert pool.release("a", missing_ok=True) == 0  # benign-race escape
    assert pool.stats["accounting_errors"] == 1
    assert pool.pages_free == 8 and pool.pages_in_use == 0
    assert pool.audit().ok


def test_page_pool_exhaustion_typed_and_never_fits():
    pool = PagePool(KVCacheConfig(num_pages=4, page_size=4))
    pool.reserve("a", 12)  # 3 of 4 pages
    with pytest.raises(KVCacheExhaustedError) as ei:
        pool.reserve("b", 8)  # needs 2, only 1 admittable
    assert ei.value.pages_needed == 2
    assert ei.value.pages_free == 1
    assert not ei.value.never_fits  # would fit once "a" retires
    with pytest.raises(KVCacheExhaustedError) as ei2:
        pool.reserve("c", 999)
    assert ei2.value.never_fits  # bigger than the whole pool: shed
    assert pool.stats["exhaustions"] == 2


def test_page_pool_watermark_and_config_validation():
    pool = PagePool(KVCacheConfig(num_pages=10, page_size=4, watermark=0.2))
    # 2 pages held back: only 8 admittable
    pool.reserve("a", 32)  # 8 pages
    with pytest.raises(KVCacheExhaustedError):
        pool.reserve("b", 1)
    for bad in (dict(num_pages=0), dict(num_pages=4, page_size=0),
                dict(num_pages=4, watermark=1.0)):
        with pytest.raises(ValueError):
            KVCacheConfig(**bad)


def test_page_pool_watermark_rounds_up_on_tiny_pools():
    """Regression: int(num_pages * watermark) floored to 0 below 1/w
    pages, silently disabling the watermark exactly where CPU tests
    live. A positive watermark must hold back >= 1 page."""
    tiny = KVCacheConfig(num_pages=4, page_size=4, watermark=0.1)
    assert tiny.held_back_pages() == 1  # 0.4 pages rounds UP, not down
    pool = PagePool(tiny)
    pool.reserve("a", 12)  # 3 of the 3 admittable pages
    with pytest.raises(KVCacheExhaustedError):
        pool.reserve("b", 1)  # the held-back page is not admittable
    # no float-noise over-rounding: 10 * 0.2 holds exactly 2, not 3
    assert KVCacheConfig(num_pages=10, watermark=0.2).held_back_pages() == 2
    # a watermark that would hold back the whole pool is a config error
    with pytest.raises(ValueError):
        KVCacheConfig(num_pages=2, page_size=4, watermark=0.9)


def test_page_pool_kv_exhaustion_fault_site():
    fi = FaultInjector()
    fi.inject("kv_exhaustion", never_fits=True)
    pool = PagePool(KVCacheConfig(num_pages=64, page_size=4),
                    fault_injector=fi)
    with pytest.raises(KVCacheExhaustedError) as ei:
        pool.reserve("a", 4)
    assert ei.value.never_fits
    assert fi.fired["kv_exhaustion"] == 1
    pool.reserve("a", 4)  # one-shot plan consumed: pool works again


# ---------------------------------------------------------------------------
# per-slot decode positions (the continuous-batching mechanism)
# ---------------------------------------------------------------------------

def test_per_slot_positions_match_full_forward(lm):
    """Rows of one decode batch advancing at DIFFERENT positions must
    reproduce the full causal forward exactly — the cache update and the
    causality mask are per-row."""
    bs = 2
    rng = np.random.RandomState(0)
    toks = rng.randint(0, VOCAB, (bs, SEQ)).astype(np.int32)
    full = np.asarray(lm.executor.build_forward()(
        lm.state.params, [jnp.asarray(toks)]))

    init_caches, step = lm.executor.build_decode(bs, SEQ)
    caches = init_caches()
    pos = np.zeros(bs, np.int32)
    for it in range(2 * SEQ):
        feed = np.stack([toks[i, min(pos[i], SEQ - 1)]
                         for i in range(bs)])[:, None]
        logits, caches = step(lm.state.params, caches,
                              jnp.asarray(pos), [jnp.asarray(feed)])
        logits = np.asarray(logits)
        for i in range(bs):
            if pos[i] >= SEQ:
                continue
            # row 0 advances every iteration, row 1 every other one
            if i == 0 or it % 2 == 0:
                np.testing.assert_allclose(
                    logits[i, 0], full[i, pos[i]], rtol=2e-4, atol=2e-4)
                pos[i] += 1
        if (pos >= SEQ).all():
            break
    assert (pos >= SEQ).all()


# ---------------------------------------------------------------------------
# admission queue + token bucket
# ---------------------------------------------------------------------------

def test_admission_queue_full_rejection_typed():
    q = AdmissionQueue(max_depth=2)
    r1 = GenerationRequest(np.arange(3), 4, deadline_s=30)
    r2 = GenerationRequest(np.arange(3), 4, deadline_s=30)
    r3 = GenerationRequest(np.arange(3), 4, deadline_s=30)
    q.offer(r1)
    q.offer(r2)
    with pytest.raises(QueueFullError):
        q.offer(r3)
    assert isinstance(r3.error, QueueFullError)  # finished typed, not lost
    assert r3.done()
    # requeue (failover) is exempt from the bound
    q.requeue(GenerationRequest(np.arange(3), 4, deadline_s=30))
    assert len(q) == 3


def test_admission_queue_deadline_shed_enqueue_and_dequeue():
    q = AdmissionQueue(max_depth=8)
    dead = GenerationRequest(np.arange(3), 4, deadline_s=0.0)
    with pytest.raises(DeadlineExceededError) as ei:
        q.offer(dead)
    assert ei.value.stage == "enqueue"
    # expires while queued -> shed at dequeue, never returned to a worker
    r = GenerationRequest(np.arange(3), 4, deadline_s=0.05)
    q.offer(r)
    time.sleep(0.08)
    assert q.poll(timeout=0.0) is None
    assert isinstance(r.error, DeadlineExceededError)
    assert r.error.stage == "dequeue"


def test_admission_queue_drain_is_typed():
    q = AdmissionQueue(max_depth=8)
    reqs = [GenerationRequest(np.arange(2), 2, deadline_s=30)
            for _ in range(3)]
    for r in reqs:
        q.offer(r)
    n = q.drain(lambda req: RequestShedError("shutdown", reason="aborted"))
    assert n == 3
    assert all(isinstance(r.error, RequestShedError) for r in reqs)


def test_token_bucket_acquire_and_aimd_adapt():
    now = [0.0]
    bucket = TokenBucket(rate=2.0, burst=2, clock=lambda: now[0])
    assert bucket.try_acquire() and bucket.try_acquire()
    assert not bucket.try_acquire()  # burst spent
    now[0] += 0.5  # refills 1 token at 2/s
    assert bucket.try_acquire()
    r0 = bucket.rate
    assert bucket.adapt(10.0, 1.0) < r0        # over target: cut
    assert bucket.adapt(0.1, 1.0) >= r0 * 0.7  # under target: grow back
    assert bucket.adapt(float("nan"), 1.0) == bucket.rate  # no samples


def test_generation_request_finish_once_and_generation_guard():
    r = GenerationRequest(np.arange(3), 4, deadline_s=30)
    gen = r.generation
    assert r._requeue_bump() == gen + 1
    # the old owner's publish loses: stale generation
    assert not r._finish(tokens=np.arange(5), generation=gen)
    assert not r.done()
    assert r._finish(tokens=np.arange(5), generation=gen + 1)
    assert r.done()
    assert r._requeue_bump() is None  # already finished
    np.testing.assert_array_equal(r.result(0.1), np.arange(5))


# ---------------------------------------------------------------------------
# continuous batching (single replica)
# ---------------------------------------------------------------------------

def _serve_cfg(**kw):
    base = dict(max_len=SEQ, slots=2, page_size=4, precompile=False,
                default_deadline_s=60.0)
    base.update(kw)
    return ServingConfig(**base)


def test_continuous_batching_matches_incremental_generate(lm):
    q = AdmissionQueue(max_depth=16)
    b = ContinuousBatcher(lm, _serve_cfg(slots=3), q).start()
    rng = np.random.RandomState(1)
    cases = []
    try:
        for _ in range(7):  # more requests than slots: queueing + reuse
            plen = int(rng.randint(1, 6))
            new = int(rng.randint(1, 6))
            prompt = rng.randint(0, VOCAB, plen).astype(np.int32)
            req = GenerationRequest(prompt, new, deadline_s=60.0)
            q.offer(req)
            cases.append((prompt, new, req))
        for prompt, new, req in cases:
            out = req.result(timeout=120.0)
            ref = incremental_generate(lm, prompt[None], max_new_tokens=new)
            np.testing.assert_array_equal(out, ref[0])
    finally:
        b.stop()
    assert b.stats["finished"] == 7
    assert b.pool.pages_in_use == 0  # every retirement released its pages


def test_continuous_batching_admits_mid_stream(lm):
    """A request arriving while the batch is mid-decode joins without
    disturbing the running sequences — the iteration-level contract."""
    q = AdmissionQueue(max_depth=8)
    b = ContinuousBatcher(lm, _serve_cfg(), q).start()
    rng = np.random.RandomState(2)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 5).astype(np.int32)
    try:
        r1 = GenerationRequest(p1, 10, deadline_s=60.0)
        q.offer(r1)
        while b.stats["admitted"] == 0:  # r1 is decoding
            time.sleep(0.005)
        r2 = GenerationRequest(p2, 4, deadline_s=60.0)
        q.offer(r2)
        out1 = r1.result(timeout=120.0)
        out2 = r2.result(timeout=120.0)
        np.testing.assert_array_equal(
            out1, incremental_generate(lm, p1[None], max_new_tokens=10)[0])
        np.testing.assert_array_equal(
            out2, incremental_generate(lm, p2[None], max_new_tokens=4)[0])
    finally:
        b.stop()


def test_continuous_batching_kv_backpressure(lm):
    """A pool covering ~one sequence serializes admission instead of
    over-committing; everything still completes."""
    q = AdmissionQueue(max_depth=8)
    cfg = _serve_cfg(num_pages=3)  # one 10-token sequence = 3 pages
    b = ContinuousBatcher(lm, cfg, q).start()
    rng = np.random.RandomState(3)
    reqs = []
    try:
        for _ in range(4):
            req = GenerationRequest(rng.randint(0, VOCAB, 3).astype(np.int32),
                                    6, deadline_s=60.0)
            q.offer(req)
            reqs.append(req)
        outs = [r.result(timeout=120.0) for r in reqs]
    finally:
        b.stop()
    assert len(outs) == 4
    assert b.pool.stats["exhaustions"] >= 1  # backpressure really engaged
    assert b.pool.pages_in_use == 0


def test_continuous_batching_sheds_never_fits_and_too_long(lm):
    q = AdmissionQueue(max_depth=8)
    b = ContinuousBatcher(lm, _serve_cfg(num_pages=2), q).start()
    try:
        # 3+10 tokens -> 4 pages > the whole 2-page pool: typed shed
        never = GenerationRequest(np.zeros(3, np.int32), 10, deadline_s=60.0)
        q.offer(never)
        with pytest.raises(RequestShedError) as ei:
            never.result(timeout=60.0)
        assert ei.value.reason == "kv_exhausted"
        # prompt + max_new beyond the compiled cache width
        long = GenerationRequest(np.zeros(SEQ - 1, np.int32), SEQ,
                                 deadline_s=60.0)
        q.offer(long)
        with pytest.raises(RequestShedError) as ei2:
            long.result(timeout=60.0)
        assert ei2.value.reason == "too_long"
    finally:
        b.stop()


def test_continuous_batching_eos_early_retirement():
    m = build_lm()
    q = AdmissionQueue(max_depth=8)
    # find what token the model emits first, then declare it EOS
    probe = GenerationRequest(np.zeros(2, np.int32), 1, deadline_s=60.0)
    b = ContinuousBatcher(m, _serve_cfg(), q).start()
    try:
        q.offer(probe)
        eos = int(probe.result(timeout=120.0)[-1])
        b.stop()
        q2 = AdmissionQueue(max_depth=8)
        b2 = ContinuousBatcher(m, _serve_cfg(eos_token_id=eos), q2).start()
        try:
            req = GenerationRequest(np.zeros(2, np.int32), 10,
                                    deadline_s=60.0)
            q2.offer(req)
            out = req.result(timeout=120.0)
            assert out[-1] == eos
            assert len(out) < 2 + 10  # retired at EOS, not max_new
            assert b2.stats["retired_eos"] == 1
        finally:
            b2.stop()
    finally:
        b.stop()


def test_continuous_batcher_rejects_two_input_graphs():
    cfg = FFConfig()
    cfg.batch_size = 2
    m = FFModel(cfg)
    a = m.create_tensor((2, 4), DataType.DT_FLOAT)
    bt = m.create_tensor((2, 4), DataType.DT_FLOAT)
    t = m.softmax(m.dense(m.add(a, bt), 3))
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    from flexflow_tpu.runtime.verify import ServingConfigError

    with pytest.raises(ServingConfigError):
        ContinuousBatcher(m, _serve_cfg(max_len=4), AdmissionQueue(4))


# ---------------------------------------------------------------------------
# replica failover + rate limiting
# ---------------------------------------------------------------------------

def test_replica_set_death_failover_and_elastic_restart(tmp_path):
    fi = FaultInjector()
    fi.inject("replica_death", at_step=2, replica="replica0",
              exc=ReplicaDeathError("injected"))
    cfg = _serve_cfg()
    rs = ReplicaSet(build_lm, cfg, replicas=2, ckpt_dir=str(tmp_path),
                    fault_injector=fi, health_timeout_s=60.0,
                    restart_backoff_s=0.05).start()
    rng = np.random.RandomState(4)
    try:
        reqs = [rs.submit(rng.randint(0, VOCAB, 3).astype(np.int32),
                          max_new_tokens=5, deadline_s=120.0)
                for _ in range(6)]
        outs = [r.result(timeout=180.0) for r in reqs]
        assert len(outs) == 6  # no admitted request was lost to the death
        t0 = time.monotonic()
        # _restart_replica registers the replacement BEFORE bumping the
        # restarts stat — wait on both, not just the count
        while (rs.replica_count() < 2 or rs.stats["restarts"] < 1) \
                and time.monotonic() - t0 < 120:
            time.sleep(0.05)
        assert rs.replica_count() == 2  # restored via the elastic path
        assert rs.stats["restarts"] == 1
        assert fi.fired["replica_death"] == 1
    finally:
        rs.stop()


def test_replica_set_warm_spare_activation(tmp_path):
    fi = FaultInjector()
    fi.inject("replica_death", replica="replica0",
              exc=ReplicaDeathError("injected"))
    rs = ReplicaSet(build_lm, _serve_cfg(), replicas=1,
                    ckpt_dir=str(tmp_path), fault_injector=fi,
                    health_timeout_s=60.0, restart_backoff_s=0.05,
                    warm_spares=1).start()
    rng = np.random.RandomState(5)
    try:
        reqs = [rs.submit(rng.randint(0, VOCAB, 3).astype(np.int32),
                          max_new_tokens=4, deadline_s=120.0)
                for _ in range(4)]
        outs = [r.result(timeout=180.0) for r in reqs]
        assert len(outs) == 4
        t0 = time.monotonic()
        while rs.stats["restarts"] < 1 and time.monotonic() - t0 < 120:
            time.sleep(0.05)
        assert rs.stats["spares_used"] == 1  # restart came from the spare
        assert rs.stats["restarts"] == 1
    finally:
        rs.stop()


def test_replica_set_rate_limiter_sheds_typed():
    cfg = _serve_cfg(rate_limit=1.0, rate_burst=2)
    rs = ReplicaSet(build_lm, cfg, replicas=1, health_timeout_s=60.0).start()
    try:
        ok = shed = 0
        for _ in range(6):  # burst 2, refill 1/s: most of these shed
            try:
                rs.submit(np.zeros(2, np.int32), max_new_tokens=2,
                          deadline_s=60.0)
                ok += 1
            except RateLimitedError:
                shed += 1
        assert ok >= 2 and shed >= 3
    finally:
        rs.stop()


def test_replica_set_stop_aborts_pending_typed():
    rs = ReplicaSet(build_lm, _serve_cfg(), replicas=1,
                    health_timeout_s=60.0).start()
    reqs = [rs.submit(np.zeros(2, np.int32), max_new_tokens=3,
                      deadline_s=120.0) for _ in range(5)]
    rs.stop(timeout=0.2)  # shut down before the queue can drain
    for r in reqs:
        assert r.done()
        if r.error is not None:
            assert isinstance(r.error, RequestShedError)


def test_metrics_find_does_not_create():
    from flexflow_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    assert reg.find("ff_serving_latency_seconds") is None
    assert reg.to_prometheus() == ""  # no empty series polluted the export
    h = reg.histogram("ff_serving_latency_seconds")
    h.observe(0.25)
    assert reg.find("ff_serving_latency_seconds") is h


def test_serving_metrics_export_through_obs_session(lm, tmp_path):
    """With a telemetry session active, the new serving series land in
    the session registry and export to Prometheus text (the
    docs/observability.md catalog entries)."""
    from flexflow_tpu import obs
    from flexflow_tpu.obs import TelemetryConfig
    from flexflow_tpu.obs.metrics import parse_prometheus

    with obs.session(TelemetryConfig(dir=str(tmp_path / "tel"))) as tel:
        q = AdmissionQueue(max_depth=4)
        b = ContinuousBatcher(lm, _serve_cfg(), q).start()
        try:
            req = GenerationRequest(np.zeros(2, np.int32), 3,
                                    deadline_s=60.0)
            q.offer(req)
            req.result(timeout=120.0)
            # typed shed: dead-on-arrival
            with pytest.raises(DeadlineExceededError):
                q.offer(GenerationRequest(np.zeros(2, np.int32), 3,
                                          deadline_s=0.0))
        finally:
            b.stop()
        series = parse_prometheus(tel.metrics.to_prometheus())
    assert series.get("ff_serving_requests_total") == 1.0
    assert series.get('ff_serving_shed_total{reason="deadline"}') == 1.0
    assert "ff_serving_queue_depth" in series
    assert "ff_kv_pages_in_use" in series
    assert any(k.startswith("ff_serving_latency_seconds_bucket")
               for k in series)


# ---------------------------------------------------------------------------
# BatchScheduler satellite fixes
# ---------------------------------------------------------------------------

def _dense_model(batch=4):
    cfg = FFConfig()
    cfg.batch_size = batch
    m = FFModel(cfg)
    x = m.create_tensor((batch, 6), DataType.DT_FLOAT)
    t = m.softmax(m.dense(m.dense(x, 16, ActiMode.AC_MODE_RELU), 3))
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def test_batchscheduler_sheds_expired_at_dequeue():
    """Satellite fix: a request whose deadline passed while queued must
    be shed with a typed error at dequeue, not executed on-device."""
    m = _dense_model()
    sched = BatchScheduler(m, max_delay_s=0.001)
    x = np.zeros(6, np.float32)
    expired = sched.submit([x], deadline=time.monotonic() - 1.0)
    live = sched.submit([x], deadline=time.monotonic() + 30.0)
    sched.start()
    try:
        assert live.event.wait(30.0)
        assert live.error is None and live.result is not None
        assert expired.event.wait(5.0)
        assert isinstance(expired.error, DeadlineExceededError)
        assert expired.error.stage == "dequeue"
        assert sched.stats["shed"] == 1
    finally:
        sched.stop()


def test_batchscheduler_queue_bound_typed():
    m = _dense_model()
    sched = BatchScheduler(m, max_queue_depth=2)  # worker not started
    x = np.zeros(6, np.float32)
    sched.submit([x])
    sched.submit([x])
    with pytest.raises(QueueFullError):
        sched.submit([x])
    assert sched.stats["shed"] == 1


def test_batchscheduler_worker_death_surfaces_degraded_retry():
    """Satellite fix: the in-flight request that dies with the worker is
    re-run degraded AND the retry is surfaced (stat + structured event),
    not silent."""
    m = _dense_model()
    fi = FaultInjector()
    fi.inject("serving_worker", at_step=0, exc=RuntimeError("worker crash"))
    sched = BatchScheduler(m, fault_injector=fi, max_worker_restarts=0)
    sched.start()
    try:
        out = sched.infer([np.zeros(6, np.float32)], timeout=30.0)
        assert out.shape == (3,)
        assert sched.stats["degraded_retries"] >= 1
        assert sched.stats["degraded"] >= 1
    finally:
        sched.stop()


def test_batchscheduler_restart_backoff_under_lock():
    """Satellite fix regression: concurrent infer() callers racing a
    worker crash must agree on the backoff window (no restart before
    the window the dying worker published)."""
    m = _dense_model()
    fi = FaultInjector()
    fi.inject("serving_worker", at_step=0, exc=RuntimeError("crash"),
              times=1)
    sched = BatchScheduler(m, fault_injector=fi, max_worker_restarts=2,
                           restart_backoff_s=0.05)
    sched.start()
    results = []

    def caller():
        try:
            results.append(sched.infer([np.zeros(6, np.float32)],
                                       timeout=30.0))
        except BaseException as e:  # noqa: BLE001 — collected for assert
            results.append(e)

    threads = [threading.Thread(target=caller) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    try:
        assert len(results) == 4
        for r in results:
            assert isinstance(r, np.ndarray), r
        # the restart happened at most max_worker_restarts times
        assert sched.stats["worker_restarts"] <= 2
    finally:
        sched.stop()


# ---------------------------------------------------------------------------
# slow chaos sweep over the new fault sites
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_sweep_serving_fault_sites(tmp_path):
    """Every new FaultInjector site, one sustained run each: all offered
    requests end in tokens or a typed error, and killed/hung replicas
    come back."""
    rng = np.random.RandomState(7)
    scenarios = [
        ("replica_death", dict(replica="replica0",
                               exc=ReplicaDeathError("chaos"))),
        # at_step=5: past the first-step compile-grace window, so the
        # watchdog's steady-state timeout is what catches the stall
        ("slow_worker", dict(replica="replica0", at_step=5, delay_s=2.0)),
        ("kv_exhaustion", dict(times=3)),
    ]
    for site, kw in scenarios:
        fi = FaultInjector()
        fi.inject(site, **kw)
        timeout_s = 0.4 if site == "slow_worker" else 60.0
        rs = ReplicaSet(
            build_lm, _serve_cfg(), replicas=2,
            ckpt_dir=str(tmp_path / site), fault_injector=fi,
            health_timeout_s=timeout_s, compile_grace_s=300.0,
            restart_backoff_s=0.05,
        ).start()
        try:
            reqs = [rs.submit(rng.randint(0, VOCAB, 3).astype(np.int32),
                              max_new_tokens=4, deadline_s=120.0)
                    for _ in range(10)]
            done = typed = 0
            for r in reqs:
                try:
                    r.result(timeout=180.0)
                    done += 1
                except RequestShedError:
                    typed += 1
            assert done + typed == 10, (site, done, typed)
            assert done > 0, site
            assert fi.fired.get(site, 0) >= 1, site
            if site in ("replica_death", "slow_worker"):
                t0 = time.monotonic()
                while (rs.replica_count() < 2
                       and time.monotonic() - t0 < 120):
                    time.sleep(0.05)
                assert rs.replica_count() == 2, site
                assert rs.stats["restarts"] >= 1, site
        finally:
            rs.stop()
