"""Test config: run everything on a virtual 8-device CPU mesh so sharding
paths are exercised without TPU hardware (SURVEY §4: substitutes for the
reference's no-cluster gap; the reference needs real GPUs for most tests).

The environment may auto-register a remote-TPU ("axon") jax backend at
interpreter boot whose client init blocks on a tunnel; tests must never touch
it. Deregistering the factory + forcing the cpu platform post-import is the
reliable way since sitecustomize already imported jax.
"""
import os

os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
